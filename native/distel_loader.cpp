// distel_native — C++ load plane: OWL functional syntax → indexed tensors.
//
// The native rebuild of the reference's load plane (AxiomLoader + Normalizer,
// reference src/knoelab/classification/init/{AxiomLoader,Normalizer}.java):
// tokenize + parse the EL fragment, lower sugar, eliminate ranges, normalize
// to NF1-NF7, intern entities to dense ids, binarize n-ary conjunctions, and
// close the (role,filler) link table under role-chain targets — emitting the
// exact flat int32 arrays distel_tpu.core.engine consumes, with zero Python
// object materialization on the hot path.
//
// Semantics mirror distel_tpu/frontend/normalizer.py + core/indexing.py
// one-for-one (the Python frontend stays the readable reference
// implementation; tests/test_native_loader.py proves closure equivalence).
//
// C ABI at the bottom; Python binds via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <string>
#include <vector>
#include <unordered_map>
#include <unordered_set>
#include <map>
#include <algorithm>

namespace {

// ---------------------------------------------------------------- tokenizer

enum TokKind : uint8_t { T_LPAR, T_RPAR, T_EQ, T_NAME, T_IRI, T_STRING, T_CARET, T_LANG, T_EOF };

struct Tok {
  TokKind kind;
  uint32_t start, end;  // byte span in the input
};

struct Tokenizer {
  const char* s;
  size_t n;
  std::vector<Tok> toks;
  std::string error;

  bool run() {
    size_t p = 0;
    while (p < n) {
      char c = s[p];
      if (c == ' ' || c == '\t' || c == '\n' || c == '\r') { p++; continue; }
      if (c == '#') { while (p < n && s[p] != '\n') p++; continue; }
      if (c == '(') { toks.push_back({T_LPAR, (uint32_t)p, (uint32_t)p + 1}); p++; continue; }
      if (c == ')') { toks.push_back({T_RPAR, (uint32_t)p, (uint32_t)p + 1}); p++; continue; }
      if (c == '=') { toks.push_back({T_EQ, (uint32_t)p, (uint32_t)p + 1}); p++; continue; }
      if (c == '<') {
        size_t q = p + 1;
        while (q < n && s[q] != '>' && s[q] != ' ' && s[q] != '\n') q++;
        if (q >= n || s[q] != '>') { error = "unterminated IRI"; return false; }
        toks.push_back({T_IRI, (uint32_t)p, (uint32_t)q + 1});
        p = q + 1;
        continue;
      }
      if (c == '"') {
        size_t q = p + 1;
        while (q < n && s[q] != '"') { if (s[q] == '\\') q++; q++; }
        if (q >= n) { error = "unterminated string"; return false; }
        toks.push_back({T_STRING, (uint32_t)p, (uint32_t)q + 1});
        p = q + 1;
        continue;
      }
      if (c == '^' && p + 1 < n && s[p + 1] == '^') {
        toks.push_back({T_CARET, (uint32_t)p, (uint32_t)p + 2});
        p += 2;
        continue;
      }
      if (c == '@') {
        size_t q = p + 1;
        while (q < n && (isalnum((unsigned char)s[q]) || s[q] == '-')) q++;
        toks.push_back({T_LANG, (uint32_t)p, (uint32_t)q});
        p = q;
        continue;
      }
      // NAME: any run excluding whitespace and ()="^
      size_t q = p;
      while (q < n) {
        char d = s[q];
        if (d == ' ' || d == '\t' || d == '\n' || d == '\r' || d == '(' ||
            d == ')' || d == '=' || d == '"' || d == '^')
          break;
        q++;
      }
      if (q == p) { error = std::string("unexpected character '") + c + "'"; return false; }
      toks.push_back({T_NAME, (uint32_t)p, (uint32_t)q});
      p = q;
    }
    toks.push_back({T_EOF, (uint32_t)n, (uint32_t)n});
    return true;
  }
};

// ------------------------------------------------------------- expressions

// Expression arena. kind: 0 atom-class, 1 atom-individual, 2 THING,
// 3 NOTHING, 4 SOME(role, filler), 5 AND(kids), 6 UNSUPPORTED.
enum ExprKind : uint8_t { E_CLS, E_IND, E_THING, E_NOTHING, E_SOME, E_AND, E_UNSUP };

struct Expr {
  ExprKind kind;
  int32_t name = -1;           // string id for atoms
  int32_t role = -1;           // role string id for SOME
  int32_t a = -1;              // filler for SOME
  std::vector<int32_t> kids;   // operands for AND
};

struct Interner {
  std::unordered_map<std::string, int32_t> ids;
  std::vector<std::string> names;
  int32_t get(const std::string& s) {
    auto it = ids.find(s);
    if (it != ids.end()) return it->second;
    int32_t id = (int32_t)names.size();
    ids.emplace(s, id);
    names.push_back(s);
    return id;
  }
};

// axiom kinds mirrored from the Python AST
enum AxKind : uint8_t {
  A_SUB, A_EQUIV, A_DISJ, A_SUBROLE, A_EQROLE, A_TRANS, A_REFLEX,
  A_DOMAIN, A_RANGE, A_CLSASSERT, A_ROLEASSERT, A_UNSUP
};

struct Axiom {
  AxKind kind;
  std::vector<int32_t> exprs;   // expr arena ids (classes/individuals)
  std::vector<int32_t> roles;   // role string ids (chain first, sup last)
};

struct Parser {
  const char* src;
  const std::vector<Tok>& toks;
  size_t pos = 0;
  std::string error;

  Interner strings;             // raw IRIs / prefixed names (resolved)
  std::vector<Expr> arena;
  std::vector<Axiom> axioms;
  std::unordered_map<std::string, std::string> prefixes;
  std::unordered_set<std::string> declared_individuals;

  int32_t thing_id, nothing_id;

  Parser(const char* s, const std::vector<Tok>& t) : src(s), toks(t) {
    prefixes["owl:"] = "http://www.w3.org/2002/07/owl#";
    prefixes["rdf:"] = "http://www.w3.org/1999/02/22-rdf-syntax-ns#";
    prefixes["rdfs:"] = "http://www.w3.org/2000/01/rdf-schema#";
    prefixes["xsd:"] = "http://www.w3.org/2001/XMLSchema#";
    thing_id = mk_expr(E_THING);
    nothing_id = mk_expr(E_NOTHING);
  }

  int32_t mk_expr(ExprKind k) {
    arena.push_back(Expr{k});
    return (int32_t)arena.size() - 1;
  }

  std::string text(const Tok& t) const { return std::string(src + t.start, t.end - t.start); }

  const Tok& peek() const { return toks[pos]; }
  const Tok& next() { return toks[pos++]; }
  bool expect(TokKind k) {
    if (toks[pos].kind != k) {
      error = "expected token kind " + std::to_string(k) + " got '" + text(toks[pos]) + "'";
      return false;
    }
    pos++;
    return true;
  }

  std::string resolve(const Tok& t) {
    if (t.kind == T_IRI) return std::string(src + t.start + 1, t.end - t.start - 2);
    std::string name = text(t);
    for (auto& kv : prefixes) {
      const std::string& pfx = kv.first;
      if (name.size() >= pfx.size() && name.compare(0, pfx.size(), pfx) == 0)
        return kv.second + name.substr(pfx.size());
    }
    return name;
  }

  static bool is_thing(const std::string& iri) {
    return iri == "http://www.w3.org/2002/07/owl#Thing" || iri == "owl:Thing" || iri == "Thing";
  }
  static bool is_nothing(const std::string& iri) {
    return iri == "http://www.w3.org/2002/07/owl#Nothing" || iri == "owl:Nothing" || iri == "Nothing";
  }

  int32_t as_class(const std::string& iri) {
    if (is_thing(iri)) return thing_id;
    if (is_nothing(iri)) return nothing_id;
    Expr e;
    e.kind = declared_individuals.count(iri) ? E_IND : E_CLS;
    e.name = strings.get(iri);
    arena.push_back(std::move(e));
    return (int32_t)arena.size() - 1;
  }

  // pre-scan Declaration(NamedIndividual(x)) so individuals are recognized
  void prescan() {
    for (size_t i = 0; i + 4 < toks.size(); i++) {
      if (toks[i].kind == T_NAME && text(toks[i]) == "Declaration" &&
          toks[i + 1].kind == T_LPAR && toks[i + 2].kind == T_NAME &&
          text(toks[i + 2]) == "NamedIndividual" && toks[i + 3].kind == T_LPAR) {
        declared_individuals.insert(resolve(toks[i + 4]));
      }
    }
  }

  bool skip_balanced() {  // consume a balanced (...) starting at LPAR
    int depth = 0;
    do {
      const Tok& t = next();
      if (t.kind == T_EOF) { error = "unexpected EOF in group"; return false; }
      if (t.kind == T_LPAR) depth++;
      else if (t.kind == T_RPAR) depth--;
    } while (depth > 0);
    return true;
  }

  bool consume_group_open() {  // already inside '(' at depth 1; eat to match
    int depth = 1;
    while (depth > 0) {
      const Tok& t = next();
      if (t.kind == T_EOF) { error = "unexpected EOF in group"; return false; }
      if (t.kind == T_LPAR) depth++;
      else if (t.kind == T_RPAR) depth--;
    }
    return true;
  }

  bool skip_annotations() {
    while (peek().kind == T_NAME && text(peek()) == "Annotation") {
      next();
      if (!expect(T_LPAR)) return false;
      pos--;  // skip_balanced expects to start at LPAR
      if (!skip_balanced()) return false;
    }
    return true;
  }

  bool parse_document() {
    prescan();
    while (peek().kind != T_EOF) {
      const Tok& t = peek();
      if (t.kind != T_NAME) { error = "expected construct, got '" + text(t) + "'"; return false; }
      std::string kw = text(t);
      if (kw == "Prefix") {
        next();
        if (!expect(T_LPAR)) return false;
        std::string pfx = text(next());
        if (peek().kind == T_EQ) next();
        else if (!pfx.empty() && pfx.back() == '=') pfx.pop_back();
        const Tok& iri = next();
        if (iri.kind != T_IRI) { error = "expected IRI in Prefix"; return false; }
        prefixes[pfx] = std::string(src + iri.start + 1, iri.end - iri.start - 2);
        if (!expect(T_RPAR)) return false;
      } else if (kw == "Ontology") {
        next();
        if (!expect(T_LPAR)) return false;
        if (peek().kind == T_IRI) { next(); if (peek().kind == T_IRI) next(); }
        while (peek().kind != T_RPAR) {
          if (peek().kind == T_EOF) { error = "unterminated Ontology("; return false; }
          if (!parse_axiom()) return false;
        }
        next();  // rpar
      } else {
        if (!parse_axiom()) return false;
      }
    }
    return true;
  }

  bool parse_axiom() {
    const Tok& t = next();
    if (t.kind != T_NAME) { error = "expected axiom, got '" + text(t) + "'"; return false; }
    std::string kw = text(t);
    if (!expect(T_LPAR)) return false;
    if (!skip_annotations()) return false;

    if (kw == "Declaration" || kw == "AnnotationAssertion" ||
        kw == "SubAnnotationPropertyOf" || kw == "AnnotationPropertyDomain" ||
        kw == "AnnotationPropertyRange") {
      return consume_group_open();
    }

    Axiom ax;
    if (kw == "SubClassOf") {
      ax.kind = A_SUB;
      int32_t a = parse_class_expr(); if (a < 0) return false;
      int32_t b = parse_class_expr(); if (b < 0) return false;
      ax.exprs = {a, b};
    } else if (kw == "EquivalentClasses" || kw == "DisjointClasses") {
      ax.kind = kw[0] == 'E' ? A_EQUIV : A_DISJ;
      while (peek().kind != T_RPAR) {
        int32_t e = parse_class_expr(); if (e < 0) return false;
        ax.exprs.push_back(e);
      }
    } else if (kw == "SubObjectPropertyOf") {
      ax.kind = A_SUBROLE;
      if (peek().kind == T_NAME && text(peek()) == "ObjectPropertyChain") {
        next();
        if (!expect(T_LPAR)) return false;
        while (peek().kind != T_RPAR) {
          int32_t r = parse_role(); if (r < 0) return false;
          ax.roles.push_back(r);
        }
        next();
      } else {
        int32_t r = parse_role(); if (r < 0) return false;
        ax.roles.push_back(r);
      }
      int32_t sup = parse_role(); if (sup < 0) return false;
      ax.roles.push_back(sup);
    } else if (kw == "EquivalentObjectProperties") {
      ax.kind = A_EQROLE;
      while (peek().kind != T_RPAR) {
        int32_t r = parse_role(); if (r < 0) return false;
        ax.roles.push_back(r);
      }
    } else if (kw == "TransitiveObjectProperty" || kw == "ReflexiveObjectProperty") {
      ax.kind = kw[0] == 'T' ? A_TRANS : A_REFLEX;
      int32_t r = parse_role(); if (r < 0) return false;
      ax.roles.push_back(r);
    } else if (kw == "ObjectPropertyDomain" || kw == "ObjectPropertyRange") {
      ax.kind = kw[14] == 'D' ? A_DOMAIN : A_RANGE;
      int32_t r = parse_role(); if (r < 0) return false;
      ax.roles.push_back(r);
      int32_t e = parse_class_expr(); if (e < 0) return false;
      ax.exprs.push_back(e);
    } else if (kw == "ClassAssertion") {
      ax.kind = A_CLSASSERT;
      int32_t e = parse_class_expr(); if (e < 0) return false;
      int32_t i = parse_individual(); if (i < 0) return false;
      ax.exprs = {e, i};
    } else if (kw == "ObjectPropertyAssertion") {
      ax.kind = A_ROLEASSERT;
      int32_t r = parse_role(); if (r < 0) return false;
      ax.roles.push_back(r);
      int32_t a = parse_individual(); if (a < 0) return false;
      int32_t b = parse_individual(); if (b < 0) return false;
      ax.exprs = {a, b};
    } else {
      // out-of-profile axiom: record kind, swallow the group
      ax.kind = A_UNSUP;
      ax.roles.push_back(strings.get(kw));  // stash the constructor name
      axioms.push_back(std::move(ax));
      return consume_group_open();
    }
    axioms.push_back(std::move(ax));
    return expect(T_RPAR);
  }

  int32_t parse_class_expr() {
    const Tok& t = next();
    if (t.kind == T_IRI) return as_class(resolve(t));
    if (t.kind != T_NAME) { error = "expected class expression, got '" + text(t) + "'"; return -1; }
    std::string name = text(t);
    bool ctor_like = name.rfind("Object", 0) == 0 || name.rfind("Data", 0) == 0;
    if (peek().kind == T_LPAR && ctor_like) {
      next();  // consume (
      if (name == "ObjectIntersectionOf") {
        Expr e;
        e.kind = E_AND;
        while (peek().kind != T_RPAR) {
          int32_t k = parse_class_expr(); if (k < 0) return -1;
          e.kids.push_back(k);
        }
        next();
        if (e.kids.size() == 1) return e.kids[0];
        arena.push_back(std::move(e));
        return (int32_t)arena.size() - 1;
      }
      if (name == "ObjectSomeValuesFrom") {
        int32_t r = parse_role(); if (r < 0) return -1;
        int32_t f = parse_class_expr(); if (f < 0) return -1;
        Expr e;
        e.kind = E_SOME;
        e.role = r;
        e.a = f;
        arena.push_back(std::move(e));
        int32_t id = (int32_t)arena.size() - 1;
        if (!expect(T_RPAR)) return -1;
        return id;
      }
      if (name == "ObjectOneOf") {
        std::vector<int32_t> inds;
        while (peek().kind != T_RPAR) {
          int32_t i = parse_individual(); if (i < 0) return -1;
          inds.push_back(i);
        }
        next();
        if (inds.size() == 1) return inds[0];
        return mk_expr(E_UNSUP);  // multi-nominal: out of profile
      }
      if (name == "ObjectHasValue") {
        // EL sugar: ObjectHasValue(r a) == ObjectSomeValuesFrom(r {a})
        // (reference loads it as a T3_1 axiom keyed on the individual,
        // init/AxiomLoader.java:702-711)
        int32_t r = parse_role(); if (r < 0) return -1;
        int32_t i = parse_individual(); if (i < 0) return -1;
        Expr e;
        e.kind = E_SOME;
        e.role = r;
        e.a = i;
        arena.push_back(std::move(e));
        int32_t id = (int32_t)arena.size() - 1;
        if (!expect(T_RPAR)) return -1;
        return id;
      }
      if (name == "DataSomeValuesFrom") {
        // datatypes-as-classes (reference EntityType.DATATYPE,
        // init/AxiomLoader.java:687-701): data property as role, named
        // datatype as class; complex data ranges stay out of profile
        int32_t r = parse_role(); if (r < 0) return -1;
        if (peek().kind == T_NAME || peek().kind == T_IRI) {
          Tok dt = next();
          if (peek().kind == T_RPAR) {
            next();
            Expr e;
            e.kind = E_SOME;
            e.role = r;
            e.a = as_class(resolve(dt));
            arena.push_back(std::move(e));
            return (int32_t)arena.size() - 1;
          }
        }
        if (!consume_group_open()) return -1;
        return mk_expr(E_UNSUP);
      }
      if (name == "DataHasValue") {
        // keyed on the literal's datatype (init/AxiomLoader.java:712-721);
        // untyped literals default to xsd:string
        int32_t r = parse_role(); if (r < 0) return -1;
        if (peek().kind == T_STRING) {
          next();
          std::string dt_iri = "http://www.w3.org/2001/XMLSchema#string";
          if (peek().kind == T_LANG) {
            next();
            dt_iri = "http://www.w3.org/1999/02/22-rdf-syntax-ns#PlainLiteral";
          }
          else if (peek().kind == T_CARET) {
            next();
            Tok dt = next();
            if (dt.kind != T_NAME && dt.kind != T_IRI) {
              error = "expected datatype after ^^"; return -1;
            }
            dt_iri = resolve(dt);
          }
          if (peek().kind == T_RPAR) {
            next();
            Expr e;
            e.kind = E_SOME;
            e.role = r;
            e.a = as_class(dt_iri);
            arena.push_back(std::move(e));
            return (int32_t)arena.size() - 1;
          }
        }
        if (!consume_group_open()) return -1;
        return mk_expr(E_UNSUP);
      }
      // unsupported constructor: swallow group
      if (!consume_group_open()) return -1;
      return mk_expr(E_UNSUP);
    }
    return as_class(resolve(t));
  }

  int32_t parse_role() {
    const Tok& t = next();
    if (t.kind == T_NAME && text(t) == "ObjectInverseOf") {
      if (!expect(T_LPAR)) return -1;
      int32_t inner = parse_role(); if (inner < 0) return -1;
      if (!expect(T_RPAR)) return -1;
      return strings.get("__inverse__:" + strings.names[inner]);
    }
    if (t.kind != T_NAME && t.kind != T_IRI) { error = "expected role, got '" + text(t) + "'"; return -1; }
    return strings.get(resolve(t));
  }

  int32_t parse_individual() {
    const Tok& t = next();
    if (t.kind != T_NAME && t.kind != T_IRI) { error = "expected individual"; return -1; }
    std::string iri = resolve(t);
    declared_individuals.insert(iri);
    Expr e;
    e.kind = E_IND;
    e.name = strings.get(iri);
    arena.push_back(std::move(e));
    return (int32_t)arena.size() - 1;
  }
};

// -------------------------------------------------------------- normalizer

// Mirrors distel_tpu/frontend/normalizer.py + core/indexing.py.  Atoms are
// interned straight to engine concept ids (⊥=0, ⊤=1); NF rows are emitted
// as ints; n-ary conjunctions binarize through shared aux concepts.

struct Normalizer {
  Parser& P;

  // concept/role interning (engine ids)
  std::unordered_map<std::string, int32_t> concept_ids;
  std::vector<std::string> concept_names;
  std::unordered_map<std::string, int32_t> role_ids;
  std::vector<std::string> role_names;

  std::vector<int32_t> nf1, nf2, nf3, nf4, nf5, nf6;  // flat rows
  std::vector<int32_t> links;                          // (role, filler)
  std::unordered_map<int64_t, int32_t> link_ids;
  std::vector<int32_t> chain_pairs;                    // (r_first, l2, lt)

  std::unordered_map<std::string, int32_t> memo;       // canon+dir → gensym concept id
  std::unordered_map<std::string, int32_t> range_memo;
  std::unordered_map<int64_t, int32_t> aux_memo;       // binarization
  int64_t gensym_counter = 0;

  // ranges: role string id → set of atom concept ids (collected pass 1)
  std::unordered_map<int32_t, std::vector<int32_t>> ranges_by_role;
  std::vector<std::pair<int32_t, int32_t>> role_edges_str;  // (sub,sup) string ids
  std::unordered_map<int32_t, std::vector<int32_t>> super_closure_str;

  std::map<std::string, int64_t> removed;
  std::vector<std::string> canon_cache;  // per-expr canonical string

  explicit Normalizer(Parser& p) : P(p) {
    concept_ids["owl:Nothing"] = 0; concept_names.push_back("owl:Nothing");
    concept_ids["owl:Thing"] = 1;   concept_names.push_back("owl:Thing");
    canon_cache.assign(P.arena.size(), std::string());
  }

  int32_t concept_of(const std::string& name) {
    auto it = concept_ids.find(name);
    if (it != concept_ids.end()) return it->second;
    int32_t id = (int32_t)concept_names.size();
    concept_ids.emplace(name, id);
    concept_names.push_back(name);
    return id;
  }

  int32_t role_of_str(int32_t string_id) {
    const std::string& iri = P.strings.names[string_id];
    auto it = role_ids.find(iri);
    if (it != role_ids.end()) return it->second;
    int32_t id = (int32_t)role_names.size();
    role_ids.emplace(iri, id);
    role_names.push_back(iri);
    return id;
  }

  int32_t atom_concept(const Expr& e) {
    switch (e.kind) {
      case E_THING: return 1;
      case E_NOTHING: return 0;
      case E_IND: return concept_of("ind:" + P.strings.names[e.name]);
      default: return concept_of(P.strings.names[e.name]);
    }
  }

  // canonical string for memo keys (matches role of expr_to_str in Python)
  const std::string& canon(int32_t eid) {
    std::string& c = canon_cache[eid];
    if (!c.empty()) return c;
    const Expr& e = P.arena[eid];
    switch (e.kind) {
      case E_CLS: c = P.strings.names[e.name]; break;
      case E_IND: c = "ind:" + P.strings.names[e.name]; break;
      case E_THING: c = "owl:Thing"; break;
      case E_NOTHING: c = "owl:Nothing"; break;
      case E_SOME:
        c = "Some(" + P.strings.names[e.role] + "," + canon(e.a) + ")";
        break;
      case E_AND: {
        std::vector<std::string> parts;
        for (int32_t k : e.kids) parts.push_back(canon(k));
        std::sort(parts.begin(), parts.end());
        c = "And(";
        for (size_t i = 0; i < parts.size(); i++) { if (i) c += ","; c += parts[i]; }
        c += ")";
        break;
      }
      case E_UNSUP: c = "UNSUP#" + std::to_string(eid); break;
    }
    return c;
  }

  bool profile_ok(int32_t eid) {
    const Expr& e = P.arena[eid];
    switch (e.kind) {
      case E_UNSUP: return false;
      case E_AND:
        for (int32_t k : e.kids) if (!profile_ok(k)) return false;
        return true;
      case E_SOME: {
        const std::string& r = P.strings.names[e.role];
        if (r.rfind("__inverse__:", 0) == 0) return false;
        return profile_ok(e.a);
      }
      default: return true;
    }
  }

  bool is_atomic(const Expr& e) { return e.kind == E_CLS || e.kind == E_IND; }
  bool atom_or_top(const Expr& e) { return is_atomic(e) || e.kind == E_THING; }
  bool atom_or_bot(const Expr& e) { return is_atomic(e) || e.kind == E_NOTHING; }

  bool lhs_unsat(int32_t eid) {
    const Expr& e = P.arena[eid];
    if (e.kind == E_NOTHING) return true;
    if (e.kind == E_AND) {
      for (int32_t k : e.kids) if (lhs_unsat(k)) return true;
      return false;
    }
    if (e.kind == E_SOME) return lhs_unsat(e.a);
    return false;
  }

  int32_t gensym() {
    std::string name = "distel:gensym#" + std::to_string(gensym_counter++);
    return concept_of(name);
  }

  // ---- pass 1: ranges + plain role hierarchy over string ids
  void pass1() {
    for (auto& ax : P.axioms) {
      if (ax.kind == A_RANGE) {
        int32_t eid = ax.exprs[0];
        if (!profile_ok(eid)) { removed["ObjectPropertyRange"]++; continue; }
        const Expr& e = P.arena[eid];
        int32_t cid;
        if (atom_or_top(e)) cid = atom_concept(e);
        else cid = flatten_rhs(eid);
        ranges_by_role[ax.roles[0]].push_back(cid);
      } else if (ax.kind == A_SUBROLE && ax.roles.size() == 2) {
        role_edges_str.push_back({ax.roles[0], ax.roles[1]});
      } else if (ax.kind == A_EQROLE) {
        size_t n = ax.roles.size();
        for (size_t i = 0; i < n; i++)
          role_edges_str.push_back({ax.roles[i], ax.roles[(i + 1) % n]});
      }
    }
    // reflexive-transitive closure (string-id space; role count is small)
    std::unordered_map<int32_t, std::vector<int32_t>> adj;
    for (auto& e : role_edges_str) { adj[e.first].push_back(e.second); adj[e.second]; }
    for (auto& kv : adj) {
      std::vector<int32_t> seen = {kv.first};
      std::unordered_set<int32_t> in_seen = {kv.first};
      std::vector<int32_t> stack = {kv.first};
      while (!stack.empty()) {
        int32_t cur = stack.back(); stack.pop_back();
        auto it = adj.find(cur);
        if (it == adj.end()) continue;
        for (int32_t nxt : it->second)
          if (in_seen.insert(nxt).second) { seen.push_back(nxt); stack.push_back(nxt); }
      }
      super_closure_str[kv.first] = std::move(seen);
    }
  }

  // ---- normalization core (mirrors _emit_sub / _flatten_lhs / _flatten_rhs)

  int32_t flatten_lhs(int32_t eid) {
    std::string key = canon(eid) + "\x01L";
    auto it = memo.find(key);
    if (it != memo.end()) return it->second;
    int32_t a = gensym();
    memo.emplace(std::move(key), a);
    emit_sub_atomrhs(eid, a);
    return a;
  }

  int32_t flatten_rhs(int32_t eid) {
    std::string key = canon(eid) + "\x01R";
    auto it = memo.find(key);
    if (it != memo.end()) return it->second;
    int32_t a = gensym();
    memo.emplace(std::move(key), a);
    emit_sub_atomlhs(a, eid);
    return a;
  }

  int32_t apply_range_rewrite(int32_t role_str, int32_t b_concept) {
    std::vector<int32_t> rr;
    auto scl = super_closure_str.find(role_str);
    if (scl != super_closure_str.end()) {
      for (int32_t sup : scl->second) {
        auto rit = ranges_by_role.find(sup);
        if (rit != ranges_by_role.end())
          rr.insert(rr.end(), rit->second.begin(), rit->second.end());
      }
    } else {
      auto rit = ranges_by_role.find(role_str);
      if (rit != ranges_by_role.end()) rr = rit->second;
    }
    std::sort(rr.begin(), rr.end());
    rr.erase(std::unique(rr.begin(), rr.end()), rr.end());
    rr.erase(std::remove(rr.begin(), rr.end(), (int32_t)1), rr.end());  // drop ⊤
    rr.erase(std::remove(rr.begin(), rr.end(), b_concept), rr.end());
    if (rr.empty()) return b_concept;
    std::string key = std::to_string(b_concept);
    for (int32_t d : rr) key += "," + std::to_string(d);
    auto it = range_memo.find(key);
    if (it != range_memo.end()) return it->second;
    int32_t x = gensym();
    range_memo.emplace(std::move(key), x);
    if (b_concept != 1) { nf1.push_back(x); nf1.push_back(b_concept); }
    for (int32_t d : rr) { nf1.push_back(x); nf1.push_back(d); }
    return x;
  }

  int32_t link_of(int32_t role_engine, int32_t filler) {
    int64_t key = ((int64_t)role_engine << 32) | (uint32_t)filler;
    auto it = link_ids.find(key);
    if (it != link_ids.end()) return it->second;
    int32_t id = (int32_t)(links.size() / 2);
    link_ids.emplace(key, id);
    links.push_back(role_engine);
    links.push_back(filler);
    return id;
  }

  int32_t aux_concept(int32_t a, int32_t b) {
    int64_t key = a <= b ? ((int64_t)a << 32) | (uint32_t)b
                         : ((int64_t)b << 32) | (uint32_t)a;
    auto it = aux_memo.find(key);
    if (it != aux_memo.end()) return it->second;
    int32_t id = concept_of("distel:aux#" + std::to_string(gensym_counter++));
    aux_memo.emplace(key, id);
    return id;
  }

  // C ⊑ d (d already an atomic concept id)
  void emit_sub_atomrhs(int32_t c_eid, int32_t d_concept) {
    const Expr& c = P.arena[c_eid];
    if (lhs_unsat(c_eid)) return;
    if (atom_or_top(c)) {
      if (d_concept == 1) return;  // ⊑ ⊤ trivial
      nf1.push_back(atom_concept(c));
      nf1.push_back(d_concept);
      return;
    }
    if (c.kind == E_AND) {
      std::vector<int32_t> ops;
      std::unordered_set<std::string> seen;
      for (int32_t k : c.kids) {
        const Expr& ke = P.arena[k];
        if (ke.kind == E_THING) continue;
        if (!seen.insert(canon(k)).second) continue;
        ops.push_back(is_atomic(ke) ? atom_concept(ke) : flatten_lhs(k));
      }
      if (ops.empty()) {
        if (d_concept != 1) { nf1.push_back(1); nf1.push_back(d_concept); }
      } else if (ops.size() == 1) {
        if (d_concept != 1) { nf1.push_back(ops[0]); nf1.push_back(d_concept); }
      } else {
        // binarize left-fold through shared aux concepts
        int32_t acc = ops[0];
        for (size_t i = 1; i + 1 < ops.size(); i++) {
          int32_t aux = aux_concept(acc, ops[i]);
          nf2.push_back(acc); nf2.push_back(ops[i]); nf2.push_back(aux);
          acc = aux;
        }
        nf2.push_back(acc); nf2.push_back(ops.back()); nf2.push_back(d_concept);
      }
      return;
    }
    if (c.kind == E_SOME) {
      const Expr& f = P.arena[c.a];
      int32_t a = atom_or_top(f) ? atom_concept_or_top(c.a) : flatten_lhs(c.a);
      nf4.push_back(role_of_str(c.role));
      nf4.push_back(a);
      nf4.push_back(d_concept);
      return;
    }
  }

  int32_t atom_concept_or_top(int32_t eid) {
    const Expr& e = P.arena[eid];
    if (e.kind == E_THING) return 1;
    return atom_concept(e);
  }

  // a ⊑ D (a already an atomic concept id)
  void emit_sub_atomlhs(int32_t a_concept, int32_t d_eid) {
    const Expr& d = P.arena[d_eid];
    if (d.kind == E_THING) return;
    if (d.kind == E_AND) {
      for (int32_t k : d.kids) emit_sub_atomlhs(a_concept, k);
      return;
    }
    if (atom_or_bot(d)) {
      nf1.push_back(a_concept);
      nf1.push_back(atom_concept(d));
      return;
    }
    if (d.kind == E_SOME) {
      const Expr& f = P.arena[d.a];
      if (f.kind == E_NOTHING) {  // a ⊑ ∃r.⊥ ⟹ a ⊑ ⊥
        nf1.push_back(a_concept);
        nf1.push_back(0);
        return;
      }
      int32_t b = atom_or_top(f) ? atom_concept_or_top(d.a) : flatten_rhs(d.a);
      b = apply_range_rewrite(d.role, b);
      nf3.push_back(a_concept);
      nf3.push_back(link_of(role_of_str(d.role), b));
      return;
    }
  }

  // general C ⊑ D
  void emit_sub(int32_t c_eid, int32_t d_eid) {
    const Expr& c = P.arena[c_eid];
    const Expr& d = P.arena[d_eid];
    if (c.kind == E_NOTHING || d.kind == E_THING) return;
    if (lhs_unsat(c_eid)) return;
    if (d.kind == E_AND) {
      for (int32_t k : d.kids) emit_sub(c_eid, k);
      return;
    }
    if (!atom_or_top(c) && !atom_or_bot(d)) {
      int32_t a = flatten_lhs(c_eid);
      emit_sub_atomlhs(a, d_eid);
      return;
    }
    if (atom_or_top(c)) {
      if (atom_or_bot(d)) {
        if (c.kind == E_THING) { nf1.push_back(1); nf1.push_back(atom_concept(d)); }
        else { nf1.push_back(atom_concept(c)); nf1.push_back(atom_concept(d)); }
      } else {
        emit_sub_atomlhs(atom_concept_or_top(c_eid), d_eid);
      }
      return;
    }
    // C complex, D atomic/⊥
    emit_sub_atomrhs(c_eid, atom_concept(d));
  }

  void lower() {
    for (auto& ax : P.axioms) {
      switch (ax.kind) {
        case A_SUB:
          if (profile_ok(ax.exprs[0]) && profile_ok(ax.exprs[1]))
            emit_sub(ax.exprs[0], ax.exprs[1]);
          else removed["SubClassOf(non-EL)"]++;
          break;
        case A_EQUIV: {
          bool ok = true;
          for (int32_t e : ax.exprs) ok = ok && profile_ok(e);
          if (!ok) { removed["EquivalentClasses(non-EL)"]++; break; }
          size_t n = ax.exprs.size();
          for (size_t i = 0; i < n; i++) emit_sub(ax.exprs[i], ax.exprs[(i + 1) % n]);
          break;
        }
        case A_DISJ: {
          bool ok = true;
          for (int32_t e : ax.exprs) ok = ok && profile_ok(e);
          if (!ok) { removed["DisjointClasses(non-EL)"]++; break; }
          for (size_t i = 0; i < ax.exprs.size(); i++)
            for (size_t j = i + 1; j < ax.exprs.size(); j++) {
              // Ci ⊓ Cj ⊑ ⊥
              const Expr& ei = P.arena[ax.exprs[i]];
              const Expr& ej = P.arena[ax.exprs[j]];
              if (lhs_unsat(ax.exprs[i]) || lhs_unsat(ax.exprs[j])) continue;
              int32_t a = is_atomic(ei) ? atom_concept(ei)
                        : (ei.kind == E_THING ? 1 : flatten_lhs(ax.exprs[i]));
              int32_t b = is_atomic(ej) ? atom_concept(ej)
                        : (ej.kind == E_THING ? 1 : flatten_lhs(ax.exprs[j]));
              if (a == b) { nf1.push_back(a); nf1.push_back(0); continue; }
              nf2.push_back(a); nf2.push_back(b); nf2.push_back(0);
            }
          break;
        }
        case A_SUBROLE: {
          bool inv = false;
          for (int32_t r : ax.roles)
            if (P.strings.names[r].rfind("__inverse__:", 0) == 0) inv = true;
          if (inv) { removed["SubObjectPropertyOf(inverse)"]++; break; }
          size_t n = ax.roles.size();  // chain..., sup
          if (n == 2) {
            nf5.push_back(role_of_str(ax.roles[0]));
            nf5.push_back(role_of_str(ax.roles[1]));
          } else if (n == 3) {
            nf6.push_back(role_of_str(ax.roles[0]));
            nf6.push_back(role_of_str(ax.roles[1]));
            nf6.push_back(role_of_str(ax.roles[2]));
          } else {
            // left-associative split with fresh roles
            int32_t acc = role_of_str(ax.roles[0]);
            for (size_t i = 1; i + 1 < n - 1; i++) {
              std::string nm = "distel:genrole#" + std::to_string(gensym_counter++);
              int32_t u;
              {
                auto it = role_ids.find(nm);
                if (it != role_ids.end()) u = it->second;
                else {
                  u = (int32_t)role_names.size();
                  role_ids.emplace(nm, u);
                  role_names.push_back(nm);
                }
              }
              nf6.push_back(acc); nf6.push_back(role_of_str(ax.roles[i])); nf6.push_back(u);
              acc = u;
            }
            nf6.push_back(acc);
            nf6.push_back(role_of_str(ax.roles[n - 2]));
            nf6.push_back(role_of_str(ax.roles[n - 1]));
          }
          break;
        }
        case A_EQROLE: {
          size_t n = ax.roles.size();
          for (size_t i = 0; i < n; i++) {
            nf5.push_back(role_of_str(ax.roles[i]));
            nf5.push_back(role_of_str(ax.roles[(i + 1) % n]));
          }
          break;
        }
        case A_TRANS: {
          int32_t r = role_of_str(ax.roles[0]);
          nf6.push_back(r); nf6.push_back(r); nf6.push_back(r);
          break;
        }
        case A_REFLEX: removed["ReflexiveObjectProperty"]++; break;
        case A_DOMAIN: {
          if (!profile_ok(ax.exprs[0])) { removed["ObjectPropertyDomain(non-EL)"]++; break; }
          // ∃r.⊤ ⊑ D
          const Expr& d = P.arena[ax.exprs[0]];
          int32_t dc;
          if (atom_or_bot(d)) dc = atom_concept(d);
          else if (d.kind == E_THING) break;
          else dc = flatten_rhs(ax.exprs[0]);
          // note: complex domains D̂ need ∃r.⊤ ⊑ A with A ⊑ D̂
          nf4.push_back(role_of_str(ax.roles[0]));
          nf4.push_back(1);
          nf4.push_back(dc);
          break;
        }
        case A_RANGE: break;  // pass 1
        case A_CLSASSERT: {
          if (!profile_ok(ax.exprs[0])) { removed["ClassAssertion(non-EL)"]++; break; }
          int32_t ind = atom_concept(P.arena[ax.exprs[1]]);
          emit_sub_atomlhs(ind, ax.exprs[0]);
          break;
        }
        case A_ROLEASSERT: {
          int32_t subj = atom_concept(P.arena[ax.exprs[0]]);
          int32_t obj = atom_concept(P.arena[ax.exprs[1]]);
          int32_t b = apply_range_rewrite(ax.roles[0], obj);
          nf3.push_back(subj);
          nf3.push_back(link_of(role_of_str(ax.roles[0]), b));
          break;
        }
        case A_UNSUP:
          removed[P.strings.names[ax.roles[0]]]++;
          break;
      }
    }
  }

  // role closure over engine role ids (Warshall; Nr small) + link chain closure
  std::vector<uint8_t> role_closure;

  void finish() {
    int32_t nr = std::max<int32_t>((int32_t)role_names.size(), 1);
    role_closure.assign((size_t)nr * nr, 0);
    for (int32_t i = 0; i < nr; i++) role_closure[(size_t)i * nr + i] = 1;
    for (size_t i = 0; i + 1 < nf5.size(); i += 2)
      role_closure[(size_t)nf5[i] * nr + nf5[i + 1]] = 1;
    for (int32_t k = 0; k < nr; k++)
      for (int32_t i = 0; i < nr; i++)
        if (role_closure[(size_t)i * nr + k])
          for (int32_t j = 0; j < nr; j++)
            if (role_closure[(size_t)k * nr + j]) role_closure[(size_t)i * nr + j] = 1;

    // close links under chain targets; build chain_pairs (r_first, l2, lt).
    // dedup key packs (r, l2, lt) disjointly: r < 2^20 roles, l2/lt < 2^22
    // links — far above real ontologies (SNOMED: ~60 roles, ~300k links).
    if (!nf6.empty()) {
      std::unordered_set<uint64_t> seen;
      bool changed = true;
      while (changed) {
        changed = false;
        for (size_t c = 0; c + 3 <= nf6.size(); c += 3) {
          int32_t r = nf6[c], s = nf6[c + 1], t = nf6[c + 2];
          size_t L = links.size() / 2;  // snapshot
          for (size_t l2 = 0; l2 < L; l2++) {
            int32_t r2 = links[l2 * 2], f2 = links[l2 * 2 + 1];
            if (!role_closure[(size_t)r2 * nr + s]) continue;
            int32_t lt = link_of(t, f2);
            uint64_t key = ((uint64_t)r << 44) | ((uint64_t)l2 << 22) | (uint64_t)lt;
            if (seen.insert(key).second) {
              chain_pairs.push_back(r);
              chain_pairs.push_back((int32_t)l2);
              chain_pairs.push_back(lt);
              changed = true;
            }
          }
        }
      }
    }
  }
};

}  // namespace

// ------------------------------------------------------------------ C ABI

extern "C" {

struct DistelLoadResult {
  // entity tables: names newline-joined
  char* concept_names; int64_t concept_names_len; int64_t n_concepts;
  char* role_names;    int64_t role_names_len;    int64_t n_roles;
  // axiom arrays (row-major int32)
  int32_t* nf1; int64_t k1;
  int32_t* nf2; int64_t k2;
  int32_t* nf3; int64_t k3;
  int32_t* nf4; int64_t k4;
  int32_t* links; int64_t n_links;
  int32_t* chain_pairs; int64_t n_chain_pairs;
  uint8_t* role_closure;  // n_roles_closure^2
  int64_t n_roles_closure;
  char* removed;  // "kind=count\n" report
  int64_t removed_len;
  char* error;    // non-null on failure
};

static char* dup_str(const std::string& s) {
  char* p = (char*)malloc(s.size() + 1);
  memcpy(p, s.data(), s.size());
  p[s.size()] = 0;
  return p;
}

static int32_t* dup_i32(const std::vector<int32_t>& v) {
  int32_t* p = (int32_t*)malloc(std::max<size_t>(v.size(), 1) * sizeof(int32_t));
  if (!v.empty()) memcpy(p, v.data(), v.size() * sizeof(int32_t));
  return p;
}

DistelLoadResult* distel_load(const char* text, int64_t len) {
  auto* out = (DistelLoadResult*)calloc(1, sizeof(DistelLoadResult));
  Tokenizer tz{text, (size_t)len};
  if (!tz.run()) { out->error = dup_str(tz.error); return out; }
  Parser parser(text, tz.toks);
  if (!parser.parse_document()) { out->error = dup_str(parser.error); return out; }
  Normalizer nz(parser);
  nz.pass1();
  nz.lower();
  nz.finish();

  std::string cn, rn;
  for (auto& s : nz.concept_names) { cn += s; cn += '\n'; }
  for (auto& s : nz.role_names) { rn += s; rn += '\n'; }
  out->concept_names = dup_str(cn); out->concept_names_len = (int64_t)cn.size();
  out->n_concepts = (int64_t)nz.concept_names.size();
  out->role_names = dup_str(rn); out->role_names_len = (int64_t)rn.size();
  out->n_roles = (int64_t)std::max<size_t>(nz.role_names.size(), 1);
  out->nf1 = dup_i32(nz.nf1); out->k1 = (int64_t)nz.nf1.size() / 2;
  out->nf2 = dup_i32(nz.nf2); out->k2 = (int64_t)nz.nf2.size() / 3;
  out->nf3 = dup_i32(nz.nf3); out->k3 = (int64_t)nz.nf3.size() / 2;
  out->nf4 = dup_i32(nz.nf4); out->k4 = (int64_t)nz.nf4.size() / 3;
  out->links = dup_i32(nz.links); out->n_links = (int64_t)nz.links.size() / 2;
  out->chain_pairs = dup_i32(nz.chain_pairs);
  out->n_chain_pairs = (int64_t)nz.chain_pairs.size() / 3;
  int64_t nr = (int64_t)std::max<size_t>(nz.role_names.size(), 1);
  out->n_roles_closure = nr;
  out->role_closure = (uint8_t*)malloc((size_t)nr * nr);
  if ((size_t)nr * nr == nz.role_closure.size())
    memcpy(out->role_closure, nz.role_closure.data(), (size_t)nr * nr);
  else {  // no roles: identity 1x1
    out->role_closure[0] = 1;
  }
  std::string rem;
  for (auto& kv : nz.removed) rem += kv.first + "=" + std::to_string(kv.second) + "\n";
  out->removed = dup_str(rem); out->removed_len = (int64_t)rem.size();
  return out;
}

void distel_free(DistelLoadResult* r) {
  if (!r) return;
  free(r->concept_names); free(r->role_names);
  free(r->nf1); free(r->nf2); free(r->nf3); free(r->nf4);
  free(r->links); free(r->chain_pairs); free(r->role_closure);
  free(r->removed); free(r->error);
  free(r);
}

}  // extern "C"
