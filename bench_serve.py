#!/usr/bin/env python
"""Traffic-shaped load benchmark for the serve fleet.

The serving analog of ``bench.py``: boots a real fleet (router
in-process, N shared-nothing replica subprocesses via the supervisor),
drives it with concurrent clients issuing a mixed op stream — deltas
(the classify work), taxonomy reads, subsumer point reads, and an
occasional fresh ontology load — and records per-op p50/p99 latency plus
aggregate classify (delta-saturation) throughput.  Re-run across replica
counts (``--replicas 1 2 4``) it measures horizontal scaling; with
``--migrate-under-load`` it performs a LIVE ontology migration mid-run
and asserts the fleet contract: zero failed requests and byte-identical
taxonomy before/after the move.

Throughput here is bounded by host cores: every replica is one Python
process executing jax CPU programs inline (one GIL each), so a 2-core
host tops out near 2x regardless of replica count — the record carries
``host.cores`` so the number reads honestly.

Usage::

    JAX_PLATFORMS=cpu python bench_serve.py --replicas 1 2 4 \
        --clients 6 --duration-s 20 --migrate-under-load \
        --out BENCH_SERVE_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import threading
import time


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


#: classes per tenant ontology — enough distinct pairs that the
#: assertion traffic below keeps finding new axioms to push
_N_CLASSES = 12


def _mk_ontology(i: int) -> str:
    """One small ontology per simulated tenant — identical SHAPE (one
    bucket: the compile cache serves every replica) but distinct names:
    a subclass chain plus one existential so CR3/CR4 stay exercised."""
    p = f"T{i}"
    lines = [
        f"SubClassOf({p}C{k} {p}C{k + 1})" for k in range(_N_CLASSES - 1)
    ]
    lines += [
        f"SubClassOf({p}C0 ObjectSomeValuesFrom(r{i} {p}C{_N_CLASSES - 1}))",
        f"SubClassOf(ObjectSomeValuesFrom(r{i} {p}C{_N_CLASSES - 1}) "
        f"{p}C1)",
    ]
    return "\n".join(lines)


class ClientWorker(threading.Thread):
    """One simulated tenant: owns one ontology, loops a shaped op mix
    (2/3 classify deltas, 1/4 taxonomy reads, the rest point reads),
    records (op, wall_s, ok) samples.  ``pause_writes`` quiesces the
    write side (the migration window needs a stable before/after
    taxonomy) while reads keep flowing."""

    def __init__(self, idx, client, oid, stop, samples, failures):
        super().__init__(name=f"bench-client-{idx}", daemon=True)
        self.idx = idx
        self.client = client
        self.oid = oid
        self.stop_ev = stop
        self.samples = samples
        self.failures = failures
        self.pause_writes = threading.Event()
        self.writes_quiesced = threading.Event()
        self._i = 0

    def run(self):
        prefix = f"T{self.idx}"
        while not self.stop_ev.is_set():
            i = self._i
            self._i += 1
            if i % 12 < 8:
                if self.pause_writes.is_set():
                    self.writes_quiesced.set()
                    time.sleep(0.01)
                    continue
                op = "classify"
                if i % 40 == 39:
                    # occasional GROWTH delta: a new concept widens the
                    # corpus (the expensive shape-changing traffic)
                    text = f"SubClassOf({prefix}New{i} {prefix}C0)"
                else:
                    # assertion traffic over existing concepts: the
                    # common production shape (no layout change)
                    a = (7 * i) % _N_CLASSES
                    b = (a + 1 + i % (_N_CLASSES - 2)) % _N_CLASSES
                    if a == b:
                        b = (b + 1) % _N_CLASSES
                    text = (
                        f"SubClassOf({prefix}C{a} {prefix}C{b})"
                    )
                fn = lambda: self.client.delta(  # noqa: E731
                    self.oid, text
                )
            elif i % 12 < 11:
                op = "taxonomy"
                fn = lambda: self.client.taxonomy(self.oid)  # noqa: E731
            else:
                op = "subsumers"
                fn = lambda: self.client.subsumers(  # noqa: E731
                    self.oid, f"{prefix}C0"
                )
            t0 = time.monotonic()
            try:
                fn()
                self.samples.append((op, time.monotonic() - t0, True))
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                self.samples.append((op, time.monotonic() - t0, False))
                self.failures.append((self.name, op, repr(e)))


def run_scenario(
    n_replicas: int,
    *,
    clients: int,
    duration_s: float,
    spill_root: str,
    migrate_under_load: bool,
    label: str = "",
    router_port: int = 0,
) -> dict:
    from distel_tpu.serve.client import ServeClient
    from distel_tpu.serve.fleet.router import RouterApp
    from distel_tpu.serve.fleet.supervisor import ReplicaSupervisor
    from distel_tpu.serve.server import make_server

    spill = os.path.join(spill_root, f"fleet{n_replicas}{label}")
    # one scheduler worker per replica: jax CPU executes inline holding
    # the GIL, so a second worker thread only adds tracing thrash
    # (measured: 6 tenants through workers=2 halve a replica's delta
    # rate vs serial) — cross-ontology concurrency comes from REPLICAS
    sup = ReplicaSupervisor(
        n_replicas,
        spill_dir=spill,
        extra_args=["--fast-path-min-concepts", "0", "--workers", "1"],
    )
    print(f"# booting {n_replicas} replica(s)…", file=sys.stderr)
    t_boot = time.monotonic()
    replicas = sup.start()
    router = RouterApp(replicas, supervisor=sup)
    router.start()
    server = make_server(router, port=router_port)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    boot_s = time.monotonic() - t_boot
    print(f"# fleet up at {url} in {boot_s:.1f}s", file=sys.stderr)

    try:
        base = ServeClient(url, timeout=300)
        oids = [base.load(_mk_ontology(i))["id"] for i in range(clients)]
        # settle: one warm delta per tenant so compile/trace cost sits
        # in setup, not in the measured window
        for i, oid in enumerate(oids):
            base.delta(oid, f"SubClassOf(T{i}Warm T{i}C0)")

        samples: list = []
        failures: list = []
        stop = threading.Event()
        workers = [
            ClientWorker(
                i,
                ServeClient(url, timeout=300, retries=2, backoff_s=0.1),
                oids[i],
                stop,
                samples,
                failures,
            )
            for i in range(clients)
        ]
        t0 = time.monotonic()
        for w in workers:
            w.start()

        migration = None
        if migrate_under_load and n_replicas >= 2:
            time.sleep(duration_s / 2)
            migration = _migrate_under_load(
                router, base, workers[0], spill_root
            )
        deadline = t0 + duration_s
        while time.monotonic() < deadline:
            time.sleep(0.1)
        stop.set()
        for w in workers:
            w.join(timeout=300)
        wall_s = time.monotonic() - t0

        by_op: dict = {}
        for op, dt, ok in samples:
            by_op.setdefault(op, []).append(dt)
        lat = {}
        for op, vals in sorted(by_op.items()):
            vals.sort()
            lat[op] = {
                "n": len(vals),
                "p50_ms": round(1e3 * _pct(vals, 0.50), 2),
                "p99_ms": round(1e3 * _pct(vals, 0.99), 2),
                "mean_ms": round(1e3 * statistics.fmean(vals), 2),
            }
        n_classify = len(by_op.get("classify", ()))
        fleet_counters = {
            short: router.metrics.counter_value(f"distel_fleet_{name}")
            for short, name in (
                ("migrations", "migrations_total"),
                ("migration_failures", "migration_failures_total"),
                ("ejections", "ejections_total"),
                ("recoveries", "recoveries_total"),
            )
        }
        fleet_counters["proxy_errors"] = router.metrics.counter_value(
            "distel_router_proxy_errors_total"
        )
        rec = {
            "scenario": label or f"scale-x{n_replicas}",
            "replicas": n_replicas,
            "clients": clients,
            "wall_s": round(wall_s, 2),
            "boot_s": round(boot_s, 2),
            "requests": len(samples),
            "failed_requests": len(failures),
            "failures": failures[:10],
            "classify_ops": n_classify,
            "classify_throughput_ops_s": round(n_classify / wall_s, 2),
            "latency": lat,
            "fleet": fleet_counters,
            "migration": migration,
        }
        return rec
    finally:
        stop_err = None
        try:
            router.close()
        except Exception as e:  # noqa: BLE001
            stop_err = e
        server.shutdown()
        server.server_close()
        sup.stop(graceful=True)
        if stop_err is not None:
            print(f"# router close: {stop_err!r}", file=sys.stderr)


def _migrate_under_load(router, client, worker, spill_root) -> dict:
    """Live-migrate worker 0's ontology mid-run: quiesce ITS writes
    (reads and every other tenant keep hammering), snapshot the
    taxonomy, move the closure, snapshot again, resume.  The fleet
    contract: zero failed requests anywhere and byte-identical taxonomy
    documents across the move."""
    oid = worker.oid
    worker.pause_writes.set()
    if not worker.writes_quiesced.wait(timeout=60):
        worker.pause_writes.clear()
        return {"ok": False, "error": "writer never quiesced"}
    # one straggler write may still be in flight: the router's own
    # migration drain handles it; the taxonomy snapshot below rides the
    # same lane so it observes the settled closure
    src = router.table.lookup(oid).rid
    before = json.dumps(client.taxonomy(oid), sort_keys=True)
    t0 = time.monotonic()
    try:
        rec = router.migrate(oid)
    except Exception as e:  # noqa: BLE001
        worker.pause_writes.clear()
        return {"ok": False, "error": repr(e), "from": src}
    after = json.dumps(client.taxonomy(oid), sort_keys=True)
    worker.pause_writes.clear()
    worker.writes_quiesced.clear()
    out = {
        "ok": True,
        "byte_identical": before == after,
        "from": rec["from"],
        "to": rec["to"],
        "migrate_wall_s": round(time.monotonic() - t0, 3),
        "spill_restore_wall_s": rec["wall_s"],
    }
    if before != after:
        out["diff"] = _tax_diff(json.loads(before), json.loads(after))
        dump = os.path.join(spill_root, "migration_mismatch.json")
        with open(dump, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "before": json.loads(before),
                    "after": json.loads(after),
                    # the acked write stream: replaying it on a fresh
                    # classifier adjudicates WHICH side under-derives
                    "journal": router._journal_texts(oid),
                },
                f, indent=1,
            )
        out["dump"] = dump
    return out


def _tax_diff(a: dict, b: dict, limit: int = 8) -> list:
    """First differing taxonomy entries — a broken byte-identity claim
    must say WHERE, not just false."""
    diffs = []
    for section in sorted(set(a) | set(b)):
        va, vb = a.get(section), b.get(section)
        if va == vb:
            continue
        if isinstance(va, dict) and isinstance(vb, dict):
            for k in sorted(set(va) | set(vb)):
                if va.get(k) != vb.get(k):
                    diffs.append(
                        f"{section}[{k}]: {va.get(k)!r} -> {vb.get(k)!r}"
                    )
                    if len(diffs) >= limit:
                        return diffs
        else:
            diffs.append(f"{section}: {va!r} -> {vb!r}")
            if len(diffs) >= limit:
                return diffs
    return diffs


def run_delta_steady_state(
    *,
    deltas: int,
    classes: int,
    exact: bool,
    label: str,
) -> dict:
    """Steady-state increment scenario (ISSUE 10): one warm base, a
    long stream of small class-only and link-creating deltas, per-delta
    latency split into COMPILE vs EXECUTE plus the delta-program cache
    hit rate — the serving regime the bucketed delta programs exist
    for.  ``exact=True`` flips the ``DISTEL_EXACT_DELTA_PROGRAMS``
    hatch: every delta builds exact-shape programs (the pre-bucketing
    behavior), which is the BEFORE leg of the record.

    Runs a single in-process ServeApp (no fleet): the measurement
    targets the delta plane, and replica processes would only add
    boot noise around it."""
    from distel_tpu.frontend.ontology_tools import snomed_shaped_ontology
    from distel_tpu.serve.client import ServeClient
    from distel_tpu.serve.server import ServeApp, make_server

    if deltas < 2:
        # fail BEFORE the minutes-long run: the scenario needs at
        # least one warm and one steady delta to report a split
        raise SystemExit(
            f"--delta-count must be >= 2 (got {deltas})"
        )
    env_key = "DISTEL_EXACT_DELTA_PROGRAMS"
    prev = os.environ.pop(env_key, None)
    if exact:
        os.environ[env_key] = "1"
    app = server = None
    try:
        app = ServeApp(workers=1, fast_path_min_concepts=0)
        server = make_server(app, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        client = ServeClient(url, timeout=600)
        oid = client.load(snomed_shaped_ontology(n_classes=classes))["id"]
        recs = []
        for i in range(deltas):
            if i % 3 == 2:
                # link-creating: the reference's property-assertion
                # traffic shape (traffic-data-load-classify.sh)
                text = (
                    f"SubClassOf(SteadyLink{i} "
                    f"ObjectSomeValuesFrom(attr0 Find{i % 5}))"
                )
            else:
                text = f"SubClassOf(Steady{i} Find{i % 7})"
            t0 = time.monotonic()
            rec = client.delta(oid, text)
            rec["wall_s"] = time.monotonic() - t0
            recs.append(rec)

        def agg(rs):
            walls = sorted(r["wall_s"] for r in rs)
            compile_s = [
                r.get("compile_s", 0) + r.get("trace_lower_s", 0)
                for r in rs
            ]
            programs = sum(r.get("delta_programs", 0) for r in rs)
            hits = sum(r.get("delta_program_hits", 0) for r in rs)
            return {
                "n": len(rs),
                "wall_p50_ms": round(1e3 * _pct(walls, 0.50), 2),
                "wall_p99_ms": round(1e3 * _pct(walls, 0.99), 2),
                "compile_mean_ms": round(
                    1e3 * statistics.fmean(compile_s), 2
                ),
                "execute_mean_ms": round(
                    1e3
                    * statistics.fmean(
                        r["wall_s"] - c for r, c in zip(rs, compile_s)
                    ),
                    2,
                ),
                "program_cache_hit_rate": round(hits / programs, 3)
                if programs
                else None,
                "throughput_deltas_s": round(
                    len(rs) / sum(r["wall_s"] for r in rs), 2
                ),
            }

        # the first few deltas pay the once-per-bucket compiles (or,
        # exact mode, just compile like everything else); steady state
        # is the rest — the regime a resident tenant actually lives
        # in.  Clamped so the steady slice is never empty at small
        # --delta-count.
        warm = min(max(3, deltas // 10), deltas - 1)
        out = {
            "scenario": label,
            "delta_programs": "exact" if exact else "bucketed",
            "classes": classes,
            "deltas": deltas,
            "fast_path": sum(r.get("path") == "fast" for r in recs),
            "all": agg(recs),
            "steady": agg(recs[warm:]),
            "first_delta": {
                "wall_ms": round(1e3 * recs[0]["wall_s"], 1),
                "compile_ms": round(
                    1e3
                    * (
                        recs[0].get("compile_s", 0)
                        + recs[0].get("trace_lower_s", 0)
                    ),
                    1,
                ),
                "program_cache_hit": recs[0].get("program_cache_hit"),
            },
        }
        return out
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if app is not None:
            app.close()
        os.environ.pop(env_key, None)
        if prev is not None:
            os.environ[env_key] = prev


def run_cohort_ab(
    *,
    sizes=(1, 2, 4, 8),
    deltas: int = 10,
    wait_ms: float = 100.0,
    label: str = "cohort-ab",
) -> dict:
    """The cohort A/B (ISSUE 12): N same-bucket tenants firing
    concurrent steady deltas against one in-process ServeApp, run
    twice per size — ``inline`` (cohort.enable=false: one device
    dispatch per tenant per vote, the pre-cohort behavior) vs
    ``cohort`` (the formation lane groups them and one vmapped
    dispatch advances the whole cohort).  Records per-tenant delta
    p50/p99, aggregate delta throughput, and the MEASURED dispatch
    counts from the process-global ``COHORT_EVENTS`` tally — on a CPU
    host the dispatch collapse is the honest headline (each vmapped
    dispatch still executes its lanes serially on one core; the
    MXU-utilization win needs a TPU host), so the record reports both
    and lets neither impersonate the other."""
    from distel_tpu.config import ClassifierConfig
    from distel_tpu.runtime.instrumentation import COHORT_EVENTS
    from distel_tpu.serve.client import ServeClient
    from distel_tpu.serve.server import ServeApp, make_server

    def leg(k: int, mode: str) -> dict:
        cfg = ClassifierConfig(
            cohort_enable=(mode == "cohort"),
            cohort_max_size=max(k, 2),
            cohort_max_wait_ms=wait_ms,
        )
        app = server = None
        try:
            app = ServeApp(cfg, workers=2, fast_path_min_concepts=0)
            server = make_server(app, port=0)
            threading.Thread(
                target=server.serve_forever, daemon=True
            ).start()
            url = f"http://127.0.0.1:{server.server_address[1]}"
            base = ServeClient(url, timeout=600)
            oids = [base.load(_mk_ontology(i))["id"] for i in range(k)]
            for i, oid in enumerate(oids):
                base.delta(oid, f"SubClassOf(T{i}Warm T{i}C0)")

            def delta_text(i, j):
                if j % 3 == 2:
                    return (
                        f"SubClassOf(T{i}L{j} "
                        f"ObjectSomeValuesFrom(r{i} T{i}C1))"
                    )
                return f"SubClassOf(T{i}S{j} T{i}C0)"

            failures: list = []

            def fire(round_ids, record):
                threads = []
                for i in round_ids:
                    def w(i=i):
                        c = ServeClient(url, timeout=600)
                        for j in range(deltas):
                            t0 = time.monotonic()
                            try:
                                rec = c.delta(
                                    oids[i], delta_text(i, j)
                                )
                            except Exception as e:  # noqa: BLE001
                                # a swallowed failure would let a
                                # partial run impersonate a clean one
                                # in the record — count it and keep
                                # the other deltas flowing
                                failures.append((i, j, repr(e)))
                                continue
                            if record is not None:
                                record.append(
                                    (time.monotonic() - t0,
                                     rec.get("path"))
                                )
                    threads.append(threading.Thread(target=w))
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()

            # un-timed warm round: first-formation cohort compiles (or
            # their inline equivalents) pay OUTSIDE the measured
            # window — the steady state is the regime under test
            warm_rec: list = []
            fire(range(k), warm_rec)
            samples: list = []
            before = COHORT_EVENTS.snapshot()
            t0 = time.monotonic()
            fire(range(k), samples)
            wall = time.monotonic() - t0
            after = COHORT_EVENTS.snapshot()
            walls = sorted(s[0] for s in samples)
            n = len(samples)
            disp = {
                key: after[key] - before[key]
                for key in (
                    "solo_dispatches",
                    "cohort_dispatches",
                    "cohort_tenant_votes",
                    "cohort_deltas",
                )
            }
            return {
                "mode": mode,
                "tenants": k,
                "deltas": n,
                "failed_requests": len(failures),
                "failures_sample": failures[:5],
                "wall_s": round(wall, 2),
                "delta_p50_ms": round(1e3 * _pct(walls, 0.50), 1),
                "delta_p99_ms": round(1e3 * _pct(walls, 0.99), 1),
                "throughput_deltas_s": round(n / wall, 2),
                "cohort_paths": sum(
                    1 for _w, p in samples if p == "cohort"
                ),
                "dispatches": disp,
                "formed": app.metrics.counter_value(
                    "distel_cohort_formed_total"
                ),
                "fallbacks": app.metrics.counter_value(
                    "distel_cohort_fallback_total"
                ),
            }
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
            if app is not None:
                app.close(final_spill=False)

    out = {
        "scenario": label,
        "tenant_deltas": deltas,
        "max_wait_ms": wait_ms,
        "note": (
            "the headline on a CPU host is the DISPATCH COLLAPSE "
            "(dispatches_per_delta: one vmapped launch advances the "
            "whole cohort where inline pays one per tenant), counted "
            "from the process-global tally.  Wall-clock throughput on "
            "a CPU host can read BELOW 1x: the vmapped program still "
            "executes its lanes serially on the same cores, and the "
            "canonical cohort roster adds inert votes (cross program, "
            "extra quiet cycles) that inline execution skips — the "
            "MXU-utilization win this path exists for needs a TPU "
            "host, where the batch dimension feeds otherwise-idle "
            "systolic array rows instead of a busy scalar core."
        ),
        "sizes": {},
    }
    for k in sizes:
        print(f"# cohort A/B at {k} tenant(s)…", file=sys.stderr)
        inline = leg(k, "inline")
        co = leg(k, "cohort")
        run_disp = co["dispatches"]["solo_dispatches"] + co[
            "dispatches"
        ]["cohort_dispatches"]
        out["sizes"][f"x{k}"] = {
            "inline": inline,
            "cohort": co,
            # run-program dispatches per steady delta, both legs — the
            # N→1 collapse reads directly off these
            "dispatches_per_delta_inline": round(
                inline["dispatches"]["solo_dispatches"]
                / max(inline["deltas"], 1),
                2,
            ),
            "dispatches_per_delta_cohort": round(
                run_disp / max(co["deltas"], 1), 2
            ),
            "throughput_speedup_x": round(
                co["throughput_deltas_s"]
                / max(inline["throughput_deltas_s"], 1e-9),
                2,
            ),
        }
    # scenario-level rollup so the doc's zero_failed_requests claim
    # covers this scenario like every other
    out["failed_requests"] = sum(
        rec[leg]["failed_requests"]
        for rec in out["sizes"].values()
        for leg in ("inline", "cohort")
    )
    return out


class _ReadWorker(threading.Thread):
    """One read client hammering a single ontology.  ``mode`` picks the
    path: "snapshot" uses the lock-free /query endpoints, "lane" the
    legacy scheduler-lane reads (/subsumers, /taxonomy) — the A/B the
    read-heavy scenario exists to measure.  Records (op, wall_s, ok,
    version, lag) samples; per-worker version monotonicity violations
    count as STALE reads (the contract says zero)."""

    def __init__(self, idx, client, oid, mode, stop, samples,
                 failures, latest_acked):
        super().__init__(name=f"bench-reader-{idx}", daemon=True)
        self.idx = idx
        self.client = client
        self.oid = oid
        self.mode = mode
        self.stop_ev = stop
        self.samples = samples
        self.failures = failures
        self.latest_acked = latest_acked  # [int] — writer's last ack
        self.stale = 0
        self._last_version = 0
        self._i = 0

    def run(self):
        while not self.stop_ev.is_set():
            i = self._i
            self._i += 1
            a = i % (_N_CLASSES - 1)
            if self.mode == "snapshot":
                if i % 3 == 0:
                    op = "subsumed"
                    fn = lambda: self.client.is_subsumed(  # noqa: E731
                        self.oid, f"RC{a}", f"RC{a + 1}"
                    )
                elif i % 3 == 1:
                    op = "subsumers"
                    fn = lambda: self.client.query_subsumers(  # noqa: E731
                        self.oid, f"RC{a}"
                    )
                else:
                    op = "slice"
                    fn = lambda: self.client.taxonomy_slice(  # noqa: E731
                        self.oid, f"RC{a}"
                    )
            else:
                op = "lane-subsumers"
                fn = lambda: self.client.subsumers(  # noqa: E731
                    self.oid, f"RC{a}"
                )
            t0 = time.monotonic()
            try:
                doc = fn()
                dt = time.monotonic() - t0
                version = doc.get("version", 0) or 0
                lag = None
                if version:  # lane reads carry no snapshot version
                    if version < self._last_version:
                        self.stale += 1  # torn/stale: version went BACK
                    self._last_version = max(self._last_version, version)
                    lag = max(0, self.latest_acked[0] - version)
                self.samples.append((op, dt, True, version, lag))
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                self.samples.append(
                    (op, time.monotonic() - t0, False, 0, 0)
                )
                self.failures.append((self.name, op, repr(e)))


def _read_lat(samples):
    walls = sorted(s[1] for s in samples if s[2])
    n_ok = len(walls)
    out = {
        "reads": len(samples),
        "read_failures": len(samples) - n_ok,
        "read_qps": None,
        "p50_ms": round(1e3 * _pct(walls, 0.50), 3) if walls else None,
        "p99_ms": round(1e3 * _pct(walls, 0.99), 3) if walls else None,
    }
    return out


def run_read_heavy(
    *,
    readers: int,
    duration_s: float,
    classes: int,
    label: str = "read-heavy",
) -> dict:
    """The read-plane A/B the query plane exists for: N reader workers
    against ONE ontology, concurrent with steady delta traffic from a
    writer thread on the SAME ontology, in three phases —

    1. ``lane``     — reads ride the scheduler lane (the legacy
       ``/subsumers`` path), queueing behind every delta;
    2. ``snapshot`` — the same read pressure through the lock-free
       ``/query/*`` endpoints, same write load;
    3. ``idle``     — ``/query/*`` with the writer stopped (the p99
       baseline the "unaffected by an in-flight classify" criterion
       compares against).

    Reports read QPS per phase, p50/p99, STALE reads (a version that
    went backwards for any single reader — must be 0), and the
    snapshot-version lag distribution (writer's last acked version
    minus the version each read was answered from)."""
    from distel_tpu.frontend.ontology_tools import snomed_shaped_ontology
    from distel_tpu.serve.client import ServeClient
    from distel_tpu.serve.server import ServeApp, make_server

    app = server = None
    try:
        app = ServeApp(workers=1, fast_path_min_concepts=0)
        server = make_server(app, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        base = ServeClient(url, timeout=600)
        text = snomed_shaped_ontology(n_classes=classes)
        # the read workers probe a chain the writer keeps extending
        text += "\n" + "\n".join(
            f"SubClassOf(RC{k} RC{k + 1})" for k in range(_N_CLASSES - 1)
        )
        oid = base.load(text)["id"]
        base.delta(oid, "SubClassOf(RWarm RC0)")  # warm delta programs

        latest_acked = [base.watermark(oid)]
        stop_writer = threading.Event()
        writes = []

        def writer():
            i = 0
            while not stop_writer.is_set():
                t0 = time.monotonic()
                try:
                    rec = base.delta(
                        oid, f"SubClassOf(RNew{i} RC{i % _N_CLASSES})"
                    )
                    latest_acked[0] = max(
                        latest_acked[0], rec.get("version", 0)
                    )
                    writes.append(time.monotonic() - t0)
                except Exception:  # noqa: BLE001 — keep the load steady
                    pass
                i += 1
                time.sleep(0.02)

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

        def phase(mode, secs):
            samples: list = []
            failures: list = []
            stop = threading.Event()
            ws = [
                _ReadWorker(
                    k,
                    ServeClient(url, timeout=600),
                    oid,
                    mode,
                    stop,
                    samples,
                    failures,
                    latest_acked,
                )
                for k in range(readers)
            ]
            t0 = time.monotonic()
            for w in ws:
                w.start()
            time.sleep(secs)
            stop.set()
            for w in ws:
                w.join(timeout=300)
            wall = time.monotonic() - t0
            lat = _read_lat(samples)
            lat["read_qps"] = round(
                (lat["reads"] - lat["read_failures"]) / wall, 1
            )
            lat["stale_reads"] = sum(w.stale for w in ws)
            lags = sorted(
                s[4] for s in samples if s[2] and s[4] is not None
            )
            if lags:
                lat["version_lag"] = {
                    "p50": _pct(lags, 0.50),
                    "p99": _pct(lags, 0.99),
                    "max": lags[-1],
                }
            lat["failures_sample"] = failures[:5]
            return lat

        secs = duration_s / 3.0
        print("# read-heavy: lane phase…", file=sys.stderr)
        lane = phase("lane", secs)
        print("# read-heavy: snapshot phase…", file=sys.stderr)
        snapshot = phase("snapshot", secs)
        stop_writer.set()
        wt.join(timeout=300)
        print("# read-heavy: idle phase…", file=sys.stderr)
        idle = phase("snapshot", secs)

        qps_ratio = (
            round(snapshot["read_qps"] / max(lane["read_qps"], 1e-9), 1)
            if lane["read_qps"]
            else None
        )
        p99_inflation = (
            round(snapshot["p99_ms"] / max(idle["p99_ms"], 1e-9), 2)
            if snapshot["p99_ms"] and idle["p99_ms"]
            else None
        )
        return {
            "scenario": label,
            "classes": classes,
            "readers": readers,
            "writer": {
                "deltas_acked": len(writes),
                "delta_p50_ms": round(
                    1e3 * _pct(sorted(writes), 0.50), 1
                )
                if writes
                else None,
                "last_version": latest_acked[0],
            },
            "lane_reads_under_write_load": lane,
            "snapshot_reads_under_write_load": snapshot,
            "snapshot_reads_idle": idle,
            "read_qps_vs_lane_x": qps_ratio,
            "p99_inflation_vs_idle_x": p99_inflation,
            "stale_reads_total": (
                lane["stale_reads"]
                + snapshot["stale_reads"]
                + idle["stale_reads"]
            ),
            "note": (
                "p99 inflation vs idle is a CPU-host artifact: this "
                "jax pin executes device programs INLINE at dispatch "
                "holding the GIL, so an in-flight delta stalls every "
                "reader thread for its duration regardless of the "
                "read path (the reads are lock-free; the interpreter "
                "is not).  On a TPU host dispatch is asynchronous and "
                "the read path never blocks on it.  The lane-vs-"
                "snapshot ratio is unaffected: both sides pay the "
                "same GIL stalls."
            ),
        }
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if app is not None:
            app.close(final_spill=False)


def run_spill_compression(*, classes: int) -> dict:
    """The cold-tier satellite record: spill a ≥4k-concept closure
    uncompressed vs compressed (``storage.compress.spills``), verify
    the checksum-gated restore answers identically, and demonstrate the
    checksum rejecting a corrupted spill."""
    import dataclasses
    import tempfile

    from distel_tpu.config import ClassifierConfig
    from distel_tpu.frontend.ontology_tools import snomed_shaped_ontology
    from distel_tpu.runtime.taxonomy import extract_taxonomy
    from distel_tpu.serve.registry import (
        ColdSpillCorrupted,
        OntologyRegistry,
    )

    tmp = tempfile.mkdtemp(prefix="distel-spill-bench-")
    out = {"scenario": "spill-compression", "classes": classes}
    cfg = ClassifierConfig(storage_compress_spills=False)
    reg = OntologyRegistry(
        cfg, spill_dir=tmp, fast_path_min_concepts=0
    )
    oid = reg.new_id()
    t0 = time.monotonic()
    reg.load(oid, snomed_shaped_ontology(n_classes=classes))
    out["classify_wall_s"] = round(time.monotonic() - t0, 2)
    entry = reg._entries[oid]
    tax_before = json.dumps(
        extract_taxonomy(reg.classifier(oid).last_result).parents,
        sort_keys=True,
    )
    out["concepts"] = reg.classifier(oid).last_result.idx.n_concepts

    def spill(compressed):
        reg.config = dataclasses.replace(
            reg.config, storage_compress_spills=compressed
        )
        with entry.lock:
            t0 = time.monotonic()
            path = reg._spill(entry)
            wall = time.monotonic() - t0
        return path, os.path.getsize(path), wall

    path_u, bytes_u, wall_u = spill(False)
    t0 = time.monotonic()
    reg.classifier(oid)  # checksum-verified restore (uncompressed)
    restore_u = time.monotonic() - t0
    path_c, bytes_c, wall_c = spill(True)
    t0 = time.monotonic()
    reg.classifier(oid)  # checksum-verified restore (compressed)
    restore_c = time.monotonic() - t0
    tax_after = json.dumps(
        extract_taxonomy(reg.classifier(oid).last_result).parents,
        sort_keys=True,
    )
    out.update(
        uncompressed={
            "bytes": bytes_u,
            "spill_wall_s": round(wall_u, 3),
            "restore_wall_s": round(restore_u, 3),
        },
        compressed={
            "bytes": bytes_c,
            "spill_wall_s": round(wall_c, 3),
            "restore_wall_s": round(restore_c, 3),
        },
        compression_ratio_x=round(bytes_u / max(bytes_c, 1), 1),
        taxonomy_identical=tax_before == tax_after,
    )
    # corrupted-spill rejection: flip one byte, watch the restore refuse
    spill(True)
    with open(entry.spill_path, "r+b") as f:
        f.seek(40)
        b = f.read(1)
        f.seek(40)
        f.write(bytes([b[0] ^ 0xFF]))
    try:
        reg.classifier(oid)
        out["checksum_rejects_corruption"] = False
    except ColdSpillCorrupted:
        out["checksum_rejects_corruption"] = True
    return out


def run_trace(path: str, *, pace: float = 0.0) -> dict:
    """Trace replay scenario (ISSUE 16): replay a recorded JSONL
    traffic trace (mixed load/add/retract/query/migrate ops — the
    first-class successor to the reference's
    ``traffic-data-load-classify.sh`` shell replay) against a single
    in-process ServeApp and report per-op ok/failed counts.

    Runs one replica: ``migrate`` ops have nowhere to go and are
    skipped-and-counted by the replayer, which the record carries so a
    trace with migrations never silently looks fully replayed."""
    from distel_tpu.serve.client import ServeClient
    from distel_tpu.serve.server import ServeApp, make_server
    from distel_tpu.serve.traces import load_trace, replay_trace

    events = load_trace(path)
    app = server = None
    try:
        app = ServeApp(workers=1, fast_path_min_concepts=0)
        server = make_server(app, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        client = ServeClient(url, timeout=600)
        rec = replay_trace(events, client, pace=pace)
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if app is not None:
            app.close()
    # server-assigned oids are fresh every run; the tracked record
    # keeps the count, not the churning ids
    rec["ontologies"] = len(rec.pop("ontologies", {}))
    return {"scenario": "trace-replay", "trace": path, "pace": pace, **rec}


def run_retract_repair(*, classes_list=(2000, 4000)) -> dict:
    """The r05 headline: retraction served as DRed delete-and-rederive
    (the ``POST .../retract`` path, wall includes overdelete + repair +
    snapshot publish, over HTTP) versus the only alternative the
    reference architecture offers — throwing the state away and
    re-classifying the survivor corpus from scratch.  Both legs end in
    a byte-identical taxonomy check against the from-scratch oracle."""
    from distel_tpu.core.incremental import IncrementalClassifier
    from distel_tpu.frontend.ontology_tools import snomed_shaped_ontology
    from distel_tpu.runtime.taxonomy import extract_taxonomy
    from distel_tpu.serve.client import ServeClient
    from distel_tpu.serve.server import ServeApp, make_server

    legs = []
    failures = 0
    for classes in classes_list:
        app = server = None
        try:
            app = ServeApp(workers=1, fast_path_min_concepts=0)
            server = make_server(app, port=0)
            threading.Thread(
                target=server.serve_forever, daemon=True
            ).start()
            url = f"http://127.0.0.1:{server.server_address[1]}"
            # a 4k-class classify runs ~20 min on a 1-core host (see
            # BENCH_SERVE_r03's spill leg): both the socket timeout and
            # the scheduler deadline must clear it
            client = ServeClient(url, timeout=3600)
            # range elimination re-emits rows for OLD axioms into later
            # batches, so the provenance gate refuses ALL retracts on a
            # range-bearing corpus (409) — the bench measures the repair
            # path, so it runs the same snomed shape minus its one
            # ObjectPropertyRange axiom
            base = "\n".join(
                line
                for line in snomed_shaped_ontology(
                    n_classes=classes
                ).splitlines()
                if not line.startswith("ObjectPropertyRange")
            )
            t0 = time.monotonic()
            oid = client.load(base, deadline_s=3600)["id"]
            load_wall = time.monotonic() - t0
            # the doomed delta: one plain subclass + one link-creating
            # axiom, the two delta shapes steady-state traffic mixes
            doomed = (
                "SubClassOf(RetractMe Find0)\n"
                "SubClassOf(RetractMe "
                "ObjectSomeValuesFrom(attr0 Find1))"
            )
            client.delta(oid, doomed, deadline_s=3600)
            t0 = time.monotonic()
            rec = client.retract(oid, doomed, deadline_s=3600)
            repair_wall = time.monotonic() - t0
            tax_served = client.taxonomy(oid, deadline_s=3600)["parents"]
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
            if app is not None:
                app.close()
        # the alternative: full from-scratch rebuild of the survivors
        t0 = time.monotonic()
        oracle = IncrementalClassifier()
        oracle.add_text(base)
        rebuild_wall = time.monotonic() - t0
        tax_oracle = extract_taxonomy(oracle.last_result).parents
        parity = json.dumps(tax_served, sort_keys=True) == json.dumps(
            tax_oracle, sort_keys=True
        )
        if not parity:
            failures += 1
        legs.append({
            "classes": classes,
            "load_wall_s": round(load_wall, 3),
            "repair_wall_s": round(repair_wall, 3),
            "repair_compile_s": round(rec.get("compile_s", 0.0), 4),
            "retracted_rows": rec.get("retracted_rows"),
            "affected_concepts": rec.get("affected_concepts"),
            "rebuild_wall_s": round(rebuild_wall, 3),
            "repair_speedup_x": round(
                rebuild_wall / max(repair_wall, 1e-9), 2
            ),
            "taxonomy_parity": parity,
        })
    return {
        "scenario": "retract-repair",
        "note": (
            "single-process CPU host, both legs run the same jax "
            "programs inline: the split isolates work volume — repair "
            "re-derives only from the cleared rows under cached "
            "bucketed programs (+ publish + HTTP), rebuild "
            "re-normalizes, re-indexes and re-saturates the whole "
            "survivor corpus"
        ),
        "legs": legs,
        "failed_requests": failures,
    }


_FARM_CONSUMER = r"""
import json, sys, time

from distel_tpu.core import artifacts
from distel_tpu.core.artifacts import ARTIFACT_EVENTS
from distel_tpu.core.incremental import IncrementalClassifier
from distel_tpu.runtime.taxonomy import extract_taxonomy

farm, base_path, delta_text = sys.argv[1], sys.argv[2], sys.argv[3]
install = None
if farm != "-":
    t0 = time.monotonic()
    install = artifacts.install(farm, require=True)
    install["install_s"] = round(time.monotonic() - t0, 3)
with open(base_path, encoding="utf-8") as f:
    base_text = f.read()
inc = IncrementalClassifier()
inc._FAST_PATH_MIN_CONCEPTS = 0
t0 = time.monotonic()
inc.add_text(base_text)
first_classify_s = time.monotonic() - t0
load = dict(inc.history[-1])
t0 = time.monotonic()
inc.add_text(delta_text)
first_delta_s = time.monotonic() - t0
delta = dict(inc.history[-1])
tax = extract_taxonomy(inc.last_result)
print("BENCH_RESULT " + json.dumps({
    "first_classify_s": round(first_classify_s, 3),
    "first_delta_s": round(first_delta_s, 3),
    "load_compile_s": load.get("compile_s", 0.0),
    "delta_compile_s": delta.get("compile_s", 0.0),
    "delta_path": delta.get("path"),
    "install": install,
    "artifact_events": ARTIFACT_EVENTS.snapshot(),
    "digest": json.dumps(
        {c: sorted(s) for c, s in tax.subsumers.items()}, sort_keys=True
    ),
}))
"""


def run_artifact_farm(*, classes: int) -> dict:
    """AOT artifact farm A/B (ISSUE 18): cold-PROCESS first-classify
    and first-delta walls before vs after a ``cli farm-build`` bake.
    Each leg is a genuinely fresh subprocess pointed at its own EMPTY
    persistent compile cache, so the before leg is a true cold start
    and the after leg's only warm source is the farm itself.  The bake
    runs through the real CLI (the operational path), per-tier
    attribution rides in-record from the manifest + the counted
    ``ARTIFACT_EVENTS``, and the closure byte-identity contract is
    asserted in-bench — a farm may only ever remove compile seconds."""
    import hashlib
    import subprocess

    from distel_tpu.frontend.ontology_tools import snomed_shaped_ontology

    work = tempfile.mkdtemp(prefix="distel-farm-bench-")
    base_path = os.path.join(work, "base.ofn")
    with open(base_path, "w", encoding="utf-8") as f:
        f.write(snomed_shaped_ontology(n_classes=classes))
    delta_text = (
        "SubClassOf(Steady0 Find0)\n"
        "SubClassOf(SteadyLink0 ObjectSomeValuesFrom(attr0 Find1))"
    )
    delta_path = os.path.join(work, "delta.ofn")
    with open(delta_path, "w", encoding="utf-8") as f:
        f.write(delta_text)
    farm = os.path.join(work, "farm")
    repo = os.path.dirname(os.path.abspath(__file__))

    def consumer(leg: str, farm_arg: str) -> dict:
        env = dict(os.environ)
        cache = os.path.join(work, f"jax-cache-{leg}")
        os.makedirs(cache, exist_ok=True)
        env["JAX_COMPILATION_CACHE_DIR"] = cache
        r = subprocess.run(
            [
                sys.executable, "-c", _FARM_CONSUMER,
                farm_arg, base_path, delta_text,
            ],
            capture_output=True, text=True, timeout=1800,
            env=env, cwd=repo,
        )
        if r.returncode != 0:
            raise SystemExit(
                f"artifact-farm {leg} leg failed:\n{r.stderr[-4000:]}"
            )
        line = [
            ln for ln in r.stdout.splitlines()
            if ln.startswith("BENCH_RESULT ")
        ][-1]
        return json.loads(line[len("BENCH_RESULT "):])

    before = consumer("before", "-")

    r = subprocess.run(
        [
            sys.executable, "-m", "distel_tpu.cli", "farm-build",
            base_path, "--out", farm, "--delta", delta_path,
        ],
        capture_output=True, text=True, timeout=1800,
        env=dict(os.environ), cwd=repo,
    )
    if r.returncode != 0:
        raise SystemExit(f"farm-build failed:\n{r.stderr[-4000:]}")
    bake = json.loads(r.stdout.splitlines()[-1])

    after = consumer("after", farm)

    # the acceptance contract, asserted where the record is minted
    assert after["digest"] == before["digest"], "farm changed the closure"
    assert after["load_compile_s"] == 0.0, after
    assert after["delta_compile_s"] == 0.0, after
    assert after["artifact_events"]["exe_hits"] > 0, after
    assert after["artifact_events"]["misses"] == 0, after
    assert after["artifact_events"]["rejected"] == 0, after

    with open(
        os.path.join(farm, "manifest.json"), encoding="utf-8"
    ) as f:
        manifest = json.load(f)
    tiers: dict = {}
    for a in manifest["artifacts"].values():
        tiers[a["tier"]] = tiers.get(a["tier"], 0) + 1

    digest = before["digest"]
    for leg in (before, after):
        leg.pop("digest", None)
    return {
        "scenario": "artifact-farm",
        "classes": classes,
        "bake": {
            k: bake.get(k)
            for k in (
                "wall_s", "written", "manifest_written", "artifacts",
                "exe", "hlo_cache_keys", "hlo_files_adopted", "bytes",
            )
        },
        "tiers_baked": tiers,
        "hlo_cache_entries": len(manifest.get("hlo_cache") or {}),
        "before": before,
        "after": after,
        "first_classify_speedup_x": round(
            before["first_classify_s"]
            / max(after["first_classify_s"], 1e-9),
            2,
        ),
        "first_delta_speedup_x": round(
            before["first_delta_s"] / max(after["first_delta_s"], 1e-9),
            2,
        ),
        "compile_s_removed": round(
            before["load_compile_s"] + before["delta_compile_s"], 2
        ),
        "closure_identical": True,
        "closure_digest_sha256": hashlib.sha256(
            digest.encode()
        ).hexdigest()[:16],
        "note": (
            "walls on this host are saturation-dominated (jax CPU "
            "programs execute inline): compile_s_removed is the "
            "honest farm win, the wall speedups understate what the "
            "same removal buys where the fixed point runs on an "
            "accelerator"
        ),
    }


def _parallel_capacity(burn_s: float = 1.5) -> float:
    """Measured parallel speedup of 2 busy processes over 1 — the real
    scaling ceiling of this host (container quotas, SMT siblings, and
    noisy neighbors all hide behind ``nproc``; a 2-core box that burns
    at 1.2x can never show 2x replica scaling, and the record should
    say so)."""
    import multiprocessing as mp

    def burn(q):
        t0 = time.monotonic()
        x = 0
        while time.monotonic() - t0 < burn_s:
            x += 1
        q.put(x)

    def run(n):
        q = mp.Queue()
        ps = [mp.Process(target=burn, args=(q,)) for _ in range(n)]
        for p in ps:
            p.start()
        total = sum(q.get() for _ in ps)
        for p in ps:
            p.join()
        return total

    solo = run(1)
    return round(run(2) / max(solo, 1), 2)


#: every scenario this bench can run — the exit-2 validator's "did you
#: mean" list (mirrors bench.py's --sections validation)
KNOWN_SCENARIOS = (
    "scale (--replicas N ...)",
    "migrate-under-load",
    "delta-steady-state",
    "cohort",
    "read-heavy",
    "spill-compression",
    "retract-repair",
    "artifact-farm",
    "trace (--trace FILE)",
)


def _check_args(ap, args) -> None:
    """Validate the scenario/flag combination BEFORE any fleet boots,
    mirroring bench.py's ``--sections`` fix: a typo'd invocation exits
    2 with the known-scenario list instead of silently running the
    default sweep (or silently skipping a scenario) and laundering the
    mistake into a published record."""

    def die(error: str, **extra) -> None:
        print(
            json.dumps(
                {
                    "error": error,
                    "known_scenarios": list(KNOWN_SCENARIOS),
                    **extra,
                }
            ),
            file=sys.stderr,
            flush=True,
        )
        raise SystemExit(2)

    # a tuning flag changed away from its default only makes sense
    # alongside the scenario that reads it — diagnose the likely
    # forgotten scenario flag before the generic no-scenario error
    owners = {
        "delta_count": "delta_steady_state",
        "delta_classes": "delta_steady_state",
        "cohort_sizes": "cohort",
        "cohort_deltas": "cohort",
        "cohort_wait_ms": "cohort",
        "readers": "read_heavy",
        "read_classes": "read_heavy",
        "spill_classes": "spill_compression",
        "retract_classes": "retract_repair",
        "farm_classes": "artifact_farm",
    }
    for flag, owner in owners.items():
        if getattr(args, flag) != ap.get_default(flag) and not getattr(
            args, owner
        ):
            die(
                f"--{flag.replace('_', '-')} requires "
                f"--{owner.replace('_', '-')}"
            )
    scenario_flags = (
        "delta_steady_state",
        "cohort",
        "read_heavy",
        "spill_compression",
        "retract_repair",
        "artifact_farm",
    )
    if not (
        args.replicas
        or args.trace is not None
        or args.migrate_under_load
        or any(getattr(args, f) for f in scenario_flags)
    ):
        die(
            "no scenario selected: pass --replicas N ... or at least "
            "one scenario flag"
        )
    if args.migrate_under_load and not args.replicas:
        # previously this combination silently skipped the migration
        # scenario — the exact failure-laundering this check exists for
        die("--migrate-under-load needs a fleet: pass --replicas >= 2")
    if args.trace is None:
        if args.trace_pace != ap.get_default("trace_pace"):
            die("--trace-pace requires --trace")
    else:
        if args.trace_pace < 0:
            die("--trace-pace must be >= 0")
        from distel_tpu.serve.traces import TraceError, load_trace

        # validate the whole trace up front: a bad line must fail the
        # invocation, not surface mid-replay as "failed requests"
        try:
            load_trace(args.trace)
        except (OSError, TraceError) as e:
            die(f"bad --trace file: {e}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=int, nargs="*", default=[1, 2, 4],
                    help="replica counts to sweep (one fleet per count; "
                         "pass none to skip the fleet sweep)")
    ap.add_argument("--clients", type=int, default=6,
                    help="concurrent simulated tenants (one ontology "
                         "each; lanes spread across replicas)")
    ap.add_argument("--duration-s", type=float, default=20.0,
                    help="measured window per scenario")
    ap.add_argument("--migrate-under-load", action="store_true",
                    help="live-migrate one ontology mid-run (replicas "
                         ">= 2) and assert zero failures + "
                         "byte-identical taxonomy")
    ap.add_argument("--delta-steady-state", action="store_true",
                    help="steady-state increment scenario: a long "
                         "stream of small deltas against one warm "
                         "base, run twice (exact-shape then bucketed "
                         "delta programs) — per-delta compile/execute "
                         "split + program-cache hit rate")
    ap.add_argument("--delta-count", type=int, default=40,
                    help="deltas per delta-steady-state leg")
    ap.add_argument("--delta-classes", type=int, default=600,
                    help="base ontology size for delta-steady-state")
    ap.add_argument("--cohort", action="store_true",
                    help="cohort A/B (ISSUE 12): N same-bucket tenants "
                         "firing concurrent deltas, inline vs cohort "
                         "execution at sizes 1/2/4/8 — per-tenant "
                         "p50/p99, aggregate delta throughput, and the "
                         "measured device-dispatch collapse")
    ap.add_argument("--cohort-sizes", type=int, nargs="*",
                    default=[1, 2, 4, 8],
                    help="tenant counts for the cohort A/B")
    ap.add_argument("--cohort-deltas", type=int, default=10,
                    help="steady deltas per tenant per cohort A/B leg")
    ap.add_argument("--cohort-wait-ms", type=float, default=100.0,
                    help="cohort formation wait (cohort.max_wait_ms) "
                         "for the cohort legs")
    ap.add_argument("--read-heavy", action="store_true",
                    help="read-plane A/B: N readers on one ontology "
                         "concurrent with steady delta traffic — "
                         "scheduler-lane reads vs lock-free /query "
                         "snapshot reads vs idle baseline (QPS, "
                         "p50/p99, stale reads, version lag)")
    ap.add_argument("--readers", type=int, default=4,
                    help="concurrent read workers for --read-heavy")
    ap.add_argument("--read-classes", type=int, default=600,
                    help="base ontology size for --read-heavy")
    ap.add_argument("--spill-compression", action="store_true",
                    help="cold-tier record: spill a large closure "
                         "uncompressed vs compressed, checksum-"
                         "verified restores, corruption rejection")
    ap.add_argument("--spill-classes", type=int, default=4000,
                    help="base ontology size for --spill-compression")
    ap.add_argument("--spill-dir", default=None,
                    help="fleet spill root (default: a temp dir)")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="replay a recorded JSONL traffic trace "
                         "(mixed load/add/retract/query/migrate ops; "
                         "see distel_tpu/serve/traces.py for the "
                         "format, traces/ for tracked examples)")
    ap.add_argument("--trace-pace", type=float, default=0.0,
                    help="multiplier on the trace's recorded inter-op "
                         "gaps (0 = replay as fast as possible, 1 = "
                         "recorded cadence)")
    ap.add_argument("--retract-repair", action="store_true",
                    help="retraction record (ISSUE 16): DRed "
                         "delete-and-rederive repair wall vs a full "
                         "from-scratch rebuild of the survivors, with "
                         "byte-identical taxonomy checks")
    ap.add_argument("--retract-classes", type=int, nargs="*",
                    default=[2000, 4000],
                    help="base ontology sizes for --retract-repair")
    ap.add_argument("--artifact-farm", action="store_true",
                    help="AOT artifact farm A/B (ISSUE 18): "
                         "cold-process first-classify + first-delta "
                         "walls before vs after a cli farm-build bake, "
                         "per-tier attribution, byte-identical closure "
                         "asserted")
    ap.add_argument("--farm-classes", type=int, default=600,
                    help="base ontology size for --artifact-farm")
    ap.add_argument("--out", default=None,
                    help="write the JSON record here as well as stdout")
    args = ap.parse_args(argv)
    _check_args(ap, args)

    spill_root = args.spill_dir or tempfile.mkdtemp(prefix="distel-bench-")
    scenarios = []
    for n in args.replicas:
        # the scaling sweep runs clean: the migration freeze/spill
        # would otherwise depress whichever scenario hosts it
        rec = run_scenario(
            n,
            clients=args.clients,
            duration_s=args.duration_s,
            spill_root=spill_root,
            migrate_under_load=False,
        )
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)
    if args.delta_steady_state:
        # BEFORE leg first (exact-shape delta programs — the hatch), so
        # the bucketed leg cannot inherit its jit caches by accident
        # (exact programs never enter the shared registry anyway)
        for exact in (True, False):
            rec = run_delta_steady_state(
                deltas=args.delta_count,
                classes=args.delta_classes,
                exact=exact,
                label="delta-steady-"
                + ("exact" if exact else "bucketed"),
            )
            print(json.dumps(rec), flush=True)
            scenarios.append(rec)
    if args.cohort:
        rec = run_cohort_ab(
            sizes=tuple(args.cohort_sizes),
            deltas=args.cohort_deltas,
            wait_ms=args.cohort_wait_ms,
        )
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)
    if args.read_heavy:
        rec = run_read_heavy(
            readers=args.readers,
            duration_s=args.duration_s,
            classes=args.read_classes,
        )
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)
    if args.spill_compression:
        rec = run_spill_compression(classes=args.spill_classes)
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)
    if args.retract_repair:
        rec = run_retract_repair(
            classes_list=tuple(args.retract_classes)
        )
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)
    if args.artifact_farm:
        rec = run_artifact_farm(classes=args.farm_classes)
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)
    if args.trace:
        rec = run_trace(args.trace, pace=args.trace_pace)
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)
    if args.migrate_under_load and args.replicas:
        n = max(max(args.replicas), 2)
        rec = run_scenario(
            n,
            clients=args.clients,
            duration_s=args.duration_s,
            spill_root=spill_root,
            migrate_under_load=True,
            label=f"migrate-under-load-x{n}",
        )
        print(json.dumps(rec), flush=True)
        scenarios.append(rec)

    by_n = {
        s["replicas"]: s
        for s in scenarios
        if s["scenario"].startswith("scale-")
    }
    scaling = {}
    if 1 in by_n:
        base = by_n[1]["classify_throughput_ops_s"] or 1e-9
        for n, s in sorted(by_n.items()):
            if n != 1:
                scaling[f"x{n}_vs_x1"] = round(
                    s["classify_throughput_ops_s"] / base, 2
                )
    by_delta = {
        s.get("delta_programs"): s
        for s in scenarios
        if s["scenario"].startswith("delta-steady-")
    }
    delta_summary = None
    if {"exact", "bucketed"} <= set(by_delta):
        e, b = by_delta["exact"]["steady"], by_delta["bucketed"]["steady"]
        delta_summary = {
            "steady_p50_speedup_x": round(
                e["wall_p50_ms"] / max(b["wall_p50_ms"], 1e-9), 2
            ),
            "steady_throughput_speedup_x": round(
                b["throughput_deltas_s"]
                / max(e["throughput_deltas_s"], 1e-9),
                2,
            ),
            "compile_ms_per_delta_exact": e["compile_mean_ms"],
            "compile_ms_per_delta_bucketed": b["compile_mean_ms"],
            "steady_hit_rate_bucketed": b["program_cache_hit_rate"],
        }
    cohort_summary = None
    for s in scenarios:
        if s.get("scenario") == "cohort-ab":
            cohort_summary = {
                size: {
                    "dispatches_per_delta_inline": rec[
                        "dispatches_per_delta_inline"
                    ],
                    "dispatches_per_delta_cohort": rec[
                        "dispatches_per_delta_cohort"
                    ],
                    "throughput_speedup_x": rec["throughput_speedup_x"],
                }
                for size, rec in s["sizes"].items()
            }
    doc = {
        "bench": "bench_serve",
        "metric": "aggregate_classify_throughput_ops_s",
        "host": {
            "cores": len(os.sched_getaffinity(0)),
            "parallel_capacity_2proc_x": _parallel_capacity(),
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "note": (
            "each replica is one Python process running jax CPU "
            "programs inline (one GIL per process): throughput scaling "
            "is ceilinged by host.parallel_capacity_2proc_x, the "
            "MEASURED parallel speedup of 2 busy processes on this "
            "host (nproc alone overstates shared/SMT hosts)"
        ),
        "scenarios": scenarios,
        "scaling": scaling,
        **(
            {"delta_steady_state": delta_summary}
            if delta_summary is not None
            else {}
        ),
        **(
            {"cohort_ab": cohort_summary}
            if cohort_summary is not None
            else {}
        ),
        "zero_failed_requests": all(
            s.get("failed_requests", 0) == 0 for s in scenarios
        ),
    }
    out = json.dumps(doc, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
