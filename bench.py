#!/usr/bin/env python
"""Benchmark driver: EL+ saturation throughput on the attached accelerator.

Prints ONE JSON line:
  {"metric": "axiom_derivations_per_sec", "value": N, "unit": "derivations/s",
   "vs_baseline": R, ...}

``vs_baseline`` is the speedup over the single-threaded CPU reference
saturation (``distel_tpu/core/oracle.py``) on the *same* corpus — the
stand-in for the reference system's throughput, since the reference
repository publishes no benchmark numbers (BASELINE.md: "published: {}").

Corpus: deterministic GALEN-shaped synthetic EL+ ontology exercising all
of CR1-CR6 (hierarchy, n-ary conjunctions, existentials, role hierarchy,
transitive partonomy, right-identity chain, domain/range).
"""

import json
import sys
import time

sys.path.insert(0, ".")

from distel_tpu.owl import parser  # noqa: E402
from distel_tpu.frontend.normalizer import normalize  # noqa: E402
from distel_tpu.frontend.ontology_tools import (  # noqa: E402
    snomed_shaped_ontology,
    synthetic_ontology,
)
from distel_tpu.core.indexing import index_ontology  # noqa: E402
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine  # noqa: E402
from distel_tpu.core import oracle as cpu_oracle  # noqa: E402


def _timed(f) -> float:
    t0 = time.time()
    f()
    return time.time() - t0


def main() -> None:
    import jax

    from distel_tpu.config import enable_compile_cache

    enable_compile_cache()
    # 16k is the measured throughput sweet spot on one v5e core: small
    # enough that the CPU-baseline run stays in budget, large enough that
    # compute dominates the ~117 ms tunnel round-trip of a warm call
    n_classes = int(sys.argv[1]) if len(sys.argv) > 1 else 16000
    text = synthetic_ontology(
        n_classes=n_classes,
        n_anatomy=max(200, n_classes // 10),
        n_locations=max(150, n_classes // 12),
        n_definitions=max(100, n_classes // 20),
    )
    norm = normalize(parser.parse(text))
    idx = index_ontology(norm)

    engine = RowPackedSaturationEngine(idx)
    # cold run = compile + execute; warm = best of 3 steady-state runs
    # (each warm call pays one host->device round trip, which is noisy
    # over the remote tunnel)
    t0 = time.time()
    result = engine.saturate()
    cold_s = time.time() - t0
    warm_s = min(
        _timed(engine.saturate) for _ in range(3)
    )
    engine_dps = result.derivations / warm_s

    # measured tunnel round-trip (a trivial device call), so readers can
    # tell when a warm number is latency- rather than compute-dominated
    import jax.numpy as jnp

    tiny = jax.jit(lambda x: x + 1)
    tiny(jnp.zeros(8)).block_until_ready()
    rtt_s = min(
        _timed(lambda: tiny(jnp.zeros(8)).block_until_ready())
        for _ in range(5)
    )

    # CPU reference baseline on the same corpus — time-bounded: the
    # sequential oracle takes minutes at this size, and its throughput
    # only FALLS as saturation proceeds (early iterations derive the
    # cheap bulk), so a budget-capped derivations/s reads in the
    # baseline's favor while keeping the bench bounded
    t0 = time.time()
    oracle_result = cpu_oracle.saturate(norm, time_budget_s=90.0)
    oracle_s = time.time() - t0
    oracle_dps = oracle_result.derivation_count() / oracle_s

    # secondary figure (default invocations only — a custom size means a
    # quick targeted run): the SNOMED-structured corpus, the many-role
    # regime of the reference's own evaluation ontology; exercises the
    # role-clustered tile-sparse matmul path
    snomed_fields = {}
    if len(sys.argv) <= 1:
        stext = snomed_shaped_ontology(n_classes=24000)
        sidx = index_ontology(normalize(parser.parse(stext)))
        sengine = RowPackedSaturationEngine(sidx)
        sres = sengine.saturate()
        s_warm = min(_timed(sengine.saturate) for _ in range(3))
        snomed_fields = {
            "snomed_shaped_24k_concepts": sidx.n_concepts,
            "snomed_shaped_24k_wall_s_warm": round(s_warm, 3),
            "snomed_shaped_24k_dps": round(sres.derivations / s_warm, 1),
        }

    print(
        json.dumps(
            {
                "metric": "axiom_derivations_per_sec",
                "value": round(engine_dps, 1),
                "unit": "derivations/s",
                "vs_baseline": round(engine_dps / oracle_dps, 2),
                "platform": jax.devices()[0].platform,
                "n_concepts": idx.n_concepts,
                "n_links": idx.n_links,
                "derivations": result.derivations,
                "iterations": result.iterations,
                "wall_s_warm": round(warm_s, 3),
                "wall_s_cold": round(cold_s, 3),
                "rtt_s": round(rtt_s, 3),
                "baseline_cpu_dps": round(oracle_dps, 1),
                "baseline_budget_s": 90.0,
                "baseline_converged": oracle_result.converged,
                **snomed_fields,
            }
        )
    )


if __name__ == "__main__":
    main()
