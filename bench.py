#!/usr/bin/env python
"""Benchmark driver: EL+ saturation throughput on the attached accelerator.

Prints ONE JSON line:
  {"metric": "axiom_derivations_per_sec", "value": N, "unit": "derivations/s",
   "vs_baseline": R, ...}

Headline corpus (r2, per the r1 verdict): the **SNOMED-structured
many-role corpus at 64k classes** (~88.5k concepts) — the largest corpus
that runs comfortably on one chip with frontier gating, in the regime the
reference's own evaluation ontology (SNOMED CT) lives in.  The warm wall
is ~100x the measured tunnel round trip, so the number is compute-, not
latency-dominated.

``vs_baseline`` (r3, per the r2 verdict: lead with a CONVERGED
denominator) is the speedup against the single-threaded CPU oracle
(``distel_tpu/core/oracle.py`` — the stand-in for the reference's
throughput, since the reference publishes no numbers; BASELINE.md
"published: {}") **at the largest size where the oracle actually
finishes** within its 600 s budget.  The headline-size comparison, whose
oracle run is necessarily time-budgeted (the sequential baseline needs
hours at 88k concepts), is disclosed separately as
``vs_baseline_budgeted`` with its convergence flag.

Other sections:

* ``step_profile`` — per-phase device-time split of one superstep at the
  headline size, from a ``jax.profiler`` capture aggregated by the
  engine's ``named_scope`` phases (``runtime/profiling.py``); parts sum
  to ``device_total_s``, and ``host_gap_s`` is the per-run host/tunnel
  remainder (wall − device).  The reference's per-phase nanoTime stamps
  (``base/Type1_1AxiomProcessorBase.java:183-214``), but measured inside
  the fused XLA program.
* roofline fields from the engine's static plan shapes: per-step HBM
  traffic and utilization, and the CR4/CR6 dense-equivalent matmul
  throughput vs the MXU's dense int8 peak (above 1.0 means the
  tile-skipping kernel beats running the contraction dense).
* incremental section (the reference's traffic-data streaming scenario,
  ``scripts/traffic-data-load-classify.sh``): a 100-axiom delta over a
  48k-class base — ABOVE the delta fast path's 32k-concept eligibility
  floor, so ``incremental_delta_fast_s`` measures the flagship path
  (base program reuse + cross-term join) and
  ``incremental_delta_rebuild_s`` measures the same delta forced down
  the full-rebuild path for comparison.
* the GALEN-shaped 16k corpus — the latency-sensitivity probe.
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

from distel_tpu.owl import parser  # noqa: E402
from distel_tpu.frontend.normalizer import normalize  # noqa: E402
from distel_tpu.frontend.ontology_tools import (  # noqa: E402
    snomed_shaped_ontology,
    synthetic_ontology,
)
from distel_tpu.core.indexing import index_ontology  # noqa: E402
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine  # noqa: E402
from distel_tpu.core import oracle as cpu_oracle  # noqa: E402

#: v5e per-chip peaks (public spec): 394 TOPS int8, 819 GB/s HBM BW
_V5E_INT8_OPS = 394e12
_V5E_HBM_BPS = 819e9

#: largest SNOMED-shaped sizes whose oracle saturation converges inside
#: the 600 s budget on this host class, largest first (r4 measured,
#: CONTENDED upper bounds: 32k classes converge in 379 s, 24k in 194 s;
#: 48k does NOT inside 653 s — so the chain starts at 32k, a slower
#: host falls through one tier at a time, and the 3000-class
#: last-resort tier guarantees SOME baseline ratio on any host)
_CONVERGED_CHAIN = (32000, 24000, 8000, 3000)

#: incremental base: comfortably above the delta fast path's
#: eligibility floor (``fast.path.min.concepts``, default 2048 since
#: the bucketed delta programs re-measure; 48k classes ≈ 66k
#: concepts), so the bench times the path PARITY.md advertises at
#: serving scale (r2 verdict item 6 / advice item 3)
_INC_BASE_CLASSES = 48000


#: transient-shaped backend failures worth retrying (the r4 capture died
#: on a single ``UNAVAILABLE`` from the axon tunnel at engine
#: construction — BENCH_r04.json is a traceback because nothing caught
#: it; the reference's ``run-all.sh`` always writes its summary.txt)
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "connection",
    "Connection",
    "socket",
    "tunnel",
    "failed to initialize",
    "Unable to initialize backend",
    "hung past",  # the subprocess-timeout hang classification
)

#: pointer emitted with a failure record so a voided round still tells
#: the reader where the last full measurement lives
_LAST_KNOWN_GOOD = (
    "BENCH_r03.json (last parsed official record); "
    "bench_r4_check.log (full r4 bench line, contention-biased)"
)


def _load1() -> float:
    try:
        with open("/proc/loadavg") as f:
            return float(f.read().split()[0])
    except Exception:
        return -1.0


def _is_transient(err: BaseException) -> bool:
    s = f"{type(err).__name__}: {err}"
    return any(m in s for m in _TRANSIENT_MARKERS)


#: subprocess walls: the axon tunnel's observed failure mode is a HANG
#: inside backend init (25+ min blocked in C++ network code, immune to
#: in-process timeouts/signals), not a fast error — so both the backend
#: probe and the bench body run as CHILD processes the parent can kill
_PROBE_TIMEOUT_S = 180.0
_BODY_TIMEOUT_S = float(os.environ.get("BENCH_BODY_TIMEOUT_S", 5400))


def _probe_backend_once() -> None:
    """Child-process body (--probe): touch the accelerator.  A trivial
    jitted op round-trips the tunnel so a half-up backend fails (or
    hangs, killably) HERE, cheaply, instead of mid-bench."""
    import jax
    import jax.numpy as jnp

    jax.devices()
    int(jax.jit(lambda x: x + 1)(jnp.zeros(4))[0])


#: env-configurable backend acquisition policy (r5: the official bench
#: burned 5×60s SERIAL retries on a black-holed tunnel — BENCH_r05.json
#: recorded 5 attempts with no per-attempt timing and no way to tune
#: the policy without editing the script)
def _backend_attempts() -> int:
    return max(int(os.environ.get("DISTEL_BENCH_BACKEND_ATTEMPTS", "5")), 1)


def _backend_backoff_s() -> float:
    return float(os.environ.get("DISTEL_BENCH_BACKEND_BACKOFF_S", "60"))


#: per-attempt records of the LAST _acquire_backend call — emitted in
#: the failure record so a voided round shows where the wall time went
_ATTEMPT_LOG: list = []


def _acquire_backend(attempts=None, backoff_s=None):
    """Probe the accelerator in a killable subprocess with bounded
    retry before any real work.  Raises the last error (a hang
    surfaces as TimeoutError — transient-shaped) after ``attempts``.
    Fails FAST on the second identical consecutive timeout: a tunnel
    black-hole never heals within one bench's backoff budget, so the
    remaining retries would only burn wall time (BENCH_r05: 5×60s)."""
    import subprocess

    if attempts is None:
        attempts = _backend_attempts()
    if backoff_s is None:
        backoff_s = _backend_backoff_s()
    _ATTEMPT_LOG.clear()
    last = None
    last_sig = None
    for i in range(attempts):
        t0 = time.time()
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--probe"],
                timeout=_PROBE_TIMEOUT_S,
                capture_output=True,
                text=True,
            )
            if p.returncode == 0:
                _ATTEMPT_LOG.append(
                    {"attempt": i + 1, "ok": True,
                     "elapsed_s": round(time.time() - t0, 1)}
                )
                return
            raise RuntimeError(
                f"backend probe rc={p.returncode}: "
                + (p.stderr or "")[-300:]
            )
        except subprocess.TimeoutExpired:
            last = TimeoutError(
                f"backend probe hung past {_PROBE_TIMEOUT_S:.0f}s "
                "(tunnel black-hole failure mode)"
            )
        except Exception as e:  # noqa: BLE001 — classified below
            last = e
            if not _is_transient(e):
                _ATTEMPT_LOG.append(
                    {"attempt": i + 1,
                     "error": f"{type(e).__name__}: {e}"[:200],
                     "elapsed_s": round(time.time() - t0, 1)}
                )
                raise
        sig = f"{type(last).__name__}: {last}"[:200]
        _ATTEMPT_LOG.append(
            {"attempt": i + 1, "error": sig,
             "elapsed_s": round(time.time() - t0, 1)}
        )
        if isinstance(last, TimeoutError) and sig == last_sig:
            print(
                "# backend hung identically twice; failing fast "
                f"after attempt {i + 1}/{attempts}",
                file=sys.stderr,
            )
            break
        last_sig = sig
        if i < attempts - 1:
            print(
                f"# backend attempt {i + 1}/{attempts} failed "
                f"({type(last).__name__}); retrying in {backoff_s:.0f}s",
                file=sys.stderr,
            )
            time.sleep(backoff_s)
    raise last


#: stdout marker the bench child prints after each completed section —
#: the parent harvests these on failure so an r05-style backend
#: black-hole mid-run no longer discards everything already measured
_PARTIAL_PREFIX = "#partial "


def _partial(**fields) -> None:
    """Checkpoint already-measured results from the bench child: one
    ``#partial {json}`` stdout line per completed section.  ``#``-lines
    are invisible to the parent's result scan (it only accepts lines
    starting with ``{``), but on a failure the parent folds every
    partial seen into the failure record's ``partial_results``."""
    print(_PARTIAL_PREFIX + json.dumps(fields), flush=True)


def _collect_partials(stdout) -> dict:
    """Merge the ``#partial`` checkpoints out of a dead child's
    captured stdout (later sections win on key collisions)."""
    if isinstance(stdout, bytes):
        stdout = stdout.decode(errors="replace")
    merged: dict = {}
    for ln in (stdout or "").splitlines():
        if ln.startswith(_PARTIAL_PREFIX):
            try:
                merged.update(json.loads(ln[len(_PARTIAL_PREFIX):]))
            except ValueError:
                pass  # a truncated partial line must not mask the error
    return merged


def _emit_failure(
    stage: str, err: BaseException, attempts: int, partial=None
) -> None:
    """One parseable JSON line instead of a traceback (r4 weak #1).
    ``partial``: sections the bench body completed before dying (the
    ``#partial`` checkpoints harvested from the child's stdout) — a
    failure after saturation keeps its measured numbers."""
    print(
        json.dumps(
            {
                "metric": "axiom_derivations_per_sec",
                "value": 0.0,
                "unit": "derivations/s",
                "vs_baseline": 0.0,
                "platform": "tpu_unavailable",
                "failed_stage": stage,
                "error": f"{type(err).__name__}: {err}"[:400],
                "attempts": attempts,
                "attempt_log": list(_ATTEMPT_LOG),
                "load1": _load1(),
                "last_known_good": _LAST_KNOWN_GOOD,
                **({"partial_results": partial} if partial else {}),
            }
        )
    )


def _timed(f) -> float:
    t0 = time.time()
    f()
    return time.time() - t0


def _saturate_timed(engine):
    """(result, cold_s, warm_s): cold = compile + run, warm = best of 3
    steady-state fixed points (never a repeat-call cache artifact: each
    saturate() rebuilds fresh initial state and runs the full loop)."""
    t0 = time.time()
    result = engine.saturate()
    cold_s = time.time() - t0
    warm_s = min(_timed(engine.saturate) for _ in range(3))
    return result, cold_s, warm_s


def main() -> None:
    """Capture-proof wrapper: whatever the backend weather — fast
    errors OR the tunnel's silent-hang mode — exactly one JSON line
    reaches stdout (r4 verdict task 2).  The probe and the bench body
    both run as killable child processes."""
    import subprocess

    # a typo'd --sections must refuse HERE, with exit code 2, before
    # the backend probe pays up to its full retry budget — the child's
    # rc=2 would otherwise be laundered into an exit-0 failure record
    names = _parse_sections_argv(sys.argv[1:])
    if names is not None:
        _check_sections(names)
    try:
        _acquire_backend()
    except Exception as e:  # noqa: BLE001
        # non-transient errors raise on the first probe, before any retry
        _emit_failure("backend_init", e, max(len(_ATTEMPT_LOG), 1))
        return
    argv = list(sys.argv[1:])
    last: BaseException = RuntimeError("unreachable")
    for attempt in range(2):
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child",
                 *argv],
                timeout=_BODY_TIMEOUT_S,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired as e:
            # a hang already consumed the wall budget: record, don't retry
            partial = (e.stdout or b"")
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            _emit_failure(
                "bench_body",
                TimeoutError(
                    f"bench body hung past {_BODY_TIMEOUT_S:.0f}s; "
                    f"partial stdout: {partial[-200:]!r}"
                ),
                attempt + 1,
                partial=_collect_partials(partial),
            )
            return
        sys.stderr.write(p.stderr or "")
        line = next(
            (
                ln
                for ln in reversed((p.stdout or "").splitlines())
                if ln.startswith("{")
            ),
            None,
        )
        if p.returncode == 0 and line:
            print(line)
            return
        last = RuntimeError(
            f"bench child rc={p.returncode}: {(p.stderr or '')[-400:]}"
        )
        if not _is_transient(last):
            _emit_failure(
                "bench_body", last, attempt + 1,
                partial=_collect_partials(p.stdout),
            )
            return
        if attempt == 0:  # no backoff after the final attempt
            print(
                "# transient bench failure; re-probing backend and "
                "retrying once",
                file=sys.stderr,
            )
            time.sleep(60.0)
            try:
                _acquire_backend(attempts=3)
            except Exception:  # noqa: BLE001 — recorded by final emit
                pass
    _emit_failure(
        "bench_body", last, 2, partial=_collect_partials(p.stdout)
    )


def _sparse_tail_probe(n_classes: int = 4000, chain_depth: int = 28) -> dict:
    """Dense-only vs adaptive observed saturation on a chain-tailed
    GALEN-shape corpus.  Returns per-round (iteration, tier, density,
    rows, wall) plus ``low_density_speedup`` — the median dense/sparse
    wall ratio over below-threshold sparse rounds at MATCHED iteration
    indices — and a byte-identity verdict on the final closures."""
    import numpy as np

    from distel_tpu.frontend.ontology_tools import chain_tailed_ontology

    idx = index_ontology(
        normalize(parser.parse(chain_tailed_ontology(n_classes, chain_depth)))
    )

    def observed(engine, sparse):
        walls, last = [], [time.time()]

        def obs(it, d, ch):
            now = time.time()
            walls.append((it, now - last[0]))
            last[0] = now

        res = engine.saturate_observed(observer=obs, sparse_tail=sparse)
        return dict(walls), res

    # pipeline off: this probe times rounds via observer inter-arrival,
    # which only equals per-round wall when observers fire at the
    # synchronous decision point (pipelined runs fire them at deferred
    # retire time — the dense/sparse wall ratio would be skewed by
    # whatever host work the overlap hid)
    e_dense = RowPackedSaturationEngine(idx, bucket=True, unroll=1,
                                        pipeline={"enable": False})
    observed(e_dense, {"enable": False})  # warm programs
    dense_walls, res_dense = observed(e_dense, {"enable": False})
    e_ad = RowPackedSaturationEngine(idx, bucket=True, unroll=1,
                                     sparse_tail=True,
                                     pipeline={"enable": False})
    observed(e_ad, None)  # warm (incl. the sparse rung programs)
    ad_walls, res_ad = observed(e_ad, None)

    identical = bool(
        np.array_equal(
            np.asarray(res_dense.packed_s), np.asarray(res_ad.packed_s)
        )
        and np.array_equal(
            np.asarray(res_dense.packed_r), np.asarray(res_ad.packed_r)
        )
    )
    thr = e_ad._sparse_cfg["density_threshold"]
    rounds = []
    speedups = []
    for st in e_ad.frontier_rounds:
        w = ad_walls.get(st.iteration)
        base = dense_walls.get(st.iteration)
        rec = {
            "iteration": st.iteration,
            "tier": st.tier,
            "density": round(st.density, 5),
            "rows_touched": st.rows_touched,
            "wall_s": round(w, 4) if w is not None else None,
        }
        if (
            st.tier == "sparse" and st.density < thr
            and st.rows_touched
            and w is not None and base is not None and w > 0
        ):
            rec["dense_wall_s"] = round(base, 4)
            speedups.append(base / w)
        rounds.append(rec)
    speedups.sort()
    return {
        "corpus": f"galen_shaped_{n_classes // 1000}k_chain{chain_depth}",
        "n_concepts": idx.n_concepts,
        "density_threshold": thr,
        "closure_identical": identical,
        "sparse_rounds": sum(
            1 for s in e_ad.frontier_rounds if s.tier == "sparse"
        ),
        "dense_rounds": sum(
            1 for s in e_ad.frontier_rounds if s.tier == "dense"
        ),
        "overflow_rounds": sum(
            1 for s in e_ad.frontier_rounds if s.overflow
        ),
        "low_density_speedup": (
            round(speedups[len(speedups) // 2], 2) if speedups else None
        ),
        "low_density_speedup_max": (
            round(speedups[-1], 2) if speedups else None
        ),
        "rounds": rounds,
    }


def _pipeline_probe(n_classes: int = 2000, chain_depth: int = 24) -> dict:
    """Pipelined vs synchronous observed saturation (ISSUE 5) on a
    chain-tailed GALEN-shape corpus, sparse tail off so every round is
    dense and the observed walls ARE the dense phase.  Three
    measurements, all PAIRED (sync and pipelined runs interleaved and
    compared per pair — this box's wall clock drifts ~2x with outside
    load, so unpaired medians are noise):

    * raw walls (``saturate()`` vs observed at depths 1/2/4): the
      pipelined loop must cost ~nothing when there is nothing to hide;
    * an I/O-observer A/B — per-round observer wait calibrated to
      ~0.7x a round's execution, modelling the serving plane's
      progress writes / metrics push / pub-sub gossip (the reference's
      per-iteration Redis barrier, and exactly what ``scale_probe
      --progress`` pays per round).  I/O waits overlap cleanly, so
      this shows the pipeline's full win: sync pays the wait serially
      between rounds, pipelined retires it while the executor runs the
      next round;
    * a CPU-observer A/B (numpy fold of the same calibrated size):
      on this 2-core rig a compute-bound observer contends with XLA
      execution for cores and memory bandwidth, so the speedup is
      bounded well below the I/O case — recorded as the honest floor
      (a real accelerator executes off-host and has no such cap).

    Plus the per-round host-time split and an inline-dispatch
    microprobe showing why dispatch goes through the executor on this
    backend."""
    import statistics

    import jax
    import numpy as np

    from distel_tpu.frontend.ontology_tools import chain_tailed_ontology

    idx = index_ontology(
        normalize(parser.parse(chain_tailed_ontology(n_classes, chain_depth)))
    )
    engine = RowPackedSaturationEngine(idx, bucket=True, unroll=1)
    engine.saturate()  # warm the fused program

    def observed(depth, observer=None):
        t0 = time.time()
        res = engine.saturate_observed(
            observer=observer,
            sparse_tail={"enable": False},
            pipeline={"enable": True, "depth": depth},
        )
        return time.time() - t0, res

    observed(1)
    observed(2)  # warm both loop paths
    walls = {1: [], 2: [], 4: []}
    sat_walls = []
    for _ in range(5):  # interleaved so outside load drifts cancel
        sat_walls.append(_timed(engine.saturate))
        for depth in (1, 2, 4):
            walls[depth].append(observed(depth)[0])
    sat_s = statistics.median(sat_walls)
    walls = {d: round(statistics.median(w), 3) for d, w in walls.items()}
    _, res = observed(2)
    frs = engine.frontier_rounds
    host_split = {
        "dispatch_s": round(sum(s.dispatch_s for s in frs), 3),
        "retire_s": round(sum(s.retire_s for s in frs), 3),
        "speculative_rounds": sum(1 for s in frs if s.inflight > 0),
    }
    rounds = max(res.iterations, 1)
    round_s = walls[1] / rounds

    def paired_ab(obs, pairs):
        """Interleaved sync/depth-2 pairs under observer ``obs``;
        the median of per-pair ratios is drift-immune."""
        syncs, p2s, ratios = [], [], []
        observed(2, obs)  # warm/settle
        for _ in range(pairs):
            ws, _ = observed(1, obs)
            wp, _ = observed(2, obs)
            syncs.append(ws)
            p2s.append(wp)
            ratios.append(ws / wp)
        return {
            "sync_wall_s": round(statistics.median(syncs), 3),
            "depth2_wall_s": round(statistics.median(p2s), 3),
            "speedup": round(statistics.median(ratios), 2),
        }

    # ---- I/O-observer A/B: the headline serving regime
    io_wait = 0.7 * round_s

    def io_obs(it, d, ch):
        time.sleep(io_wait)

    io_ab = paired_ab(io_obs, 5)
    io_ab["observer_wait_s_per_round"] = round(io_wait, 4)
    io_ab["depth2_vs_saturate"] = round(io_ab["depth2_wall_s"] / sat_s, 2)

    # ---- CPU-observer A/B: the contention-bounded floor.  The numpy
    # unit is WARM-calibrated (median after warmup): the first pass
    # pays cold allocation and would overstate the unit ~10x
    blob = np.random.default_rng(0).random(1_000_000)
    for _ in range(5):
        float(np.sum(np.sqrt(blob)))
    t0 = time.time()
    for _ in range(20):
        float(np.sum(np.sqrt(blob)))
    chunk_s = max((time.time() - t0) / 20, 1e-4)
    chunks = max(1, int(0.7 * round_s / chunk_s))

    def cpu_obs(it, d, ch):
        for _ in range(chunks):
            float(np.sum(np.sqrt(blob)))

    cpu_ab = paired_ab(cpu_obs, 3)
    cpu_ab["observer_load_s_per_round"] = round(chunks * chunk_s, 4)

    # ---- inline-dispatch microprobe (why dispatch goes through the
    # executor): on this jax/CPU runtime the observe program executes
    # INLINE at dispatch — the dispatch call absorbs the round's wall
    # and the later fetch returns immediately — so merely deferring
    # the device_get would hide nothing; the pipeline's single-worker
    # executor moves round execution off the control thread instead
    engine._ensure_observe_jit()
    sp, rp = engine.initial_state()
    dirty = engine.initial_dirty()
    sp, rp, ch, bits, dirty = engine._observe_jit(
        sp, rp, dirty, engine._masks
    )
    jax.block_until_ready((sp, rp, ch, bits, dirty))
    d_s, f_s = [], []
    for _ in range(4):
        t0 = time.time()
        sp, rp, ch, bits, dirty = engine._observe_jit(
            sp, rp, dirty, engine._masks
        )
        d_s.append(time.time() - t0)
        t0 = time.time()
        jax.block_until_ready((ch, bits))
        f_s.append(time.time() - t0)
    dispatch_med = statistics.median(d_s)
    fetch_med = statistics.median(f_s)

    return {
        "corpus": f"galen_shaped_{n_classes // 1000}k_chain{chain_depth}",
        "n_concepts": idx.n_concepts,
        "rounds": res.iterations,
        "saturate_wall_s": round(sat_s, 3),
        "observed_wall_s": {
            "sync": walls[1], "depth2": walls[2], "depth4": walls[4],
        },
        "depth2_vs_saturate": round(walls[2] / sat_s, 2),
        "sync_vs_depth2": round(walls[1] / walls[2], 2),
        "host_split": host_split,
        "io_observer": io_ab,
        "cpu_observer": cpu_ab,
        "inline_dispatch": {
            "dispatch_s": round(dispatch_med, 4),
            "fetch_s": round(fetch_med, 4),
            # True ⇒ the dispatch call absorbs the round's execution
            # (the later fetch is ~instant): deferring the device_get
            # alone hides nothing on this backend — the executor is
            # what makes the overlap real
            "inline": bool(
                dispatch_med > max(10 * fetch_med, 0.002)
            ),
        },
    }


def _fused_rounds_probe(
    n_classes: int = 4000, chain_depth: int = 28, ks=(4, 8)
) -> dict:
    """Device-resident fused rounds A/B (ISSUE 17): the per-round
    adaptive controller (K=1) vs fused K-round windows on the 4k
    chain-tailed fixed point.  The headline figure is the DISPATCH
    COLLAPSE — device launches per retired round — counted at the
    jit-call sites by ``DISPATCH_EVENTS`` snapshot deltas, never
    inferred from wall clocks: steady-state windows retire exactly K
    rounds per launch, and the end-to-end launch count drops from R to
    ``ceil(R / K)`` (the terminal window retires the convergence
    remainder, so the overall ratio rounds down from K).  Walls are
    recorded for completeness but on a 1-core CPU host the device
    rounds serialize with the host anyway — the launch-count collapse
    is the portable result; the latency win it buys needs a real
    accelerator host (see ``host_caveat`` in the record).  Closure
    byte-identity vs K=1 is asserted per K.  Also re-fits the ledger
    cost model with fused-aware round accounting
    (``rounds_in_window``) and records the 128k s/round prediction."""
    import numpy as np

    from distel_tpu.frontend.ontology_tools import chain_tailed_ontology
    from distel_tpu.runtime.instrumentation import DISPATCH_EVENTS

    idx = index_ontology(
        normalize(parser.parse(chain_tailed_ontology(n_classes, chain_depth)))
    )

    def observed(engine, k):
        before = DISPATCH_EVENTS.snapshot()
        t0 = time.time()
        res = engine.saturate_observed(
            sparse_tail=True,
            fused_rounds={"rounds": k},
            pipeline={"enable": False},
        )
        wall = time.time() - t0
        after = DISPATCH_EVENTS.snapshot()
        disp = {
            key: after[key] - before[key]
            for key in after
            if key != "last_window_rounds"
        }
        return res, wall, disp

    def build():
        return RowPackedSaturationEngine(
            idx, bucket=True, unroll=1, sparse_tail=True,
            pipeline={"enable": False},
        )

    e_base = build()
    observed(e_base, 1)  # warm programs
    res_b, wall_b, disp_b = observed(e_base, 1)
    rounds_total = int(res_b.iterations)
    base_launches = (
        disp_b["dense_dispatches"] + disp_b["sparse_dispatches"]
    )

    runs = {}
    for k in ks:
        eng = build()
        observed(eng, k)  # warm (incl. the fused window program)
        res_f, wall_f, disp = observed(eng, k)
        identical = bool(
            np.array_equal(
                np.asarray(res_b.packed_s), np.asarray(res_f.packed_s)
            )
            and np.array_equal(
                np.asarray(res_b.packed_r), np.asarray(res_f.packed_r)
            )
        )
        launches = (
            disp["dense_dispatches"] + disp["sparse_dispatches"]
            + disp["fused_windows"]
        )
        # full windows counted from the per-round telemetry: each
        # retired round carries its window's size
        full_windows = (
            sum(
                1 for st in eng.frontier_rounds
                if st.rounds_in_window == k
            ) // k
        )
        runs[f"k{k}"] = {
            "rounds": int(res_f.iterations),
            "closure_identical": identical,
            "wall_s": round(wall_f, 3),
            "launches_total": launches,
            "fused_windows": disp["fused_windows"],
            "fused_rounds_retired": disp["fused_rounds_retired"],
            "per_round_launches": (
                disp["dense_dispatches"] + disp["sparse_dispatches"]
            ),
            "full_windows": full_windows,
            # steady-state collapse: rounds per launch over the
            # windows that ran full — exactly K by count
            "steady_state_collapse": (
                float(k) if full_windows else None
            ),
            # end-to-end collapse: the K=1 controller's launch count
            # over this run's (terminal partial window included)
            "overall_collapse": (
                round(base_launches / launches, 2) if launches else None
            ),
            "wall_speedup_vs_k1": (
                round(wall_b / wall_f, 2) if wall_f > 0 else None
            ),
        }

    # fused-aware cost model re-fit: rounds now count as
    # sum(rounds_in_window), so a fused ledger's s/round stays the
    # per-round figure — record the 128k prediction for trend watch
    refit = None
    try:
        from distel_tpu.obs import costmodel

        model = costmodel.fit_from_paths(
            costmodel.default_basis_paths(
                os.path.dirname(os.path.abspath(__file__))
            ),
            shards=1,
        )
        refit = model.describe(128_000)
    except Exception as e:  # noqa: BLE001 — the A/B stands without it
        refit = {"error": f"{type(e).__name__}: {e}"}

    return {
        "corpus": f"galen_shaped_{n_classes // 1000}k_chain{chain_depth}",
        "n_concepts": idx.n_concepts,
        "rounds_total": rounds_total,
        "baseline_launches": base_launches,
        "host_caveat": (
            "1-core CPU host: device rounds serialize with the host, "
            "so wall_speedup_vs_k1 understates (or inverts) the "
            "latency win the launch-count collapse buys on an "
            "accelerator host with real per-dispatch overhead; the "
            "counted collapse figures are backend-agnostic"
        ),
        "runs": runs,
        "costmodel_refit_128k": refit,
    }


def _cr6_tiles_probe(n_classes: int = 4000) -> dict:
    """CR6 live-tile kernel A/B (ISSUE 13) — the re-landed r5 int8
    tile probe, tracked: window-formulation vs live-tile engines on the
    chain-heavy SNOMED shape, byte-identity asserted, with

    * live-MAC fraction before/after (``step_cost_model`` — the 0.068
      figure BENCH_r03 recorded is the *before* at 64k),
    * the tile occupancy histogram and tiled-vs-window MAC volume from
      the engine's schedule stats,
    * dense-vs-tiled per-step wall at MATCHED convergence (same
      iteration counts, warm best-of-3 full fixed points plus a warm
      single public-step wall), and
    * per-rule step attribution via ``profile_saturation`` when the
      host has device tracing (absent on the plain CPU host: the
      record then carries the wall-based split and says so).

    Runs inside the bench child, so the DISTEL_BENCH_BACKEND_ATTEMPTS
    retry machinery and the ``#partial`` checkpoints apply: a tunnel
    outage mid-run produces a partial record instead of a lost one —
    the failure mode that killed the original r5 probe."""
    import numpy as np

    from distel_tpu.runtime.instrumentation import STEP_RULE_EVENTS

    text = snomed_shaped_ontology(n_classes=n_classes)
    idx = index_ontology(normalize(parser.parse(text)))
    mk = lambda **kw: RowPackedSaturationEngine(
        idx, bucket=True, unroll=1, **kw
    )
    e_win = mk(cr6_tiles={"enable": False})
    e_til = mk(cr6_tiles=True)
    rec = {
        "corpus": f"snomed_shaped_{n_classes // 1000}k",
        "n_concepts": idx.n_concepts,
        "n_links": idx.n_links,
        "chain_rows": int(len(idx.chain_pairs)),
        "tiles": dict(e_til.cr6_tiles_stats),
    }
    if not e_til.cr6_tiles_stats.get("active"):
        rec["error"] = "tile schedule inactive on this corpus"
        return rec
    r_win, _, w_win = _saturate_timed(e_win)
    r_til, _, w_til = _saturate_timed(e_til)
    identical = bool(
        np.array_equal(
            np.asarray(r_win.packed_s), np.asarray(r_til.packed_s)
        )
        and np.array_equal(
            np.asarray(r_win.packed_r), np.asarray(r_til.packed_r)
        )
    )
    c_win = e_win.step_cost_model()
    c_til = e_til.step_cost_model()
    steps_w = max(r_win.iterations, 1)
    steps_t = max(r_til.iterations, 1)

    # warm single-superstep wall (the public all-dirty step), the
    # per-step figure the acceptance asks for without the loop around it
    def step_wall(engine):
        sp, rp = engine.initial_state()
        sp, rp = engine.step(sp, rp)  # warm the step program
        import jax

        jax.block_until_ready((sp, rp))
        best = float("inf")
        for _ in range(3):
            t0 = time.time()
            o = engine.step(sp, rp)
            jax.block_until_ready(o)
            best = min(best, time.time() - t0)
        return best

    rec.update(
        closure_identical=identical,
        iterations={"window": r_win.iterations, "tiled": r_til.iterations},
        matched_convergence=r_win.iterations == r_til.iterations,
        wall_s_warm={"window": round(w_win, 3), "tiled": round(w_til, 3)},
        per_step_wall_s={
            "window": round(w_win / steps_w, 4),
            "tiled": round(w_til / steps_t, 4),
        },
        step_wall_s={
            "window": round(step_wall(e_win), 4),
            "tiled": round(step_wall(e_til), 4),
        },
        tiled_speedup=round(w_win / max(w_til, 1e-9), 2),
        mm_live_mac_fraction={
            "window": round(
                c_win["mm_live_macs"]
                / max(c_win["mm_dense_equiv_macs"], 1),
                4,
            ),
            "tiled": round(
                c_til["mm_live_macs"]
                / max(c_til["mm_dense_equiv_macs"], 1),
                4,
            ),
        },
    )
    # per-rule device attribution — needs a tracing-capable host; the
    # CPU fallback records the reason instead of fake numbers
    for name, engine in (("window", e_win), ("tiled", e_til)):
        try:
            from distel_tpu.runtime.profiling import profile_saturation

            prof = profile_saturation(engine)
            per_step = prof["per_step_s"]
            total = sum(per_step.values()) or 1.0
            rec.setdefault("rule_seconds_per_step", {})[name] = per_step
            rec.setdefault("cr6_step_share", {})[name] = round(
                per_step.get("cr6", 0.0) / total, 4
            )
        except Exception as e:  # host without device tracing
            rec.setdefault("rule_seconds_per_step", {})[name] = {
                "error": str(e)[:160]
            }
    rec["step_rule_gauges"] = STEP_RULE_EVENTS.snapshot()
    return rec


def _sharded_saturation_inner(
    n_classes: int = 1200, chain_depth: int = 16
) -> dict:
    """The measurement body of the ``sharded_saturation`` section —
    requires a backend already holding >= 4 devices (virtual CPU mesh
    or real chips).  Grid: {1, 2, 4} word-axis shards x {dense
    synchronous, sparse-tail, pipelined depth 2/4} observed adaptive
    runs on a chain-tailed GALEN shape, interleaved repetitions so
    outside load drifts cancel.  Every cell's final closure is
    digest-compared — the MULTICHIP A/B's closure-identity half — and
    the pipelined cells record their dispatch/retire host-time split
    (the per-shard deferred-fold overlap ISSUE 15 ports to the mesh
    path).  On a CPU host the record is about CORRECTNESS + dispatch
    accounting, not speedup: virtual shards serialize on the host's
    cores (the caveat field says so in-record)."""
    import hashlib
    import statistics

    import jax
    import numpy as np

    from distel_tpu.core.engine import fetch_global
    from distel_tpu.frontend.ontology_tools import chain_tailed_ontology

    idx = index_ontology(
        normalize(parser.parse(chain_tailed_ontology(n_classes, chain_depth)))
    )

    devs = jax.devices()
    if len(devs) < 4:
        raise RuntimeError(
            f"sharded_saturation needs >= 4 devices, found {len(devs)}"
        )
    modes = {
        "dense_sync": dict(
            sparse_tail={"enable": False}, pipeline={"enable": False}
        ),
        "sparse_tail": dict(
            sparse_tail=True, pipeline={"enable": False}
        ),
        "pipelined_d2": dict(
            sparse_tail=True, pipeline={"enable": True, "depth": 2}
        ),
        "pipelined_d4": dict(
            sparse_tail=True, pipeline={"enable": True, "depth": 4}
        ),
    }
    engines = {}
    for shards in (1, 2, 4):
        mesh = (
            None
            if shards == 1
            else jax.sharding.Mesh(np.array(devs[:shards]), ("c",))
        )
        engines[shards] = RowPackedSaturationEngine(
            idx, bucket=True, unroll=1, mesh=mesh
        )

    def run(shards, mode):
        eng = engines[shards]
        t0 = time.time()
        res = eng.saturate_observed(**modes[mode])
        wall = time.time() - t0
        return wall, res, list(eng.frontier_rounds)

    digests = {}
    cells = {s: {m: {"walls": []} for m in modes} for s in engines}
    # warm every cell (compiles + registry fills), then record the
    # closure digest and telemetry from a WARM pass — the cold pass's
    # dispatch_s would be dominated by the cell's program compiles
    for shards in engines:
        for mode in modes:
            run(shards, mode)
            _w, res, frs = run(shards, mode)
            ps, pr = fetch_global((res.packed_s, res.packed_r))
            digests[(shards, mode)] = hashlib.sha256(
                np.asarray(ps).tobytes() + np.asarray(pr).tobytes()
            ).hexdigest()
            c = cells[shards][mode]
            c["rounds"] = int(res.iterations)
            c["sparse_rounds"] = sum(
                1 for s in frs if s.tier == "sparse"
            )
            c["dispatch_s"] = round(sum(s.dispatch_s for s in frs), 4)
            c["retire_s"] = round(sum(s.retire_s for s in frs), 4)
            c["speculative_rounds"] = sum(
                1 for s in frs if s.inflight > 0
            )
    # interleaved timed reps: cell order inside each rep, reps outermost
    for _rep in range(3):
        for shards in engines:
            for mode in modes:
                cells[shards][mode]["walls"].append(run(shards, mode)[0])
    uniq = set(digests.values())
    out_shards = {}
    for shards in engines:
        row = {}
        for mode in modes:
            c = cells[shards][mode]
            row[mode] = {
                "wall_s": round(statistics.median(c["walls"]), 3),
                "rounds": c["rounds"],
                "sparse_rounds": c["sparse_rounds"],
                "dispatch_s": c["dispatch_s"],
                "retire_s": c["retire_s"],
                "speculative_rounds": c["speculative_rounds"],
            }
        ds = row["dense_sync"]["wall_s"]
        row["vs_dense_sync"] = {
            m: round(ds / row[m]["wall_s"], 2)
            for m in modes
            if row[m]["wall_s"] > 0
        }
        out_shards[str(shards)] = row
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or -1
    return {
        "corpus": f"galen_shaped_{n_classes}_chain{chain_depth}",
        "n_concepts": idx.n_concepts,
        "platform": devs[0].platform,
        "schedulable_cores": cores,
        "host_caveat": (
            "virtual CPU mesh: all shards execute on the host's "
            f"{cores} schedulable core(s), so N-shard walls include "
            "full serialization of per-shard work plus collective "
            "overhead — the closure-identity and dispatch/retire "
            "accounting are the portable result; shard-scaling walls "
            "need real chips"
        ),
        "closure_identical": len(uniq) == 1,
        "closure_digest": next(iter(uniq)) if len(uniq) == 1 else None,
        "digests": {
            f"{s}x:{m}": d for (s, m), d in sorted(digests.items())
        } if len(uniq) != 1 else None,
        "shards": out_shards,
    }


def _sharded_saturation_probe() -> dict:
    """Dense vs sparse-tail vs pipelined adaptive saturation on 1/2/4
    virtual word-axis shards (ISSUE 15) — the MULTICHIP_r06 feeder.
    The measurement needs >= 4 devices; when this process's backend
    has fewer (the usual bench environment: one real chip or one CPU
    device), the body re-execs in a subprocess pinned to a 4-device
    virtual CPU mesh — the same recipe scale_probe and the multichip
    dryrun use — and relays its record."""
    import subprocess

    from distel_tpu.testing.cpumesh import cpu_mesh_env, initialized_devices

    # an ALREADY-INITIALIZED backend with >= 4 devices of ANY platform
    # measures inline: the virtual CPU mesh (the pytest/conftest case)
    # or a real 4+-chip host — the latter is the record this section
    # ultimately wants, without the serialization caveat.  An
    # uninitialized backend is never probed (touching jax.devices()
    # cold would initialize the axon tunnel chip — see cpumesh).
    if len(initialized_devices()) >= 4:
        return _sharded_saturation_inner()
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--sharded-inner"],
        env=cpu_mesh_env(4),
        capture_output=True,
        text=True,
        timeout=3000,
    )
    sys.stderr.write(p.stderr or "")
    line = next(
        (
            ln
            for ln in reversed((p.stdout or "").splitlines())
            if ln.startswith("{")
        ),
        None,
    )
    if p.returncode != 0 or not line:
        raise RuntimeError(
            f"sharded_saturation child rc={p.returncode}: "
            f"{(p.stderr or '')[-300:]}"
        )
    return json.loads(line)


#: named bench sections runnable standalone via ``--sections a,b`` —
#: each still goes through main()'s probe/retry/partial machinery, so
#: a CPU host (or a half-up tunnel) can produce a BENCH record of just
#: the sections it can afford (BENCH_r06.json is the cr6_tiles section
#: run this way; MULTICHIP_r06.json is the sharded_saturation section)
_SECTIONS = {
    "cr6_tiles": _cr6_tiles_probe,
    "sparse_tail": _sparse_tail_probe,
    "pipelined_observed": _pipeline_probe,
    "sharded_saturation": _sharded_saturation_probe,
    "fused_rounds": _fused_rounds_probe,
}


def _parse_sections_argv(argv):
    """``--sections a,b`` / ``--sections=a,b`` from an argv list;
    None when the flag is absent (full bench run)."""
    names = None
    for i, a in enumerate(argv):
        if a == "--sections":
            # a dangling flag (value forgotten) must NOT read as "no
            # flag -> full bench": [] fails _check_sections loudly
            names = (
                argv[i + 1].replace(",", " ").split()
                if i + 1 < len(argv)
                else []
            )
        elif a.startswith("--sections="):
            names = a.split("=", 1)[1].replace(",", " ").split()
    return names


def _check_sections(names) -> None:
    """Fail LOUDLY at launch on any unknown section name: a typo'd
    `--sections cr6_tile` used to "run" an empty record and exit 0 —
    a silent no-op that reads as a measured bench until someone opens
    the JSON (ISSUE 14 satellite).  Called from main() BEFORE the
    backend probe pays its retry budget, and again in the child."""
    unknown = sorted(set(names) - set(_SECTIONS))
    if unknown or not names:
        print(
            json.dumps(
                {
                    "error": f"unknown bench section(s): {unknown}"
                    if unknown else "no sections named",
                    "known_sections": sorted(_SECTIONS),
                }
            ),
            file=sys.stderr,
            flush=True,
        )
        raise SystemExit(2)


def _run_sections(names, load1_start: float) -> None:
    _check_sections(names)
    import jax

    from distel_tpu.config import enable_compile_cache

    enable_compile_cache()
    out = {
        "metric": "bench_sections",
        "sections": list(names),
        "platform": jax.devices()[0].platform,
        "load1_start": round(load1_start, 2),
    }
    for name in names:
        fn = _SECTIONS[name]
        t0 = time.time()
        out[name] = fn()
        out[name]["section_wall_s"] = round(time.time() - t0, 1)
        _partial(**{name: out[name]})
    out["load1_end"] = round(_load1(), 2)
    print(json.dumps(out))


def _run_bench(load1_start: float) -> None:
    import jax

    from distel_tpu.config import enable_compile_cache

    enable_compile_cache()
    n_classes = int(sys.argv[1]) if len(sys.argv) > 1 else 64000
    custom = len(sys.argv) > 1

    # ---- primary: SNOMED-structured many-role corpus ----
    text = snomed_shaped_ontology(n_classes=n_classes)
    norm = normalize(parser.parse(text))
    idx = index_ontology(norm)
    engine = RowPackedSaturationEngine(idx)
    result, cold_s, warm_s = _saturate_timed(engine)
    engine_dps = result.derivations / warm_s
    # checkpoint the headline the moment it exists: a backend
    # black-hole later in the run (r05 mode) keeps this measured
    _partial(
        saturation={
            "corpus": f"snomed_shaped_{n_classes // 1000}k",
            "derivations_per_sec": round(engine_dps, 1),
            "wall_s_warm": round(warm_s, 3),
            "wall_s_cold": round(cold_s, 3),
            "derivations": result.derivations,
            "iterations": result.iterations,
        }
    )

    # measured tunnel round-trip (a trivial device call), so readers can
    # tell when a warm number is latency- rather than compute-dominated
    import jax.numpy as jnp

    tiny = jax.jit(lambda x: x + 1)
    tiny(jnp.zeros(8)).block_until_ready()
    rtt_s = min(
        _timed(lambda: tiny(jnp.zeros(8)).block_until_ready())
        for _ in range(5)
    )

    # ---- step profile: trace one more full fixed point ----
    step_profile = None
    try:
        from distel_tpu.runtime.profiling import profile_saturation

        prof = profile_saturation(engine)
        steps = max(prof["iterations"], 1)
        step_profile = {
            "per_step_s": prof["per_step_s"],
            "device_total_s": prof["device_total_s"],
            "device_per_step_s": round(prof["device_total_s"] / steps, 4),
            "host_gap_s": prof["host_gap_s"],
            "profiled_wall_s": prof["wall_s"],
        }
    except Exception as e:  # backend without device tracing
        step_profile = {"error": str(e)[:200]}

    # ---- roofline from static plan shapes ----
    # step_cost_model() counts the UNGATED step (frontier gating skips
    # chunks in late supersteps), so both rates are labeled
    # dense-equivalent: the work a naive dense/ungated program would
    # have to move per measured second.  Values above 1.0x peak mean
    # the skipping logic beats brute force, not that silicon overclocked.
    cost = engine.step_cost_model()
    steps = result.iterations
    sec_per_step = warm_s / max(steps, 1)
    hbm_bps = cost["hbm_bytes"] / sec_per_step
    mm_ops = 2.0 * cost["mm_dense_equiv_macs"] / sec_per_step
    roofline = {
        "hbm_bytes_per_step_ungated": cost["hbm_bytes"],
        "hbm_gbps_dense_equiv": round(hbm_bps / 1e9, 1),
        "mm_dense_equiv_tops": round(mm_ops / 1e12, 2),
        "mm_live_mac_fraction": round(
            cost["mm_live_macs"] / max(cost["mm_dense_equiv_macs"], 1), 4
        ),
    }
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        roofline["hbm_dense_equiv_vs_peak"] = round(
            hbm_bps / _V5E_HBM_BPS, 3
        )
        roofline["mm_dense_equiv_vs_int8_peak"] = round(
            mm_ops / _V5E_INT8_OPS, 2
        )

    # ---- budget-capped baseline on the primary corpus (disclosed) ----
    # derived_count() (new facts, excluding the S(X)={X,⊤} init) is the
    # same unit as the engines' `derivations`, so the ratio compares
    # like with like
    t0 = time.time()
    oracle_result = cpu_oracle.saturate(norm, time_budget_s=90.0)
    oracle_s = time.time() - t0
    oracle_dps = oracle_result.derived_count() / oracle_s

    extra = {}
    vs_converged = None
    if not custom:
        # ---- THE baseline ratio: largest size the oracle finishes ----
        for conv_classes in _CONVERGED_CHAIN:
            ctext = snomed_shaped_ontology(n_classes=conv_classes)
            cnorm = normalize(parser.parse(ctext))
            # oracle FIRST: a non-converging tier then costs only its
            # oracle budget, not a discarded engine compile+run too
            t0 = time.time()
            coracle = cpu_oracle.saturate(cnorm, time_budget_s=600.0)
            c_oracle_s = time.time() - t0
            if coracle.converged:
                cidx = index_ontology(cnorm)
                cengine = RowPackedSaturationEngine(cidx)
                cres, _, c_warm = _saturate_timed(cengine)
                vs_converged = round(
                    (cres.derivations / c_warm)
                    / (coracle.derived_count() / c_oracle_s),
                    2,
                )
                extra["baseline_converged_n_concepts"] = cidx.n_concepts
                extra["baseline_converged_oracle_s"] = round(c_oracle_s, 1)
                break

        # ---- incremental delta (the reference's traffic-data
        # scenario): 48k-class base (above the 32k-concept fast-path
        # floor), then a 100-axiom batch over the closure — timed down
        # BOTH paths: base-program reuse (flagship) and forced rebuild
        from distel_tpu.core.incremental import IncrementalClassifier

        delta = "\n".join(
            f"SubClassOf(BenchDelta{i} Find{i * 7})" for i in range(100)
        )
        inc = IncrementalClassifier()
        inc.add_text(snomed_shaped_ontology(n_classes=_INC_BASE_CLASSES))
        extra["incremental_base_concepts"] = len(
            inc.indexer.concept_names
        )
        t0 = time.time()
        dres = inc.add_text(delta)
        extra["incremental_delta_fast_s"] = round(time.time() - t0, 2)
        extra["incremental_delta_axioms"] = 100
        extra["incremental_delta_new_derivations"] = dres.derivations

        # role-INTRODUCING delta over the same live base (r4: the last
        # uniform-insert capability the reference has — T4/T5 axioms as
        # plain inserts, ``init/AxiomLoader.java:1051-1132``): a new
        # subrole of an existing attribute, 50 property assertions over
        # it, and an ∃-on-the-left axiom — must stay on the fast path
        # and beat the rebuild walls below
        delta_role = (
            "SubObjectPropertyOf(benchNewRole attr0)\n"
            + "\n".join(
                f"SubClassOf(BenchR{i} "
                f"ObjectSomeValuesFrom(benchNewRole Find{i * 11}))"
                for i in range(50)
            )
            + "\nSubClassOf(ObjectSomeValuesFrom(benchNewRole Find11)"
            " BenchRoleHit)"
        )
        eng_before = inc._base_engine
        t0 = time.time()
        rres = inc.add_text(delta_role)
        extra["incremental_role_delta_fast_s"] = round(time.time() - t0, 2)
        extra["incremental_role_delta_took_fast_path"] = (
            inc._base_engine is eng_before
        )
        extra["incremental_role_delta_new_derivations"] = rres.derivations

        # closure-CHANGING role delta over the same live base (r5: the
        # masks-only partial rebuild, verdict task 5): an r ⊑ s edge
        # between two EXISTING BASE roles (attr7 ⊑ attr8) flips cells
        # of the restricted role closure — previously a guaranteed full
        # rebuild; now ``rebind_role_closure`` swaps the compiled base
        # program's factored masks + live-window tables in place (no
        # recompile) and attr8's ∃-axioms fire on attr7's existing
        # links.  ``took_fast_path`` records whether the rebind fit the
        # program's window slots (it falls back to the rebuild loudly
        # when not); the wall is comparable against the rebuild walls
        # below either way.
        eng_before = inc._base_engine
        t0 = time.time()
        cres = inc.add_text("SubObjectPropertyOf(attr7 attr8)")
        extra["incremental_closure_delta_fast_s"] = round(
            time.time() - t0, 2
        )
        extra["incremental_closure_delta_took_fast_path"] = (
            inc._base_engine is eng_before
        )
        extra["incremental_closure_delta_new_derivations"] = cres.derivations

        # rebuild path, BOTH walls (r3 verdict item 7: README quoted a
        # warm figure while the driver captured compile-included — ~4x
        # apart and neither labeled): cold = engine build + jit compile
        # + solve (what a user pays once per new shape), warm = the
        # same rebuild with the program served from the persistent
        # compile cache (what every later identical-shape rebuild
        # pays).  Three runs make both walls honest regardless of what
        # an earlier bench left in ~/.cache: run 1 populates the cache
        # for THIS corpus+delta (wall unrecorded — could be a stale
        # hit), run 2 is a guaranteed cache hit (warm), run 3 forces a
        # fresh compile by disabling the disk cache (cold)
        def _rebuild_wall():
            inc_r = IncrementalClassifier()
            inc_r.add_text(
                snomed_shaped_ontology(n_classes=_INC_BASE_CLASSES)
            )
            inc_r.drop_base_program()  # force the rebuild path
            t0 = time.time()
            inc_r.add_text(delta)
            return round(time.time() - t0, 2)

        _rebuild_wall()  # populate
        extra["incremental_delta_rebuild_warm_s"] = _rebuild_wall()
        jax.config.update("jax_enable_compilation_cache", False)
        try:
            extra["incremental_delta_rebuild_cold_s"] = _rebuild_wall()
        finally:
            jax.config.update("jax_enable_compilation_cache", True)

        # ---- latency-sensitivity probe: GALEN-shaped 16k ----
        gtext = synthetic_ontology(
            n_classes=16000, n_anatomy=1600, n_locations=1333,
            n_definitions=800,
        )
        gidx = index_ontology(normalize(parser.parse(gtext)))
        gengine = RowPackedSaturationEngine(gidx)
        gres, _, g_warm = _saturate_timed(gengine)
        extra.update(
            galen_16k_concepts=gidx.n_concepts,
            galen_16k_wall_s_warm=round(g_warm, 3),
            galen_16k_dps=round(gres.derivations / g_warm, 1),
        )

        # ---- adaptive sparse tail (ISSUE 4): GALEN shape with a deep
        # subclass-chain tail — the regime where late rounds derive a
        # handful of facts but the dense step still pays a full-corpus
        # sweep.  Both runs are observed fixed points (unroll=1 so
        # rounds line up); the record carries per-round tier + density
        # and the low-density speedup at matched iterations.
        extra["sparse_tail"] = _sparse_tail_probe()
        _partial(sparse_tail=extra["sparse_tail"])

        # ---- CR6 live-tile kernel (ISSUE 13): window vs tiled A/B at
        # matched convergence — live-MAC fraction, occupancy, per-step
        # wall, per-rule attribution where the host can trace
        extra["cr6_tiles"] = _cr6_tiles_probe()
        _partial(cr6_tiles=extra["cr6_tiles"])

        # ---- pipelined observed saturation (ISSUE 5): speculative
        # round dispatch with deferred frontier folds — raw walls vs
        # saturate()/sync, the loaded-observer hiding A/B, and the
        # dependent-dispatch microprobe that bounds what CPU can show
        extra["pipelined_observed"] = _pipeline_probe()
        _partial(pipelined_observed=extra["pipelined_observed"])

    budgeted_ratio = round(engine_dps / oracle_dps, 2)
    print(
        json.dumps(
            {
                "metric": "axiom_derivations_per_sec",
                "value": round(engine_dps, 1),
                "unit": "derivations/s",
                # converged-denominator ratio leads (r2 verdict item 10);
                # the budgeted headline-size ratio is disclosed next to it
                "vs_baseline": (
                    vs_converged
                    if vs_converged is not None
                    else budgeted_ratio
                ),
                "vs_baseline_denominator": (
                    "converged" if vs_converged is not None else "budgeted"
                ),
                "vs_baseline_budgeted": budgeted_ratio,
                "platform": jax.devices()[0].platform,
                "corpus": f"snomed_shaped_{n_classes // 1000}k",
                "n_concepts": idx.n_concepts,
                "n_links": idx.n_links,
                "derivations": result.derivations,
                "iterations": result.iterations,
                "wall_s_warm": round(warm_s, 3),
                "wall_s_cold": round(cold_s, 3),
                # compile/execute split of the cold wall (ISSUE 2): the
                # engine's AOT build telemetry separates program-build
                # cost from saturation throughput in the perf record
                "compile_s": round(
                    engine.compile_stats.compile_s
                    + engine.compile_stats.trace_lower_s,
                    3,
                ),
                "persistent_cache_hits": (
                    engine.compile_stats.persistent_cache_hits
                ),
                "program_cache_hit": (
                    engine.compile_stats.program_cache_hit
                ),
                "bucket_signature": engine.bucket_signature,
                "rtt_s": round(rtt_s, 3),
                "baseline_cpu_dps": round(oracle_dps, 1),
                "baseline_budget_s": 90.0,
                "baseline_converged": oracle_result.converged,
                # contention disclosure (r4 weak #2: a background job
                # holding the single core slows the CPU oracle ~2x and
                # inflates vs_baseline; load1 at bench start makes the
                # bias visible in the record itself)
                "load1_start": round(load1_start, 2),
                "load1_end": round(_load1(), 2),
                "contended": load1_start > 1.25,
                "step_profile": step_profile,
                **roofline,
                **extra,
            }
        )
    )


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe_backend_once()
    elif "--sharded-inner" in sys.argv:
        # the sharded_saturation measurement body, re-exec'd into a
        # process whose env pins a 4-device virtual CPU mesh (see
        # _sharded_saturation_probe); prints exactly one JSON line
        from distel_tpu.config import enable_compile_cache
        from distel_tpu.testing.cpumesh import force_cpu_mesh

        force_cpu_mesh(4)
        enable_compile_cache()
        print(json.dumps(_sharded_saturation_inner()))
    elif "--child" in sys.argv:
        sys.argv = [sys.argv[0]] + [
            a for a in sys.argv[1:] if a != "--child"
        ]
        names = _parse_sections_argv(sys.argv[1:])
        if names is not None:
            _run_sections(names, _load1())
        else:
            _run_bench(_load1())
    else:
        main()
