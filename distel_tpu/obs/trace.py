"""End-to-end request tracing (stdlib only).

The reference's only per-request visibility is nanoTime phase prints per
processor (``base/Type1_1AxiomProcessorBase.java:183-214``); the serve
plane already exceeds that in *aggregate* (Prometheus ``/metrics``,
per-round ``FrontierStats``), but aggregates cannot answer "where did
THIS request spend its time" or "what exactly happened around the
migration at 14:07".  This module is the causal layer: a W3C
``traceparent``-style context minted by the client (or the first server
hop), propagated router → replica → scheduler lane → registry →
classifier phases → per-saturation-round events, recorded into a
bounded in-process ring and exportable as JSONL or Chrome trace-event
JSON (loadable in Perfetto / ``chrome://tracing``).

Design constraints, in order:

* **off-path when disabled** — ``SpanRecorder(enable=False)`` yields a
  shared no-op span without touching the thread-local or the ring; the
  hot-path hooks (``active_span()``) are one ``threading.local`` read;
* **no new deps** — trace ids are ``os.urandom`` hex, the wire format is
  the 55-char ``00-<trace_id>-<span_id>-<flags>`` header, exports are
  plain ``json``;
* **bounded memory** — finished spans land in a ``deque(maxlen=...)``;
  a resident server can trace forever without growing.

Span timestamps are wall-clock epoch seconds (durations are measured
with ``perf_counter`` where precision matters); Chrome export converts
to microseconds, which Perfetto renders directly.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import re
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

#: ``00-<32 hex trace id>-<16 hex span id>-<2 hex flags>`` (W3C
#: traceparent, version 00)
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

#: header name, shared by client / router / replica
TRACEPARENT_HEADER = "traceparent"

#: id minting via the module PRNG (seeded from os.urandom at import):
#: trace ids need uniqueness, not cryptographic strength, and a
#: getrandbits is ~30x cheaper than an os.urandom syscall on the
#: request path
_ids = random.Random(os.urandom(16))
_ids_lock = threading.Lock()


def _hex_id(bits: int) -> str:
    with _ids_lock:
        return format(_ids.getrandbits(bits), "0{}x".format(bits // 4))


#: cached pid: os.getpid() is an unconditional syscall on some kernels
#: (measured 18 µs under the CI sandbox — dominating span creation);
#: refreshed after fork so a forked worker's spans carry its own pid
#: (and its id stream reseeds — forked PRNG state must not collide)
_PID = os.getpid()


def _after_fork():
    global _PID, _ids
    _PID = os.getpid()
    _ids = random.Random(os.urandom(16))


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_after_fork)


class TraceContext:
    """Immutable propagation token: what crosses a process boundary."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    @classmethod
    def mint(cls, sampled: bool = True) -> "TraceContext":
        return cls(_hex_id(128), _hex_id(64), sampled)

    @classmethod
    def from_traceparent(cls, header: Optional[str]) -> Optional["TraceContext"]:
        """Parse a ``traceparent`` header; None on absence or any
        malformation (a bad header must never fail the request)."""
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None:
            return None
        trace_id, span_id, flags = m.groups()
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        try:
            sampled = bool(int(flags, 16) & 1)
        except ValueError:
            return None
        return cls(trace_id, span_id, sampled)

    def to_traceparent(self) -> str:
        return "00-{}-{}-{}".format(
            self.trace_id, self.span_id, "01" if self.sampled else "00"
        )


class Span:
    """One recorded operation.  Mutated only by the thread that opened
    it (events/attrs) until ``finish``, then frozen into the ring as a
    dict."""

    __slots__ = (
        "name", "service", "trace_id", "span_id", "parent_id",
        "start_s", "end_s", "pid", "tid", "attrs", "events", "status",
        "_recorder",
    )

    def __init__(self, name, service, trace_id, span_id, parent_id,
                 start_s, recorder):
        self.name = name
        self.service = service
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.end_s: Optional[float] = None
        self.pid = _PID
        self.tid = threading.get_ident() & 0xFFFFFFFF
        self.attrs: Dict[str, object] = {}
        self.events: List[dict] = []
        self.status = "ok"
        self._recorder = recorder

    #: spans are always sampled once they exist (unsampled requests
    #: never allocate one) — hooks may branch on this uniformly with
    #: the no-op span
    sampled = True

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, True)

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def set_status(self, status: str) -> None:
        self.status = status

    def add_event(self, name: str, attrs: Optional[dict] = None,
                  ts_s: Optional[float] = None) -> None:
        self.events.append({
            "name": name,
            "ts_s": time.time() if ts_s is None else ts_s,
            "attrs": dict(attrs or {}),
        })

    def as_dict(self) -> dict:
        end = self.end_s if self.end_s is not None else time.time()
        return {
            "name": self.name,
            "service": self.service,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": self.start_s,
            "end_s": end,
            "duration_s": round(max(end - self.start_s, 0.0), 6),
            "pid": self.pid,
            "tid": self.tid,
            "status": self.status,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class _NoopSpan:
    """The disabled/unsampled stand-in: every mutator is a no-op, so
    instrumentation sites never branch on enablement themselves."""

    __slots__ = ()
    sampled = False
    trace_id = None
    span_id = None
    _recorder = None

    def context(self):
        return None

    def set_attr(self, key, value):
        pass

    def set_status(self, status):
        pass

    def add_event(self, name, attrs=None, ts_s=None):
        pass


NOOP = _NoopSpan()


class _UnsampledSpan:
    """Context-only carrier for an UNSAMPLED request: records nothing,
    but holds a trace context with ``sampled=False`` so every
    downstream hop (client header injection, router forward, scheduler
    submit) inherits the DON'T-sample decision instead of re-rooting
    its own trace — without this, ``obs.sample_rate=0.1`` would leak
    orphan partial traces at each hop."""

    __slots__ = ("trace_id", "span_id")
    sampled = False
    _recorder = None

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def context(self):
        return TraceContext(self.trace_id, self.span_id, sampled=False)

    def set_attr(self, key, value):
        pass

    def set_status(self, status):
        pass

    def add_event(self, name, attrs=None, ts_s=None):
        pass

#: per-thread active span (the propagation mechanism inside one
#: process; across processes the traceparent header carries it)
_tls = threading.local()


def active_span() -> Optional[Span]:
    """The thread's active span, or None.  One attribute read — safe on
    every hot path."""
    return getattr(_tls, "span", None)


def current_context() -> Optional[TraceContext]:
    sp = active_span()
    return sp.context() if sp is not None else None


@contextlib.contextmanager
def activate(span: Span):
    """Make ``span`` the thread's active span for the block (nesting
    restores the previous one)."""
    prev = getattr(_tls, "span", None)
    _tls.span = span
    try:
        yield span
    finally:
        _tls.span = prev


@contextlib.contextmanager
def child_span(name: str, attrs: Optional[dict] = None):
    """A child span of the thread's active span, recorded through the
    same recorder; a no-op when nothing is active (library code calls
    this unconditionally — registry restore, phase timers)."""
    sp = active_span()
    if sp is None or sp._recorder is None:
        yield NOOP
        return
    with sp._recorder.span(name, parent=sp, attrs=attrs) as child:
        yield child


def add_span_event(name: str, attrs: Optional[dict] = None) -> None:
    """Append an event to the thread's active span, if any."""
    sp = active_span()
    if sp is not None:
        sp.add_event(name, attrs)


def add_round_event(st) -> None:
    """Attach one saturation round's ``FrontierStats`` to the active
    span — the hook ``runtime/instrumentation.FRONTIER_EVENTS`` calls so
    a traced classify shows its per-round tier/density/dispatch/retire
    timeline (the PR 5 pipeline's overlap, visible per request)."""
    sp = active_span()
    if sp is not None:
        sp.add_event(
            "saturation.round",
            {
                "iteration": st.iteration,
                "tier": st.tier,
                "density": round(st.density, 5),
                "rows_touched": st.rows_touched,
                "derivations": st.derivations,
                "overflow": st.overflow,
                "dispatch_s": round(st.dispatch_s, 6),
                "retire_s": round(st.retire_s, 6),
                "inflight": st.inflight,
            },
        )


def add_phase_span(parent: Span, name: str, start_s: float,
                   duration_s: float) -> None:
    """Record one finished classifier phase as a complete child span of
    ``parent`` (``runtime/instrumentation.PhaseTimer`` calls this with
    its measured wall — the phases of a traced request nest under its
    lane-exec span)."""
    rec = parent._recorder
    if rec is not None:
        rec.record_complete(
            f"phase:{name}", parent, start_s, start_s + duration_s
        )


class SpanRecorder:
    """Thread-safe bounded span store with config-gated sampling.

    One per process role (replica, router, client harness); finished
    spans freeze into a ``deque(maxlen=capacity)`` of dicts served by
    ``/debug/trace``.  ``enable=False`` makes every entry point yield
    :data:`NOOP` without touching the ring or the thread-local —
    tracing is fully off-path."""

    def __init__(
        self,
        service: str = "distel",
        *,
        capacity: int = 2048,
        enable: bool = True,
        sample_rate: float = 1.0,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if not (0.0 <= sample_rate <= 1.0):
            raise ValueError("sample_rate must be in [0, 1]")
        self.service = service
        self.enabled = bool(enable)
        self.sample_rate = float(sample_rate)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)

    # ----------------------------------------------------------- create

    def _sampled(self, parent) -> bool:
        if parent is None:
            return (
                self.sample_rate >= 1.0
                or random.random() < self.sample_rate
            )
        return bool(getattr(parent, "sampled", True))

    def start(
        self,
        name: str,
        parent=None,
        attrs: Optional[dict] = None,
        start_s: Optional[float] = None,
    ) -> Optional[Span]:
        """Open a span (caller must :meth:`finish` it).  ``parent``: a
        :class:`Span`, a :class:`TraceContext`, or None (new root under
        the sampling decision).  Returns None when disabled or
        unsampled."""
        if not self.enabled or not self._sampled(parent):
            return None
        if parent is None:
            trace_id, parent_id = _hex_id(128), None
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        span = Span(
            name, self.service, trace_id, _hex_id(64), parent_id,
            time.time() if start_s is None else start_s, self,
        )
        if attrs:
            span.attrs.update(attrs)
        return span

    def finish(self, span: Span, end_s: Optional[float] = None) -> None:
        span.end_s = time.time() if end_s is None else end_s
        with self._lock:
            self._ring.append(span.as_dict())

    @contextlib.contextmanager
    def span(self, name: str, parent=None, attrs: Optional[dict] = None):
        """Open + activate + record a span around a block; exceptions
        mark ``status="error"`` and re-raise.  Yields :data:`NOOP` when
        disabled; for an enabled-but-unsampled request it yields (and
        ACTIVATES) a context-only carrier so the don't-sample decision
        propagates to every downstream hop."""
        if not self.enabled:
            yield NOOP
            return
        sp = self.start(name, parent=parent, attrs=attrs)
        if sp is None:
            if parent is not None:
                carrier = _UnsampledSpan(parent.trace_id, parent.span_id)
            else:
                carrier = _UnsampledSpan(_hex_id(128), _hex_id(64))
            with activate(carrier):
                yield carrier
            return
        try:
            with activate(sp):
                yield sp
        except BaseException as e:
            sp.status = "error"
            sp.attrs.setdefault("error", f"{type(e).__name__}: {e}"[:200])
            raise
        finally:
            self.finish(sp)

    def record_complete(
        self,
        name: str,
        parent,
        start_s: float,
        end_s: float,
        attrs: Optional[dict] = None,
    ) -> None:
        """Record an already-finished interval (queue waits, phase
        timers) as a span under ``parent`` without activating it."""
        sp = self.start(name, parent=parent, attrs=attrs, start_s=start_s)
        if sp is not None:
            self.finish(sp, end_s=end_s)

    # ------------------------------------------------------------- read

    def spans(
        self, trace_id: Optional[str] = None, limit: Optional[int] = None
    ) -> List[dict]:
        """Finished spans, oldest first, optionally filtered by
        trace_id / bounded to the newest ``limit``."""
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        if limit is not None and limit >= 0:
            # guard limit=0 explicitly: out[-0:] is the WHOLE list
            out = out[-limit:] if limit else []
        return out

    def jsonl(self, trace_id: Optional[str] = None) -> str:
        lines = [json.dumps(s) for s in self.spans(trace_id)]
        return "\n".join(lines) + ("\n" if lines else "")

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def chrome_trace(spans: Iterable[dict]) -> dict:
    """Convert recorded span dicts (possibly merged across processes —
    the router's stitched view) into Chrome trace-event JSON:
    ``{"traceEvents": [...]}`` with complete (``ph="X"``) events per
    span, instant (``ph="i"``) events per span event, and
    ``process_name`` metadata so Perfetto labels each service's track.
    """
    events: List[dict] = []
    #: (real pid, service) → synthetic display pid: distinct services
    #: sharing one OS process (in-process fleet rigs, the test client
    #: next to the router) must land on SEPARATE Perfetto tracks
    procs: Dict[tuple, int] = {}
    for sp in spans:
        real_pid = int(sp.get("pid", 0))
        tid = int(sp.get("tid", 0))
        svc = str(sp.get("service", "distel"))
        pid = procs.setdefault((real_pid, svc), len(procs) + 1)
        start = float(sp["start_s"])
        end = float(sp.get("end_s") or start)
        args = {
            "trace_id": sp.get("trace_id"),
            "span_id": sp.get("span_id"),
            "parent_id": sp.get("parent_id"),
            "status": sp.get("status", "ok"),
            "os_pid": real_pid,
        }
        args.update(sp.get("attrs") or {})
        events.append({
            "name": sp["name"],
            "cat": svc,
            "ph": "X",
            "ts": round(start * 1e6, 3),
            "dur": round(max(end - start, 0.0) * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
        for ev in sp.get("events") or ():
            events.append({
                "name": ev["name"],
                "cat": svc,
                "ph": "i",
                "s": "t",
                "ts": round(float(ev["ts_s"]) * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": ev.get("attrs") or {},
            })
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": disp,
            "args": {"name": f"{svc} (pid {real_pid})"},
        }
        for (real_pid, svc), disp in sorted(
            procs.items(), key=lambda kv: kv[1]
        )
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
