"""Calibrated rounds-vs-size cost model + online ETA (stdlib only).

The SCALE_r05 128k run was launched on a hand-waved 5-10 h band,
under-estimated by >=45%, and killed blind after 14h22m.  This module
is the calibration layer that ROADMAP item asks for:

* :func:`load_probe_lines` — back-compat reader for the tracked
  ``SCALE_r04_probes.jsonl`` / ``SCALE_r05_probes.jsonl`` line formats
  (flat compile probes, flat exec records incl. resumed tails, and the
  r04 component-partitioned record with its nested ``exec`` block) —
  they seed the first fitted model;
* :func:`load_ledger_observations` — the same observations from run
  ledgers (``distel_tpu/obs/ledger.py``), so every completed observed
  run sharpens the next launch's prediction;
* :func:`fit_cost_model` — power-law fits of rounds-vs-size and
  seconds-per-round-vs-size (log-log least squares past two distinct
  sizes; a single observation anchors the documented default
  exponents, which reproduce the measured 128k behavior from the 64k
  point: ~34 min/round and ~14 h total);
* :class:`OnlineEta` — the in-flight estimate re-stamped into the
  ledger each round: rolling round-wall median x remaining-rounds from
  the derivation-curve tail (geometric decay extrapolation), falling
  back to the fitted model while the frontier is still growing;
* :func:`guard_launch` — the launch budget guard ``scale_probe`` and
  ``cli classify --budget-s`` refuse over-budget runs with.
"""

from __future__ import annotations

import glob
import json
import math
import os
import statistics
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

#: anchored-fit exponents used when the basis holds only ONE executed
#: size (a regression needs two).  seconds-per-round ~ n^2: the packed
#: step is bit-table matmuls over an O(n^2)-bit state (64k galen
#: measured 516 s/round -> predicts ~34 min/round at 128k, matching
#: SCALE_r05's observed ~40 min rounds).  rounds ~ n^0.3: fixed-point
#: depth grows with taxonomy depth, far sublinearly with size (64k's 20
#: rounds -> ~25 at 128k; the killed run had burned ~21 without
#: converging).
DEFAULT_ROUNDS_EXP = 0.3
DEFAULT_SPR_EXP = 2.0
#: compile-wall ~ n^0.7: BENCH_r03's 64k cold-minus-warm gap puts the
#: compile near 60 s where compile300k_512_cold_r5.log measured
#: 148-209 s at 300k — a ~3x wall over a ~4.7x size step
DEFAULT_COMPILE_EXP = 0.7


def geometric_tail_remaining(
    deltas: Sequence[int], decay_ceiling: float = 0.98
) -> Optional[int]:
    """Remaining-rounds estimate from the derivation-curve tail: EL+
    saturation frontiers drain roughly geometrically, so the median
    decay ratio of recent per-round derivation deltas predicts how
    many more rounds until the frontier empties.  None while the curve
    is too short or not draining (ratio >= ``decay_ceiling``) —
    extrapolating a growing curve would lie.  Shared by
    :class:`OnlineEta` (the in-flight ETA) and the rowpacked engine's
    K-adaptive fused terminal window."""
    ds = [d for d in deltas if d > 0]
    if len(ds) < 3:
        return None
    ratios = [b / a for a, b in zip(ds, ds[1:])]
    r = statistics.median(ratios)
    if r >= decay_ceiling:
        return None
    remaining = math.ceil(math.log(max(ds[-1], 2.0)) / -math.log(r))
    return max(1, min(remaining, 100_000))


@dataclass
class ProbeObs:
    """One normalized historical observation.

    ``kind``: ``"exec"`` (an observed fixed-point execution — the only
    kind the model fits), ``"compile"`` (an AOT compile-only probe), or
    ``"partitioned"`` (the r04 component-partitioned batch execution —
    parsed for completeness, excluded from the superstep fit because
    its rounds are per-component, not whole-corpus supersteps)."""

    n: int
    kind: str
    source: str
    #: rounds PAIRED with ``wall_s`` (a resumed session's tail) — the
    #: seconds-per-round fit's consistent pairing
    rounds: Optional[int] = None
    wall_s: Optional[float] = None
    #: cumulative rounds of the whole logical run/chain when known —
    #: the rounds-vs-size fit must see run TOTALS, or resumed tails
    #: would systematically under-predict round counts (and walls)
    rounds_total: Optional[int] = None
    compile_s: Optional[float] = None
    #: mesh shape of the run (1 = single device).  A dimension of the
    #: seconds-per-round signal, NOT a free covariate: sharded rounds
    #: cost differently per shard (on a real mesh, less; on a 1-core
    #: virtual mesh, more), so a fit must never silently pool 1-shard
    #: and N-shard points — see :func:`fit_cost_model`'s ``shards``.
    shards: int = 1

    @property
    def s_per_round(self) -> Optional[float]:
        """Wall per round, NET of any recorded compile seconds: a cold
        process's session wall includes the trace+compile roster, and
        pooling that into s/round systematically over-prices every
        warm (or artifact-farmed) launch.  When the recorded compile
        is nonsensically >= the wall, the raw pairing stands — bad
        splits must not zero the signal."""
        if self.rounds and self.wall_s:
            w = self.wall_s - (self.compile_s or 0.0)
            if w <= 0:
                w = self.wall_s
            return w / self.rounds
        return None

    @property
    def run_rounds(self) -> Optional[int]:
        return self.rounds_total if self.rounds_total else self.rounds


def _obs_from_probe_doc(doc: dict, source: str) -> List[ProbeObs]:
    """Normalize one historical probe line (any vintage) into
    observations; unrecognized shapes yield nothing rather than an
    error — this reader must keep accepting every line ever appended
    to the tracked probe files."""
    out: List[ProbeObs] = []
    if not isinstance(doc, dict):
        return out
    # the mesh dimension: modern records carry n_shards explicitly;
    # historical scale_probe lines recorded their virtual mesh size as
    # `devices` (0/absent = single device)
    shards = int(doc.get("n_shards") or doc.get("devices") or 1)
    # r04 component-partitioned record: nested exec block, classes_total
    ex = doc.get("exec")
    if isinstance(ex, dict) and "wall_s" in ex:
        n = doc.get("classes_total") or doc.get("n_classes")
        if n:
            out.append(
                ProbeObs(
                    n=int(n),
                    kind="partitioned",
                    source=source,
                    rounds=int(ex.get("iterations") or 0) or None,
                    wall_s=float(ex["wall_s"]),
                    shards=shards,
                )
            )
        return out
    n = doc.get("n_classes")
    if not n:
        return out
    n = int(n)
    # flat exec record: `iterations`/`exec_wall_s` are the POST-RESUME
    # tail on resumed runs (a consistent rounds/wall pairing either
    # way, which is exactly what a seconds-per-round fit wants)
    if doc.get("exec_wall_s") is not None and doc.get("iterations"):
        out.append(
            ProbeObs(
                n=n,
                kind="exec",
                source=source,
                rounds=int(doc["iterations"]),
                wall_s=float(doc["exec_wall_s"]),
                # resumed records carry the chain's cumulative count
                rounds_total=int(doc.get("iterations_total") or 0) or None,
                # a record that split its compile out lets s_per_round
                # price execution alone (and seeds the compile fit)
                compile_s=float(
                    doc.get("compile_s") or doc.get("step_compile_s") or 0
                ) or None,
                shards=shards,
            )
        )
    elif doc.get("step_compile_s") is not None:
        out.append(
            ProbeObs(
                n=n,
                kind="compile",
                source=source,
                compile_s=float(doc["step_compile_s"]),
                shards=shards,
            )
        )
    return out


def load_probe_lines(path: str) -> List[ProbeObs]:
    """Parse one ``SCALE_r0N_probes.jsonl``-style file.  Tolerant by
    contract: unknown line shapes are skipped (the files accumulated
    across probe-script generations), a torn final line is a crash
    artifact, never an error."""
    out: List[ProbeObs] = []
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            out.extend(
                _obs_from_probe_doc(doc, f"{os.path.basename(path)}:{lineno}")
            )
    return out


def load_ledger_observations(path: str) -> List[ProbeObs]:
    """Exec observations from a run-ledger file: ONE per chain, not
    per session — a resumed chain's sessions are tails of one logical
    run, and feeding tail round counts into the rounds-vs-size fit
    would systematically under-predict (the SCALE_r05 failure mode).
    ``rounds``/``wall_s`` pair the chain's recorded rounds with the
    summed session walls (the seconds-per-round signal);
    ``rounds_total`` is the last cumulative round index (the
    rounds-fit signal).  Crashed sessions contribute their last round's
    elapsed — partial progress is still calibration signal."""
    from distel_tpu.obs import ledger as _ledger

    out: List[ProbeObs] = []
    records = _ledger.read_ledger(path, strict=False)
    for chain_id, recs in _ledger.chains(records).items():
        opens = [r for r in recs if r.get("ev") == "open"]
        if not opens:
            continue
        meta = opens[0].get("meta") or {}
        n = meta.get("n_classes")
        if not n:
            continue
        shards = int(meta.get("n_shards") or meta.get("devices") or 1)
        rounds_ = [r for r in recs if r.get("ev") == "round"]
        if not rounds_:
            continue
        closes = {
            r.get("run_id"): r for r in recs if r.get("ev") == "close"
        }
        wall = 0.0
        compile_s = 0.0
        for op in opens:
            rid = op.get("run_id")
            close = closes.get(rid)
            if close is not None and close.get("wall_s"):
                wall += float(close["wall_s"])
                # sessions that split their compile wall out (cold
                # starts) let s_per_round price execution alone
                compile_s += float(close.get("compile_s") or 0.0)
            else:
                tail = [r for r in rounds_ if r.get("run_id") == rid]
                if tail and tail[-1].get("elapsed_s"):
                    wall += float(tail[-1]["elapsed_s"])
        if wall <= 0:
            continue
        out.append(
            ProbeObs(
                n=int(n),
                kind="exec",
                source=f"{os.path.basename(path)}#{chain_id}",
                # a fused-window record covers rounds_in_window retired
                # rounds (ISSUE 17) — count rounds, not records, or the
                # s/round signal inflates K×
                rounds=sum(
                    int(r.get("rounds_in_window") or 1) for r in rounds_
                ),
                wall_s=wall,
                # max, not last-in-file: a crashed tail can outrank the
                # resumed session's newest record
                rounds_total=max(
                    int(r.get("round") or 0) for r in rounds_
                ) or None,
                compile_s=compile_s or None,
                shards=shards,
            )
        )
    return out


def _is_ledger_file(path: str) -> bool:
    """Sniff: ledger records carry an ``ev`` field on line 1."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            first = f.readline().strip()
        return bool(first) and "ev" in json.loads(first)
    except (OSError, json.JSONDecodeError):
        return False


def gather_observations(paths: Sequence[str]) -> List[ProbeObs]:
    out: List[ProbeObs] = []
    for p in paths:
        if not os.path.exists(p):
            continue
        if _is_ledger_file(p):
            out.extend(load_ledger_observations(p))
        else:
            out.extend(load_probe_lines(p))
    return out


def default_basis_paths(root: str = ".") -> List[str]:
    """The calibration basis a launch guard fits from when none is
    given: the tracked SCALE probe files plus every ledger under
    ``runs/`` (``DISTEL_COSTMODEL_BASIS`` overrides, colon-separated)."""
    env = os.environ.get("DISTEL_COSTMODEL_BASIS")
    if env:
        return [p for p in env.split(":") if p]
    paths = [
        os.path.join(root, "SCALE_r04_probes.jsonl"),
        os.path.join(root, "SCALE_r05_probes.jsonl"),
    ]
    paths += sorted(glob.glob(os.path.join(root, "runs", "*.ledger.jsonl")))
    return [p for p in paths if os.path.exists(p)]


def _fit_power(
    pts: Sequence[Tuple[float, float]], default_exp: float
) -> Tuple[float, float]:
    """Least-squares power-law fit ``y = coef * x**exp`` in log space;
    with a single distinct x the curve is anchored through the median
    point at ``default_exp``."""
    pts = [(x, y) for x, y in pts if x > 0 and y > 0]
    xs = sorted({x for x, _ in pts})
    if len(xs) >= 2:
        lx = [math.log(x) for x, _ in pts]
        ly = [math.log(y) for _, y in pts]
        mx, my = statistics.fmean(lx), statistics.fmean(ly)
        den = sum((a - mx) ** 2 for a in lx)
        exp = sum((a - mx) * (b - my) for a, b in zip(lx, ly)) / den
        coef = math.exp(my - exp * mx)
        return coef, exp
    x, y = sorted(pts)[len(pts) // 2]
    return y / (x**default_exp), default_exp


@dataclass
class CostModel:
    """Fitted rounds-vs-size and seconds-per-round-vs-size curves
    (power laws; ``basis`` records every observation that shaped them,
    so a refused launch can print WHY it was refused)."""

    rounds_coef: float
    rounds_exp: float
    spr_coef: float
    spr_exp: float
    #: fitted compile-wall curve, a SEPARATE term from s/round: a cold
    #: process pays it once before round 1, a warm process (in-registry
    #: programs, or an AOT artifact farm covering the roster) pays
    #: zero.  None when the basis holds no compile observation — the
    #: prediction then prices execution only, as before.
    compile_coef: Optional[float] = None
    compile_exp: Optional[float] = None
    basis: List[dict] = field(default_factory=list)
    #: the mesh shape this model was fitted FOR: the shard count whose
    #: observations exclusively shaped the fit, or None when the basis
    #: pooled mixed shard counts (either no ``shards`` was requested,
    #: or nothing matched and the fit fell back — ``mixed_shards``
    #: marks the fallback so a launch record shows the prediction is
    #: cross-mesh extrapolation, not same-shape calibration)
    shards: Optional[int] = None
    mixed_shards: bool = False

    def predict_rounds(self, n: int) -> float:
        return max(1.0, self.rounds_coef * float(n) ** self.rounds_exp)

    def predict_seconds_per_round(self, n: int) -> float:
        return self.spr_coef * float(n) ** self.spr_exp

    def predict_wall_s(self, n: int) -> float:
        """Execution wall (rounds x s/round) — compile is priced by
        :meth:`predict_compile_s` and added by the launch guard, which
        knows whether the process will actually pay it."""
        return self.predict_rounds(n) * self.predict_seconds_per_round(n)

    def predict_compile_s(self, n: int) -> float:
        """The cold-process trace+compile wall (0.0 with no compile
        observations in the basis)."""
        if self.compile_coef is None or self.compile_exp is None:
            return 0.0
        return self.compile_coef * float(n) ** self.compile_exp

    def describe(self, n: int) -> dict:
        return {
            "n_classes": int(n),
            "predicted_rounds": round(self.predict_rounds(n), 1),
            "predicted_s_per_round": round(
                self.predict_seconds_per_round(n), 2
            ),
            "predicted_wall_s": round(self.predict_wall_s(n), 1),
            "predicted_compile_s": round(self.predict_compile_s(n), 1),
            "rounds_fit": [round(self.rounds_coef, 6), round(self.rounds_exp, 4)],
            "spr_fit": [round(self.spr_coef, 10), round(self.spr_exp, 4)],
            "shards": self.shards,
            "mixed_shards": self.mixed_shards,
            "basis": self.basis,
        }

    def as_dict(self) -> dict:
        return {
            "rounds_coef": self.rounds_coef,
            "rounds_exp": self.rounds_exp,
            "spr_coef": self.spr_coef,
            "spr_exp": self.spr_exp,
            "compile_coef": self.compile_coef,
            "compile_exp": self.compile_exp,
            "shards": self.shards,
            "mixed_shards": self.mixed_shards,
            "basis": self.basis,
        }


def fit_cost_model(
    observations: Sequence[ProbeObs], shards: Optional[int] = None
) -> Optional[CostModel]:
    """Fit from executed observations; None when the basis holds no
    executed run at all (a guard without a model must say so, not
    invent numbers).

    ``shards`` selects the mesh dimension: seconds-per-round is a
    per-mesh-shape quantity (an N-shard round and a 1-shard round of
    the same corpus are different programs on different silicon), so a
    launch prediction fits ONLY from observations of the launching
    run's shard count when any exist.  With none matching, the fit
    falls back to the full pool — explicitly marked ``mixed_shards``
    in the model and the launch record, never silently — because a
    cross-mesh extrapolated guard still beats no guard (the SCALE_r05
    failure mode was a hand-waved band, not a mis-dimensioned fit)."""
    ex = [
        o
        for o in observations
        if o.kind == "exec" and o.n and o.rounds and o.wall_s
    ]
    if not ex:
        return None
    mixed = False
    if shards is not None:
        matching = [o for o in ex if o.shards == int(shards)]
        if matching:
            ex = matching
        else:
            mixed = True
    # rounds fit: whole-run totals (a resumed tail's count would
    # under-predict); spr fit: the consistently paired tail rounds/wall
    rounds_coef, rounds_exp = _fit_power(
        [(o.n, o.run_rounds) for o in ex], DEFAULT_ROUNDS_EXP
    )
    spr_coef, spr_exp = _fit_power(
        [(o.n, o.s_per_round) for o in ex], DEFAULT_SPR_EXP
    )
    # the compile fit pools ALL observations that recorded a compile
    # wall (compile-only probes AND split exec records): compile cost
    # is a property of the program roster at a size, not of the mesh
    # selection above
    cpts = [
        (o.n, o.compile_s)
        for o in observations
        if o.n and o.compile_s
    ]
    compile_coef = compile_exp = None
    if cpts:
        compile_coef, compile_exp = _fit_power(cpts, DEFAULT_COMPILE_EXP)
    basis = [
        {
            "source": o.source,
            "n_classes": o.n,
            "rounds": o.run_rounds,
            "s_per_round": round(o.s_per_round, 2),
            "shards": o.shards,
        }
        for o in ex
    ]
    return CostModel(
        rounds_coef, rounds_exp, spr_coef, spr_exp,
        compile_coef=compile_coef, compile_exp=compile_exp,
        basis=basis,
        shards=(None if mixed or shards is None else int(shards)),
        mixed_shards=mixed,
    )


def fit_from_paths(
    paths: Sequence[str], shards: Optional[int] = None
) -> Optional[CostModel]:
    return fit_cost_model(gather_observations(paths), shards=shards)


def guard_launch(
    model: Optional[CostModel],
    n: int,
    budget_s: float,
    force: bool = False,
    warm_artifacts: bool = False,
) -> dict:
    """The launch budget decision: predict the wall from the fitted
    model and decide whether the run fits ``budget_s``.  Returns the
    full decision record (the caller prints it and refuses on
    ``allowed=False``); with no model the launch is allowed but the
    record says the prediction basis was empty.

    ``warm_artifacts``: the launching process consumes an AOT artifact
    farm (or an already-warm registry) covering its roster, so the
    compile wall is ZERO — the fitted compile term is priced out of
    the total instead of over-refusing the launch (the pre-farm bug:
    compile seconds pooled into s/round charged every warm run a cold
    compile per round)."""
    rec = {
        "budget_s": float(budget_s),
        "forced": bool(force),
        "warm_artifacts": bool(warm_artifacts),
    }
    if model is None:
        rec.update(
            allowed=True,
            fits=None,
            reason="no executed observations in the calibration basis",
        )
        return rec
    rec.update(model.describe(n))
    if warm_artifacts:
        rec["predicted_compile_s"] = 0.0
    rec["predicted_total_s"] = round(
        rec["predicted_wall_s"] + rec["predicted_compile_s"], 1
    )
    fits = rec["predicted_total_s"] <= budget_s
    rec["fits"] = fits
    rec["allowed"] = bool(fits or force)
    if not fits:
        rec["reason"] = (
            f"predicted wall {rec['predicted_total_s']:.0f}s "
            f"(exec {rec['predicted_wall_s']:.0f}s + compile "
            f"{rec['predicted_compile_s']:.0f}s) exceeds the "
            f"stage budget {budget_s:.0f}s"
            + (" (forced past the guard)" if force else "; pass --force to override")
        )
    return rec


class OnlineEta:
    """In-flight completion estimate, re-computed every observed round.

    ``eta_s = median(recent round walls) x remaining_rounds``, where
    the remaining-rounds estimate extrapolates the derivation-curve
    tail: EL+ saturation frontiers drain roughly geometrically, so the
    median decay ratio of the recent per-round derivation deltas
    predicts how many more rounds until the frontier empties.  While
    the curve is still growing (ratio >= ~1) the fitted model's
    rounds-vs-size prediction stands in; with neither, the ETA is
    honestly unknown (None, rendered as -1 in gauges)."""

    def __init__(
        self,
        model: Optional[CostModel] = None,
        n: Optional[int] = None,
        window: int = 8,
    ):
        self._model = model
        self._n = n
        self._walls: deque = deque(maxlen=window)
        self._deltas: deque = deque(maxlen=window)
        self.rounds = 0

    def _tail_remaining(self) -> Optional[int]:
        return geometric_tail_remaining(self._deltas)

    def update(
        self, round_wall_s: float, deriv_delta: int
    ) -> Tuple[Optional[float], Optional[int]]:
        """Feed one retired round; returns ``(eta_s, remaining_rounds)``
        (None, None while unknowable)."""
        self.rounds += 1
        if round_wall_s > 0:
            self._walls.append(float(round_wall_s))
        self._deltas.append(int(deriv_delta))
        remaining = self._tail_remaining()
        if remaining is None and self._model is not None and self._n:
            remaining = max(
                1, int(round(self._model.predict_rounds(self._n))) - self.rounds
            )
        if remaining is None or not self._walls:
            return None, remaining
        return statistics.median(self._walls) * remaining, remaining
