"""Fleet flight recorder: a bounded, structured control-plane event log.

Every hard incident on record (the r4/r5 tunnel black-holes, the
SCALE_r05 128k run killed blind, PR 6's intermittent split-brain) was
diagnosed from ad-hoc prints because nothing kept a causal, timestamped
record of what the control plane *decided*.  This is that record: the
router (and each replica) appends one dict per state-changing event —
heartbeat verdicts, ejections, respawns, journal replays, migration
stages with per-stage timing, rebalance proposals, registry
spill/restore/evict — into a ``deque(maxlen=capacity)``, queryable at
``/debug/events``, dumped as JSONL on shutdown, and surfaced by
``cli fleet``.

Events carry a monotonic per-recorder ``seq`` (ordering survives equal
wall-clock stamps) and, when a trace span is active on the recording
thread, the span's ``trace_id`` — so a migration triggered by an admin
request correlates with that request's trace.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import List, Optional

from distel_tpu.obs import trace as _trace


class FlightRecorder:
    """Thread-safe bounded event log.  ``record`` is cheap (dict build +
    deque append under one lock) — safe on heartbeat/migration paths."""

    def __init__(self, capacity: int = 4096, service: str = "distel"):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.service = service
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0

    def record(self, kind: str, **fields) -> dict:
        """Append one event; returns the recorded dict (tests assert on
        it).  ``kind`` is the event type (``eject``, ``migrate_start``,
        ...); ``fields`` are its structured payload."""
        ev = {"kind": kind, "ts": time.time(), "service": self.service}
        sp = _trace.active_span()
        if sp is not None:
            ev["trace_id"] = sp.trace_id
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        return ev

    def events(
        self,
        kind: Optional[str] = None,
        limit: Optional[int] = None,
        **match,
    ) -> List[dict]:
        """Events oldest-first, filtered by ``kind`` and/or exact field
        matches (``oid="ont-0001"``), bounded to the newest ``limit``."""
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        for key, want in match.items():
            out = [e for e in out if e.get(key) == want]
        if limit is not None and limit >= 0:
            # guard limit=0 explicitly: out[-0:] is the WHOLE list
            out = out[-limit:] if limit else []
        return out

    def jsonl(self) -> str:
        lines = [json.dumps(e) for e in self.events()]
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self, path: str) -> int:
        """Write every buffered event as JSONL; returns the count.  The
        shutdown hook — a SIGTERM'd fleet leaves its black box on disk."""
        events = self.events()
        with open(path, "w", encoding="utf-8") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        return len(events)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
