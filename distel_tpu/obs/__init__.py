"""Observability: end-to-end request tracing + the fleet flight
recorder (stdlib only — importable everywhere, off-path when disabled).

Layout::

    trace.py   TraceContext (W3C ``traceparent`` wire form), Span,
               SpanRecorder (bounded ring, config-gated sampling,
               JSONL + Chrome trace-event export — Perfetto-loadable),
               thread-local propagation helpers the serve plane,
               scheduler, registry, and engine round hooks share
    flight.py  FlightRecorder — bounded structured control-plane event
               log (heartbeat verdicts, ejections, migration stages,
               journal replays), queryable at ``/debug/events`` and
               dumped as JSONL on shutdown
    ledger.py  RunLedger — crash-safe append-only JSONL run ledger
               (one record per observed saturation round, plus
               open/snapshot/resume/close chain markers), the
               stall/regression/memory StallWatchdog, the
               ``distel_run_*`` gauge bridge (RUN_EVENTS), and the
               LedgerObserver adapter for ``saturate_observed``
    costmodel.py  fitted rounds-vs-size cost model (seeded from the
               tracked SCALE probe lines + historical ledgers), the
               online ETA, and the launch budget guard

Config knobs (``config.ClassifierConfig`` / ``obs.*`` properties):
``obs.enable``, ``obs.sample_rate``, ``obs.ring.capacity``,
``obs.flight.capacity``, ``obs.ledger.enable``, ``obs.ledger.dir``.
"""

from distel_tpu.obs.flight import FlightRecorder
from distel_tpu.obs.ledger import (
    RUN_EVENTS,
    BudgetExhausted,
    LedgerObserver,
    RunLedger,
    StallWatchdog,
)
from distel_tpu.obs.trace import (
    NOOP,
    Span,
    SpanRecorder,
    TraceContext,
    active_span,
    add_span_event,
    child_span,
    chrome_trace,
    current_context,
)

__all__ = [
    "BudgetExhausted",
    "FlightRecorder",
    "LedgerObserver",
    "NOOP",
    "RUN_EVENTS",
    "RunLedger",
    "StallWatchdog",
    "Span",
    "SpanRecorder",
    "TraceContext",
    "active_span",
    "add_span_event",
    "child_span",
    "chrome_trace",
    "current_context",
]
