"""Run ledger: crash-safe, append-only JSONL telemetry for long
saturation runs (stdlib only).

The SCALE_r05 128k run burned 14h22m and was killed with NO durable
record beyond ad-hoc stdout progress lines.  The ledger is the durable
replacement — one structured record per observed superstep round plus
run-open / snapshot / resume / run-close markers, keyed by the PR 7
``run_id`` / ``chain_run_id`` pair so a chain of resumed sessions reads
as ONE logical run:

``open``      session start: corpus/engine meta, the fitted cost
              model's launch prediction, the stage budget
``resume``    this session continued from a snapshot (names the
              writing session and carries the chain root forward)
``round``     one retired superstep: round index (cumulative across
              the chain), tier/density/rows_touched, per-round and
              cumulative derivations, dispatch/retire host-time split,
              pipeline occupancy, per-rule seconds (latest
              ``STEP_RULE_EVENTS`` capture), host/device memory
              high-water marks, and the online ETA re-stamped fresh
``snapshot``  an atomic resumable snapshot landed on disk
``anomaly``   the stall/regression/memory watchdog fired
``close``     session end with status + predicted-vs-actual scoring
              (a killed session simply lacks one — that absence IS the
              crash record)

Writers append one line per record and flush immediately: a SIGKILL
can tear at most the final line, which :func:`read_ledger` tolerates
(any OTHER malformed line is corruption and fails strict parsing).

:class:`LedgerObserver` adapts the ledger to both engines'
``saturate_observed`` hooks (``observer`` + ``frontier_observer``) —
the scale probes, the serve plane's rebuild path (behind
``obs.ledger.enable``), and anything else running an observed fixed
point feed it the same way.  :data:`RUN_EVENTS` is the process-global
bridge to the serve plane's ``distel_run_*`` gauges and the
``/debug/runs`` endpoint.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

#: record types a valid ledger may carry
_KNOWN_EVENTS = frozenset(
    ("open", "resume", "round", "snapshot", "anomaly", "close")
)


class LedgerCorrupt(ValueError):
    """A ledger line that is neither valid JSON nor the torn final
    line of a killed writer."""


class BudgetExhausted(RuntimeError):
    """Raised out of an observed run when the in-flight stage budget is
    spent — the caller snapshots and exits cleanly instead of being
    killed mid-round hours later."""


# --------------------------------------------------------------- writer


class RunLedger:
    """Append-only JSONL writer for one session of one run chain.
    Thread-safe; every record carries ``run_id``, ``chain_run_id``, a
    monotone per-session ``seq``, and a wall-clock ``ts``."""

    def __init__(
        self,
        path: str,
        run_id: str,
        chain_run_id: Optional[str] = None,
        fsync: bool = False,
    ):
        self.path = path
        self.run_id = run_id
        self.chain_run_id = chain_run_id or run_id
        self._fsync = fsync
        self._lock = threading.Lock()
        self._seq = 0
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._truncate_torn_tail(path)
        self._f = open(path, "a", encoding="utf-8")

    @staticmethod
    def _truncate_torn_tail(path: str) -> None:
        """Drop a predecessor's torn final line before appending.  A
        SIGKILL mid-write leaves a partial line with no trailing
        newline; appending this session's records straight onto it
        would merge them into one garbled MID-file line that fails the
        strict parse.  The fragment was never durable — the reader
        would discard it anyway — so truncate back to the last
        complete line."""
        try:
            with open(path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) == b"\n":
                    return
                # scan back to the last newline (bounded: one record)
                f.seek(0)
                data = f.read()
                keep = data.rfind(b"\n") + 1
                f.truncate(keep)
        except FileNotFoundError:
            return

    def write(self, ev: str, **fields) -> dict:
        doc = {
            "ev": ev,
            "run_id": self.run_id,
            "chain_run_id": self.chain_run_id,
            "ts": round(time.time(), 3),
        }
        doc.update(fields)
        with self._lock:
            self._seq += 1
            doc["seq"] = self._seq
            # serialized under the lock so seq order and file order
            # agree even with concurrent writers
            line = json.dumps(doc)
            self._f.write(line + "\n")
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
        return doc

    # typed record constructors — thin, but they pin the schema
    def open_run(
        self,
        meta: Optional[dict] = None,
        predicted: Optional[dict] = None,
        budget_s: Optional[float] = None,
    ) -> dict:
        fields = {"schema": SCHEMA_VERSION, "meta": meta or {}}
        if predicted is not None:
            fields["predicted"] = predicted
        if budget_s is not None:
            fields["budget_s"] = float(budget_s)
        return self.write("open", **fields)

    def resume(self, **fields) -> dict:
        return self.write("resume", **fields)

    def round(self, **fields) -> dict:
        return self.write("round", **fields)

    def snapshot(self, **fields) -> dict:
        return self.write("snapshot", **fields)

    def anomaly(self, **fields) -> dict:
        return self.write("anomaly", **fields)

    def close_run(self, status: str, **fields) -> dict:
        return self.write("close", status=status, **fields)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# --------------------------------------------------------------- reader


def read_ledger(path: str, strict: bool = True) -> List[dict]:
    """Parse a ledger file.  A torn FINAL line (killed writer) is
    dropped silently; any other malformed line raises
    :class:`LedgerCorrupt` under ``strict`` and is skipped otherwise."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().split("\n")
    # trailing "" from the final newline: every COMPLETE line ends \n
    if lines and lines[-1] == "":
        lines.pop()
        torn_last = False
    else:
        torn_last = True
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            doc = json.loads(line)
            if not isinstance(doc, dict) or "ev" not in doc:
                raise ValueError("not a ledger record")
        except ValueError:
            if torn_last and i == len(lines) - 1:
                continue  # crash artifact, not corruption
            if strict:
                raise LedgerCorrupt(
                    f"{path}:{i + 1}: malformed ledger line: {line[:120]!r}"
                )
            continue
        out.append(doc)
    return out


def chains(records: List[dict]) -> Dict[str, List[dict]]:
    """Group ledger records by ``chain_run_id``, file order preserved."""
    out: Dict[str, List[dict]] = {}
    for rec in records:
        out.setdefault(rec.get("chain_run_id") or "?", []).append(rec)
    return out


def validate_chain(records: List[dict]) -> dict:
    """Structural validation of ONE chain's records (file order):

    * the first record is ``open``; every later session starts with
      its own ``open`` (an ``open`` arriving while the previous
      session never closed marks that predecessor CRASHED — the
      SIGKILL case the ledger exists for — not corruption);
    * round indices are strictly monotone within a session, and a
      session may only rewind PAST a crashed predecessor's tail: a
      kill that lands after the last snapshot leaves rounds the
      resumed session re-derives, and its re-recorded rounds
      SUPERSEDE the crashed tail's (overlap with the same session, or
      with a cleanly closed one, is corruption);
    * ``close`` only ever follows that session's ``open``; nothing but
      a new session's ``open`` follows a ``close``.

    Raises ``ValueError`` on violation; returns a summary dict whose
    round figures count the EFFECTIVE (surviving) rounds."""
    summary, _ = _validate_chain(records)
    return summary


def _validate_chain(records: List[dict]) -> Tuple[dict, List[dict]]:
    """``validate_chain`` plus the effective round records — the
    surviving per-round sequence after crashed-tail supersede (what
    reports, curves, and totals should be computed from)."""
    if not records:
        raise ValueError("empty chain")
    if records[0].get("ev") != "open":
        raise ValueError(
            f"chain must start with an open record, got {records[0].get('ev')!r}"
        )
    # sessions are identified by their POSITION in the chain (which
    # ``open`` they follow), never by run_id — scale_probe's --run-id
    # legitimately pins the same id across resumed sessions
    tagged: List[Tuple[int, dict]] = []  # (session ordinal, round rec)
    session = -1
    open_run: Optional[str] = None
    closed_sessions: set = set()
    closed_runs = crashed_runs = 0
    snapshots = anomalies = 0
    converged = False
    for i, rec in enumerate(records):
        ev = rec.get("ev")
        if ev not in _KNOWN_EVENTS:
            raise ValueError(f"record {i}: unknown event {ev!r}")
        if ev == "open":
            if open_run is not None:
                # the previous session died without a close — exactly
                # what a killed 14h run looks like; the resumed session
                # appending here is the chain working as designed
                crashed_runs += 1
            session += 1
            open_run = rec.get("run_id")
            continue
        if open_run is None:
            raise ValueError(
                f"record {i}: {ev!r} outside any open session"
            )
        if ev == "round":
            idx = rec.get("round")
            if not isinstance(idx, int):
                raise ValueError(f"record {i}: round without an index")
            while tagged and tagged[-1][1]["round"] >= idx:
                prev_sess, prev = tagged[-1]
                if prev_sess == session:
                    raise ValueError(
                        f"record {i}: round index {idx} not monotone "
                        f"(previous {prev['round']})"
                    )
                if prev_sess in closed_sessions:
                    raise ValueError(
                        f"record {i}: round index {idx} not monotone — "
                        f"overlaps round {prev['round']} of cleanly "
                        f"closed session {prev.get('run_id')!r}"
                    )
                # the crashed predecessor recorded past its last
                # snapshot; the resumed session re-derived this round —
                # its record supersedes the crashed tail's
                tagged.pop()
            tagged.append((session, rec))
        elif ev == "snapshot":
            snapshots += 1
        elif ev == "anomaly":
            anomalies += 1
        elif ev == "close":
            closed_runs += 1
            closed_sessions.add(session)
            converged = rec.get("status") == "converged"
            open_run = None
    effective = [rec for _, rec in tagged]
    summary = {
        "runs": sum(1 for r in records if r.get("ev") == "open"),
        "closed_runs": closed_runs,
        "crashed_runs": crashed_runs,
        # one fused-window record covers rounds_in_window retired
        # rounds (ISSUE 17): count retired rounds, not records
        "rounds": sum(
            int(r.get("rounds_in_window") or 1) for r in effective
        ),
        "records": len(effective),
        "last_round": effective[-1]["round"] if effective else -1,
        "snapshots": snapshots,
        "anomalies": anomalies,
        "converged": converged,
        "open_session": open_run,  # non-None = crashed/in-flight tail
    }
    return summary, effective


def report_chain(records: List[dict]) -> dict:
    """The ``cli runs report`` payload for one chain: round count,
    derivation/completeness curve, per-rule share trend, ETA trail, and
    predicted-vs-actual scoring — everything the SCALE_r05 postmortem
    had to reconstruct from stdout scrollback, off one file."""
    summary, rounds = _validate_chain(records)
    # ``rounds`` is the EFFECTIVE sequence (crashed-tail overlap
    # superseded by the resumed session's re-derived records), so the
    # curve stays monotone; the wall accounting below still charges
    # every session its raw recorded elapsed — superseded rounds were
    # genuinely executed
    opens = [r for r in records if r.get("ev") == "open"]
    closes = [r for r in records if r.get("ev") == "close"]
    curve = [
        {
            "round": r.get("round"),
            "derivations_total": r.get("derivations_total"),
            "elapsed_s": r.get("elapsed_s"),
            "eta_s": r.get("eta_s"),
        }
        for r in rounds
    ]
    # per-rule share trend: each round carrying a rule_seconds split
    # contributes its normalized shares; report the mean share per rule
    share_sum: Dict[str, float] = {}
    share_rounds = 0
    for r in rounds:
        rs = r.get("rule_seconds")
        if not rs:
            continue
        total = sum(rs.values())
        if total <= 0:
            continue
        share_rounds += 1
        for rule, secs in rs.items():
            share_sum[rule] = share_sum.get(rule, 0.0) + secs / total
    rule_shares = {
        rule: round(s / share_rounds, 4) for rule, s in share_sum.items()
    } if share_rounds else {}
    # chain wall: sum of per-session walls (sessions may be days apart,
    # so last.ts - first.ts would count the gap the machine sat idle);
    # a crashed session contributes its last recorded round's elapsed.
    # Walked positionally, not by run_id — --run-id may pin one id
    # across every session of the chain.
    wall_s = 0.0
    sess_open = False
    sess_last_elapsed: Optional[float] = None
    for rec in records:
        ev = rec.get("ev")
        if ev == "open":
            if sess_open and sess_last_elapsed:
                wall_s += float(sess_last_elapsed)  # crashed predecessor
            sess_open = True
            sess_last_elapsed = None
        elif ev == "round" and rec.get("elapsed_s"):
            sess_last_elapsed = rec["elapsed_s"]
        elif ev == "close":
            if rec.get("wall_s"):
                wall_s += float(rec["wall_s"])
            elif sess_last_elapsed:
                wall_s += float(sess_last_elapsed)
            sess_open = False
            sess_last_elapsed = None
    if sess_open and sess_last_elapsed:
        wall_s += float(sess_last_elapsed)  # in-flight/crashed tail
    out = {
        **summary,
        "chain_run_id": records[0].get("chain_run_id"),
        "derivations_total": (
            rounds[-1].get("derivations_total") if rounds else 0
        ),
        "wall_s": round(wall_s, 1),
        "curve": curve,
        "rule_shares": rule_shares,
        "tiers": {
            t: sum(1 for r in rounds if r.get("tier") == t)
            for t in sorted({r.get("tier") for r in rounds if r.get("tier")})
        },
    }
    # launch-prediction scoring: the FIRST session's predicted wall vs
    # the measured chain wall
    pred = opens[0].get("predicted") if opens else None
    if pred and pred.get("predicted_wall_s") and wall_s > 0:
        out["launch_prediction"] = {
            "predicted_wall_s": pred["predicted_wall_s"],
            "actual_wall_s": round(wall_s, 1),
            "error": round(
                (pred["predicted_wall_s"] - wall_s) / wall_s, 3
            ),
        }
    # final ETA scoring: the last mid-run ETA stamp vs what the rest of
    # the run actually took (closes re-score it; crashed chains keep
    # the raw trail)
    scored = [c.get("eta_final") for c in closes if c.get("eta_final")]
    if scored:
        out["eta_final"] = scored[-1]
    return out


# ------------------------------------------------ process-global gauges


class RunTelemetry:
    """Process-global run telemetry: the newest live run's per-round
    figures (the ``distel_run_*`` gauge family samples them) plus a
    bounded per-run summary table behind ``/debug/runs``.  Thread-safe:
    serve rebuilds on scheduler workers and probe scripts both feed
    it."""

    _GAUGE_DEFAULTS = {
        "round": 0.0,
        "derivation_rate": 0.0,
        "eta_s": -1.0,
        "budget_remaining_s": -1.0,
        "stall": 0.0,
    }

    def __init__(self, capacity: int = 32):
        self._lock = threading.Lock()
        self._runs: "deque[dict]" = deque(maxlen=capacity)
        self._by_id: Dict[str, dict] = {}
        self._last: Dict[str, float] = dict(self._GAUGE_DEFAULTS)
        #: the run whose figures the gauges sample — the newest LIVE
        #: run; an older concurrent run's update/end must not clobber
        self._live_id: Optional[str] = None

    def begin(
        self, run_id: str, chain_run_id: str = "", meta: Optional[dict] = None
    ) -> None:
        rec = {
            "run_id": run_id,
            "chain_run_id": chain_run_id or run_id,
            "status": "running",
            "started_unix": round(time.time(), 3),
            "meta": dict(meta or {}),
            **self._GAUGE_DEFAULTS,
        }
        with self._lock:
            if run_id in self._by_id:
                self._runs.remove(self._by_id[run_id])
            self._runs.append(rec)
            # deque eviction: rebuild the id map from what survived
            self._by_id = {r["run_id"]: r for r in self._runs}
            self._live_id = run_id
            self._last = {k: rec[k] for k in self._GAUGE_DEFAULTS}

    def update(self, run_id: str, **fields) -> None:
        with self._lock:
            rec = self._by_id.get(run_id)
            if rec is None:
                return
            for k, v in fields.items():
                rec[k] = v
            if run_id == self._live_id:
                self._last = {
                    k: float(rec.get(k, d) if rec.get(k) is not None else d)
                    for k, d in self._GAUGE_DEFAULTS.items()
                }

    def end(self, run_id: str, status: str) -> None:
        with self._lock:
            rec = self._by_id.get(run_id)
            if rec is not None:
                rec["status"] = status
                rec["ended_unix"] = round(time.time(), 3)
            if run_id == self._live_id:
                self._live_id = None
                self._last = dict(self._GAUGE_DEFAULTS)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return {f"distel_run_{k}": v for k, v in self._last.items()}

    def runs(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._runs]


RUN_EVENTS = RunTelemetry()


# ------------------------------------------------------------- watchdog


class StallWatchdog:
    """Per-run anomaly detector fed once per retired round:

    * ``stall`` — ``stall_rounds`` consecutive non-terminal rounds
      deriving nothing (the engine claims progress, the closure says
      otherwise);
    * ``round_wall_regression`` — a round costing more than
      ``wall_factor`` x the rolling median (tier mis-selection, host
      contention, a tunnel starting to black-hole);
    * ``memory_growth`` — the host peak-RSS high-water mark rising for
      ``mem_rounds`` consecutive rounds (a steady-state fixed point
      should plateau; monotone growth ends in the OOM killer).

    Each detection is written to the ledger, mirrored to an optional
    flight recorder, and raised as the ``distel_run_stall`` gauge;
    detections re-arm only after the condition clears, so a long stall
    is one anomaly record, not thousands."""

    def __init__(
        self,
        ledger: Optional[RunLedger] = None,
        flight=None,
        telemetry: Optional[RunTelemetry] = None,
        run_id: str = "",
        stall_rounds: int = 3,
        wall_factor: float = 4.0,
        min_median_s: float = 0.05,
        mem_rounds: int = 8,
        window: int = 16,
    ):
        self._ledger = ledger
        self._flight = flight
        self._telemetry = telemetry
        self._run_id = run_id
        self.stall_rounds = max(int(stall_rounds), 1)
        self.wall_factor = float(wall_factor)
        #: rolling medians below this never flag a regression — a
        #: sub-50ms sparse round followed by a dense round is a tier
        #: interleave, not a regression (the detector exists for the
        #: 40-MINUTE rounds of SCALE_r05, not microbenchmarks)
        self.min_median_s = float(min_median_s)
        self.mem_rounds = max(int(mem_rounds), 2)
        self._walls: deque = deque(maxlen=window)
        self._zero_streak = 0
        self._mem_streak = 0
        self._last_mem: Optional[float] = None
        self._active: set = set()
        self.stalled = False

    def _emit(self, kind: str, round_idx: int, **fields) -> dict:
        ev = {"anomaly": kind, "round": round_idx, **fields}
        if self._ledger is not None:
            self._ledger.anomaly(**ev)
        if self._flight is not None:
            self._flight.record("run_anomaly", run_id=self._run_id, **ev)
        return ev

    def observe(
        self,
        round_idx: int,
        deriv_delta: int,
        changed: bool,
        round_wall_s: float,
        host_mb: Optional[float] = None,
    ) -> List[dict]:
        fired: List[dict] = []
        # ---- non-terminal zero-derivation stall
        if changed and deriv_delta == 0:
            self._zero_streak += 1
        else:
            self._zero_streak = 0
            self._active.discard("stall")
        if (
            self._zero_streak >= self.stall_rounds
            and "stall" not in self._active
        ):
            self._active.add("stall")
            fired.append(
                self._emit(
                    "stall", round_idx, zero_rounds=self._zero_streak
                )
            )
        self.stalled = "stall" in self._active
        # ---- round-wall regression vs the rolling median
        if len(self._walls) >= 3 and round_wall_s > 0:
            import statistics

            med = statistics.median(self._walls)
            if (
                med >= self.min_median_s
                and round_wall_s > self.wall_factor * med
            ):
                if "wall" not in self._active:
                    self._active.add("wall")
                    fired.append(
                        self._emit(
                            "round_wall_regression",
                            round_idx,
                            round_wall_s=round(round_wall_s, 3),
                            rolling_median_s=round(med, 3),
                            factor=round(round_wall_s / med, 1),
                        )
                    )
            else:
                self._active.discard("wall")
        if round_wall_s > 0:
            self._walls.append(round_wall_s)
        # ---- monotone host-memory growth (peak RSS keeps climbing)
        if host_mb is not None:
            if self._last_mem is not None and host_mb > self._last_mem:
                self._mem_streak += 1
            elif self._last_mem is not None:
                self._mem_streak = 0
                self._active.discard("mem")
            self._last_mem = host_mb
            if (
                self._mem_streak >= self.mem_rounds
                and "mem" not in self._active
            ):
                self._active.add("mem")
                fired.append(
                    self._emit(
                        "memory_growth",
                        round_idx,
                        host_mb=round(host_mb, 1),
                        growth_rounds=self._mem_streak,
                    )
                )
        if self._telemetry is not None:
            self._telemetry.update(
                self._run_id, stall=1.0 if self.stalled else 0.0
            )
        return fired


# ------------------------------------------------ memory high-water marks


def host_peak_mb() -> Optional[float]:
    """Host peak RSS in MiB (``ru_maxrss`` — kilobytes on Linux, bytes
    on macOS); None where the resource module is unavailable."""
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        if sys.platform == "darwin":
            return peak / (1 << 20)
        return peak / 1024.0
    except Exception:
        return None


def device_peak_mb() -> Optional[float]:
    """Accelerator peak bytes in use, when the backend reports memory
    stats (TPU/GPU; the CPU backend answers None).  Lazy jax import so
    the obs package stays stdlib-importable."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats:
            peak = stats.get("peak_bytes_in_use") or stats.get(
                "bytes_in_use"
            )
            if peak is not None:
                return float(peak) / (1 << 20)
    except Exception:
        pass
    return None


# ------------------------------------------ the saturate_observed adapter


class LedgerObserver:
    """Bundles the ``observer`` / ``frontier_observer`` callback pair
    both engines' ``saturate_observed`` accepts into one ledger-writing
    unit: per retired round it assembles the full round record (tier
    telemetry when the adaptive controller supplies it, per-rule
    seconds from the latest ``STEP_RULE_EVENTS`` capture, memory
    high-water marks, the freshly re-stamped online ETA), appends it,
    feeds the watchdog, and updates :data:`RUN_EVENTS`.

    ``budget_s``: in-flight stage budget — once total elapsed exceeds
    it the observer raises :class:`BudgetExhausted` AFTER recording the
    round (callers with a ``state_observer`` snapshot first; see
    ``scripts/scale_probe.py``).  The per-round cost is one dict build
    + one flushed line write — measured <=1% of a warm classify's wall
    (the acceptance bound this module ships under)."""

    def __init__(
        self,
        ledger: RunLedger,
        *,
        model=None,
        n_for_model: Optional[int] = None,
        budget_s: Optional[float] = None,
        budget_spent_s: float = 0.0,
        base_iters: int = 0,
        base_derivs: int = 0,
        flight=None,
        telemetry: Optional[RunTelemetry] = RUN_EVENTS,
        watchdog: Optional[StallWatchdog] = None,
        track_device_mem: bool = True,
        raise_on_budget: bool = True,
    ):
        from distel_tpu.obs.costmodel import OnlineEta

        self.ledger = ledger
        self.base_iters = int(base_iters)
        self.base_derivs = int(base_derivs)
        self.budget_s = budget_s
        self._budget_spent = float(budget_spent_s)
        self._raise_on_budget = raise_on_budget
        self.budget_exhausted = False
        self._telemetry = telemetry
        self._track_device_mem = track_device_mem
        self._eta = OnlineEta(model=model, n=n_for_model)
        self.watchdog = (
            watchdog
            if watchdog is not None
            else StallWatchdog(
                ledger=ledger,
                flight=flight,
                telemetry=telemetry,
                run_id=ledger.run_id,
            )
        )
        self._t0 = time.perf_counter()
        self._last_t = self._t0
        self._prev_derivs = 0
        self._win_rounds = 0  # fused-window accumulation (ISSUE 17)
        self._win_delta = 0
        self._rule_captures = -1
        self._rule_seconds: Optional[dict] = None
        self._st = None  # FrontierStats stash (rowpacked engines only)
        self.last_eta_s: Optional[float] = None
        self.last_elapsed_s = 0.0
        self.last_iteration = 0
        self.last_derivations = 0
        self.rounds = 0
        if telemetry is not None:
            telemetry.begin(
                ledger.run_id,
                chain_run_id=ledger.chain_run_id,
                meta={"ledger": ledger.path},
            )

    # the two callables saturate_observed takes; frontier_observer runs
    # first for a given iteration in both controllers
    def frontier_observer(self, st) -> None:
        self._st = st

    def _rule_split(self) -> Optional[dict]:
        """Latest per-rule per-step seconds, refreshed only when a new
        profiling capture landed (the snapshot costs a lock)."""
        try:
            from distel_tpu.runtime.instrumentation import STEP_RULE_EVENTS
        except Exception:
            return None
        snap = STEP_RULE_EVENTS.snapshot()
        if snap["captures"] != self._rule_captures:
            self._rule_captures = snap["captures"]
            self._rule_seconds = (
                {k: round(v, 6) for k, v in snap["per_rule"].items()}
                if snap["per_rule"]
                else None
            )
        return self._rule_seconds

    def observer(self, iteration: int, derivations: int, changed: bool):
        now = time.perf_counter()
        st = self._st
        riw = (
            int(getattr(st, "rounds_in_window", 1) or 1)
            if st is not None and st.iteration == iteration
            else 1
        )
        self.rounds += 1
        delta = int(derivations) - self._prev_derivs
        self._prev_derivs = int(derivations)
        self.last_iteration = int(iteration)
        self.last_derivations = int(derivations)
        if riw > 1:
            # fused window (ISSUE 17): this round surfaced together
            # with its window-mates — ONE ledger record per surfaced
            # window, written at the window's last round, carrying the
            # whole window wall plus ``rounds_in_window`` so readers
            # divide instead of fitting window walls as round walls.
            # The ETA still sees every retired round: the device-
            # honest per-round wall rides in the FrontierStats.
            eta_s, remaining = self._eta.update(
                float(getattr(st, "wall_s", 0.0) or 0.0), delta
            )
            self.last_eta_s = eta_s
            self._win_rounds += 1
            self._win_delta += delta
            if self._win_rounds < riw:
                return
            delta = self._win_delta
        self._win_rounds = 0
        self._win_delta = 0
        round_wall = now - self._last_t
        self._last_t = now
        elapsed = now - self._t0
        self.last_elapsed_s = elapsed
        round_total = self.base_iters + int(iteration)
        if riw == 1:
            eta_s, remaining = self._eta.update(round_wall, delta)
            self.last_eta_s = eta_s
        host_mb = host_peak_mb()
        fields = {
            "round": round_total,
            "iteration": int(iteration),
            "derivations": delta,
            "derivations_total": self.base_derivs + int(derivations),
            "changed": bool(changed),
            "round_wall_s": round(round_wall, 4),
            "rounds_in_window": riw,
            "elapsed_s": round(elapsed, 3),
        }
        if st is not None and st.iteration == iteration:
            fields.update(
                tier=st.tier,
                density=round(st.density, 5),
                rows_touched=st.rows_touched,
                dispatch_s=round(st.dispatch_s, 4),
                retire_s=round(st.retire_s, 4),
                inflight=st.inflight,
            )
        if eta_s is not None:
            fields["eta_s"] = round(eta_s, 1)
            fields["eta_rounds_remaining"] = remaining
        if host_mb is not None:
            fields["host_mb"] = round(host_mb, 1)
        if self._track_device_mem:
            dev_mb = device_peak_mb()
            if dev_mb is not None:
                fields["device_mb"] = round(dev_mb, 1)
        rule_seconds = self._rule_split()
        if rule_seconds:
            fields["rule_seconds"] = rule_seconds
        budget_remaining = None
        if self.budget_s is not None:
            budget_remaining = self.budget_s - self._budget_spent - elapsed
            fields["budget_remaining_s"] = round(budget_remaining, 1)
        self.watchdog.observe(
            round_total, delta, bool(changed), round_wall, host_mb
        )
        self.ledger.round(**fields)
        if self._telemetry is not None:
            self._telemetry.update(
                self.ledger.run_id,
                round=float(round_total),
                derivation_rate=(
                    delta / round_wall if round_wall > 0 else 0.0
                ),
                eta_s=eta_s,
                budget_remaining_s=budget_remaining,
            )
        if (
            budget_remaining is not None
            and budget_remaining <= 0
            and changed
        ):
            # ``raise_on_budget=False`` only FLAGS here: callers with a
            # state_observer persist this round's snapshot first, then
            # raise themselves (the observer runs before the
            # state_observer in both engines' loops)
            self.budget_exhausted = True
            if self._raise_on_budget:
                raise BudgetExhausted(
                    f"stage budget {self.budget_s:.0f}s exhausted at "
                    f"round {round_total} ({elapsed:.0f}s this session)"
                )

    def close(self, status: str, **fields) -> dict:
        """Write the close record, scoring the last in-flight ETA
        against what the tail actually took."""
        elapsed = time.perf_counter() - self._t0
        doc = {
            "iterations": self.rounds,
            "wall_s": round(elapsed, 3),
            **fields,
        }
        if self.last_eta_s is not None:
            # the ETA stamped at the LAST round predicted the remaining
            # tail; with the run over, the truth of that tail is known
            actual_tail = elapsed - self.last_elapsed_s
            doc["eta_final"] = {
                "predicted_tail_s": round(self.last_eta_s, 1),
                "actual_tail_s": round(actual_tail, 1),
                "error_s": round(self.last_eta_s - actual_tail, 1),
            }
        rec = self.ledger.close_run(status, **doc)
        if self._telemetry is not None:
            self._telemetry.end(self.ledger.run_id, status)
        return rec


# ------------------------------------------------ serve-plane integration

_REBUILD_SEQ = [0]
_REBUILD_LOCK = threading.Lock()


def rebuild_ledger_observer(config, meta: Optional[dict] = None):
    """The serve/classify rebuild path's ledger hook (behind the
    ``obs.ledger.enable`` knob): opens (or appends to) the per-process
    rebuild ledger under ``obs.ledger.dir`` and returns a
    :class:`LedgerObserver` whose ``close()`` the caller owes after
    the run.  Returns None when the knob is off or the dir is
    unwritable (telemetry must never fail a classify)."""
    if not getattr(config, "obs_ledger", False):
        return None
    try:
        with _REBUILD_LOCK:
            _REBUILD_SEQ[0] += 1
            seq = _REBUILD_SEQ[0]
        run_id = "rebuild-{}-{:x}-{}".format(
            time.strftime("%Y%m%dT%H%M%S"), os.getpid(), seq
        )
        path = os.path.join(
            config.obs_ledger_dir or "runs",
            "rebuild-{:x}.ledger.jsonl".format(os.getpid()),
        )
        ledger = RunLedger(path, run_id)
        ledger.open_run(meta=meta or {})
        return LedgerObserver(ledger, track_device_mem=False)
    except OSError:
        return None
