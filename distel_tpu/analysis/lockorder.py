"""Rule 1: static lock-order analysis.

Builds the lock-acquisition graph of the serve/obs planes from
``with <lock>:`` nesting and intra-package call edges, then reports

* ``lock-order-cycle`` — a cycle in the acquired-while-holding graph:
  two schedules can acquire the same locks in opposite orders, i.e. a
  deadlock a test schedule may never hit;
* ``lock-order-cross-module`` — a lock acquired while holding a lock
  that lives in a different module.  Not a bug by itself, but every
  such edge is a standing constraint on the callee module ("never call
  back into the holder") that nothing else records — the committed
  baseline is where each one carries its justification.

Lock identity is ``Class.attr`` (one id per allocation role, like the
runtime lockdep's allocation-site classes in
:mod:`distel_tpu.testing.lockdep` — the static and runtime views name
locks compatibly).  The ``"caller holds ``x.lock``"`` docstring
convention marks helper functions whose callers hold a lock; the rule
treats those locks as held at entry, so the helper's acquisitions
order after them exactly as at runtime.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from distel_tpu.analysis.findings import Finding
from distel_tpu.analysis.project import (
    ClassInfo,
    Module,
    Project,
    caller_holds_tokens,
)

RULE_CYCLE = "lock-order-cycle"
RULE_CROSS = "lock-order-cross-module"


@dataclass
class _Edge:
    held: str
    acquired: str
    path: str
    line: int
    via: str  # function chain that witnessed the edge


@dataclass
class _FuncFacts:
    qualid: str
    path: str
    cls: Optional[ClassInfo]
    entry_held: FrozenSet[str] = frozenset()
    #: blocking acquisitions made directly in this function
    acquires: Set[str] = field(default_factory=set)
    #: (held-set, callee-spec, line)
    calls: List[Tuple[FrozenSet[str], "_CallSpec", int]] = field(
        default_factory=list
    )
    edges: List[_Edge] = field(default_factory=list)


@dataclass(frozen=True)
class _CallSpec:
    kind: str  # "self" | "module" | "attr" | "name"
    name: str  # method/function name
    extra: str = ""  # receiver attr / module alias


class _LockResolver:
    """Maps a ``with``-context / ``.acquire()`` receiver expression to
    a stable lock id (``Class.attr``) or None."""

    def __init__(self, project: Project, module: Module,
                 cls: Optional[ClassInfo]):
        self.project = project
        self.module = module
        self.cls = cls

    def resolve(self, node: ast.expr) -> Optional[str]:
        if not isinstance(node, ast.Attribute):
            return None
        attr = node.attr
        recv = node.value
        # self.X
        if isinstance(recv, ast.Name) and recv.id == "self" and self.cls:
            if attr in self.cls.lock_attrs:
                return f"{self.cls.name}.{attr}"
            return None
        # self.A.B → type(A).B when the ctor typed A; else fall through
        # to the unique-lock-attr match below
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and self.cls is not None
        ):
            tname = self.cls.attr_types.get(recv.attr)
            if tname:
                ci = self.project.find_class(tname)
                if ci and attr in ci.lock_attrs:
                    return f"{ci.name}.{attr}"
        # var.X / self.A.X: unique class carrying lock attr X wins
        owners = self.project.classes_with_lock_attr(attr)
        if len(owners) == 1:
            return f"{owners[0].name}.{attr}"
        return None

    def resolve_token(self, token: str) -> Optional[str]:
        """Docstring token (``entry.lock`` / ``self._lock``) → lock id."""
        parts = token.split(".")
        attr = parts[-1]
        if len(parts) >= 2 and parts[-2] == "self" and self.cls:
            if attr in self.cls.lock_attrs:
                return f"{self.cls.name}.{attr}"
            return None
        owners = self.project.classes_with_lock_attr(attr)
        if len(owners) == 1:
            return f"{owners[0].name}.{attr}"
        if self.cls and attr in self.cls.lock_attrs:
            return f"{self.cls.name}.{attr}"
        return None


def _entry_held(fn: ast.FunctionDef, resolver: _LockResolver) -> FrozenSet[str]:
    held: Set[str] = set()
    for token in caller_holds_tokens(fn):
        lid = resolver.resolve_token(token)
        if lid:
            held.add(lid)
    return frozenset(held)


class _FuncWalker(ast.NodeVisitor):
    """Walks one function body tracking the held-lock stack."""

    def __init__(self, facts: _FuncFacts, resolver: _LockResolver):
        self.facts = facts
        self.resolver = resolver
        self.held: List[str] = list(facts.entry_held)

    # ------------------------------------------------------- helpers

    def _record_acquire(self, lid: str, line: int, blocking: bool) -> None:
        if blocking:
            self.facts.acquires.add(lid)
            for h in self.held:
                if h != lid:
                    self.facts.edges.append(
                        _Edge(h, lid, self.facts.path, line,
                              self.facts.qualid)
                    )

    # -------------------------------------------------------- visits

    def visit_With(self, node: ast.With) -> None:
        self._with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._with(node)

    def _with(self, node) -> None:
        base = len(self.held)
        pushed = 0
        for item in node.items:
            lid = self.resolver.resolve(item.context_expr)
            if lid is not None:
                self._record_acquire(lid, node.lineno, blocking=True)
                self.held.append(lid)
                pushed += 1
            else:
                # a non-lock context manager may still contain calls
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        # remove exactly the with-pushed slice: a bare .acquire()
        # inside the body appends PAST it and legitimately outlives
        # the with — popping positionally would strip the wrong locks
        del self.held[base:base + pushed]

    def visit_Call(self, node: ast.Call) -> None:
        spec = self._callee(node)
        if spec is not None and spec.name in ("acquire", "release"):
            recv = node.func.value if isinstance(
                node.func, ast.Attribute
            ) else None
            lid = self.resolver.resolve(recv) if recv is not None else None
            if lid is not None:
                if spec.name == "acquire":
                    blocking = True
                    if node.args and isinstance(
                        node.args[0], ast.Constant
                    ):
                        blocking = bool(node.args[0].value)
                    for kw in node.keywords:
                        if kw.arg == "blocking" and isinstance(
                            kw.value, ast.Constant
                        ):
                            blocking = bool(kw.value.value)
                    self._record_acquire(lid, node.lineno, blocking)
                    self.held.append(lid)
                else:
                    if lid in self.held:
                        # remove the innermost occurrence
                        for i in range(len(self.held) - 1, -1, -1):
                            if self.held[i] == lid:
                                del self.held[i]
                                break
                self.generic_visit(node)
                return
        if spec is not None:
            self.facts.calls.append(
                (frozenset(self.held), spec, node.lineno)
            )
        self.generic_visit(node)

    def _callee(self, node: ast.Call) -> Optional[_CallSpec]:
        fn = node.func
        if isinstance(fn, ast.Name):
            return _CallSpec("name", fn.id)
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                return _CallSpec("self", fn.attr)
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
            ):
                return _CallSpec("attr", fn.attr, recv.attr)
            if isinstance(recv, ast.Name):
                return _CallSpec("name_attr", fn.attr, recv.id)
        return None

    # nested defs/lambdas run later, under unknown locks — skip them
    def visit_FunctionDef(self, node) -> None:  # noqa: D102
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:  # noqa: D102
        pass


def _collect_facts(project: Project, paths: List[str]) -> Dict[str, _FuncFacts]:
    facts: Dict[str, _FuncFacts] = {}
    for path in paths:
        module = project.modules[path]
        for cls in module.classes.values():
            for mname, fn in cls.methods.items():
                resolver = _LockResolver(project, module, cls)
                qualid = f"{cls.name}.{mname}"
                ff = _FuncFacts(qualid, path, cls)
                ff.entry_held = _entry_held(fn, resolver)
                walker = _FuncWalker(ff, resolver)
                for stmt in fn.body:
                    walker.visit(stmt)
                facts[qualid] = ff
        for fname, fn in module.functions.items():
            resolver = _LockResolver(project, module, None)
            qualid = f"{path}::{fname}"
            ff = _FuncFacts(qualid, path, None)
            ff.entry_held = _entry_held(fn, resolver)
            walker = _FuncWalker(ff, resolver)
            for stmt in fn.body:
                walker.visit(stmt)
            facts[qualid] = ff
    return facts


def _resolve_call(
    project: Project,
    facts: Dict[str, _FuncFacts],
    caller: _FuncFacts,
    spec: _CallSpec,
) -> Optional[str]:
    if spec.kind == "self" and caller.cls is not None:
        qid = f"{caller.cls.name}.{spec.name}"
        return qid if qid in facts else None
    if spec.kind == "name":
        qid = f"{caller.path}::{spec.name}"
        return qid if qid in facts else None
    if spec.kind == "attr" and caller.cls is not None:
        tname = caller.cls.attr_types.get(spec.extra)
        if tname:
            qid = f"{tname}.{spec.name}"
            if qid in facts:
                return qid
    if spec.kind in ("attr", "name_attr"):
        # unique method name across analyzed classes
        owners = [
            cis[0].name
            for cis in project.classes_by_name.values()
            if len(cis) == 1 and spec.name in cis[0].methods
        ]
        candidates = [
            f"{c}.{spec.name}" for c in owners if f"{c}.{spec.name}" in facts
        ]
        if len(candidates) == 1:
            return candidates[0]
    return None


def _lock_module(project: Project, lock_id: str) -> str:
    cls_name = lock_id.split(".", 1)[0]
    ci = project.find_class(cls_name)
    return ci.module if ci is not None else "?"


def check(project: Project, paths: Optional[List[str]] = None) -> List[Finding]:
    if paths is None:
        paths = sorted(project.modules)
    paths = [p for p in paths if p in project.modules]
    facts = _collect_facts(project, paths)

    # transitive blocking acquisitions per function (fixpoint)
    eff: Dict[str, Set[str]] = {q: set(f.acquires) for q, f in facts.items()}
    resolved: Dict[Tuple[str, int], Optional[str]] = {}
    changed = True
    guard = 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for qid, ff in facts.items():
            for i, (_held, spec, _line) in enumerate(ff.calls):
                key = (qid, i)
                if key not in resolved:
                    resolved[key] = _resolve_call(project, facts, ff, spec)
                callee = resolved[key]
                if callee is None:
                    continue
                add = eff[callee] - eff[qid]
                if add:
                    eff[qid] |= add
                    changed = True

    # edge set: direct nesting + held × callee's effective acquisitions
    edges: Dict[Tuple[str, str], _Edge] = {}
    for qid, ff in facts.items():
        for e in ff.edges:
            edges.setdefault((e.held, e.acquired), e)
        for i, (held, spec, line) in enumerate(ff.calls):
            callee = resolved.get((qid, i))
            if callee is None or not held:
                continue
            for acq in eff[callee]:
                for h in held:
                    if h != acq:
                        edges.setdefault(
                            (h, acq),
                            _Edge(h, acq, ff.path, line,
                                  f"{qid} -> {callee}"),
                        )

    findings: List[Finding] = []

    # ---- cycles: DFS over the edge graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    seen_cycles: Set[FrozenSet[str]] = set()

    def _find_cycle_from(start: str) -> Optional[List[str]]:
        stack = [(start, [start])]
        visited: Set[str] = set()
        while stack:
            node, trail = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    return trail
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, trail + [nxt]))
        return None

    for node in sorted(graph):
        cyc = _find_cycle_from(node)
        if cyc is None:
            continue
        key = frozenset(cyc)
        if key in seen_cycles:
            continue
        seen_cycles.add(key)
        # identity (symbol/message/path) built ONLY from the sorted
        # lock set — witness call chains and DFS orderings are
        # unstable under unrelated refactors and live in `note`
        ordered = sorted(cyc)
        witness = edges.get(
            (cyc[0], cyc[1 % len(cyc)])
        ) or next(iter(edges.values()))
        findings.append(
            Finding(
                rule=RULE_CYCLE,
                path=_lock_module(project, ordered[0]),
                line=witness.line,
                symbol=" <-> ".join(ordered),
                message=(
                    "lock-order cycle among "
                    + ", ".join(ordered)
                    + " — two schedules can acquire these in opposite"
                    " orders and deadlock"
                ),
                note="one witness order: "
                + " -> ".join(cyc + [cyc[0]])
                + f" via {witness.via}",
            )
        )

    # ---- cross-module acquire-while-holding
    for (a, b), e in sorted(edges.items()):
        ma, mb = _lock_module(project, a), _lock_module(project, b)
        if ma == mb or "?" in (ma, mb):
            continue
        findings.append(
            Finding(
                rule=RULE_CROSS,
                # anchor to the HELD lock's defining module — stable
                # regardless of which call site witnessed the edge
                path=ma,
                line=e.line,
                symbol=f"{a} -> {b}",
                message=(
                    f"{b} ({mb}) is acquired while holding {a} ({ma}); "
                    f"{mb} must never call back under {a}"
                ),
                note=f"witness: {e.via} at {e.path}:{e.line}",
            )
        )
    return findings
