"""Rule 4: config-knob drift.

Three planes must agree about every knob: the ``ClassifierConfig``
dataclass field, the ``from_properties`` java-properties key, and the
README documentation.  They drift independently (a knob lands with its
PR, the properties key follows, the docs never do), and the failure
modes are silent: a dead field nobody reads, a documented spelling that
parses to nothing, a properties key that sets a field that no longer
exists.

Findings:

* ``knob-dead`` — a config field no code ever reads (outside its
  definition and the properties parser);
* ``knob-undocumented`` — a properties key README never mentions (an
  operator cannot discover it);
* ``knob-misspelled`` — a ``from_properties`` branch that tests a key
  but assigns no known field, or assigns a field the dataclass does
  not define (the classic silent-typo: the key parses, nothing
  changes).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from distel_tpu.analysis.findings import Finding
from distel_tpu.analysis.project import Project

RULE_DEAD = "knob-dead"
RULE_UNDOC = "knob-undocumented"
RULE_MISSPELLED = "knob-misspelled"

#: properties-key prefixes handled dynamically (``backend.CR1 = tpu``)
_DYNAMIC_KEY_PREFIXES = ("backend.",)


def _config_class(project: Project, config_path: str):
    mod = project.modules.get(config_path)
    if mod is None:
        return None
    for cls in mod.classes.values():
        if "Config" in cls.name:
            return cls
    return None


def _fields(cls) -> Dict[str, int]:
    """Dataclass field name → definition line."""
    out: Dict[str, int] = {}
    for item in cls.node.body:
        if isinstance(item, ast.AnnAssign) and isinstance(
            item.target, ast.Name
        ):
            out[item.target.id] = item.lineno
    return out


def _properties_map(cls) -> List[Tuple[str, Optional[str], int]]:
    """(properties key, assigned field | None, line) triples from the
    ``from_properties`` parser.  A branch that tests several keys
    (reference spellings) yields one triple per key."""
    fn = cls.methods.get("from_properties")
    if fn is None:
        return []
    out: List[Tuple[str, Optional[str], int]] = []

    def keys_of(test: ast.expr) -> List[str]:
        ks = []
        for sub in ast.walk(test):
            if (
                isinstance(sub, ast.Compare)
                and isinstance(sub.left, ast.Constant)
                and isinstance(sub.left.value, str)
                and any(isinstance(op, ast.In) for op in sub.ops)
            ):
                ks.append(sub.left.value)
        return ks

    def fields_of(body) -> List[Tuple[Optional[str], int]]:
        fs = []
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "cfg"
                        ):
                            fs.append((tgt.attr, tgt.lineno))
        return fs

    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                branches = [(stmt.test, stmt.body)]
                node = stmt
                while (
                    len(node.orelse) == 1
                    and isinstance(node.orelse[0], ast.If)
                ):
                    node = node.orelse[0]
                    branches.append((node.test, node.body))
                tail = node.orelse
                for test, body in branches:
                    ks = keys_of(test)
                    fs = fields_of(body)
                    for k in ks:
                        if fs:
                            for fname, line in fs:
                                out.append((k, fname, line))
                        else:
                            out.append((k, None, test.lineno))
                    walk(body)
                walk(tail)
            elif isinstance(stmt, ast.For):
                # `for key in ("a", "b"):  if key in raw:` — the
                # multi-spelling loop: every constant in the iterable
                # is a key for the loop body's cfg assignment
                ks = [
                    sub.value
                    for sub in ast.walk(stmt.iter)
                    if isinstance(sub, ast.Constant)
                    and isinstance(sub.value, str)
                    and not sub.value.startswith(_DYNAMIC_KEY_PREFIXES)
                ]
                fs = fields_of(stmt.body)
                for k in ks:
                    for fname, line in fs:
                        out.append((k, fname, line))

    walk(fn.body)
    # drop duplicate (key, field) pairs, keep first line
    seen: Set[Tuple[str, Optional[str]]] = set()
    uniq = []
    for k, f, line in out:
        if (k, f) not in seen:
            seen.add((k, f))
            uniq.append((k, f, line))
    return uniq


def _attribute_reads(project: Project,
                     field_names: Set[str]) -> Dict[str, int]:
    """field → count of attribute LOADS across the project (any
    receiver), excluding the parser's ``cfg.x = ...`` stores and the
    dataclass definition."""
    counts = {f: 0 for f in field_names}
    for path, mod in project.modules.items():
        for sub in ast.walk(mod.tree):
            if not isinstance(sub, ast.Attribute):
                continue
            if sub.attr not in counts:
                continue
            if isinstance(sub.ctx, (ast.Store, ast.Del)):
                continue
            counts[sub.attr] += 1
    return counts


def check(
    project: Project,
    readme_text: str = "",
    config_path: str = "distel_tpu/config.py",
) -> List[Finding]:
    cls = _config_class(project, config_path)
    if cls is None:
        return []
    fields = _fields(cls)
    props = _properties_map(cls)
    findings: List[Finding] = []

    # ---- misspelled: parser branches that set nothing / set unknowns
    for key, fname, line in props:
        if fname is None:
            findings.append(
                Finding(
                    rule=RULE_MISSPELLED,
                    path=config_path,
                    line=line,
                    symbol=key,
                    message=(
                        f"from_properties tests {key!r} but assigns no "
                        "config field — the key parses to nothing"
                    ),
                )
            )
        elif fname not in fields:
            findings.append(
                Finding(
                    rule=RULE_MISSPELLED,
                    path=config_path,
                    line=line,
                    symbol=f"{key}->{fname}",
                    message=(
                        f"from_properties assigns cfg.{fname} for key "
                        f"{key!r}, but the dataclass defines no such "
                        "field — a silent typo the parser never catches"
                    ),
                )
            )

    # ---- dead: fields nothing reads
    reads = _attribute_reads(project, set(fields))
    for fname, line in sorted(fields.items()):
        if reads.get(fname, 0) == 0:
            findings.append(
                Finding(
                    rule=RULE_DEAD,
                    path=config_path,
                    line=line,
                    symbol=fname,
                    message=(
                        f"config field {fname} is never read anywhere "
                        "— dead knob (delete it or wire it through)"
                    ),
                )
            )

    # ---- undocumented: properties keys README never mentions.  The
    # canonical (non-reference) spelling per field is the FIRST key in
    # parser order; reference-compat aliases (NODES_LIST, chunk.size)
    # ride along undocumented by design, so only the canonical key is
    # held to the README bar.
    canonical: Dict[str, Tuple[str, int]] = {}
    for key, fname, line in props:
        if fname is not None and fname not in canonical:
            canonical[fname] = (key, line)
    for fname, (key, line) in sorted(canonical.items()):
        if key not in readme_text and fname not in readme_text:
            findings.append(
                Finding(
                    rule=RULE_UNDOC,
                    path=config_path,
                    line=line,
                    symbol=key,
                    message=(
                        f"properties key {key!r} (config field "
                        f"{fname}) is not documented in README — "
                        "operators cannot discover it"
                    ),
                )
            )
    return findings
