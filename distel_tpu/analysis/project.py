"""Source model for distel-lint: parsed modules + shared AST facts.

A :class:`Project` is a root directory and a set of parsed python
modules (repo-relative posix paths → :class:`Module`).  Rules consume
the pre-computed per-module facts — classes, their lock attributes,
attribute types inferred from constructor assignments — so each rule
stays a small pass over a shared index instead of five ad-hoc AST
walks.  Tests build projects from temp dirs of fixture snippets; the
CLI builds one from the repo.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

#: the load-bearing docstring convention: a sentence like "Caller
#: holds ``entry.lock``." marks a helper whose callers hold a lock.
#: BOTH lock rules parse it through :func:`caller_holds_tokens` — one
#: parser, so the rules can never disagree about which helpers are
#: lock-covered
#: the sentence tail: up to the first period that ENDS a sentence —
#: a period followed by non-space (``entry.lock``) is part of a token
_HOLDS_SENTENCE_RE = re.compile(
    r"[Cc]aller(?:s)?[^.]{0,40}?holds?\s+((?:[^.]|\.(?=\S))*)"
)
_HOLDS_TOKEN_RE = re.compile(r"[A-Za-z_][\w.]*(?:lock|_cv)\w*")


def caller_holds_tokens(fn) -> List[str]:
    """Raw lock tokens (``"entry.lock"``, ``"self._lock"``) named by a
    function docstring's "Caller holds ..." sentence(s).  Whitespace is
    normalized first so the sentence survives docstring line wraps."""
    doc = re.sub(r"\s+", " ", ast.get_docstring(fn) or "")
    out: List[str] = []
    for m in _HOLDS_SENTENCE_RE.finditer(doc):
        out.extend(_HOLDS_TOKEN_RE.findall(m.group(1)))
    return out

#: constructors that mint a lock object (attribute paths as written)
_LOCK_CTORS = {
    ("threading", "Lock"),
    ("threading", "RLock"),
    ("threading", "Condition"),
}

#: bare names that mint a lock when imported from threading
_LOCK_NAMES = {"Lock", "RLock", "Condition"}


def _call_target(call: ast.Call) -> Optional[Tuple[str, ...]]:
    """Dotted-name tuple of a call's callee (``threading.Lock`` →
    ``("threading", "Lock")``), or None for non-name callees."""
    parts: List[str] = []
    node = call.func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def is_lock_ctor(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    tgt = _call_target(node)
    if tgt is None:
        return False
    if len(tgt) == 2 and tgt in _LOCK_CTORS:
        return True
    return len(tgt) == 1 and tgt[0] in _LOCK_NAMES


@dataclass
class ClassInfo:
    module: str  # repo-relative path
    name: str
    node: ast.ClassDef
    #: attribute names assigned a lock constructor anywhere in the class
    lock_attrs: Set[str] = field(default_factory=set)
    #: attr name → class name, from ``self.x = ClassName(...)``
    attr_types: Dict[str, str] = field(default_factory=dict)
    #: attr name → True when assigned an array-producing expression
    #: (``jnp.asarray(...)``, ``jnp.zeros(...)``, ``device_put`` ...)
    array_attrs: Set[str] = field(default_factory=set)
    #: method name → FunctionDef/AsyncFunctionDef
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)


_ARRAY_MINTERS = {
    "asarray", "array", "zeros", "ones", "full", "arange", "where",
    "device_put", "packbits",
}


def _is_array_expr(node: ast.expr) -> bool:
    """Does this expression look like it builds a device/ndarray?"""
    if isinstance(node, ast.Call):
        tgt = _call_target(node)
        if tgt and tgt[-1] in _ARRAY_MINTERS:
            return True
        # jnp.x.astype(...) / jnp.asarray(...).reshape(...)
        if isinstance(node.func, ast.Attribute):
            return _is_array_expr(node.func.value)
    return False


@dataclass
class Module:
    path: str  # repo-relative posix path
    source: str
    tree: ast.AST
    #: class name → info, for classes defined here
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    #: top-level function name → node
    functions: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    #: imported name → dotted module it came from
    #: (``from distel_tpu.serve.metrics import Metrics`` →
    #: ``{"Metrics": "distel_tpu.serve.metrics"}``)
    imports: Dict[str, str] = field(default_factory=dict)


class Project:
    """Parsed view of the analyzed tree.

    ``files``: explicit ``{relpath: source}`` mapping (tests); or scan
    ``root`` for ``include`` prefixes (CLI).  Paths are posix-style and
    repo-relative throughout.
    """

    def __init__(
        self,
        root: str,
        files: Optional[Dict[str, str]] = None,
        include: Optional[List[str]] = None,
    ):
        self.root = root
        self.modules: Dict[str, Module] = {}
        if files is None:
            files = {}
            for rel in self._scan(root, include):
                try:
                    with open(
                        os.path.join(root, rel), "r", encoding="utf-8"
                    ) as f:
                        files[rel.replace(os.sep, "/")] = f.read()
                except OSError:
                    continue
        for rel, src in sorted(files.items()):
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError:
                continue  # a broken file is pytest's problem, not lint's
            self.modules[rel] = self._index(rel, src, tree)
        #: class name → [ClassInfo] across modules (collision-aware)
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        for mod in self.modules.values():
            for ci in mod.classes.values():
                self.classes_by_name.setdefault(ci.name, []).append(ci)

    @staticmethod
    def _scan(root: str, include: Optional[List[str]]) -> List[str]:
        out: List[str] = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames
                if d not in (".git", "__pycache__", "build", ".claude")
            ]
            for fn in filenames:
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if include is None or any(
                    rel == p or rel.startswith(p.rstrip("/") + "/")
                    for p in include
                ):
                    out.append(rel)
        return sorted(out)

    # ------------------------------------------------------- indexing

    @staticmethod
    def _index(rel: str, src: str, tree: ast.AST) -> Module:
        mod = Module(path=rel, source=src, tree=tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = node.module
        for node in getattr(tree, "body", []):
            if isinstance(node, ast.ClassDef):
                mod.classes[node.name] = Project._index_class(rel, node)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                mod.functions[node.name] = node
        return mod

    @staticmethod
    def _index_class(rel: str, node: ast.ClassDef) -> ClassInfo:
        ci = ClassInfo(module=rel, name=node.name, node=node)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[item.name] = item
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            )
            value = sub.value
            if value is None:
                continue
            for tgt in targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                if is_lock_ctor(value):
                    ci.lock_attrs.add(tgt.attr)
                elif _is_array_expr(value):
                    ci.array_attrs.add(tgt.attr)
                elif isinstance(value, ast.Call):
                    tgt_path = _call_target(value)
                    if tgt_path is not None and tgt_path[-1][:1].isupper():
                        ci.attr_types[tgt.attr] = tgt_path[-1]
        return ci

    # -------------------------------------------------------- queries

    def classes_with_lock_attr(self, attr: str) -> List[ClassInfo]:
        return [
            ci
            for cis in self.classes_by_name.values()
            for ci in cis
            if attr in ci.lock_attrs
        ]

    def find_class(self, name: str) -> Optional[ClassInfo]:
        cis = self.classes_by_name.get(name, [])
        return cis[0] if len(cis) == 1 else None
