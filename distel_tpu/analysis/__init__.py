"""distel-lint: project-specific static analysis (stdlib ``ast`` only).

The reference DistEL got its concurrency safety for free: every set
update is one atomic single-threaded Redis Lua ``eval`` (PAPER.md).
The TPU port replaced that with hand-rolled Python locking spread over
``serve/``, ``obs/`` and the runtime aggregates — and the worst bugs
shipped so far (PR 6's busy-ejection split-brain, PR 4's
gauge-named-like-a-counter metric) were invariant violations a
project-specific static pass catches before review.  This package is
that pass: five rules, each encoding a contract this repo actually
carries:

* :mod:`~distel_tpu.analysis.lockorder` — lock-acquisition graph from
  ``with <lock>:`` nesting + intra-package call edges; cycles and
  cross-module acquire-while-holding.  Runtime counterpart:
  :mod:`distel_tpu.testing.lockdep`.
* :mod:`~distel_tpu.analysis.purity` — traced-purity / bucket
  invariant: functions reached from ``jax.jit`` must not close over
  ontology arrays, host-sync traced values (``float()``/``.item()``/
  ``np.asarray``), or Python-branch on traced values (PR 2's "a traced
  program is a pure function of ``bucket_signature``").
* :mod:`~distel_tpu.analysis.sharedstate` — attributes mutated both
  inside and outside a ``with <lock>:`` block on the same class.
* :mod:`~distel_tpu.analysis.knobs` — config-knob drift between
  ``config.py`` fields, ``from_properties`` keys, actual reads, and
  README documentation.
* :mod:`~distel_tpu.analysis.metricnames` — metric-family discipline:
  counters end ``_total``, gauges/histograms never do, and every
  minted family is covered by the README family table.

Run it: ``python -m distel_tpu.cli lint`` (committed baseline:
``.distel-lint-baseline.json``; tier-1 CI gates on it).
"""

from distel_tpu.analysis.findings import Baseline, Finding
from distel_tpu.analysis.project import Project
from distel_tpu.analysis.runner import ALL_RULES, run_rules

__all__ = [
    "ALL_RULES",
    "Baseline",
    "Finding",
    "Project",
    "run_rules",
]
