"""Finding + baseline model for distel-lint.

A finding's identity is its **fingerprint** — ``rule | path | symbol |
message`` hashed, deliberately excluding the line number so ordinary
edits above a finding don't churn the baseline.  The baseline file is a
JSON document mapping fingerprints to ``{finding..., justification}``;
every committed entry must carry a non-empty one-line justification
(the lint run fails otherwise — a suppression nobody can defend is a
bug with paperwork).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional


@dataclass
class Finding:
    #: rule id, e.g. "lock-order-cycle", "metric-name"
    rule: str
    #: repo-relative posix path — must be STABLE for the finding's
    #: identity (rules anchor e.g. a lock edge to the held lock's
    #: defining module, not to whichever call site witnessed it)
    path: str
    #: 1-based line of the primary site (0 = whole-file / cross-file)
    line: int
    #: stable symbol the finding anchors to (class.attr, function, knob,
    #: metric family, lock pair) — part of the fingerprint
    symbol: str
    #: human message; must not embed line numbers or witness call
    #: chains (fingerprint stability — an unrelated refactor must not
    #: churn the baseline)
    message: str
    #: unstable diagnostic detail (witness chain, schedule info) —
    #: rendered, exported, NEVER fingerprinted
    note: str = ""

    def fingerprint(self) -> str:
        h = hashlib.sha1(
            "|".join((self.rule, self.path, self.symbol, self.message))
            .encode("utf-8")
        )
        return h.hexdigest()[:12]

    def as_dict(self) -> dict:
        d = asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        out = f"{loc}: [{self.rule}] {self.symbol}: {self.message}"
        if self.note:
            out += f" [{self.note}]"
        return out


@dataclass
class BaselineEntry:
    finding: dict
    justification: str = ""


class Baseline:
    """Committed suppression set: pre-existing findings with a one-line
    justification each.  ``filter`` splits a run's findings into fresh
    (fail the build) vs baselined; stale entries (nothing fired) are
    reported so the file shrinks as debts are paid."""

    def __init__(self, entries: Optional[Dict[str, BaselineEntry]] = None):
        self.entries: Dict[str, BaselineEntry] = entries or {}

    # ------------------------------------------------------------- io

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        entries = {}
        for fp, rec in doc.get("findings", {}).items():
            entries[fp] = BaselineEntry(
                finding=rec.get("finding", {}),
                justification=rec.get("justification", ""),
            )
        return cls(entries)

    def save(self, path: str) -> None:
        doc = {
            "comment": (
                "distel-lint baseline: pre-existing findings, each "
                "carrying a one-line justification.  Regenerate "
                "candidates with `cli lint --write-baseline`, then "
                "justify every entry by hand."
            ),
            "findings": {
                fp: {
                    "finding": e.finding,
                    "justification": e.justification,
                }
                for fp, e in sorted(self.entries.items())
            },
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")

    # ---------------------------------------------------------- policy

    @classmethod
    def from_findings(
        cls, findings: List[Finding], justification: str = ""
    ) -> "Baseline":
        return cls(
            {
                f.fingerprint(): BaselineEntry(
                    finding=f.as_dict(), justification=justification
                )
                for f in findings
            }
        )

    def unjustified(self) -> List[str]:
        """Fingerprints whose entry has no justification — a committed
        baseline with one of these fails the lint run."""
        return [
            fp
            for fp, e in sorted(self.entries.items())
            if not e.justification.strip()
        ]

    def filter(self, findings: List[Finding]):
        """``(fresh, suppressed, stale_fingerprints)``."""
        fired = set()
        fresh: List[Finding] = []
        suppressed: List[Finding] = []
        for f in findings:
            fp = f.fingerprint()
            if fp in self.entries:
                fired.add(fp)
                suppressed.append(f)
            else:
                fresh.append(f)
        stale = [fp for fp in sorted(self.entries) if fp not in fired]
        return fresh, suppressed, stale
