"""Rule 5: metric-name discipline.

PR 4 shipped gauges named ``distel_frontier_*_rounds_total`` — a
counter suffix on a gauge path, which trips promtool lint and breaks
``rate()`` semantics for anyone graphing them; the rename cost a
review round that a static check catches in milliseconds.  This rule
statically collects every minted metric family and enforces:

* ``metric-name`` — counters (``counter_inc`` sites) end ``_total``;
  gauges (``gauge_set``/``gauge_fn``/``*_GAUGES`` tables) and
  histograms (``observe`` sites) never do;
* ``metric-readme`` — every minted family is covered by the README
  family table (exact, ``{a,b}``-brace expanded, or ``prefix_*``
  wildcard), and every exact README family still exists in code —
  both directions of doc drift.
"""

from __future__ import annotations

import ast
import itertools
import re
from typing import Dict, List, Optional, Set, Tuple

from distel_tpu.analysis.findings import Finding
from distel_tpu.analysis.project import Project

RULE_NAME = "metric-name"
RULE_README = "metric-readme"

#: a COMPLETE family name — trailing-underscore strings are prefixes
#: (tempdir names, dynamic-family concatenation), not families
_FAMILY_RE = re.compile(r"^distel_[a-z0-9_]*[a-z0-9]$")


def _is_family(name: str) -> bool:
    """A plausible metric family.  The package namespace itself
    (``"distel_tpu"``, env-var-ish ``"distel_tpu_..."`` strings) is
    excluded on BOTH the mint and README sides — path/package tokens
    would otherwise register as families and the cross-check would
    only balance by accident."""
    if name == "distel_tpu" or name.startswith("distel_tpu_"):
        return False
    return bool(_FAMILY_RE.match(name))

#: method name → family kind for literal first-argument call sites
_KIND_BY_CALL = {
    "counter_inc": "counter",
    "counter_value": "counter",
    "gauge_set": "gauge",
    "gauge_fn": "gauge",
    "observe": "histogram",
    "describe": None,  # declaration, kindless
}

#: README tokens: distel_* with optional {a,b} braces / label blocks /
#: trailing wildcard
_README_TOKEN_RE = re.compile(r"distel_[a-zA-Z0-9_{},*=.]*")


def collect_minted(
    project: Project, paths: Optional[List[str]] = None
) -> Dict[str, List[Tuple[str, str, int]]]:
    """family → [(kind, path, line)] for statically visible mints."""
    if paths is None:
        paths = sorted(project.modules)
    out: Dict[str, List[Tuple[str, str, int]]] = {}

    def note(fam: str, kind: Optional[str], path: str, line: int):
        if _is_family(fam):
            out.setdefault(fam, []).append((kind or "mention", path, line))

    for path in paths:
        mod = project.modules.get(path)
        if mod is None:
            continue
        for sub in ast.walk(mod.tree):
            if isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ):
                kind = _KIND_BY_CALL.get(sub.func.attr, "skip")
                if kind != "skip" and sub.args and isinstance(
                    sub.args[0], ast.Constant
                ) and isinstance(sub.args[0].value, str):
                    note(sub.args[0].value, kind, path, sub.lineno)
            elif isinstance(sub, ast.Assign):
                # gauge/counter tables (`_FRONTIER_GAUGES = ...`,
                # `_ARTIFACT_COUNTERS = ...`) register through
                # gauge_group/counter_group with computed names — type
                # their string members by the GAUGE/COUNTER in the
                # target
                names = [
                    t.id for t in sub.targets if isinstance(t, ast.Name)
                ]
                table_kind = None
                if any("GAUGE" in n.upper() for n in names):
                    table_kind = "gauge"
                elif any("COUNTER" in n.upper() for n in names):
                    table_kind = "counter"
                if table_kind is not None:
                    for c in ast.walk(sub.value):
                        if isinstance(c, ast.Constant) and isinstance(
                            c.value, str
                        ):
                            note(c.value, table_kind, path, c.lineno)
            elif isinstance(sub, ast.Constant) and isinstance(
                sub.value, str
            ) and sub.value.startswith("distel_"):
                # bare string constants keep families visible for the
                # README cross-check even when the mint site is dynamic
                # (the REQUEST_METRIC getattr indirection)
                note(sub.value, None, path, sub.lineno)
    return out


def _kind_of(sites: List[Tuple[str, str, int]]) -> Optional[str]:
    kinds = {k for k, _p, _l in sites if k in (
        "counter", "gauge", "histogram",
    )}
    if len(kinds) == 1:
        return next(iter(kinds))
    return None  # unknown or conflicting — naming check skips it


def _expand_readme_tokens(text: str) -> Tuple[Set[str], Set[str]]:
    """(exact family names, wildcard prefixes) documented in README."""
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for token in _README_TOKEN_RE.findall(text):
        token = token.rstrip(".,")
        # strip a label block: distel_x_seconds{phase=...}
        token = re.sub(r"\{[^}]*=[^}]*\}", "", token)
        # expand {a,b} alternation groups
        parts = re.split(r"(\{[^}]*\})", token)
        options = [
            p[1:-1].split(",") if p.startswith("{") else [p]
            for p in parts
            if p
        ]
        for combo in itertools.product(*options) if options else ():
            name = "".join(combo)
            if name.endswith("*"):
                if name != "distel_tpu*":
                    prefixes.add(name[:-1])
            elif _is_family(name):
                exact.add(name)
    return exact, prefixes


def check(
    project: Project,
    readme_text: str = "",
    paths: Optional[List[str]] = None,
) -> List[Finding]:
    minted = collect_minted(project, paths)
    findings: List[Finding] = []

    # ---- naming discipline
    for fam, sites in sorted(minted.items()):
        kind = _kind_of(sites)
        path, line = sites[0][1], sites[0][2]
        for k, p, l in sites:
            if k == (kind or ""):
                path, line = p, l
                break
        if kind == "counter" and not fam.endswith("_total"):
            findings.append(
                Finding(
                    rule=RULE_NAME, path=path, line=line, symbol=fam,
                    message=(
                        f"counter family {fam} must end in _total "
                        "(Prometheus counter convention; rate() and "
                        "promtool depend on it)"
                    ),
                )
            )
        elif kind in ("gauge", "histogram") and fam.endswith("_total"):
            findings.append(
                Finding(
                    rule=RULE_NAME, path=path, line=line, symbol=fam,
                    message=(
                        f"{kind} family {fam} carries the "
                        "counter-reserved _total suffix — rename (the "
                        "PR 4 frontier-gauge mistake)"
                    ),
                )
            )

    # ---- README family-table cross-check
    if readme_text:
        exact, prefixes = _expand_readme_tokens(readme_text)
        for fam, sites in sorted(minted.items()):
            covered = fam in exact or any(
                fam.startswith(p) for p in prefixes
            )
            if not covered:
                path, line = sites[0][1], sites[0][2]
                findings.append(
                    Finding(
                        rule=RULE_README, path=path, line=line,
                        symbol=fam,
                        message=(
                            f"metric family {fam} is minted but "
                            "missing from the README family table"
                        ),
                    )
                )
        minted_names = set(minted)
        for fam in sorted(exact):
            if fam in minted_names:
                continue
            # histogram suffixes of a minted family are documented
            base = re.sub(r"_(bucket|sum|count|max)$", "", fam)
            if base in minted_names:
                continue
            findings.append(
                Finding(
                    rule=RULE_README, path="README.md", line=0,
                    symbol=fam,
                    message=(
                        f"README documents metric family {fam}, but "
                        "nothing in the tree mints it — stale doc "
                        "(renamed or removed family)"
                    ),
                )
            )
    return findings
