"""distel-lint orchestration: scopes, rule registry, CLI entry.

Each rule runs over the slice of the tree whose contract it encodes —
the lock rules over the threaded serve/obs planes, the purity rule
over the jit-compiled core, the drift rules over everything plus
README.md.  Scoping is what keeps the signal clean: the ~80 legitimate
host-side syncs in the rowpacked CONTROLLER never meet the purity rule
because the controller is not reachable from a jit root, and test
fixtures never meet any rule because tests are not analyzed.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional

from distel_tpu.analysis import (
    knobs,
    lockorder,
    metricnames,
    purity,
    sharedstate,
)
from distel_tpu.analysis.findings import Baseline, Finding
from distel_tpu.analysis.project import Project

#: modules whose hand-rolled locking replaced the reference's atomic
#: Redis Lua scripts — the lock rules' jurisdiction
LOCK_SCOPE_PREFIXES = (
    "distel_tpu/serve/",
    "distel_tpu/obs/",
    "distel_tpu/runtime/instrumentation.py",
    "distel_tpu/core/program_cache.py",
    "distel_tpu/parallel/",
)

#: modules that build jit programs — the purity rule's jurisdiction
PURITY_SCOPE_PREFIXES = (
    "distel_tpu/core/",
    "distel_tpu/ops/",
)

#: what the CLI parses (tests/fixtures deliberately excluded)
DEFAULT_INCLUDE = [
    "distel_tpu",
    "bench.py",
    "bench_serve.py",
    "scripts",
    "__graft_entry__.py",
]


def _scope(project: Project, prefixes) -> List[str]:
    return [
        p
        for p in sorted(project.modules)
        if any(
            p == pre or p.startswith(pre)
            for pre in prefixes
        )
    ]


def run_rules(
    project: Project,
    readme_text: str = "",
    rules: Optional[List[str]] = None,
) -> List[Finding]:
    wanted = set(rules) if rules else None

    def on(name: str) -> bool:
        return wanted is None or name in wanted

    findings: List[Finding] = []
    if on("lock-order"):
        findings += lockorder.check(
            project, _scope(project, LOCK_SCOPE_PREFIXES)
        )
    if on("traced-purity"):
        findings += purity.check(
            project, _scope(project, PURITY_SCOPE_PREFIXES)
        )
    if on("shared-state"):
        findings += sharedstate.check(
            project, _scope(project, LOCK_SCOPE_PREFIXES)
        )
    if on("knobs"):
        findings += knobs.check(project, readme_text)
    if on("metric-names"):
        findings += metricnames.check(
            project, readme_text,
            [p for p in sorted(project.modules)
             if p.startswith("distel_tpu/")],
        )
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    return findings


ALL_RULES = (
    "lock-order",
    "traced-purity",
    "shared-state",
    "knobs",
    "metric-names",
)

#: rule group → the finding rule-ids it emits, so a ``--rules`` subset
#: run can scope baseline stale/unjustified reporting to the groups
#: that actually ran (entries of unselected rules are NOT stale — they
#: just didn't get a chance to fire)
RULE_IDS = {
    "lock-order": (lockorder.RULE_CYCLE, lockorder.RULE_CROSS),
    "traced-purity": (
        purity.RULE_CAPTURE, purity.RULE_SYNC, purity.RULE_BRANCH,
    ),
    "shared-state": (sharedstate.RULE,),
    "knobs": (
        knobs.RULE_DEAD, knobs.RULE_UNDOC, knobs.RULE_MISSPELLED,
    ),
    "metric-names": (metricnames.RULE_NAME, metricnames.RULE_README),
}


def repo_root() -> str:
    import distel_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        distel_tpu.__file__
    )))


def lint_main(args) -> int:
    """``cli lint`` entry: run the rules, apply the baseline, report.

    Exit codes: 0 clean (or all findings baselined), 1 fresh findings
    or an invalid baseline, 2 usage errors."""
    t0 = time.time()
    root = args.root or repo_root()
    readme_path = os.path.join(root, "README.md")
    readme_text = ""
    if os.path.exists(readme_path):
        with open(readme_path, "r", encoding="utf-8") as f:
            readme_text = f.read()
    project = Project(root, include=DEFAULT_INCLUDE)
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    if rules:
        unknown = sorted(set(rules) - set(ALL_RULES))
        if unknown:
            print(
                f"unknown rule(s) {unknown}; expected {list(ALL_RULES)}",
                file=sys.stderr,
            )
            return 2
    findings = run_rules(project, readme_text, rules)

    if args.write_baseline:
        if rules:
            # a subset run produces a subset baseline — committing it
            # would silently drop every other rule's suppressions
            print(
                "--write-baseline needs a full-rule run (drop --rules)",
                file=sys.stderr,
            )
            return 2
        bl = Baseline.from_findings(findings)
        bl.save(args.write_baseline)
        print(
            json.dumps(
                {
                    "written": args.write_baseline,
                    "findings": len(findings),
                    "note": (
                        "justify every entry by hand before "
                        "committing — lint fails on empty "
                        "justifications"
                    ),
                }
            )
        )
        return 0

    baseline = Baseline()
    baseline_path = args.baseline
    if baseline_path is None:
        default = os.path.join(root, ".distel-lint-baseline.json")
        if os.path.exists(default):
            baseline_path = default
    if baseline_path:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    fresh, suppressed, stale = baseline.filter(findings)
    unjustified = baseline.unjustified()
    if rules:
        # scope baseline bookkeeping to the rule ids that actually
        # ran: unselected rules' entries are neither stale nor held
        # to the justification bar on this run
        active_ids = {
            rid for group in rules for rid in RULE_IDS.get(group, ())
        }

        def _active(fp: str) -> bool:
            return (
                baseline.entries[fp].finding.get("rule") in active_ids
            )

        stale = [fp for fp in stale if _active(fp)]
        unjustified = [fp for fp in unjustified if _active(fp)]

    if args.json:
        doc = {
            "fresh": [f.as_dict() for f in fresh],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline": stale,
            "unjustified_baseline": unjustified,
            "wall_s": round(time.time() - t0, 3),
        }
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")

    for f in fresh:
        print(f.render())
    for fp in stale:
        entry = baseline.entries[fp].finding
        print(
            f"stale baseline entry {fp} "
            f"({entry.get('rule')}: {entry.get('symbol')}) — the "
            "finding no longer fires; drop it from the baseline",
            file=sys.stderr,
        )
    for fp in unjustified:
        print(
            f"baseline entry {fp} has no justification — every "
            "committed suppression needs a one-line why",
            file=sys.stderr,
        )
    summary = {
        "findings": len(findings),
        "fresh": len(fresh),
        "baselined": len(suppressed),
        "stale_baseline": len(stale),
        "unjustified_baseline": len(unjustified),
        "wall_s": round(time.time() - t0, 3),
    }
    print(json.dumps(summary))
    return 1 if fresh or unjustified else 0
