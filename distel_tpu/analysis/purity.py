"""Rule 2: traced-purity / bucket-invariant lint.

PR 2's bucketing contract: a traced program is a pure function of
``engine.bucket_signature`` — ALL ontology content rides in the
runtime-argument pytree, so same-bucket ontologies share one compiled
executable and one persistent-cache entry.  Nothing enforces that
today except the comment block at the top of ``_step``; a single
``self._fillers`` read added inside the trace silently re-specializes
the program per ontology and the cold-start win evaporates (no test
fails — the answers stay right, only compile sharing dies).

Inside functions reached from ``jax.jit`` (direct calls, decorators,
``self._shard_jit(fn, ...)``, lambdas), three checks:

* ``traced-closure-capture`` — reads of ``self.<attr>`` where the
  class assigns that attr an array expression, UNLESS the read is the
  documented fallback idiom (guarded by ``<param> is None`` or by a
  ``self._bucket`` branch — the legitimate non-bucketed path);
* ``traced-host-sync`` — ``float()``/``int()``/``bool()`` /
  ``.item()`` / ``np.asarray`` / ``jax.device_get`` applied to a
  traced value (a silent device→host transfer per call inside the
  program, or an outright tracer error at run time);
* ``traced-python-branch`` — Python ``if``/``while`` on a traced
  value (a tracer error under jit; a silent per-trace specialization
  under concrete inputs).

"Traced value" is a per-function taint set: the function's parameters
(minus ``self`` and ``jax.jit`` static args) plus anything assigned
from an expression that mentions a tainted name or calls into
``jnp``/``lax``; ``.shape``/``.dtype``/``.ndim``/``len()`` launder the
taint (static under trace).  Host-side controller code
(``saturate_observed`` and friends) is never reached from a jit root,
which is what keeps the ~80 legitimate host-side syncs in
``rowpacked_engine.py`` out of the signal.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from distel_tpu.analysis.findings import Finding
from distel_tpu.analysis.project import Module, Project, _call_target

RULE_CAPTURE = "traced-closure-capture"
RULE_SYNC = "traced-host-sync"
RULE_BRANCH = "traced-python-branch"

#: callee attribute paths that mark a first argument as a jit root
_JIT_HEADS = {("jax", "jit"), ("jit",), ("pjit",), ("jax", "pjit")}

#: receivers whose module taints a call result / marks traced compute
_TRACED_MODULES = {"jnp", "lax", "jsp"}

#: taint-laundering attribute reads (static under trace)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "at", "aval"}

_HOST_CASTS = {"float", "int", "bool", "complex"}


def _func_index(module: Module):
    """Qualified name → (node, owner-class-name | None) for every
    function in the module, including nested defs (``Class.meth``,
    ``Class.meth.<fn>``, ``func``)."""
    out: Dict[str, Tuple[ast.AST, Optional[str]]] = {}

    def walk_fn(node, prefix: str, owner: Optional[str]):
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qid = f"{prefix}{sub.name}"
                out[qid] = (sub, owner)
                walk_fn(sub, qid + ".", owner)
            elif not isinstance(sub, ast.ClassDef):
                walk_fn(sub, prefix, owner)

    for node in module.tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qid = f"{node.name}.{item.name}"
                    out[qid] = (item, node.name)
                    walk_fn(item, qid + ".", node.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = (node, None)
            walk_fn(node, node.name + ".", None)
    return out


def _is_jit_call(node: ast.Call) -> bool:
    tgt = _call_target(node)
    if tgt is None:
        return False
    if tgt in _JIT_HEADS:
        return True
    # functools.partial(jax.jit, static_argnums=...) as a decorator
    if tgt[-1] == "partial" and node.args:
        head = _call_target(ast.Call(
            func=node.args[0], args=[], keywords=[]
        ))
        if head in _JIT_HEADS:
            return True
    # self._shard_jit(fn, ...) — the engine's shard_map+jit scaffold
    return tgt[-1].endswith("shard_jit")


def _jit_static_argnums(node: ast.Call) -> Set[int]:
    for kw in node.keywords:
        if kw.arg == "static_argnums":
            vals = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            return {
                v.value
                for v in vals
                if isinstance(v, ast.Constant)
                and isinstance(v.value, int)
            }
    return set()


class _RootCollector(ast.NodeVisitor):
    """Find jit roots in a module: names of functions/methods passed to
    ``jax.jit``-like calls (plus lambdas, resolved through their
    bodies), with per-root static argnums."""

    def __init__(self):
        #: (owner-class-or-None, bare function name) → static argnums
        self.roots: Dict[Tuple[Optional[str], str], Set[int]] = {}
        self._class: Optional[str] = None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_call(node) and node.args:
            self._root_arg(node.args[0], _jit_static_argnums(node))
        # functools.partial(jax.jit, ...) used as decorator is handled
        # by the decorator scan below
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for dec in node.decorator_list:
            if (
                isinstance(dec, ast.Call) and _is_jit_call(dec)
            ) or (
                not isinstance(dec, ast.Call)
                and _call_target(ast.Call(func=dec, args=[], keywords=[]))
                in _JIT_HEADS
            ):
                statics = (
                    _jit_static_argnums(dec)
                    if isinstance(dec, ast.Call)
                    else set()
                )
                self.roots[(self._class, node.name)] = statics
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _root_arg(self, arg: ast.expr, statics: Set[int]) -> None:
        if isinstance(arg, ast.Lambda):
            # the lambda body's calls are the roots (`jax.jit(lambda
            # sp, rp: self._step(sp, rp)[:2])`)
            for sub in ast.walk(arg.body):
                if isinstance(sub, ast.Call):
                    self._root_arg(sub.func, set())
            return
        if isinstance(arg, ast.Attribute) and isinstance(
            arg.value, ast.Name
        ) and arg.value.id == "self":
            self.roots[(self._class, arg.attr)] = statics
        elif isinstance(arg, ast.Name):
            # local nested def or module function — key by bare name
            # under the current class scope first, module scope second
            self.roots[(self._class, arg.id)] = statics
            self.roots[(None, arg.id)] = statics


def _reached(module: Module, funcs, roots):
    """Transitively reached functions from the jit roots via
    ``self.x()`` / bare-name calls inside the module.  Returns
    ``(traced: qid → root static argnums, root_qids)`` — only roots
    carry static argnums; non-root reached functions are tainted from
    their call sites instead."""
    by_key: Dict[Tuple[Optional[str], str], List[str]] = {}
    for qid, (_node, owner) in funcs.items():
        bare = qid.rsplit(".", 1)[-1]
        by_key.setdefault((owner, bare), []).append(qid)
        by_key.setdefault((None, bare), []).append(qid)

    # pre-seed EVERY root with its static argnums before expanding:
    # a root reached first as another root's callee must not have its
    # statics clobbered by the empty callee entry (the static param
    # would read as tainted and fire bogus branch/sync findings)
    root_qids: Set[str] = set()
    traced: Dict[str, Set[int]] = {}
    for key, statics in roots.items():
        for qid in by_key.get(key, []):
            traced[qid] = traced.get(qid, set()) | set(statics)
            root_qids.add(qid)
    expanded: Set[str] = set()
    work: List[str] = list(traced)
    while work:
        qid = work.pop()
        if qid in expanded:
            continue
        expanded.add(qid)
        node, owner = funcs[qid]
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            callee_keys = []
            fn = sub.func
            if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name
            ) and fn.value.id == "self":
                callee_keys.append((owner, fn.attr))
            elif isinstance(fn, ast.Name):
                key = (owner, fn.id)
                callee_keys.append(
                    key if key in by_key else (None, fn.id)
                )
            # function-valued arguments (lax.while_loop(cond, body),
            # lax.cond(pred, t, f)) run traced too
            for arg in sub.args:
                if isinstance(arg, ast.Name):
                    key = (owner, arg.id)
                    if key in by_key:
                        callee_keys.append(key)
            for callee_key in callee_keys:
                for cq in by_key.get(callee_key, []):
                    if cq not in traced:
                        traced[cq] = set()
                    if cq not in expanded:
                        work.append(cq)
    return traced, root_qids, by_key


def _seed_taints(funcs, traced, root_qids, by_key) -> Dict[str, Set[str]]:
    """Per-function seed taint.  Roots taint every parameter (minus
    ``self`` and jit static argnums — the values jit feeds are
    tracers); non-root reached functions taint only the parameters
    their call sites actually pass tainted expressions into — the
    host-side plan builders a traced function calls AT TRACE TIME with
    static arguments stay untainted, which is what keeps trace-time
    Python (shape planning, table selection) out of the signal."""
    seeds: Dict[str, Set[str]] = {}
    for qid in traced:
        node, _owner = funcs[qid]
        params = _params(node)
        if qid in root_qids:
            statics = traced[qid]
            # static_argnums index the signature jit actually sees:
            # for a jit over the BOUND method (jax.jit(self._kern)),
            # that signature excludes self — offset the def's params
            off = 1 if params[:1] == ["self"] else 0
            static_names = {
                params[i + off]
                for i in statics
                if i + off < len(params)
            }
            seeds[qid] = set(params) - {"self"} - static_names
        else:
            seeds[qid] = set()
    for _ in range(4):  # cross-function fixpoint
        changed = False
        for qid in traced:
            node, owner = funcs[qid]
            local = _local_taint(node, seeds[qid])
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                skip_self = False
                if isinstance(fn, ast.Attribute) and isinstance(
                    fn.value, ast.Name
                ) and fn.value.id == "self":
                    key = (owner, fn.attr)
                    skip_self = True
                elif isinstance(fn, ast.Name):
                    key = (owner, fn.id)
                    if key not in by_key:
                        key = (None, fn.id)
                else:
                    continue
                for cq in by_key.get(key, []):
                    if cq not in traced:
                        continue
                    cparams = _params(funcs[cq][0])
                    if skip_self and cparams[:1] == ["self"]:
                        cparams = cparams[1:]
                    for i, arg in enumerate(sub.args):
                        if i < len(cparams) and _mentions_tainted(
                            arg, local
                        ):
                            if cparams[i] not in seeds[cq]:
                                seeds[cq].add(cparams[i])
                                changed = True
                    for kw in sub.keywords:
                        if kw.arg and kw.arg in cparams and \
                                _mentions_tainted(kw.value, local):
                            if kw.arg not in seeds[cq]:
                                seeds[cq].add(kw.arg)
                                changed = True
        if not changed:
            break
    return seeds


def _params(node) -> List[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


_LAUNDER_CALLS = {"len", "isinstance", "hasattr", "callable", "range",
                  "type"}


def _mentions_tainted(node: ast.expr, tainted: Set[str]) -> bool:
    """Does this expression carry a traced value?  ``.shape`` /
    ``.dtype`` / ``len()`` subtrees launder the taint — they are
    static under trace."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False  # static metadata read — whole subtree laundered
    if isinstance(node, ast.Call):
        tgt = _call_target(node)
        if tgt and len(tgt) == 1 and tgt[0] in _LAUNDER_CALLS:
            return False
        if tgt and tgt[0] in _TRACED_MODULES:
            return True
    return any(
        _mentions_tainted(child, tainted)
        for child in ast.iter_child_nodes(node)
        if isinstance(child, (ast.expr, ast.keyword))
        or isinstance(child, ast.comprehension)
    )


def _guarded_by_fallback(
    ancestors: List[ast.AST], params: Set[str]
) -> bool:
    """Is this site inside a ``<param> is None`` guard or a
    ``self._bucket`` conditional — the legitimate non-bucketed
    fallback idiom?"""
    for anc in ancestors:
        test = None
        if isinstance(anc, (ast.If, ast.IfExp)):
            test = anc.test
        if test is None:
            continue
        for sub in ast.walk(test):
            if isinstance(sub, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in sub.ops
            ):
                names = [
                    n.id
                    for n in ast.walk(sub)
                    if isinstance(n, ast.Name)
                ]
                if any(n in params for n in names):
                    return True
            if isinstance(sub, ast.Attribute) and "bucket" in sub.attr:
                return True
    return False


def _local_taint(node, seed: Set[str]) -> Set[str]:
    """Seed taint + forward assignment fixpoint within one function."""
    tainted = set(seed)
    for _ in range(4):  # small fixpoint: taint flows forward
        before = len(tainted)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                if _mentions_tainted(sub.value, tainted):
                    for tgt in sub.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                if sub.value is not None and _mentions_tainted(
                    sub.value, tainted
                ):
                    for n in ast.walk(sub.target):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        if len(tainted) == before:
            break
    return tainted


class _PurityWalker:
    """Per-traced-function checks (iterative walk keeping the ancestor
    chain for guard detection)."""

    def __init__(
        self,
        path: str,
        qid: str,
        node,
        seed_taint: Set[str],
        array_attrs: Set[str],
        findings: List[Finding],
    ):
        self.path = path
        self.qid = qid
        self.node = node
        self.array_attrs = array_attrs
        self.findings = findings
        self.param_names = set(_params(node))
        self.tainted: Set[str] = set(seed_taint)

    def run(self) -> None:
        self.tainted = _local_taint(self.node, self.tainted)
        self._walk(self.node, [])

    def _walk(self, node: ast.AST, ancestors: List[ast.AST]) -> None:
        for sub in ast.iter_child_nodes(node):
            self._visit(sub, ancestors)
            self._walk(sub, ancestors + [sub])

    def _visit(self, sub: ast.AST, ancestors: List[ast.AST]) -> None:
        # ---- closure capture of ontology arrays
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
            and sub.attr in self.array_attrs
            and not _guarded_by_fallback(
                ancestors + [sub], self.param_names
            )
        ):
            self.findings.append(
                Finding(
                    rule=RULE_CAPTURE,
                    path=self.path,
                    line=sub.lineno,
                    symbol=f"{self.qid}:self.{sub.attr}",
                    message=(
                        f"traced function reads self.{sub.attr} (an "
                        "ontology-derived array) from its closure — "
                        "bucketed programs must carry all content in "
                        "the runtime-arg pytree"
                    ),
                )
            )
        # ---- host syncs
        if isinstance(sub, ast.Call):
            tgt = _call_target(sub)
            if (
                tgt
                and len(tgt) == 1
                and tgt[0] in _HOST_CASTS
                and sub.args
                and _mentions_tainted(sub.args[0], self.tainted)
            ):
                self._sync(sub, f"{tgt[0]}()")
            elif (
                tgt
                and tgt[-1] in ("asarray", "array")
                and tgt[0] == "np"
                and sub.args
                and _mentions_tainted(sub.args[0], self.tainted)
            ):
                self._sync(sub, "np." + tgt[-1])
            elif tgt == ("jax", "device_get"):
                self._sync(sub, "jax.device_get")
            elif (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "item"
                and _mentions_tainted(sub.func.value, self.tainted)
            ):
                self._sync(sub, ".item()")
        # ---- python branching on traced values
        if isinstance(sub, (ast.If, ast.While)):
            test = sub.test
            if self._value_branch(test):
                self.findings.append(
                    Finding(
                        rule=RULE_BRANCH,
                        path=self.path,
                        line=sub.lineno,
                        symbol=f"{self.qid}",
                        message=(
                            "Python branch on a traced value "
                            f"({ast.unparse(test)[:60]!r}) — use "
                            "lax.cond/lax.select inside a traced "
                            "program"
                        ),
                    )
                )

    def _value_branch(self, test: ast.expr) -> bool:
        # `x is None` / `x is not None` tests are structural, not value
        if isinstance(test, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return False
        # `"sel4" in sa`: dict-KEY membership is pytree structure,
        # static under trace
        if isinstance(test, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in test.ops
        ) and isinstance(test.left, ast.Constant):
            return False
        if isinstance(test, ast.BoolOp):
            return any(self._value_branch(v) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(
            test.op, ast.Not
        ):
            return self._value_branch(test.operand)
        # laundered reads (.shape/len) are static
        if isinstance(test, ast.Call):
            tgt = _call_target(test)
            if tgt and tgt[-1] in ("len", "isinstance", "hasattr",
                                   "callable"):
                return False
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
                return False
        return _mentions_tainted(test, self.tainted)

    def _sync(self, node: ast.Call, what: str) -> None:
        self.findings.append(
            Finding(
                rule=RULE_SYNC,
                path=self.path,
                line=node.lineno,
                symbol=f"{self.qid}:{what}",
                message=(
                    f"{what} on a traced value inside a jit-reached "
                    "function forces a host sync (or a tracer error) — "
                    "keep the value on device or move the fold to the "
                    "controller"
                ),
            )
        )


def check(project: Project, paths: Optional[List[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    if paths is None:
        paths = sorted(project.modules)
    for path in paths:
        module = project.modules.get(path)
        if module is None:
            continue
        funcs = _func_index(module)
        collector = _RootCollector()
        collector.visit(module.tree)
        if not collector.roots:
            continue
        traced, root_qids, by_key = _reached(
            module, funcs, collector.roots
        )
        seeds = _seed_taints(funcs, traced, root_qids, by_key)
        for qid in sorted(traced):
            node, owner = funcs[qid]
            array_attrs: Set[str] = set()
            if owner is not None:
                ci = module.classes.get(owner)
                if ci is not None:
                    array_attrs = ci.array_attrs
            _PurityWalker(
                path, qid, node, seeds.get(qid, set()), array_attrs,
                findings,
            ).run()
    # dedupe identical (rule, symbol, message) repeats at different
    # lines (a loop-unrolled pattern fires once, not N times)
    seen: Set[str] = set()
    out: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if fp not in seen:
            seen.add(fp)
            out.append(f)
    return out
