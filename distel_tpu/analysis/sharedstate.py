"""Rule 3: shared-state heuristic.

For every class that owns a lock (``self._lock = threading.Lock()``
and friends), find instance attributes that are **mutated both inside
and outside** a ``with <that class's lock>:`` block.  A lock that
covers only some writers is the PR 6 split-brain shape: every reader
that takes the lock believes it sees a consistent value while an
unlocked writer races it.

Covered mutation forms: ``self.x = ...`` / ``self.x += ...`` and
mutating method calls (``self.x.append(...)``, ``.pop``, ``.add``,
``.update``, ...), both via ``self`` inside the class and via a typed
receiver from outside it (``entry.inc = ...`` in the registry counts
against ``_Entry``).  ``__init__`` (and other constructors) are
exempt — construction happens before the object is shared.  The
``"caller holds ``x.lock``"`` docstring convention marks a helper as
lock-covered without a lexical ``with``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from distel_tpu.analysis.findings import Finding
from distel_tpu.analysis.project import (
    ClassInfo,
    Module,
    Project,
    caller_holds_tokens,
)

RULE = "shared-state"

#: method names that mutate common containers
_MUTATORS = {
    "append", "extend", "insert", "pop", "popleft", "remove", "clear",
    "add", "discard", "update", "setdefault", "appendleft",
}

#: constructor-ish methods exempt from the both-sides check
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


@dataclass
class _Site:
    path: str
    line: int
    func: str
    locked: bool


class _MutationWalker(ast.NodeVisitor):
    """Collect attribute mutations in one function, tagged with whether
    any analyzed-class lock is held at the site."""

    def __init__(
        self,
        project: Project,
        module: Module,
        cls: Optional[ClassInfo],
        func_name: str,
        path: str,
        sites: Dict[Tuple[str, str], List[_Site]],
        entry_locked_attrs: Set[str],
    ):
        self.project = project
        self.module = module
        self.cls = cls
        self.func_name = func_name
        self.path = path
        self.sites = sites
        #: receivers whose lock is held RIGHT NOW: "self" and/or local
        #: variable names ("entry"), plus "*" when entry docstring says
        #: the caller holds a lock attr without naming the receiver
        self.locked_receivers: List[str] = sorted(entry_locked_attrs)

    # ------------------------------------------------------- helpers

    def _owner_of(self, recv: ast.expr, attr: str):
        """(class-name, receiver-token) owning the mutated attr, or
        None when the receiver can't be typed."""
        if isinstance(recv, ast.Name):
            if recv.id == "self" and self.cls is not None:
                return self.cls.name, "self"
            # typed local: unique analyzed class owning this attr as a
            # lock attr OR declared in __slots__/assignments — approx:
            # unique class with a lock whose methods/ctor assign attr
            owners = [
                cis[0]
                for cis in self.project.classes_by_name.values()
                if len(cis) == 1
                and cis[0].lock_attrs
                and _class_has_attr(cis[0], attr)
            ]
            if len(owners) == 1:
                return owners[0].name, recv.id
        return None

    def _record(self, recv: ast.expr, attr: str, line: int) -> None:
        owner = self._owner_of(recv, attr)
        if owner is None:
            return
        cls_name, token = owner
        ci = self.project.find_class(cls_name)
        if ci is None or not ci.lock_attrs or attr in ci.lock_attrs:
            return
        locked = token in self.locked_receivers or (
            token == "self" and "<self-lock>" in self.locked_receivers
        )
        self.sites.setdefault((cls_name, attr), []).append(
            _Site(self.path, line, self.func_name, locked)
        )

    # -------------------------------------------------------- visits

    def visit_With(self, node: ast.With) -> None:
        self._with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._with(node)

    def _with(self, node) -> None:
        pushed: List[str] = []
        for item in node.items:
            ce = item.context_expr
            if isinstance(ce, ast.Attribute):
                attr, recv = ce.attr, ce.value
                is_lock = False
                if (
                    isinstance(recv, ast.Name)
                    and recv.id == "self"
                    and self.cls is not None
                    and attr in self.cls.lock_attrs
                ):
                    is_lock = True
                    token = "<self-lock>"
                elif isinstance(recv, ast.Name) and self.project\
                        .classes_with_lock_attr(attr):
                    is_lock = True
                    token = recv.id
                if is_lock:
                    self.locked_receivers.append(token)
                    pushed.append(token)
        for stmt in node.body:
            self.visit(stmt)
        for token in pushed:
            self.locked_receivers.remove(token)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._target(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._target(node.target)
        self.generic_visit(node)

    def _target(self, tgt: ast.expr) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._target(el)
            return
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value  # self.x[k] = v mutates self.x
        if isinstance(tgt, ast.Attribute):
            self._record(tgt.value, tgt.attr, tgt.lineno)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _MUTATORS
            and isinstance(fn.value, ast.Attribute)
        ):
            self._record(fn.value.value, fn.value.attr, node.lineno)
        self.generic_visit(node)

    # nested defs run on other threads/timing — analyzed separately
    def visit_FunctionDef(self, node) -> None:  # noqa: D102
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def _class_has_attr(ci: ClassInfo, attr: str) -> bool:
    for sub in ast.walk(ci.node):
        if isinstance(sub, ast.Attribute) and sub.attr == attr and (
            isinstance(sub.value, ast.Name) and sub.value.id == "self"
        ):
            return True
        if isinstance(sub, ast.Constant) and sub.value == attr:
            return True  # __slots__ entry
    return False


def check(project: Project, paths: Optional[List[str]] = None) -> List[Finding]:
    if paths is None:
        paths = sorted(project.modules)
    sites: Dict[Tuple[str, str], List[_Site]] = {}
    for path in paths:
        module = project.modules.get(path)
        if module is None:
            continue
        for cls in module.classes.values():
            for mname, fn in cls.methods.items():
                if mname in _EXEMPT_METHODS:
                    continue
                # the shared "Caller holds ``x.lock``" parser (ONE
                # parser for both lock rules): "self.X"/bare lock
                # attrs of this class cover self-mutations, a named
                # receiver ("entry.lock") covers that variable's
                tokens: Set[str] = set()
                for token in caller_holds_tokens(fn):
                    parts = token.split(".")
                    recv = parts[0] if len(parts) > 1 else None
                    if recv not in (None, "self"):
                        tokens.add(recv)
                    elif recv == "self" or parts[-1] in cls.lock_attrs:
                        tokens.add("<self-lock>")
                walker = _MutationWalker(
                    project, module, cls, f"{cls.name}.{mname}",
                    path, sites, tokens,
                )
                for stmt in fn.body:
                    walker.visit(stmt)
        for fname, fn in module.functions.items():
            walker = _MutationWalker(
                project, module, None, fname, path, sites,
                set(),
            )
            for stmt in fn.body:
                walker.visit(stmt)

    findings: List[Finding] = []
    for (cls_name, attr), slist in sorted(sites.items()):
        locked = [s for s in slist if s.locked]
        unlocked = [s for s in slist if not s.locked]
        if not locked or not unlocked:
            continue
        un = unlocked[0]
        lk = locked[0]
        findings.append(
            Finding(
                rule=RULE,
                path=un.path,
                line=un.line,
                symbol=f"{cls_name}.{attr}",
                message=(
                    f"{cls_name}.{attr} is mutated under a lock in "
                    f"{lk.func} but WITHOUT one in {un.func} — either "
                    "every writer takes the lock or none should"
                ),
            )
        )
    return findings
