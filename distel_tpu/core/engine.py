"""The TPU saturation engine: EL+ completion as boolean tensor algebra.

This replaces, in one ``jax.jit``-compiled function, the reference's entire
distributed run-time — the per-rule processors
(``base/Type*AxiomProcessorBase.java``), their ~12 Redis Lua kernels
(``misc/ScriptsCollection.java:5-135``), the barrier/convergence vote
(``controller/CommunicationHandler.java:49-84``), and the work-stealing
load balancer (``worksteal/``) — with dataflow the XLA compiler schedules
statically:

  state   S[x, a]  bool — a ∈ S(x)       (the reference's inverted result
                                          zsets, ``init/AxiomLoader.java:1237-1245``)
          R[x, l]  bool — (x, filler(l)) ∈ R(role(l)) over the closed link
                                          table (see ``core/indexing.py``)

  CR1  S[:, b]  ∨= S[:, a]                       column gather/scatter
  CR2  S[:, b]  ∨= S[:, a1] ∧ S[:, a2]           column gather/scatter
  CR3  R[:, l]  ∨= S[:, a]                       column gather/scatter
  CR4  S[:, b_j] ∨= (R ⊙ W)[:, j]                MXU matmul [Nc,L]@[L,K4]
         W[l, j] = H[role(l), s_j] ∧ S[filler(l), a_j]
  CR6  R[:, lt_p] ∨= (R ⊙ D)[:, p]               MXU matmul [Nc,L]@[L,P]
         D[l, p] = H[role(l), r_p] ∧ R[filler(l), l2_p]
  CR5  S[:, ⊥]  ∨= R ⊙ S[fillers, ⊥]             MXU matvec

(⊙ = AND-OR semiring product, executed as a bf16 matmul with f32
accumulation + threshold — exact for < 2^24 terms.)

Role hierarchy (CR5' in the reference, ``base/Type4AxiomProcessorBase.java``)
never materializes: consumers read through the static reflexive-transitive
closure masks M4/M6.  The fixed-point loop is ``lax.while_loop`` with a
global any(changed) — under a sharded mesh XLA lowers that to the ``psum``
collective, which IS the reference's AND-vote barrier
(``controller/CommunicationHandler.java:78-83``) in one instruction.

Semi-naive/delta evaluation (the reference's score-cursor machinery,
``misc/Util.java:68-93``): dense recomputation is idempotent and each
iteration only costs the same matmuls, so the frontier bookkeeping
disappears; ``changed`` plays the role of the global delta test.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, NamedTuple, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distel_tpu.core.indexing import BOTTOM_ID, TOP_ID, IndexedOntology


class SaturationState(NamedTuple):
    s: jax.Array          # [Nc, Nc] bool
    r: jax.Array          # [Nc, L] bool
    iteration: jax.Array  # i32 scalar
    changed: jax.Array    # bool scalar


@dataclass
class SaturationResult:
    s: np.ndarray
    r: np.ndarray
    iterations: int
    derivations: int
    idx: IndexedOntology
    converged: bool = True

    def subsumers(self, concept_id: int) -> Set[int]:
        return set(np.nonzero(self.s[concept_id])[0].tolist())

    def subsumer_dict(self) -> Dict[int, Set[int]]:
        n = self.idx.n_concepts
        return {c: set(np.nonzero(self.s[c, :n])[0].tolist()) for c in range(n)}

    def unsatisfiable(self) -> Set[int]:
        col = self.s[: self.idx.n_concepts, BOTTOM_ID]
        return set(np.nonzero(col)[0].tolist())


def _pad_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


class SaturationEngine:
    """Compiles an indexed ontology into a jitted fixed-point program.

    ``pad_multiple`` pads the concept axis so shards divide evenly on a
    mesh (and MXU tiles line up); padded rows/columns hold inert concepts.
    """

    def __init__(
        self,
        idx: IndexedOntology,
        *,
        pad_multiple: int = 128,
        mesh: Optional[jax.sharding.Mesh] = None,
        concept_axis: str = "c",
        matmul_dtype=jnp.bfloat16,
    ):
        self.idx = idx
        self.mesh = mesh
        self.concept_axis = concept_axis
        self.matmul_dtype = matmul_dtype
        if mesh is not None:
            shards = mesh.shape[concept_axis]
            pad_multiple = max(pad_multiple, 8) * shards
        self.nc = _pad_up(max(idx.n_concepts, 2), pad_multiple)
        self.nl = max(_pad_up(idx.n_links, 8), 8)

        h = idx.role_closure
        link_roles = idx.links[:, 0] if idx.n_links else np.zeros(0, np.int64)

        # static gather/scatter index vectors
        self._nf1 = (idx.nf1[:, 0], idx.nf1[:, 1])
        self._nf2 = (idx.nf2[:, 0], idx.nf2[:, 1], idx.nf2[:, 2])
        self._nf3 = (idx.nf3[:, 0], idx.nf3[:, 1])
        self._nf4 = (idx.nf4[:, 0], idx.nf4[:, 1], idx.nf4[:, 2])
        self._cp = (
            idx.chain_pairs[:, 0],
            idx.chain_pairs[:, 1],
            idx.chain_pairs[:, 2],
        )

        # fillers of every (padded) link; padded links point at ⊥'s row but
        # have all-False mask columns, so they never fire
        fillers = np.zeros(self.nl, np.int32)
        if idx.n_links:
            fillers[: idx.n_links] = idx.links[:, 1]
        self._fillers = fillers

        # M4[l, j] = H[role(l), s_j] — static role-closure mask for CR4
        k4 = len(idx.nf4)
        m4 = np.zeros((self.nl, k4), bool)
        if k4 and idx.n_links:
            m4[: idx.n_links, :] = h[link_roles][:, idx.nf4[:, 0]]
        self._m4 = m4

        # M6[l, p] = H[role(l), r_p] — static first-leg mask for CR6
        p6 = len(idx.chain_pairs)
        m6 = np.zeros((self.nl, p6), bool)
        if p6 and idx.n_links:
            m6[: idx.n_links, :] = h[link_roles][:, idx.chain_pairs[:, 0]]
        self._m6 = m6

        self._sharding = None
        if mesh is not None:
            P = jax.sharding.PartitionSpec
            self._sharding = {
                "s": jax.sharding.NamedSharding(mesh, P(concept_axis, None)),
                "r": jax.sharding.NamedSharding(mesh, P(concept_axis, None)),
                "rep": jax.sharding.NamedSharding(mesh, P()),
            }

        self._step_jit = jax.jit(self._step)
        self._saturate_jit = jax.jit(self._saturate_loop, static_argnums=(1,))

    # ------------------------------------------------------------ state

    def initial_state(self) -> Tuple[jax.Array, jax.Array]:
        """S(X) = {X, ⊤} for every concept (reference
        ``init/AxiomLoader.java:1237-1245``); R empty."""
        s = jnp.eye(self.nc, dtype=bool)
        s = s.at[:, TOP_ID].set(True)
        r = jnp.zeros((self.nc, self.nl), dtype=bool)
        if self._sharding is not None:
            s = jax.device_put(s, self._sharding["s"])
            r = jax.device_put(r, self._sharding["r"])
        return s, r

    def embed_state(self, s_old, r_old) -> Tuple[jax.Array, jax.Array]:
        """Embed a previous saturated state (old concept/link universe) into
        this engine's (padded, possibly larger) arrays.  Ids are stable by
        construction (``Indexer`` interns append-only), so the old arrays
        land in the top-left block; new rows get the S(X)={X,⊤} init."""
        s_old = np.asarray(s_old)
        r_old = np.asarray(r_old)
        no, lo = s_old.shape[0], r_old.shape[1]
        if (no, s_old.shape[1], lo) == (self.nc, self.nc, self.nl):
            s, r = jnp.asarray(s_old), jnp.asarray(r_old)
        else:
            s = np.eye(self.nc, dtype=bool)
            s[:, TOP_ID] = True
            nn = min(no, self.nc)
            s[:nn, :nn] |= s_old[:nn, :nn]
            r = np.zeros((self.nc, self.nl), dtype=bool)
            r[:nn, : min(lo, self.nl)] = r_old[:nn, : min(lo, self.nl)]
            s, r = jnp.asarray(s), jnp.asarray(r)
        if self._sharding is not None:
            s = jax.device_put(s, self._sharding["s"])
            r = jax.device_put(r, self._sharding["r"])
        return s, r

    # ------------------------------------------------------------- rules

    def _andor(self, a: jax.Array, b: jax.Array) -> jax.Array:
        """AND-OR semiring product of boolean matrices on the MXU."""
        dt = self.matmul_dtype
        prod = jnp.matmul(
            a.astype(dt), b.astype(dt), preferred_element_type=jnp.float32
        )
        return prod > 0

    def _step(self, s: jax.Array, r: jax.Array) -> Tuple[jax.Array, jax.Array]:
        idx = self.idx
        # CR1: a ⊑ b
        if len(idx.nf1):
            a, b = self._nf1
            s = s.at[:, b].max(s[:, a])
        # CR2: a1 ⊓ a2 ⊑ b
        if len(idx.nf2):
            a1, a2, b = self._nf2
            s = s.at[:, b].max(s[:, a1] & s[:, a2])
        # CR3: a ⊑ ∃link
        if len(idx.nf3):
            a, l = self._nf3
            r = r.at[:, l].max(s[:, a])
        # CR4: ∃s.a ⊑ b via one [Nc,L]@[L,K4] semiring matmul
        if len(idx.nf4):
            _, a4, b4 = self._nf4
            sf = s[self._fillers]                       # [L, Nc]
            w = jnp.asarray(self._m4) & sf[:, a4]       # [L, K4]
            t = self._andor(r, w)                       # [Nc, K4]
            s = s.at[:, b4].max(t)
        # CR6: role chains via one [Nc,L]@[L,P] semiring matmul
        if len(idx.chain_pairs):
            _, l2, lt = self._cp
            rf = r[self._fillers]                       # [L, L]
            d = jnp.asarray(self._m6) & rf[:, l2]       # [L, P]
            t6 = self._andor(r, d)                      # [Nc, P]
            r = r.at[:, lt].max(t6)
        # CR5: ⊥ back-propagation over all role pairs
        if idx.has_bottom_axioms and idx.n_links:
            botf = s[self._fillers, BOTTOM_ID]          # [L]
            newbot = self._andor(r, botf[:, None])[:, 0]
            s = s.at[:, BOTTOM_ID].max(newbot)
        return s, r

    def step(self, s: jax.Array, r: jax.Array) -> Tuple[jax.Array, jax.Array]:
        return self._step_jit(s, r)

    # -------------------------------------------------------- fixed point

    def _saturate_loop(
        self, state: Tuple[jax.Array, jax.Array], max_iters: int
    ) -> SaturationState:
        s0, r0 = state

        def cond(st: SaturationState):
            return st.changed & (st.iteration < max_iters)

        def body(st: SaturationState):
            s2, r2 = self._step(st.s, st.r)
            # global convergence vote — the reference's barrier AND-vote
            # (controller/CommunicationHandler.java:78-83) as one psum
            changed = jnp.any(s2 != st.s) | jnp.any(r2 != st.r)
            return SaturationState(s2, r2, st.iteration + 1, changed)

        init = SaturationState(
            s0, r0, jnp.asarray(0, jnp.int32), jnp.asarray(True)
        )
        return lax.while_loop(cond, body, init)

    def saturate(
        self,
        max_iters: int = 10_000,
        *,
        initial: Optional[Tuple[jax.Array, jax.Array]] = None,
        allow_incomplete: bool = False,
    ) -> SaturationResult:
        """Run to fixed point.  ``initial`` resumes from a prior (possibly
        smaller) saturated state — the incremental-reasoning path: EL+ is
        monotone, so re-saturating from an old closure plus new axioms
        equals classifying from scratch (the reference's CURRENT_INCREMENT
        design, ``init/AxiomLoader.java:119-129``)."""
        if initial is None:
            initial = self.initial_state()
        else:
            initial = self.embed_state(*initial)
        # count only logical rows — padded inert rows also accumulate
        # ⊤-sourced bits and must not inflate the derivation metric
        n = self.idx.n_concepts
        init_bits = int(jnp.sum(initial[0][:n])) + int(jnp.sum(initial[1][:n]))
        final = self._saturate_jit(initial, max_iters)
        jax.block_until_ready(final.s)
        converged = not bool(final.changed)
        if not converged and not allow_incomplete:
            raise RuntimeError(
                f"saturation did not converge within {max_iters} iterations"
            )
        s = np.asarray(final.s)
        r = np.asarray(final.r)
        derivations = int(s[:n].sum()) + int(r[:n].sum()) - init_bits
        return SaturationResult(
            s=s,
            r=r,
            iterations=int(final.iteration),
            derivations=derivations,
            idx=self.idx,
            converged=converged,
        )
