"""The TPU saturation engine: EL+ completion as boolean tensor algebra.

This replaces, in one ``jax.jit``-compiled function, the reference's entire
distributed run-time — the per-rule processors
(``base/Type*AxiomProcessorBase.java``), their ~12 Redis Lua kernels
(``misc/ScriptsCollection.java:5-135``), the barrier/convergence vote
(``controller/CommunicationHandler.java:49-84``), and the work-stealing
load balancer (``worksteal/``) — with dataflow the XLA compiler schedules
statically:

  state   S[x, a]  bool — a ∈ S(x)       (the reference's inverted result
                                          zsets, ``init/AxiomLoader.java:1237-1245``)
          R[x, l]  bool — (x, filler(l)) ∈ R(role(l)) over the closed link
                                          table (see ``core/indexing.py``)

  CR1  S[:, b]  ∨= S[:, a]                       column gather/scatter
  CR2  S[:, b]  ∨= S[:, a1] ∧ S[:, a2]           column gather/scatter
  CR3  R[:, l]  ∨= S[:, a]                       column gather/scatter
  CR4  S[:, b_j] ∨= (R ⊙ W)[:, j]                MXU matmul [Nc,L]@[L,K4]
         W[l, j] = H[role(l), s_j] ∧ S[filler(l), a_j]
  CR6  R[:, lt_p] ∨= (R ⊙ D)[:, p]               MXU matmul [Nc,L]@[L,P]
         D[l, p] = H[role(l), r_p] ∧ R[filler(l), l2_p]
  CR5  S[:, ⊥]  ∨= R ⊙ S[fillers, ⊥]             MXU matvec

(⊙ = AND-OR semiring product, executed as a bf16 matmul with f32
accumulation + threshold — exact for < 2^24 terms.)

Role hierarchy (CR5' in the reference, ``base/Type4AxiomProcessorBase.java``)
never materializes: consumers read through the static reflexive-transitive
closure masks M4/M6.  The fixed-point loop is ``lax.while_loop`` with a
global any(changed) — under a sharded mesh XLA lowers that to the ``psum``
collective, which IS the reference's AND-vote barrier
(``controller/CommunicationHandler.java:78-83``) in one instruction.

Semi-naive/delta evaluation (the reference's score-cursor machinery,
``misc/Util.java:68-93``): dense recomputation is idempotent and each
iteration only costs the same matmuls, so the frontier bookkeeping
disappears; ``changed`` plays the role of the global delta test.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, NamedTuple, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distel_tpu.core.indexing import BOTTOM_ID, TOP_ID, IndexedOntology
from distel_tpu.runtime.instrumentation import DISPATCH_EVENTS


class SaturationState(NamedTuple):
    s: jax.Array          # [Nc, Nc] bool
    r: jax.Array          # [Nc, L] bool
    iteration: jax.Array  # i32 scalar
    changed: jax.Array    # bool scalar


class _RunOutput(NamedTuple):
    """Device-resident outputs of one fixed-point run.  S and R travel
    host-ward bit-packed (uint32, 32 concepts/word) — a 32x smaller D2H
    transfer than XLA's byte-per-bool layout, which dominates wall time on
    remote-attached chips; derivation counts are reduced on device for the
    same reason."""

    packed_s: jax.Array   # [Nc, Nc/32] uint32
    packed_r: jax.Array   # [Nc, L/32] uint32
    iteration: jax.Array  # i32 scalar
    changed: jax.Array    # bool scalar
    bits: jax.Array       # [Nc] i32: per-row popcount of live rows of S+R
                          # (host sums in int64 — a device-side grand total
                          # would overflow i32 past ~46k concepts and x64 is
                          # disabled by default)


def _pack_bits(x: jax.Array) -> jax.Array:
    """bool [N, M] (M % 32 == 0) → uint32 [N, M/32], little-endian bit order
    (bit i of word w = column 32*w + i)."""
    w = x.reshape(x.shape[0], -1, 32).astype(jnp.uint32)
    weights = jnp.left_shift(
        jnp.asarray(1, jnp.uint32), jnp.arange(32, dtype=jnp.uint32)
    )
    return jnp.sum(w * weights, axis=-1, dtype=jnp.uint32)


def _unpack_bits_host(p: np.ndarray, m: int) -> np.ndarray:
    """Inverse of :func:`_pack_bits` on the host."""
    b = np.unpackbits(
        np.ascontiguousarray(p).view(np.uint8), axis=1, bitorder="little"
    )
    # unpackbits yields fresh 0/1 uint8 — reinterpret, don't copy
    return b[:, :m].view(np.bool_)


@dataclass
class SaturationResult:
    """Result of a saturation run.  ``packed_s``/``packed_r`` may still be
    **device-resident** jax arrays: ``saturate`` fetches only scalars and
    per-row counts, so a run whose consumer never reads the closure pays no
    bulk D2H transfer (on remote-attached chips the tunnel runs ~10 MB/s —
    two orders of magnitude below the device's compute on the same data).
    ``s``/``r`` transfer + unpack lazily on first access and cache.

    ``transposed=True`` marks row-packed-engine results, whose packed
    arrays are subsumer-major ([a, xw] / [l, xw]); ``s``/``r`` still
    always present the x-major [x, a] / [x, l] view."""

    packed_s: np.ndarray  # [Nc, Nc/32] uint32 (np or device jax.Array)
    packed_r: np.ndarray  # [Nc, L/32] uint32 (np or device jax.Array)
    iterations: int
    derivations: int
    idx: IndexedOntology
    converged: bool = True
    transposed: bool = False
    _s: Optional[np.ndarray] = field(default=None, repr=False)
    _r: Optional[np.ndarray] = field(default=None, repr=False)

    def _fetch(self) -> None:
        """One-time D2H transfer of the packed closure (no-op if
        host-side).  Under a multi-controller run this is a collective
        allgather — every process must read in the same order."""
        if not isinstance(self.packed_s, np.ndarray):
            self.packed_s, self.packed_r = fetch_global(
                (self.packed_s, self.packed_r)
            )

    @property
    def s(self) -> np.ndarray:
        if self._s is None:
            self._fetch()
            u = _unpack_bits_host(self.packed_s, self.packed_s.shape[1] * 32)
            self._s = u.T if self.transposed else u
        return self._s

    @property
    def r(self) -> np.ndarray:
        if self._r is None:
            self._fetch()
            u = _unpack_bits_host(self.packed_r, self.packed_r.shape[1] * 32)
            self._r = u.T if self.transposed else u
        return self._r

    def subsumers(self, concept_id: int) -> Set[int]:
        return set(np.nonzero(self.s[concept_id])[0].tolist())

    def subsumer_dict(self) -> Dict[int, Set[int]]:
        n = self.idx.n_concepts
        return {c: set(np.nonzero(self.s[c, :n])[0].tolist()) for c in range(n)}

    def unsatisfiable(self) -> Set[int]:
        col = self.s[: self.idx.n_concepts, BOTTOM_ID]
        return set(np.nonzero(col)[0].tolist())


def _pad_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _host_bit_total(bits: np.ndarray) -> int:
    """Sum per-row popcounts in int64 on the host (a device-side grand total
    would overflow i32 past ~46k concepts; x64 is disabled by default)."""
    return int(np.asarray(bits, np.int64).sum())


def check_embed_fits(allow_shrink: bool, **dims: Tuple[int, int]) -> None:
    """Refuse to silently clip a shrinking universe on resume.

    ``dims`` maps an axis name to ``(old, new)``; any ``old > new`` means
    the caller is embedding a state whose universe exceeds this engine's —
    concept ids are append-only, so that only happens on a mismatched
    snapshot (wrong corpus / unaligned names), and clipping would warm-start
    from a silently truncated closure.  Name-realign instead
    (``load_snapshot_state(..., idx=idx)``) or opt in explicitly."""
    if allow_shrink:
        return
    over = {k: v for k, v in dims.items() if v[0] > v[1]}
    if over:
        detail = ", ".join(f"{k}: {o} > {n}" for k, (o, n) in over.items())
        raise ValueError(
            f"embed_state: old state exceeds this engine's universe "
            f"({detail}); realign the snapshot by name "
            f"(load_snapshot_state(path, idx=engine.idx)) or pass "
            f"allow_shrink=True to clip deliberately"
        )


def observed_loop(
    observe_step, s, r, init_total: int, unroll: int, budget: int, observer,
    state_observer=None, pipeline_depth: int = 1, round_stats=None,
):
    """Shared superstep/observer protocol of both engines'
    ``saturate_observed``: run ``observe_step`` (returning
    ``(s, r, changed, live_bits)``) until convergence or budget, calling
    ``observer(iteration, derivations, changed)`` after each round.

    ``pipeline_depth > 1`` runs the loop PIPELINED: up to ``depth``
    rounds are speculatively dispatched before the oldest round's
    ``changed``/``bits`` fold is retired from the in-flight queue —
    rounds depend only on device-carried state, so round N+1's device
    execution overlaps round N's host fold.  Dispatch goes through a
    dedicated single-worker executor, which makes the overlap real
    even on backends whose dispatch is blocking (the jax CPU runtime
    executes this program inline at dispatch; a true async-dispatch
    accelerator pays one cheap indirection).
    The retired sequence (per-round totals, observer calls, the final
    state) is byte-identical to the synchronous loop: the same step
    programs run in the same order, only the host-side fetch is
    deferred.  On convergence at round N, the ≤depth-1 speculatively
    dispatched extra rounds are no-ops at the fixed point (every rule
    is a monotone OR — their derivation deltas are provably zero): they
    are dropped unretired and excluded from iteration/derivation
    accounting, so converged results report the true fixed-point round
    count.

    ``state_observer(iteration, derivations, changed, s, r)`` — if given —
    additionally receives the LIVE device state after each round, so a
    long run can snapshot mid-flight (the r4 verdict's resume ask: two
    consecutive rounds lost a multi-hour 128k execution at teardown
    because in-flight state was never persisted).  The callback runs
    synchronously between rounds; the arrays it sees are the round's
    outputs and are not donated until the next ``observe_step`` call, so
    fetching them inside the callback is race-free.  That contract is
    incompatible with speculative dispatch (a retired round's arrays
    would already be donated into the next in-flight round), so a
    ``state_observer`` forces ``pipeline_depth`` to 1.

    ``round_stats(iteration, delta, changed, dispatch_s, retire_s,
    inflight)`` — if given — is called once per RETIRED round with the
    round's derivation delta and its host-time split (``inflight`` is
    the queue occupancy when the round was dispatched; 0 means it was
    dispatched synchronously) — the hook the engines hang per-round
    ``FrontierStats`` telemetry on.

    The state arrives in the CALLING ENGINE's working layout — wire-packed
    subsumer-major uint32 (sp, rp) from ``RowPackedSaturationEngine``, but
    UNPACKED x-major bool (s, r) from the dense ``SaturationEngine`` — so
    a snapshot callback is engine-specific: only the row-packed pair may
    be saved as a ``transposed=True`` wire snapshot
    (``runtime/checkpoint.py`` v2); wrapping dense bool arrays that way
    would persist garbage words without an error."""
    import time as _time
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    depth = max(int(pipeline_depth), 1)
    if state_observer is not None:
        depth = 1
    iteration, converged, total = 0, False, init_total
    dispatched = 0
    pending = deque()  # (iteration_after, handle, dispatch_s)
    # depth > 1: rounds run on a dedicated single-worker executor, so
    # round N+1's device execution overlaps round N's host retire/fold/
    # observer work even when the backend's dispatch is blocking (the
    # jax CPU runtime executes this program INLINE at dispatch — a
    # deferred device_get alone would hide nothing there; on a true
    # async-dispatch backend the executor hop is one cheap indirection).
    # One worker + FIFO submission keeps the round order — and thus the
    # retired sequence — byte-identical to the synchronous loop.
    pool = (
        ThreadPoolExecutor(1, thread_name_prefix="observed-pipeline")
        if depth > 1
        else None
    )
    latest = None  # newest dispatched round's future (pool mode only)
    try:
        while True:
            # keep the device queue full: dispatch until the queue holds
            # ``depth`` rounds (depth 1 == the synchronous loop: one
            # dispatch, immediately retired below)
            while dispatched < budget and len(pending) < depth:
                t0 = _time.perf_counter()
                if pool is None:
                    s, r, changed_dev, bits = observe_step(s, r)
                    handle = (changed_dev, bits)
                else:
                    # producer/consumer split: the worker runs the
                    # round AND fetches its observables to the host, so
                    # every device-side wait — including the jax CPU
                    # runtime's dispatch quirks (dependent dispatch
                    # blocks holding the GIL; dispatch may execute the
                    # program inline) — lands on the worker thread,
                    # overlapped with the main thread's fold/observer
                    # work.  The future resolves to HOST values; the
                    # single worker runs tasks in order, so ``prev`` is
                    # done before the closure starts and result() is
                    # instant
                    def _run(prev=latest, s0=s, r0=r):
                        a, b = (s0, r0) if prev is None else prev.result()[:2]
                        a, b, changed_d, bits_d = observe_step(a, b)
                        return (a, b) + fetch_global((changed_d, bits_d))

                    handle = latest = pool.submit(_run)
                dispatch_s = _time.perf_counter() - t0
                dispatched += unroll
                DISPATCH_EVENTS.record_dense()
                pending.append((dispatched, handle, dispatch_s))
            if not pending:
                break  # budget exhausted without convergence
            it_after, handle, dispatch_s = pending.popleft()
            inflight = len(pending)
            t0 = _time.perf_counter()
            if pool is None:
                changed, bits_host = fetch_global(handle)
            else:
                _, _, changed, bits_host = handle.result()
            retire_s = _time.perf_counter() - t0
            prev_total = total
            total = _host_bit_total(bits_host)
            iteration = it_after
            if round_stats is not None:
                # before ``observer``, so an observer that correlates
                # per-round telemetry (scale_probe's progress lines) sees
                # THIS round's stats, matching the adaptive controller's
                # frontier_observer-then-observer ordering
                round_stats(
                    iteration, total - prev_total, bool(changed),
                    dispatch_s, retire_s, inflight,
                )
            if observer is not None:
                observer(iteration, total - init_total, bool(changed))
            if state_observer is not None:
                # depth is 1 here, so s/r ARE this round's outputs and
                # the next dispatch (which would donate them) has not
                # happened
                state_observer(
                    iteration, total - init_total, bool(changed), s, r
                )
            if not changed:
                # drop the in-flight speculative rounds: at the fixed
                # point they change nothing (s/r — the newest dispatched
                # round's outputs — are byte-identical to this round's),
                # and their iterations never count
                converged = True
                break
    finally:
        if pool is not None:
            pool.shutdown(wait=True)
    if latest is not None:
        # pool mode: the main-thread s/r are stale — the current state
        # is the newest dispatched round's outputs (resolved: shutdown
        # above waited out the queue)
        s, r = latest.result()[:2]
    return s, r, iteration, total, converged


def fetch_global(tree):
    """``jax.device_get`` that also works on arrays spanning other
    processes' devices (multi-controller runs): such arrays are gathered
    with ``process_allgather``, which is a collective — every process
    must call this on the same values, which they do (SPMD epilogue).
    The fallback is selected per leaf by addressability, so unrelated
    ``RuntimeError``s (e.g. a donated buffer) surface unchanged; when
    every leaf is local the whole tree goes through ONE ``device_get``
    (a per-leaf loop costs one tunnel round trip per leaf on
    remote-attached devices — measured 4x the warm fixed-point wall
    time at small scale)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if all(
        not isinstance(x, jax.Array) or x.is_fully_addressable
        for x in leaves
    ):
        return jax.device_get(tree)

    def get(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return jax.device_get(x)

    return jax.tree_util.tree_map(get, tree)


def fresh_init_total(idx: IndexedOntology) -> int:
    """Live bits of the S(X)={X,⊤} initial state: one diagonal bit per
    live concept plus the full ⊤ row, overlapping at (⊤, ⊤).  Used so
    ``saturate`` never computes the init count INSIDE the donated run
    program: with buffer donation + memory-pressure rematerialization
    the tunnel XLA was observed (96k many-role corpus) to alias the
    early init-count buffer onto the in-place loop state, returning the
    FINAL count twice and reporting zero derivations."""
    return 2 * idx.n_concepts - 1


def finish_device_run(
    out,
    idx: IndexedOntology,
    budget: int,
    allow_incomplete: bool,
    transposed: bool,
    init_total: int,
) -> "SaturationResult":
    """Shared epilogue of the packed engines' ``saturate``: ``out`` is
    ``(sp, rp, iteration, changed, bits)`` where the scalars
    may carry one lane per shard.  Fetches only scalars and per-row
    counts — the packed closure stays device-resident until someone reads
    it (``SaturationResult._fetch``).  ``init_total`` is computed by the
    caller OUTSIDE the run program (see :func:`fresh_init_total`)."""
    sp, rp = out[0], out[1]
    it, changed, bits = fetch_global(out[2:])
    it, changed = np.max(it), np.max(changed)
    converged = not bool(changed)
    if not converged and not allow_incomplete:
        raise RuntimeError(
            f"saturation did not converge within {budget} iterations"
        )
    return SaturationResult(
        packed_s=sp,
        packed_r=rp,
        iterations=int(it),
        derivations=_host_bit_total(bits) - init_total,
        idx=idx,
        converged=converged,
        transposed=transposed,
    )


class SaturationEngine:
    """Compiles an indexed ontology into a jitted fixed-point program.

    ``pad_multiple`` pads the concept axis so shards divide evenly on a
    mesh (and MXU tiles line up); padded rows/columns hold inert concepts.
    """

    def __init__(
        self,
        idx: IndexedOntology,
        *,
        pad_multiple: int = 128,
        mesh: Optional[jax.sharding.Mesh] = None,
        concept_axis: str = "c",
        matmul_dtype=None,
        unroll: int = 4,
    ):
        self.idx = idx
        self.mesh = mesh
        self.concept_axis = concept_axis
        if matmul_dtype is None:
            # bf16 feeds the MXU at twice the rate of f32; CPU's thunk
            # runtime cannot execute a raw bf16 dot, so tests fall back
            matmul_dtype = (
                jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
            )
        self.matmul_dtype = matmul_dtype
        # steps per while_loop body: amortizes the per-iteration convergence
        # vote (a host roundtrip on remote-attached chips); extra post-
        # convergence steps are idempotent and cost only the step itself
        self.unroll = max(int(unroll), 1)
        # bit-packing needs both axes 32-aligned; the mesh needs the concept
        # axis divisible by the shard count — make pad_multiple satisfy both
        pad_multiple = _pad_up(max(pad_multiple, 32), 32)
        if mesh is not None:
            pad_multiple *= mesh.shape[concept_axis]
        self.nc = _pad_up(max(idx.n_concepts, 2), pad_multiple)
        self.nl = max(_pad_up(idx.n_links, 32), 32)

        h = idx.role_closure
        link_roles = idx.links[:, 0] if idx.n_links else np.zeros(0, np.int64)

        # static gather/scatter index vectors
        self._nf1 = (idx.nf1[:, 0], idx.nf1[:, 1])
        self._nf2 = (idx.nf2[:, 0], idx.nf2[:, 1], idx.nf2[:, 2])
        self._nf3 = (idx.nf3[:, 0], idx.nf3[:, 1])
        self._nf4 = (idx.nf4[:, 0], idx.nf4[:, 1], idx.nf4[:, 2])
        self._cp = (
            idx.chain_pairs[:, 0],
            idx.chain_pairs[:, 1],
            idx.chain_pairs[:, 2],
        )

        # fillers of every (padded) link; padded links point at ⊥'s row but
        # have all-False mask columns, so they never fire
        fillers = np.zeros(self.nl, np.int32)
        if idx.n_links:
            fillers[: idx.n_links] = idx.links[:, 1]
        self._fillers = fillers

        # M4[l, j] = H[role(l), s_j] — static role-closure mask for CR4
        k4 = len(idx.nf4)
        m4 = np.zeros((self.nl, k4), bool)
        if k4 and idx.n_links:
            m4[: idx.n_links, :] = h[link_roles][:, idx.nf4[:, 0]]
        self._m4 = m4

        # M6[l, p] = H[role(l), r_p] — static first-leg mask for CR6
        p6 = len(idx.chain_pairs)
        m6 = np.zeros((self.nl, p6), bool)
        if p6 and idx.n_links:
            m6[: idx.n_links, :] = h[link_roles][:, idx.chain_pairs[:, 0]]
        self._m6 = m6

        self._sharding = None
        if mesh is not None:
            P = jax.sharding.PartitionSpec
            self._sharding = {
                "s": jax.sharding.NamedSharding(mesh, P(concept_axis, None)),
                "r": jax.sharding.NamedSharding(mesh, P(concept_axis, None)),
                "rep": jax.sharding.NamedSharding(mesh, P()),
            }

        self._step_jit = jax.jit(self._step)
        self._observe_jit = None
        self._live_bits_jit = None
        self._pack_jit = jax.jit(_pack_bits)
        self._initial_jit = None
        self._run_fresh_jit = jax.jit(self._run_fresh, static_argnums=(0,))
        self._run_from_jit = jax.jit(self._run_from, static_argnums=(1,))

    # ------------------------------------------------------------ state

    def _initial_arrays(self) -> Tuple[jax.Array, jax.Array]:
        """S(X) = {X, ⊤} for every concept (reference
        ``init/AxiomLoader.java:1237-1245``); R empty.  Traceable — used
        both inside the jitted fresh-run program and eagerly."""
        s = jnp.eye(self.nc, dtype=bool)
        s = s.at[:, TOP_ID].set(True)
        r = jnp.zeros((self.nc, self.nl), dtype=bool)
        if self._sharding is not None:
            s = lax.with_sharding_constraint(s, self._sharding["s"])
            r = lax.with_sharding_constraint(r, self._sharding["r"])
        return s, r

    def initial_state(self) -> Tuple[jax.Array, jax.Array]:
        if self._initial_jit is None:
            self._initial_jit = jax.jit(self._initial_arrays)
        return self._initial_jit()

    def embed_state(
        self, s_old, r_old, *, allow_shrink: bool = False
    ) -> Tuple[jax.Array, jax.Array]:
        """Embed a previous saturated state (old concept/link universe) into
        this engine's (padded, possibly larger) arrays.  Ids are stable by
        construction (``Indexer`` interns append-only), so the old arrays
        land in the top-left block; new rows get the S(X)={X,⊤} init."""
        s_old = np.asarray(s_old)
        r_old = np.asarray(r_old)
        if s_old.dtype == np.uint32:
            raise TypeError(
                "packed transposed state (uint32) is only understood by "
                "the row-packed engine; pass unpacked bool arrays (e.g. "
                "load_snapshot_state(path, unpack=True))"
            )
        no, lo = s_old.shape[0], r_old.shape[1]
        check_embed_fits(
            allow_shrink,
            concepts=(no, self.nc),
            subsumers=(s_old.shape[1], self.nc),
            link_rows=(r_old.shape[0], self.nc),
            links=(lo, self.nl),
        )
        if (no, s_old.shape[1], lo) == (self.nc, self.nc, self.nl):
            s, r = jnp.asarray(s_old), jnp.asarray(r_old)
        else:
            s = np.eye(self.nc, dtype=bool)
            s[:, TOP_ID] = True
            nn = min(no, self.nc)
            s[:nn, :nn] |= s_old[:nn, :nn]
            r = np.zeros((self.nc, self.nl), dtype=bool)
            r[:nn, : min(lo, self.nl)] = r_old[:nn, : min(lo, self.nl)]
            s, r = jnp.asarray(s), jnp.asarray(r)
        if self._sharding is not None:
            s = jax.device_put(s, self._sharding["s"])
            r = jax.device_put(r, self._sharding["r"])
        return s, r

    # ------------------------------------------------------------- rules

    def _andor(self, a: jax.Array, b: jax.Array) -> jax.Array:
        """AND-OR semiring product of boolean matrices on the MXU."""
        dt = self.matmul_dtype
        prod = jnp.matmul(
            a.astype(dt), b.astype(dt), preferred_element_type=jnp.float32
        )
        return prod > 0

    def _step(self, s: jax.Array, r: jax.Array) -> Tuple[jax.Array, jax.Array]:
        idx = self.idx
        # CR1: a ⊑ b
        if len(idx.nf1):
            a, b = self._nf1
            s = s.at[:, b].max(s[:, a])
        # CR2: a1 ⊓ a2 ⊑ b
        if len(idx.nf2):
            a1, a2, b = self._nf2
            s = s.at[:, b].max(s[:, a1] & s[:, a2])
        # CR3: a ⊑ ∃link
        if len(idx.nf3):
            a, l = self._nf3
            r = r.at[:, l].max(s[:, a])
        # CR4: ∃s.a ⊑ b via one [Nc,L]@[L,K4] semiring matmul
        if len(idx.nf4):
            _, a4, b4 = self._nf4
            sf = s[self._fillers]                       # [L, Nc]
            w = jnp.asarray(self._m4) & sf[:, a4]       # [L, K4]
            t = self._andor(r, w)                       # [Nc, K4]
            s = s.at[:, b4].max(t)
        # CR6: role chains via one [Nc,L]@[L,P] semiring matmul
        if len(idx.chain_pairs):
            _, l2, lt = self._cp
            rf = r[self._fillers]                       # [L, L]
            d = jnp.asarray(self._m6) & rf[:, l2]       # [L, P]
            t6 = self._andor(r, d)                      # [Nc, P]
            r = r.at[:, lt].max(t6)
        # CR5: ⊥ back-propagation over all role pairs
        if idx.has_bottom_axioms and idx.n_links:
            botf = s[self._fillers, BOTTOM_ID]          # [L]
            newbot = self._andor(r, botf[:, None])[:, 0]
            s = s.at[:, BOTTOM_ID].max(newbot)
        return s, r

    def step(self, s: jax.Array, r: jax.Array) -> Tuple[jax.Array, jax.Array]:
        return self._step_jit(s, r)

    # -------------------------------------------------------- fixed point

    def _live_bits(self, s: jax.Array, r: jax.Array) -> jax.Array:
        """Per-row popcount of the non-padded rows of S and R ([Nc] i32).
        Padded inert rows also accumulate ⊤-sourced bits and must not
        inflate the derivation metric."""
        n = self.idx.n_concepts
        live = jnp.arange(self.nc) < n
        per_row = jnp.sum(s, axis=1, dtype=jnp.int32) + jnp.sum(
            r, axis=1, dtype=jnp.int32
        )
        return jnp.where(live, per_row, 0)

    def _advance(
        self, s: jax.Array, r: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """One unrolled superstep + global convergence vote — the
        reference's barrier AND-vote
        (controller/CommunicationHandler.java:78-83) as one psum."""
        s2, r2 = s, r
        for _ in range(self.unroll):
            s2, r2 = self._step(s2, r2)
        changed = jnp.any(s2 != s) | jnp.any(r2 != r)
        return s2, r2, changed

    def _fixed_point(
        self, s0: jax.Array, r0: jax.Array, max_iters: int
    ) -> _RunOutput:
        unroll = self.unroll

        def cond(st: SaturationState):
            return st.changed & (st.iteration < max_iters)

        def body(st: SaturationState):
            s2, r2, changed = self._advance(st.s, st.r)
            return SaturationState(s2, r2, st.iteration + unroll, changed)

        init = SaturationState(
            s0, r0, jnp.asarray(0, jnp.int32), jnp.asarray(True)
        )
        final = lax.while_loop(cond, body, init)
        return _RunOutput(
            packed_s=_pack_bits(final.s),
            packed_r=_pack_bits(final.r),
            iteration=final.iteration,
            changed=final.changed,
            bits=self._live_bits(final.s, final.r),
        )

    def _run_fresh(self, max_iters: int) -> _RunOutput:
        s0, r0 = self._initial_arrays()
        return self._fixed_point(s0, r0, max_iters)

    def _run_from(
        self, state: Tuple[jax.Array, jax.Array], max_iters: int
    ) -> _RunOutput:
        s0, r0 = state
        return self._fixed_point(s0, r0, max_iters)

    def _observe_round(
        self, s: jax.Array, r: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        """:meth:`_advance` plus the live-bit count — the observable unit
        of :meth:`saturate_observed`."""
        s2, r2, changed = self._advance(s, r)
        return s2, r2, changed, self._live_bits(s2, r2)

    def saturate_observed(
        self,
        max_iters: int = 10_000,
        *,
        observer=None,
        state_observer=None,
        initial: Optional[Tuple[jax.Array, jax.Array]] = None,
        allow_incomplete: bool = False,
        pipeline_depth: int = 1,
    ) -> SaturationResult:
        """Fixed point with per-superstep observation.

        The observable analog of the reference's progress plane: the
        pub-sub gossip consumed by ``worksteal/ProgressMessageHandler.java``
        and the timed completeness snapshots of ``misc/ResultSnapshotter.java``.
        One fused program per superstep instead of one per run.  With
        ``pipeline_depth > 1`` the per-superstep host fold is retired
        from an in-flight queue instead of blocking each round (see
        :func:`observed_loop`), which recovers most of
        :meth:`saturate`'s wall time while keeping the per-round
        observation; at the default depth 1 each round still pays a
        blocking host sync.

        ``observer`` is called after every superstep with
        ``(iteration, derivations_so_far, changed)``.
        """
        if self._observe_jit is None:
            # old s/r are dead after each call — donate so the per-superstep
            # path needs no more state memory than the fused while_loop
            self._observe_jit = jax.jit(
                self._observe_round, donate_argnums=(0, 1)
            )
        if initial is None:
            s, r = self.initial_state()
        else:
            # embed_state may return the caller's buffers unchanged when
            # shapes already match — copy so donation can't delete them
            s, r = self.embed_state(*initial)
            s, r = jnp.array(s, copy=True), jnp.array(r, copy=True)
        init_total = _host_bit_total(fetch_global(self._live_bits(s, r)))
        budget = _pad_up(max_iters, self.unroll)
        s, r, iteration, total, converged = observed_loop(
            self._observe_jit, s, r, init_total, self.unroll, budget, observer,
            state_observer=state_observer, pipeline_depth=pipeline_depth,
        )
        packed_s, packed_r = self._pack_jit(s), self._pack_jit(r)
        return self._finish(
            packed_s, packed_r, iteration, total - init_total,
            converged, allow_incomplete, budget,
        )

    def saturate(
        self,
        max_iters: int = 10_000,
        *,
        initial: Optional[Tuple[jax.Array, jax.Array]] = None,
        allow_incomplete: bool = False,
    ) -> SaturationResult:
        """Run to fixed point.  ``initial`` resumes from a prior (possibly
        smaller) saturated state — the incremental-reasoning path: EL+ is
        monotone, so re-saturating from an old closure plus new axioms
        equals classifying from scratch (the reference's CURRENT_INCREMENT
        design, ``init/AxiomLoader.java:119-129``).

        The whole run — init, unrolled while_loop, derivation popcount,
        bit-packing — is one XLA program; the host only receives two packed
        uint32 arrays and three scalars."""
        # round the iteration budget up to a whole number of unrolled bodies
        budget = _pad_up(max_iters, self.unroll)
        # the init count is never computed inside the (donated) run
        # program — see fresh_init_total; fresh runs use the analytic
        # count, resumes pay one eager live-bits round trip
        if initial is None:
            init_total = fresh_init_total(self.idx)
            out = self._run_fresh_jit(budget)
        else:
            state = self.embed_state(*initial)
            if self._live_bits_jit is None:
                self._live_bits_jit = jax.jit(self._live_bits)
            init_total = _host_bit_total(
                fetch_global(self._live_bits_jit(*state))
            )
            out = self._run_from_jit(state, budget)
        # exactly one host sync for the whole run — scalars and per-row
        # counts only; the packed closure stays on device until someone
        # actually reads it (SaturationResult._fetch)
        iteration, changed, bits = fetch_global(
            (out.iteration, out.changed, out.bits)
        )
        derivations = _host_bit_total(bits) - init_total
        return self._finish(
            out.packed_s, out.packed_r, int(iteration), derivations,
            not bool(changed), allow_incomplete, budget,
        )

    def _finish(
        self,
        packed_s: np.ndarray,
        packed_r: np.ndarray,
        iterations: int,
        derivations: int,
        converged: bool,
        allow_incomplete: bool,
        budget: int,
    ) -> SaturationResult:
        if not converged and not allow_incomplete:
            raise RuntimeError(
                f"saturation did not converge within {budget} iterations"
            )
        return SaturationResult(
            packed_s=packed_s,
            packed_r=packed_r,
            iterations=iterations,
            derivations=derivations,
            idx=self.idx,
            converged=converged,
        )
