"""Incremental classification: add axiom batches to a saturated closure.

The reference's streaming mode (``CURRENT_INCREMENT`` counter +
score-cursor deltas, ``init/AxiomLoader.java:119-129``,
``Type1_1AxiomProcessor.java:126-129,359-368``; exercised by
``scripts/traffic-data-load-classify.sh``): a new axiom batch classifies
on top of the existing saturated store without recomputation.

TPU-native version: EL+ saturation is monotone, so the previous closure
S/R is a *sound starting point* — we re-index with the persistent
``Indexer`` (append-only ids), embed the old state into the grown padded
arrays, and run the fixed point again.  Iterations needed ≈ the depth of
*new* consequences only, because everything old is already closed — the
tensor-shaped analog of semi-naive delta evaluation.

Known trade-off: each increment re-traces the saturation program,
because the rule index tables are baked into the jaxpr as constants and
any new axiom changes them (measured: ~13 s per delta at 48k classes on
a v5e, all of it engine build + retrace + compile — the closure itself
stays device-resident between increments, and with the L-frontier the
re-saturation converges in a handful of cheap steps).  The designed fix
is an alternating delta engine — reuse the base corpus's compiled
program (its factored masks are already traced arguments, so role-box
growth rebinds without recompiling) and compile only a small program
for the delta axioms plus the (old-axioms x new-links) cross terms, the
reference's two-sided increment join — deferred: the cross-term
coverage (CR4/CR6 over new links, CR5 over the grown link table) has
enough soundness corners that it needs its own verification round.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from distel_tpu.config import ClassifierConfig
from distel_tpu.core.engine import SaturationResult
from distel_tpu.core.indexing import Indexer
from distel_tpu.frontend.normalizer import NormalizedOntology, Normalizer
from distel_tpu.owl import loader as owl_loader


def _merge(into: NormalizedOntology, batch: NormalizedOntology) -> None:
    into.nf1.extend(batch.nf1)
    into.nf2.extend(batch.nf2)
    into.nf3.extend(batch.nf3)
    into.nf4.extend(batch.nf4)
    into.nf5.extend(batch.nf5)
    into.nf6.extend(batch.nf6)
    into.removed.update(batch.removed)
    into.gensyms.update(batch.gensyms)


class IncrementalClassifier:
    """Owns the persistent Normalizer (shared gensym cache — the reference's
    NORMALIZE_CACHE role), the persistent Indexer (stable ids), and the
    running closure."""

    def __init__(self, config: Optional[ClassifierConfig] = None):
        self.config = config or ClassifierConfig()
        from distel_tpu.parallel import setup

        self._mesh = setup(self.config)
        self.indexer = Indexer()
        self.accumulated = NormalizedOntology()
        self._normalizer_cache: dict = {}
        #: packed closure between increments — device jax.Arrays on the
        #: transposed path (never fetched to host), numpy otherwise
        self._state: Optional[Tuple] = None
        self.increment = 0  # the reference's CURRENT_INCREMENT counter
        self.history: List[dict] = []
        self.last_result: Optional[SaturationResult] = None

    def add_text(self, text: str) -> SaturationResult:
        return self.add_ontology(owl_loader.load(text))

    def _pop_state(self):
        state, self._state = self._state, None
        return state

    def add_ontology(self, onto) -> SaturationResult:
        normalizer = Normalizer(cache=self._normalizer_cache)
        batch = normalizer.normalize(onto)
        self._normalizer_cache = normalizer.export_cache()
        _merge(self.accumulated, batch)

        idx = self.indexer.index(self.accumulated)
        from distel_tpu.runtime.classifier import make_engine

        engine = make_engine(self.config, idx, mesh=self._mesh)
        # hand the old closure over without keeping a reference in this
        # frame: the embed copies it into the grown arrays, and holding
        # the old device buffers through the run would add a full extra
        # state to peak HBM — the difference between the incremental and
        # batch ceilings
        self.last_result = None
        result = engine.saturate(
            self.config.max_iterations,
            initial=self._pop_state(),
        )
        if result.transposed:
            # keep the closure packed AND device-resident: the next
            # increment's embed runs on device, so the closure never
            # crosses the tunnel between increments (a host round trip
            # costs minutes at 64k scale)
            self._state = (result.packed_s, result.packed_r)
        else:
            self._state = (result.s, result.r)
        self.increment += 1
        self.history.append(
            {
                "increment": self.increment,
                "batch_axioms": batch.axiom_count(),
                "iterations": result.iterations,
                "new_derivations": result.derivations,
            }
        )
        self.last_result = result
        return result
