"""Incremental classification: add axiom batches to a saturated closure.

The reference's streaming mode (``CURRENT_INCREMENT`` counter +
score-cursor deltas, ``init/AxiomLoader.java:119-129``,
``Type1_1AxiomProcessor.java:126-129,359-368``; exercised by
``scripts/traffic-data-load-classify.sh``): a new axiom batch classifies
on top of the existing saturated store without recomputation.

TPU-native version: EL+ saturation is monotone, so the previous closure
S/R is a *sound starting point* — we re-index with the persistent
``Indexer`` (append-only ids), embed the old state into the grown padded
arrays, and run the fixed point again.  Iterations needed ≈ the depth of
*new* consequences only, because everything old is already closed — the
tensor-shaped analog of semi-naive delta evaluation.

Retrace amortization — the **delta fast path** (``_delta_fast_path``):
for class-only deltas (no new links, roles, or chain pairs — the
dominant streaming shape) over a base of ≥32k concepts, the base
corpus's compiled program is reused as-is and only a small program over
the delta's own axiom rows is compiled; the two alternate to a joint
fixed point.  Soundness rests on the transposed packed layout: the base
program's rules operate on subsumer/link ROWS, and the delta's new
concepts are new bit LANES inside the base engine's padding, which
every row op processes correctly without knowing they exist.  Measured
at 48k classes: 7-10.6 s per 50-200-axiom delta vs 13.3-14.3 s for the
full rebuild — and unlike the rebuild, the fast path's cost does not
grow with the corpus (the base program never recompiles).  Deltas that
add links/roles/chains, overflow the concept padding, or arrive on a
small corpus take the full-rebuild path unchanged.  The remaining
general fix — cross-term programs for (old axioms x new links), the
reference's two-sided increment join — stays deferred to its own
verification round.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from distel_tpu.config import ClassifierConfig
from distel_tpu.core.engine import SaturationResult
from distel_tpu.core.indexing import Indexer
from distel_tpu.frontend.normalizer import NormalizedOntology, Normalizer
from distel_tpu.owl import loader as owl_loader


def _merge(into: NormalizedOntology, batch: NormalizedOntology) -> None:
    into.nf1.extend(batch.nf1)
    into.nf2.extend(batch.nf2)
    into.nf3.extend(batch.nf3)
    into.nf4.extend(batch.nf4)
    into.nf5.extend(batch.nf5)
    into.nf6.extend(batch.nf6)
    into.removed.update(batch.removed)
    into.gensyms.update(batch.gensyms)


class IncrementalClassifier:
    """Owns the persistent Normalizer (shared gensym cache — the reference's
    NORMALIZE_CACHE role), the persistent Indexer (stable ids), and the
    running closure."""

    #: extra concept-id headroom built into the full-path engine so
    #: later class-only deltas reuse its compiled program (new concepts
    #: are new bit lanes inside the existing padding)
    _CAPACITY_PAD = 2048

    #: below this many base concepts the full rebuild is cheaper than
    #: the fast path's fixed compile costs (see _delta_fast_path)
    _FAST_PATH_MIN_CONCEPTS = 32_768

    def __init__(self, config: Optional[ClassifierConfig] = None):
        self.config = config or ClassifierConfig()
        from distel_tpu.parallel import setup

        self._mesh = setup(self.config)
        self.indexer = Indexer()
        self.accumulated = NormalizedOntology()
        self._normalizer_cache: dict = {}
        #: packed closure between increments — device jax.Arrays on the
        #: transposed path (never fetched to host), numpy otherwise
        self._state: Optional[Tuple] = None
        self.increment = 0  # the reference's CURRENT_INCREMENT counter
        self.history: List[dict] = []
        self.last_result: Optional[SaturationResult] = None
        #: base-program reuse (the delta fast path): the engine compiled
        #: by the last full rebuild + the index snapshot it was built at
        self._base_engine = None
        self._base_idx = None

    def add_text(self, text: str) -> SaturationResult:
        return self.add_ontology(owl_loader.load(text))

    def _pop_state(self):
        state, self._state = self._state, None
        return state

    def add_ontology(self, onto) -> SaturationResult:
        normalizer = Normalizer(cache=self._normalizer_cache)
        batch = normalizer.normalize(onto)
        self._normalizer_cache = normalizer.export_cache()
        _merge(self.accumulated, batch)

        idx = self.indexer.index(self.accumulated)
        result = self._delta_fast_path(idx)
        if result is None:
            result = self._full_rebuild(idx)
        if result.transposed:
            # keep the closure packed AND device-resident: the next
            # increment's embed runs on device, so the closure never
            # crosses the tunnel between increments (a host round trip
            # costs minutes at 64k scale)
            self._state = (result.packed_s, result.packed_r)
        else:
            self._state = (result.s, result.r)
        self.increment += 1
        self.history.append(
            {
                "increment": self.increment,
                "batch_axioms": batch.axiom_count(),
                "iterations": result.iterations,
                "new_derivations": result.derivations,
            }
        )
        self.last_result = result
        return result

    def _full_rebuild(self, idx) -> SaturationResult:
        """Compile a fresh engine for the whole accumulated corpus (with
        concept-id headroom so subsequent class-only deltas can reuse its
        program) and saturate from the previous closure."""
        import dataclasses

        from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
        from distel_tpu.runtime.classifier import make_engine

        cfg = dataclasses.replace(
            self.config,
            pad_multiple=max(self.config.pad_multiple, self._CAPACITY_PAD),
        )
        # the stale base engine's device constants and compiled programs
        # are useless once a rebuild starts — free them before the new
        # engine allocates
        self._base_engine = self._base_idx = None
        engine = make_engine(cfg, idx, mesh=self._mesh)
        # hand the old closure over without keeping a reference in this
        # frame: the embed copies it into the grown arrays, and holding
        # the old device buffers through the run would add a full extra
        # state to peak HBM — the difference between the incremental and
        # batch ceilings
        self.last_result = None
        result = engine.saturate(
            self.config.max_iterations,
            initial=self._pop_state(),
        )
        if isinstance(engine, RowPackedSaturationEngine):
            self._base_engine, self._base_idx = engine, idx
        else:
            self._base_engine = self._base_idx = None
        return result

    def _delta_fast_path(self, idx) -> Optional[SaturationResult]:
        """Reuse the base corpus's compiled program for a class-only
        delta — the amortization the reference gets from its increments
        being plain Redis inserts (``init/AxiomLoader.java:119-129``).

        Eligible when the delta adds no links, no roles, no chain pairs,
        and its new concepts fit the base engine's padding: then the base
        program is CORRECT as-is over the grown state (its rules operate
        on subsumer/link ROWS; new concepts are new bit lanes of the
        transposed packed state, which every row op processes blindly),
        and only a small engine over the delta's own axiom rows is
        compiled.  The two alternate to a joint fixed point.  Termination
        uses the engines' RAW change signal (``iterations > unroll`` ⇔
        some vote derived something): the base engine's derivation
        *count* masks bit lanes past its own concept universe, so a
        counted zero could lie about lanes it derived into."""
        base, b = self._base_engine, self._base_idx
        if base is None or self._state is None:
            return None
        if b.n_concepts < self._FAST_PATH_MIN_CONCEPTS:
            # below ~32k concepts the full rebuild is cheaper than the
            # fast path's fixed costs (delta-program + embed + live-bit
            # compiles through the remote-compile tunnel); measured at
            # 16k: rebuild 9.3 s vs fast path 13.1 s, at 48k: rebuild
            # 13.5-14.3 s vs fast path 7.0-10.6 s
            return None
        import dataclasses

        import jax

        from distel_tpu.core.engine import _host_bit_total, fetch_global
        from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine

        if (
            idx.n_concepts > base.nc
            or idx.n_links != b.n_links
            or idx.n_roles != b.n_roles
            or len(idx.chain_pairs) != len(b.chain_pairs)
            or not np.array_equal(idx.role_closure, b.role_closure)
        ):
            return None
        # the delta program carries only the delta's own axiom rows —
        # giving it the full CR1/CR2 tables was measured SLOWER (the
        # per-delta compile of 48k-row plans outweighs the base votes it
        # saves); the base pass closes cross-hierarchy consequences at
        # one level per vote, which the reused compiled program does at
        # ~0.35 s/vote.  nf1-nf3 are appended in arrival order, so the
        # tail slice IS the delta; nf4 is globally SORTED by the indexer
        # (indexing.py: nf4_rows.sort()), so its delta must be a set
        # difference — a positional slice would drop a new axiom that
        # sorts into the prefix from BOTH programs (silent incompleteness).
        def _nf4_delta():
            if len(idx.nf4) == len(b.nf4):
                return idx.nf4[:0]
            span = np.int64(max(idx.n_concepts, 1))
            key = lambda t: (
                t[:, 0].astype(np.int64) * span + t[:, 1]
            ) * span + t[:, 2]
            return idx.nf4[~np.isin(key(idx.nf4), key(b.nf4))]

        delta_idx = dataclasses.replace(
            idx,
            nf1=idx.nf1[len(b.nf1):],
            nf2=idx.nf2[len(b.nf2):],
            nf3=idx.nf3[len(b.nf3):],
            nf4=_nf4_delta(),
        )
        # the delta program carries only the rules its axiom slices
        # need — CR6 stays with the base program (no new chain pairs);
        # CR5 is structural over the full link table, so it joins the
        # delta only when the delta introduces the first bottom axioms
        rules = set()
        for name, tab in (
            ("CR1", delta_idx.nf1),
            ("CR2", delta_idx.nf2),
            ("CR3", delta_idx.nf3),
            ("CR4", delta_idx.nf4),
        ):
            if len(tab):
                rules.add(name)

        if idx.has_bottom_axioms and not base._bottom:
            rules.add("CR5")
        if not rules:
            return None  # nothing new for the engines: rebuild path
        delta_engine = RowPackedSaturationEngine(
            delta_idx,
            # state shapes must match the base program's exactly
            pad_multiple=base.nc,
            min_links_pad=base.nl,
            mesh=self._mesh,
            matmul_dtype=self.config.matmul_jnp_dtype(),
            rules=frozenset(rules),
        )
        if (delta_engine.nc, delta_engine.nl) != (base.nc, base.nl):
            return None  # layouts still diverge: take the rebuild path
        self.last_result = None
        # a one-slot box keeps this frame from pinning any state tuple
        # through a saturate call (a held reference would add a full
        # extra S_T+R_T to peak HBM — the same hazard _full_rebuild's
        # _pop_state dance avoids)
        box = [delta_engine.embed_state(*self._pop_state())]
        lb = jax.jit(delta_engine._live_bits)
        start_total = _host_bit_total(fetch_global(lb(*box[0])))
        iters = 0
        rounds = 0
        while True:
            # init_total=0: derivation accounting happens once at the
            # end under the full universe's live mask (the base engine
            # would miss bit lanes past its own concept count anyway);
            # termination uses the engines' RAW change signal
            r = delta_engine.saturate(
                self.config.max_iterations, initial=box.pop(), init_total=0
            )
            iters += r.iterations
            unproductive = r.iterations <= delta_engine.unroll
            box.append((r.packed_s, r.packed_r))
            del r
            if rounds and unproductive:
                # the base pass before this derived into a state the
                # delta rules had already closed: joint fixed point
                break
            r = base.saturate(
                self.config.max_iterations, initial=box.pop(), init_total=0
            )
            iters += r.iterations
            unproductive = r.iterations <= base.unroll
            box.append((r.packed_s, r.packed_r))
            del r
            rounds += 1
            if unproductive:
                break  # base derived nothing beyond the delta's closure
        final_total = _host_bit_total(fetch_global(lb(*box[0])))
        return SaturationResult(
            packed_s=box[0][0],
            packed_r=box[0][1],
            iterations=iters,
            derivations=final_total - start_total,
            idx=idx,
            converged=True,
            transposed=True,
        )
