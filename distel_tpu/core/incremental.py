"""Incremental classification: add axiom batches to a saturated closure.

The reference's streaming mode (``CURRENT_INCREMENT`` counter +
score-cursor deltas, ``init/AxiomLoader.java:119-129``,
``Type1_1AxiomProcessor.java:126-129,359-368``; exercised by
``scripts/traffic-data-load-classify.sh``): a new axiom batch classifies
on top of the existing saturated store without recomputation.

TPU-native version: EL+ saturation is monotone, so the previous closure
S/R is a *sound starting point* — we re-index with the persistent
``Indexer`` (append-only ids), embed the old state into the grown padded
arrays, and run the fixed point again.  Iterations needed ≈ the depth of
*new* consequences only, because everything old is already closed — the
tensor-shaped analog of semi-naive delta evaluation.

Retrace amortization — the **delta fast path** (``_delta_fast_path``):
over a base past ``ClassifierConfig.fast_path_min_concepts`` (default
2048), the base corpus's compiled program is reused as-is and only
small delta programs run — SHAPE-BUCKETED since ISSUE 10 (base layout
pinned via ``state_dims``, delta tables and link-window bounds as
runtime arguments), so in the steady state they are program-registry
hits, not compiles.  Soundness rests on
the transposed packed layout: the base program's rules operate on
subsumer/link ROWS; the delta's new concepts are new bit LANES inside
the base engine's padding, which every row op processes correctly
without knowing they exist, and the delta's new LINKS are padding rows
the base program's stale tables keep inert (sentinel roles, ⊤
fillers).  Class-only deltas run one delta program (the delta's own
axiom rows); link-creating deltas — the reference's property-assertion
traffic shape (``scripts/traffic-data-load-classify.sh``) — add the
CROSS program: the full CR4/CR6 tables contracted against only the
new-link window, the tensor form of the reference's two-sided T3₂
increment join (``base/Type3_2AxiomProcessorBase.java:100-174``).  All
programs round-robin with the base program to a joint fixed point.
Deltas that ADD roles (including subroles of existing ones, and new
chain axioms over them) stay on the fast path — a new role is invisible
to the base program by construction, exactly like new links (see
``_delta_fast_path``).  Deltas that change the closure between EXISTING
roles (``r ⊑ s`` added, or an old→old pair routed through a new role)
ALSO stay on the fast path via the masks-only partial rebuild: the
closure reaches the compiled base program only through runtime
arguments (factored masks + live-window tables), which
``RowPackedSaturationEngine.rebind_role_closure`` recomputes and swaps
in place — no recompile — and monotonicity keeps the embedded old
closure a sound warm start.  Only deltas the rebind structurally cannot
express (a build-time-dead chunk revived, window slots exhausted beyond
the reserved headroom) or that overflow a padding reservation take the
full-rebuild path.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from distel_tpu.config import ClassifierConfig
from distel_tpu.core.engine import SaturationResult
from distel_tpu.core.indexing import Indexer
from distel_tpu.frontend.normalizer import NormalizedOntology, Normalizer
from distel_tpu.owl import loader as owl_loader


def _merge(into: NormalizedOntology, batch: NormalizedOntology) -> None:
    into.nf1.extend(batch.nf1)
    into.nf2.extend(batch.nf2)
    into.nf3.extend(batch.nf3)
    into.nf4.extend(batch.nf4)
    into.nf5.extend(batch.nf5)
    into.nf6.extend(batch.nf6)
    into.removed.update(batch.removed)
    into.gensyms.update(batch.gensyms)


def rebuild_engine(
    config: ClassifierConfig,
    idx,
    mesh=None,
    *,
    capacity_pad: Optional[int] = None,
    link_pad: Optional[int] = None,
    window_headroom: Optional[int] = None,
):
    """THE engine construction of the incremental full-rebuild path —
    capacity-padded concept/link headroom plus rebind window slots —
    extracted so the warmup plane (``runtime/warmup.py``) builds
    byte-identical programs: a warmup precompile only pays off if it
    compiles exactly the program a later serve load will request, which
    means the same construction path, not just the same corpus.  The
    keyword overrides exist for callers (tests, a tuned deployment)
    that changed a classifier instance's reservation attributes."""
    import dataclasses as _dc

    from distel_tpu.runtime.classifier import make_engine

    if capacity_pad is None:
        capacity_pad = IncrementalClassifier._CAPACITY_PAD
    if link_pad is None:
        link_pad = IncrementalClassifier._LINK_PAD
    if window_headroom is None:
        window_headroom = IncrementalClassifier._WINDOW_HEADROOM
    cfg = _dc.replace(
        config,
        pad_multiple=max(config.pad_multiple, capacity_pad),
    )
    return make_engine(
        cfg,
        idx,
        mesh=mesh,
        min_concepts=idx.n_concepts + capacity_pad,
        min_links_pad=idx.n_links + link_pad,
        window_headroom=window_headroom,
    )


def delta_program_kwargs(
    config: ClassifierConfig, base, mesh=None, *, bucket: bool
) -> dict:
    """THE shape interlock of a delta/cross program against a compiled
    base engine: state shapes pinned to the base program's exactly (the
    programs round-robin over ONE packed state), the L-window width
    pinned so the link-axis chunk evening cannot drift ``nl``.  Shared
    by ``_delta_fast_path`` and the warmup plane
    (:func:`warm_delta_programs`) — a warmed delta program only pays
    off if it is byte-identical to the one live traffic will request,
    which means the same construction kwargs, not just the same corpus.

    ``bucket=True`` (the steady-state serving posture) additionally
    puts the delta engine in shape-bucketed mode with the base layout
    pinned verbatim: delta table rows, gate/selection arrays and the
    link-window bounds all ride as runtime arguments over
    ladder-quantized capacities, so the traced delta/cross programs
    are pure functions of their bucket signature — compiled once per
    bucket per process (``core/program_cache.PROGRAMS``), shared
    across ontologies and replicas via the persistent HLO cache."""
    kw = dict(
        pad_multiple=base.nc,
        min_links_pad=base.nl,
        l_chunk=base.lc,
        mesh=mesh,
        matmul_dtype=config.matmul_jnp_dtype(),
        # the delta/cross programs run the same CR6 formulation the
        # config selects for the base — a warmed roster only pays off
        # if it is byte-identical to what live traffic will request
        cr6_tiles=config.cr6_tiles_config(),
    )
    if bucket:
        kw.update(
            bucket=True,
            bucket_ratio=config.bucket_ratio,
            state_dims=(base.nc, base.nl),
        )
    return kw


class DeltaPlan:
    """One increment's executable fast-path roster, planning separated
    from execution (ISSUE 12): ``engines`` is the round-robin order —
    the delta (B) program, the cross program when links grew, and the
    BASE program last; ``bucketed`` records whether the delta programs
    run shape-bucketed (the cohort precondition).  Built by
    ``IncrementalClassifier._delta_fast_plan``; executed inline by
    ``_execute_delta_plan`` or jointly for N same-roster tenants by
    ``core/cohort.py``'s vmapped loop."""

    __slots__ = ("engines", "base", "bucketed", "idx")

    def __init__(self, engines, base, bucketed, idx):
        self.engines = engines
        self.base = base
        self.bucketed = bool(bucketed)
        self.idx = idx

    def roster_key(self) -> tuple:
        """Position-wise bucket signatures — two tenants may share one
        cohort dispatch iff their roster keys are EQUAL (same program
        at every round-robin position, so the vmapped joint loop runs
        the identical vote sequence each tenant would run solo)."""
        return tuple(e.bucket_signature for e in self.engines)


def warm_delta_programs(
    config: ClassifierConfig,
    base_engine,
    idx,
    mesh=None,
    max_iters: Optional[int] = None,
    cohort_sizes: Optional[List[int]] = None,
) -> List[dict]:
    """AOT the canonical steady-state delta-program buckets for a
    warmed base — the delta-plane half of the warmup precompile: after
    this, even the FIRST delta a restarted replica serves runs
    compile-free (program-registry hit), not just the second.

    The roster mirrors the two traffic shapes of the reference's
    streaming scenario (``scripts/traffic-data-load-classify.sh``):

    * class-only assertion deltas — the B program with one NF1 row
      (the floor rung covers 1-8 rows, i.e. any small delta batch);
    * link-creating deltas — the B program with one NF3 row (+CR5
      when the corpus has bottom axioms, matching the fast path's rule
      selection) and the CROSS program: the full CR4/CR6 tables × a
      one-link window (window bounds are runtime content, so this
      covers EVERY later delta's window).

    Program content is irrelevant — bucketed programs are pure
    functions of their bucket signature — so synthetic one-row tables
    over the base corpus resolve to exactly the rungs live deltas
    will request.  Returns one record per warmed roster.

    ``cohort_sizes`` (None = ``config.cohort_warm_size_list()``): also
    AOT the COHORT variants (``core/cohort.py`` — ``vmap`` of each
    roster program plus the base program over the pow2 tenant ladder)
    at these sizes, so a restarted replica's FIRST cohort dispatches
    compile-free too."""
    import dataclasses

    from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine

    if not config.shape_buckets or base_engine is None:
        return []
    if not isinstance(base_engine, RowPackedSaturationEngine):
        return []
    if (
        idx.n_concepts >= base_engine.nc
        or idx.n_links >= base_engine.nl
    ):
        return []  # no dead-row reserve: live deltas would run exact
    kw = delta_program_kwargs(config, base_engine, mesh=mesh, bucket=True)
    budget = max_iters or config.max_iterations
    empty2 = np.zeros((0, 2), np.int64)
    empty3 = np.zeros((0, 3), np.int64)
    blank = dataclasses.replace(
        idx, nf1=empty2, nf2=empty3, nf3=empty2, nf4=empty3,
        chain_pairs=empty3,
    )
    # synthetic one-row tables anchor to a REAL base row when one
    # exists (same roles → same live-window structure → same rung as
    # live traffic); content is runtime-args either way
    def row_of(tab, width):
        return (
            np.asarray(tab[:1])
            if len(tab)
            else np.zeros((1, width), np.int64)
        )

    one_nf1 = row_of(idx.nf1, 2)
    one_nf3 = row_of(idx.nf3, 2)
    link_tables = {"nf3": one_nf3}
    link_rules = {"CR3"}
    if len(idx.chain_pairs):
        # a new link INSTANTIATES existing chain axioms, so a
        # link-creating delta's B program carries chain-pair delta
        # rows (CR6) whenever the base has chains
        link_tables["chain_pairs"] = row_of(idx.chain_pairs, 3)
        link_rules.add("CR6")
    if idx.has_bottom_axioms:
        # mirror _delta_fast_path: a link-creating delta carries CR5
        # when bottom axioms exist (links_grew is True on that path)
        link_rules.add("CR5")
    rosters = [
        (
            "delta[CR1]",
            dataclasses.replace(blank, nf1=one_nf1),
            frozenset({"CR1"}),
            None,
        ),
        (
            "delta[link]",
            dataclasses.replace(blank, **link_tables),
            frozenset(link_rules),
            None,
        ),
        # the B program carries EVERY row since the last rebuild, so
        # mixed steady-state traffic (class-only deltas followed by a
        # link-creating one) requests the combined rule set — warm it
        (
            "delta[mixed]",
            dataclasses.replace(blank, nf1=one_nf1, **link_tables),
            frozenset(link_rules | {"CR1"}),
            None,
        ),
    ]
    cross_rules = set()
    if len(idx.nf4):
        cross_rules.add("CR4")
    if len(idx.chain_pairs):
        cross_rules.add("CR6")
    if cross_rules and idx.n_links:
        # window over the real link whose role satisfies the MOST
        # table families: steady link traffic adds links that DO join
        # the tables (that is what makes them derive), so warming a
        # window of non-matching roles would build all-dead (rung-0)
        # window slabs and miss the rung live deltas actually request
        h = np.asarray(idx.role_closure).astype(bool)

        def covered(roles):
            if not len(roles):
                return np.zeros(h.shape[0], bool)
            return h[:, np.unique(np.asarray(roles))].any(axis=1)

        in4 = covered(idx.nf4[:, 0] if len(idx.nf4) else ())
        in6 = covered(
            idx.chain_pairs[:, 0] if len(idx.chain_pairs) else ()
        )
        link_roles = np.asarray(idx.links[:, 0])
        score = (
            in4[link_roles].astype(int) + in6[link_roles].astype(int)
        )
        best = int(np.argmax(score))
        rosters.append(
            ("cross", idx, frozenset(cross_rules), (best, best + 1))
        )
    out = []
    engines = []
    for name, eng_idx, rules, window in rosters:
        eng = RowPackedSaturationEngine(
            eng_idx,
            rules=rules,
            **(dict(kw, link_window=window) if window else kw),
        )
        stats = eng.precompile(budget, programs=("run",))
        rec = stats.as_dict()
        rec["program"] = name
        rec["bucket_signature"] = eng.bucket_signature
        out.append(rec)
        engines.append((name, eng))
    if cohort_sizes is None:
        cohort_sizes = config.cohort_warm_size_list()
    if cohort_sizes and config.cohort_enable:
        from distel_tpu.core.cohort import warm_cohort_programs

        # cohort traffic requests the CANONICAL roster (the planner's
        # cohort_shape normalization resolves every small delta to the
        # delta[mixed] shape + cross + the base program — see
        # IncrementalClassifier._canonical_delta_tables), so only those
        # three positions need cohort variants warmed
        warm_names = {"delta[mixed]", "cross"}
        roster = [
            (name, eng) for name, eng in engines if name in warm_names
        ] + [("base", base_engine)]
        for name, eng in roster:
            for rec in warm_cohort_programs(
                [eng], cohort_sizes, budget
            ):
                rec["program"] = f"cohort[{name}x{rec['rung']}]"
                out.append(rec)
    return out


class IncrementalClassifier:
    """Owns the persistent Normalizer (shared gensym cache — the reference's
    NORMALIZE_CACHE role), the persistent Indexer (stable ids), and the
    running closure."""

    #: extra concept-id headroom built into the full-path engine so
    #: later class-only deltas reuse its compiled program (new concepts
    #: are new bit lanes inside the existing padding)
    _CAPACITY_PAD = 2048

    #: extra link-ROW headroom reserved by the full rebuild: a later
    #: link-creating delta parks its new links in these rows (where the
    #: base program's stale tables keep them inert — sentinel roles,
    #: ⊤ fillers) instead of forcing a rebuild
    _LINK_PAD = 2048

    #: below this many base concepts the full rebuild wins over the
    #: fast path's fixed costs.  The CLASS default mirrors
    #: ``ClassifierConfig.fast_path_min_concepts`` (the real knob —
    #: ``fast.path.min.concepts`` in properties files); ``__init__``
    #: copies the config value onto the instance, and tests/ops code
    #: may still assign the instance attribute directly to force a
    #: path.  History: 32_768 while every delta paid a fresh XLA
    #: compile (exact-shape delta programs); re-measured at 2_048 once
    #: bucketed delta programs made the steady state compile-free (see
    #: the config field's comment for the measurement).
    _FAST_PATH_MIN_CONCEPTS = 2_048

    #: inert live-window slots reserved per CR4/CR6 chunk of the base
    #: program so a later closure-growing role delta (r ⊑ s between
    #: existing roles) rebinds masks in place instead of rebuilding
    #: (engine.rebind_role_closure); 2 covers one new subrole run
    #: landing inside a chunk's link neighborhood on each side
    _WINDOW_HEADROOM = 2

    def __init__(self, config: Optional[ClassifierConfig] = None):
        self.config = config or ClassifierConfig()
        from distel_tpu.parallel import setup

        self._mesh = setup(self.config)
        #: instance copy of the config knob (assignable directly — the
        #: test/ops idiom ``inc._FAST_PATH_MIN_CONCEPTS = 0`` forces
        #: the fast path regardless of scale)
        self._FAST_PATH_MIN_CONCEPTS = int(
            self.config.fast_path_min_concepts
        )
        self.indexer = Indexer()
        self.accumulated = NormalizedOntology()
        self._normalizer_cache: dict = {}
        #: cross-increment range-elimination state (ranges + plain role
        #: hierarchy) and the per-role effective range sets as of the
        #: last increment — new batches must see old ranges, and OLD nf3
        #: rows must be retrofitted when a later batch grows a role's
        #: effective range set (the reference's runtime re-emit,
        #: ``RolePairHandler.java:380-444``)
        self._range_state = None
        self._range_eff: dict = {}
        #: packed closure between increments — device jax.Arrays on the
        #: transposed path (never fetched to host), numpy otherwise
        self._state: Optional[Tuple] = None
        self.increment = 0  # the reference's CURRENT_INCREMENT counter
        self.history: List[dict] = []
        self.last_result: Optional[SaturationResult] = None
        #: base-program reuse (the delta fast path): the engine compiled
        #: by the last full rebuild + the index snapshot it was built at
        self._base_engine = None
        self._base_idx = None
        #: program-build telemetry of the last increment (CompileStats
        #: of the rebuild engine, or the summed delta programs on the
        #: fast path) — the serve registry exports it to /metrics
        self.last_compile = None
        #: fast-path program accounting of the last increment (None on
        #: the rebuild path): delta_bucketed, delta_programs /
        #: delta_program_hits counts, and the B program's
        #: delta_signature — merged into the history record so the
        #: serve plane can export per-delta cache-hit rates and attach
        #: the bucket signature to classify trace spans
        self.last_delta_stats: Optional[dict] = None
        #: warm-tier parking slot (serve storage hierarchy): the index
        #: retained by :meth:`demote` so :meth:`promote` can rebuild
        #: without replaying the frontend
        self._warm_idx = None
        #: span provenance, one record per ingest (ISSUE 16): ``_merge``
        #: appends each batch's rows onto the accumulated lists in
        #: order, so every ingest owns a CONTIGUOUS ``(start, end)``
        #: range per NF family — enough provenance for :meth:`retract`
        #: to map a text back to the told rows it produced.  Records:
        #: ``{"text", "spans": {nf: (start, end)} | None, "retracted"}``
        #: (spans shift down as earlier ingests are retracted).
        self._ingests: List[dict] = []

    def add_text(self, text: str) -> SaturationResult:
        return self.add_ontology(owl_loader.load(text), source_text=text)

    def drop_base_program(self) -> None:
        """Forget the compiled base program so the NEXT delta takes the
        full-rebuild path — the loud, supported way to time or compare
        the rebuild (bench.py's fast-vs-rebuild figures) instead of
        poking private attributes."""
        self._base_engine = self._base_idx = None

    def _pop_state(self):
        state, self._state = self._state, None
        return state

    # ------------------------------------------------- warm tier (serve)

    def demote(self) -> int:
        """Serve warm-tier hook: drop the compiled engine, its
        program/device-constant references, and every device-resident
        array, keeping only host state — the frontend caches
        (normalizer memo, append-only indexer, accumulated corpus), the
        retained index, and the packed closure as host numpy wire
        arrays.  The result is the "warm" representation of the storage
        hierarchy: a fraction of the hot footprint, promotable back by
        :meth:`promote` WITHOUT the cold path's frontend replay
        (parse → normalize → index of every text).  Returns the
        retained packed-state bytes (the warm tier's accounting unit).
        """
        if self.last_result is None:
            raise ValueError(
                "nothing to demote: no increment has completed"
            )
        res = self.last_result
        if res.transposed:
            res._fetch()
            state = (np.asarray(res.packed_s), np.asarray(res.packed_r))
        else:
            state = (np.asarray(res.s), np.asarray(res.r))
        self._state = state
        self._warm_idx = res.idx
        self._base_engine = self._base_idx = None
        self.last_result = None
        self.last_compile = None
        self.last_delta_stats = None
        return int(state[0].nbytes + state[1].nbytes)

    def promote(self) -> SaturationResult:
        """Warm→hot: rebuild the engine over the index :meth:`demote`
        retained and warm-start from the host packed state — one quiet
        saturation pass under a (normally registry-cached) bucket
        program.  No parse, no normalize, no re-index: the milliseconds
        restore the warm tier exists for, vs the cold restore's full
        frontend replay."""
        if self._warm_idx is None:
            raise ValueError("promote needs a prior demote")
        idx, self._warm_idx = self._warm_idx, None
        result = self._full_rebuild(idx)
        if result.transposed:
            self._state = (result.packed_s, result.packed_r)
        else:
            self._state = (result.s, result.r)
        self.history.append(
            {
                "increment": self.increment,
                "iterations": result.iterations,
                "new_derivations": result.derivations,
                "path": "promote",
                **(
                    self.last_compile.as_dict()
                    if self.last_compile is not None
                    else {}
                ),
            }
        )
        self.last_result = result
        return result

    def _ingest(self, onto, source_text: Optional[str] = None):
        """Frontend half of an increment: normalize the batch under the
        persistent caches (gensym memo, range state), merge it into the
        accumulated corpus, and re-index with the append-only
        ``Indexer``.  No saturation — split out so ``restore`` can
        replay a spilled classifier's numbering without re-deriving its
        closure.  Returns ``(idx, batch)``.

        ``source_text``: the raw axiom text this batch came from —
        recorded with the batch's row spans so :meth:`retract` can map
        the text back to its rows (``None`` leaves the ingest
        unretractable, e.g. pre-parsed ontology objects)."""
        from distel_tpu.core.retract import NF_FAMILIES

        normalizer = Normalizer(
            cache=self._normalizer_cache, range_state=self._range_state
        )
        batch = normalizer.normalize(onto)
        # append-only range retrofit of earlier increments' rows (the
        # emitted rows land in ``batch`` and merge like any delta; a
        # retrofit that creates links rides the link-delta fast path or
        # overflows into the rebuild path like any other link growth).
        # NOTE the retrofit rows are attributed to THIS ingest's spans
        # even though they belong to older texts — the reason
        # :meth:`retract` refuses while range machinery is active.
        normalizer.retrofit_ranges(self.accumulated.nf3, self._range_eff)
        self._normalizer_cache = normalizer.export_cache()
        self._range_state = normalizer.export_range_state()
        before = {
            fam: len(getattr(self.accumulated, fam)) for fam in NF_FAMILIES
        }
        _merge(self.accumulated, batch)
        self._ingests.append(
            {
                "text": source_text,
                "spans": {
                    fam: (before[fam], len(getattr(self.accumulated, fam)))
                    for fam in NF_FAMILIES
                },
                "retracted": False,
            }
        )
        self._range_eff = {
            r: normalizer.effective_ranges(r)
            for r in self.accumulated.roles()
        }
        return self.indexer.index(self.accumulated), batch

    def add_ontology(
        self, onto, source_text: Optional[str] = None
    ) -> SaturationResult:
        idx, batch = self._ingest(onto, source_text=source_text)
        self.last_compile = None
        self.last_delta_stats = None
        result = self._delta_fast_path(idx)
        path = "fast" if result is not None else "rebuild"
        if result is None:
            result = self._full_rebuild(idx)
        return self._finish_increment(batch, result, path)

    def _finish_increment(
        self, batch, result: SaturationResult, path: str
    ) -> SaturationResult:
        """Commit one increment's result: retain the packed closure,
        bump the increment counter, append the history record.  Split
        out of :meth:`add_ontology` so the COHORT delta path
        (``core/cohort.py`` — ingest and plan per tenant, execute N
        tenants under one vmapped dispatch) can complete each member's
        increment with byte-identical bookkeeping."""
        if result.transposed:
            # keep the closure packed AND device-resident: the next
            # increment's embed runs on device, so the closure never
            # crosses the tunnel between increments (a host round trip
            # costs minutes at 64k scale)
            self._state = (result.packed_s, result.packed_r)
        else:
            self._state = (result.s, result.r)
        self.increment += 1
        self.history.append(
            {
                "increment": self.increment,
                "batch_axioms": batch.axiom_count(),
                "iterations": result.iterations,
                "new_derivations": result.derivations,
                # which saturation plane served the increment — the
                # serve layer's fast-path-vs-rebuild ratio comes from
                # here ("fast": base program reused; "rebuild": fresh
                # compile; "cohort": fast path via a cohort dispatch)
                "path": path,
                **(
                    self.last_compile.as_dict()
                    if self.last_compile is not None
                    else {}
                ),
                **(self.last_delta_stats or {}),
            }
        )
        self.last_result = result
        return result

    # --------------------------------------------------------- retraction

    def retract(self, text: str) -> SaturationResult:
        """Retract a previously-added axiom text and repair the closure
        (DRed delete-and-rederive, ``core/retract.py`` — ISSUE 16).

        The text must match a live prior :meth:`add_text` /
        :meth:`add_ontology` ``source_text`` exactly; the ingest's row
        spans locate the told rows to remove.  Refusals
        (:class:`~distel_tpu.core.retract.RetractionError` subclasses)
        mutate nothing.  The repair clears the overdeletion set's S/R
        rows and re-saturates from the surviving told axioms via the
        normal rebuild machinery — same concept/link universe (ids are
        append-only), so under shape buckets the repair's engine is a
        program-registry hit and a small repair compiles nothing.  The
        repaired result is byte-identical (taxonomy level) to a
        from-scratch classify of the surviving texts.

        Note the overdeletion reads the unpacked closure on the host —
        O(closure) like a snapshot spill; retraction is a rare op, not
        steady-state traffic."""
        from distel_tpu.core import retract as retract_mod

        if self.last_result is None:
            raise retract_mod.RetractionError(
                "retract needs a saturated closure "
                "(no increment has completed)"
            )
        k = retract_mod.find_ingest(self._ingests, text)
        if (self._range_state and self._range_state[0]) or any(
            self._range_eff.values()
        ):
            raise retract_mod.EntangledRetraction(
                "retraction refused: range-elimination machinery is "
                "active — range retrofits re-emit rows for OLD axioms "
                "into later batches, so span provenance cannot "
                "attribute rows to texts"
            )
        spans = self._ingests[k]["spans"]
        dead = retract_mod.dead_rows(self.accumulated, spans)
        retract_mod.check_entanglement(self.accumulated, spans, dead)
        # ---- all refusal checks passed: mutate
        res = self.last_result
        aff = retract_mod.affected_concepts(res.idx, res.s, res.r, dead)
        retract_mod.remove_spans(self.accumulated, self._ingests, k)
        retract_mod.purge_normalizer_cache(self._normalizer_cache, dead)
        # re-index the surviving corpus: ids are append-only and the
        # survivors are a subset, so the concept/link universe (and the
        # bucket signature) is unchanged — only the told tables,
        # role closure, and original_classes shrink
        idx = self.indexer.index(self.accumulated)
        self._state = retract_mod.clear_rows(res.s, res.r, aff)
        self.last_compile = None
        self.last_delta_stats = None
        result = self._full_rebuild(idx)
        if result.transposed:
            self._state = (result.packed_s, result.packed_r)
        else:
            self._state = (result.s, result.r)
        self.increment += 1
        rows_removed = sum(len(v) for v in dead.values())
        self.history.append(
            {
                "increment": self.increment,
                "retracted_rows": rows_removed,
                "affected_concepts": int(aff.sum()),
                "iterations": result.iterations,
                "new_derivations": result.derivations,
                "path": "retract",
                **(
                    self.last_compile.as_dict()
                    if self.last_compile is not None
                    else {}
                ),
            }
        )
        self.last_result = result
        return result

    def _replay_retract(self, text: str) -> None:
        """Frontend-only retraction replay for :meth:`restore`: remove
        the rows and purge the memo exactly like :meth:`retract`, but
        derive nothing — the closure comes from the post-repair
        snapshot being restored."""
        from distel_tpu.core import retract as retract_mod

        k = retract_mod.find_ingest(self._ingests, text)
        dead = retract_mod.dead_rows(
            self.accumulated, self._ingests[k]["spans"]
        )
        retract_mod.remove_spans(self.accumulated, self._ingests, k)
        retract_mod.purge_normalizer_cache(self._normalizer_cache, dead)

    # --------------------------------------------------- spill / restore

    def snapshot(self, path: str, compressed: bool = True) -> None:
        """Spill the running closure to disk (``runtime/checkpoint``'s
        ``.npz`` wire form) — the serve plane's LRU-eviction and
        graceful-shutdown artifact.  Restore with :meth:`restore`."""
        from distel_tpu.runtime.checkpoint import save_snapshot

        if self.last_result is None:
            raise ValueError(
                "nothing to snapshot: no increment has completed"
            )
        save_snapshot(path, self.last_result, compressed=compressed)

    @classmethod
    def restore(
        cls,
        texts: List[str],
        snapshot_path: str,
        config: Optional[ClassifierConfig] = None,
    ) -> "IncrementalClassifier":
        """Rebuild a live classifier from its spilled closure.

        ``texts`` are the ontology texts previously fed to
        :meth:`add_text`, in order; replaying them through the FRONTEND
        only (parse → normalize → index — no saturation) reconstructs
        the persistent caches and the exact append-only numbering the
        snapshot was taken under, so the spilled state re-embeds as an
        identity remap.  Entries may also be retraction markers
        (``{"op": "retract", "text": ...}`` — the serve registry's
        op-log form): those replay through the frontend too (row
        removal + memo purge, :meth:`_replay_retract`), no repair —
        the snapshot already holds the post-repair closure.  One full rebuild then warm-starts from the
        embedded closure; monotone EL+ saturation makes it a converged
        start, so the fixed point terminates after one quiet pass and
        the restored classifier is ready for further deltas (with a
        fresh compiled base program for the fast path).  Under
        ``config.shape_buckets`` the rebuild engine is shape-BUCKETED:
        the spilled closure embeds into the quantized padded layout and
        the "fresh" base program is normally a program-registry or
        persistent-cache hit, so a restore costs one quiet saturation
        pass, not an XLA compile."""
        from distel_tpu.runtime.checkpoint import load_snapshot_state

        inc = cls(config)
        idx = None
        for entry in texts:
            if isinstance(entry, dict):
                if entry.get("op") != "retract":
                    raise ValueError(
                        f"unknown op-log entry in restore: {entry!r}"
                    )
                inc._replay_retract(entry["text"])
                idx = inc.indexer.index(inc.accumulated)
            else:
                idx, _ = inc._ingest(
                    owl_loader.load(entry), source_text=entry
                )
            inc.increment += 1
        if idx is None:
            raise ValueError("restore needs at least one replayed text")
        # wire-packed state for the row-packed engine (identity remap
        # under the replayed numbering); densify for reference engines
        unpack = config is not None and config.engine in ("packed", "dense")
        state, info = load_snapshot_state(
            snapshot_path, idx=idx, unpack=unpack
        )
        inc._state = state
        result = inc._full_rebuild(idx)
        if result.transposed:
            inc._state = (result.packed_s, result.packed_r)
        else:
            inc._state = (result.s, result.r)
        inc.history.append(
            {
                "increment": inc.increment,
                "restored_from": snapshot_path,
                "iterations": result.iterations,
                "new_derivations": result.derivations,
                "path": "restore",
                **(
                    inc.last_compile.as_dict()
                    if inc.last_compile is not None
                    else {}
                ),
            }
        )
        inc.last_result = result
        return inc

    def _full_rebuild(self, idx) -> SaturationResult:
        """Compile a fresh engine for the whole accumulated corpus (with
        concept-id headroom so subsequent class-only deltas can reuse its
        program) and saturate from the previous closure."""
        from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine

        # the stale base engine's device constants and compiled programs
        # are useless once a rebuild starts — free them before the new
        # engine allocates (and a retained warm-tier index is now stale)
        self._base_engine = self._base_idx = None
        self._warm_idx = None
        # reservations for later deltas (see rebuild_engine): concept-
        # lane headroom even when n_concepts lands exactly on a pad
        # boundary, link rows for the cross-term path's new links, and
        # live-window slots so a closure-growing role delta can rebind
        # the compiled program's masks instead of rebuilding
        engine = rebuild_engine(
            self.config,
            idx,
            mesh=self._mesh,
            capacity_pad=self._CAPACITY_PAD,
            link_pad=self._LINK_PAD,
            window_headroom=self._WINDOW_HEADROOM,
        )
        # hand the old closure over without keeping a reference in this
        # frame: the embed copies it into the grown arrays, and holding
        # the old device buffers through the run would add a full extra
        # state to peak HBM — the difference between the incremental and
        # batch ceilings
        self.last_result = None
        from distel_tpu.obs import trace as obs_trace

        _sp = obs_trace.active_span()
        traced_rounds = (
            self.config.obs_trace_rounds
            and _sp is not None
            and _sp.sampled  # an unsampled carrier records nothing —
            # it must not pay the observed loop either
            and hasattr(engine, "saturate_observed")
        )
        ledger_obs = None
        if self.config.obs_ledger and hasattr(engine, "saturate_observed"):
            # run ledger (obs.ledger.enable): the rebuild saturation
            # emits one durable JSONL record per round — same opt-in
            # rationale as obs.trace_rounds (the observed program
            # compiles outside the bucket registry)
            from distel_tpu.obs.ledger import rebuild_ledger_observer

            ledger_obs = rebuild_ledger_observer(
                self.config,
                meta={
                    "kind": "rebuild",
                    "increment": self.increment,
                    # n_classes keys the cost-model fit — without it a
                    # rebuild ledger is dead weight in the calibration
                    # basis (costmodel.load_ledger_observations skips it)
                    "n_classes": int(len(idx.original_classes)),
                    "n_concepts": idx.n_concepts,
                    "n_links": idx.n_links,
                    # mesh shape keys the cost-model fit's shards
                    # dimension: 1-shard and N-shard seconds-per-round
                    # points must never silently mix in one basis
                    "n_shards": int(getattr(engine, "n_shards", 1) or 1),
                },
            )
        if traced_rounds or ledger_obs is not None:
            # traced request under obs.trace_rounds, and/or a ledgered
            # rebuild: run the observed loop (byte-identical per
            # retired round, ~parity wall under the default pipeline —
            # tests/test_pipeline.py pins both) so every saturation
            # round lands as a span event on the request's trace and/or
            # a ledger record.
            kw = {}
            if ledger_obs is not None:
                kw["observer"] = ledger_obs.observer
                if isinstance(engine, RowPackedSaturationEngine):
                    # tier/density/dispatch split telemetry: only the
                    # rowpacked controller exposes the frontier hook
                    kw["frontier_observer"] = ledger_obs.frontier_observer
            try:
                result = engine.saturate_observed(
                    self.config.max_iterations,
                    initial=self._pop_state(),
                    **kw,
                )
            except BaseException:
                if ledger_obs is not None:
                    ledger_obs.close("error")
                    ledger_obs.ledger.close()
                raise
            if ledger_obs is not None:
                ledger_obs.close(
                    "converged" if result.converged else "incomplete",
                    iterations=int(result.iterations),
                    derivations=int(result.derivations),
                )
                ledger_obs.ledger.close()
        else:
            result = engine.saturate(
                self.config.max_iterations,
                initial=self._pop_state(),
            )
        self.last_compile = getattr(engine, "compile_stats", None)
        if isinstance(engine, RowPackedSaturationEngine):
            self._base_engine, self._base_idx = engine, idx
        else:
            self._base_engine = self._base_idx = None
        return result

    def _bucket_delta_eligible(self, idx, base) -> bool:
        """Whether this delta's B/cross programs run SHAPE-BUCKETED
        (compiled once per bucket per process, shared via the program
        registry + persistent cache) rather than exact-shape.  Needs
        the base layout's LAST concept/link rows free: bucketed plans
        OR their quantization pad segments into row ``nc-1``/``nl-1``,
        which must be past the real corpus.  At the reservation edge
        (corpus grown exactly to the base's padded capacity) the delta
        falls back to the exact-shape programs — byte-identical
        closure either way, just not shared.
        ``DISTEL_EXACT_DELTA_PROGRAMS=1`` forces the exact-shape path
        (the before/after A-B hatch ``bench_serve.py``'s
        delta-steady-state scenario measures with)."""
        if not self.config.shape_buckets:
            return False
        if os.environ.get("DISTEL_EXACT_DELTA_PROGRAMS"):
            return False
        return idx.n_concepts < base.nc and idx.n_links < base.nl

    def _canonical_delta_tables(self, idx, b, delta_idx, links_grew):
        """The canonical cohort roster's tables (ISSUE 12), or None
        when this delta cannot take the canonical shape.

        Canonical = the base-structure-determined union of the two
        reference traffic shapes (class assertions + property
        assertions, ``scripts/traffic-data-load-classify.sh``) —
        exactly the ``delta[mixed]`` roster ``warm_delta_programs``
        warms.  A member whose delta lacks a family rides an INERT
        REPLAY row instead: re-deriving a base axiom's consequences
        against a closure already containing them sets no new bit
        (monotone + idempotent), so padding changes neither the fixed
        point nor any vote's change signal — it only aligns the traced
        program's table rungs so heterogeneous deltas share one
        signature.  Returns ``(canon_idx, rules, link_window | None)``.
        """
        import dataclasses

        from distel_tpu.core.indexing import TOP_ID

        # only the canonical families can be padded; a delta carrying
        # nf2/nf4 rows (or chain axioms over a chainless base, where no
        # inert chain row exists for its peers) keeps its content shape
        if len(delta_idx.nf2) or len(delta_idx.nf4):
            return None
        if len(delta_idx.chain_pairs) and not len(b.chain_pairs):
            return None
        tables = {}
        rules = {"CR1"}
        inert1 = (
            np.asarray(b.nf1[:1])
            if len(b.nf1)
            else np.asarray([[TOP_ID, TOP_ID]], np.int64)
        )
        tables["nf1"] = (
            np.asarray(delta_idx.nf1) if len(delta_idx.nf1) else inert1
        )
        if len(b.nf3):
            rules.add("CR3")
            tables["nf3"] = (
                np.asarray(delta_idx.nf3)
                if len(delta_idx.nf3)
                else np.asarray(b.nf3[:1])
            )
            if len(b.chain_pairs):
                rules.add("CR6")
                tables["chain_pairs"] = (
                    np.asarray(delta_idx.chain_pairs)
                    if len(delta_idx.chain_pairs)
                    else np.asarray(b.chain_pairs[:1])
                )
        elif len(delta_idx.nf3):
            # link-creating delta over an nf3-less base: class-only
            # peers would have no inert nf3 row to pad with
            return None
        if idx.has_bottom_axioms:
            # uniform across link-creating and class-only members (the
            # solo roster gates CR5 on links_grew; the extra sweep here
            # is an idempotent re-derivation)
            rules.add("CR5")
        canon_idx = dataclasses.replace(
            delta_idx,  # nf2/nf4 stay the (guarded) empty delta tables
            nf1=tables["nf1"],
            nf3=tables.get("nf3", delta_idx.nf3),
            chain_pairs=tables.get(
                "chain_pairs", delta_idx.chain_pairs
            ),
        )
        # the cross program joins the FULL nf4/chain tables against a
        # link window: the delta's new links when they exist, else ONE
        # existing base link (inert replay) so class-only members share
        # the cross position too.  Window bounds are runtime arguments
        # in bucket mode, so every member requests the same program.
        window = None
        if len(idx.nf4) or len(idx.chain_pairs):
            if links_grew:
                window = (b.n_links, idx.n_links)
            elif b.n_links:
                window = (b.n_links - 1, b.n_links)
        return canon_idx, rules, window

    def _delta_fast_path(self, idx) -> Optional[SaturationResult]:
        """Plan + execute the delta fast path (None = take the rebuild
        path).  The planning half (:meth:`_delta_fast_plan`) builds the
        engine roster; the execution half (:meth:`_execute_delta_plan`)
        runs the round-robin joint fixed point inline — the cohort path
        (``core/cohort.py``) reuses the SAME planner per tenant and
        replaces only the executor with one vmapped joint loop, which
        is what makes cohort results byte-identical to solo ones."""
        plan = self._delta_fast_plan(idx)
        if plan is None:
            return None
        return self._execute_delta_plan(plan)

    def _delta_fast_plan(
        self, idx, *, cohort_shape: bool = False
    ) -> Optional["DeltaPlan"]:
        """Eligibility guards + engine roster of the delta fast path —
        everything up to (but not including) device execution.  May
        mutate the base engine (the masks-only closure rebind), so a
        returned plan must be EXECUTED, not discarded.

        Reuse of the base corpus's compiled program is the
        amortization the reference gets from its increments being plain
        Redis inserts (``init/AxiomLoader.java:119-129``).

        Eligible when the delta's new concepts fit the base engine's
        concept-lane padding and its new links (if any) fit the reserved
        link rows, with the role closure RESTRICTED TO THE BASE ROLES
        unchanged (new roles are fine; reference parity:
        ``init/AxiomLoader.java:1051-1132`` accepts T4/T5 axioms as
        plain inserts): then the base program is CORRECT as-is over the
        grown state (its rules operate on subsumer/link ROWS; new
        concepts are new bit lanes of the transposed packed state, which
        every row op processes blindly; new links — including links of
        new roles — sit in padding rows its stale tables keep inert)
        and only small delta programs compile:

        * B — the delta's own axiom rows against the full state;
        * A — (link-creating deltas only) the FULL CR4/CR6 tables
          against the new-link window: together with B these are the
          two one-sided halves of the reference's two-sided T3₂
          increment join (``base/Type3_2AxiomProcessorBase.java:100-174``,
          dual score cursors ``Type3_2AxiomProcessor.java:99-106``).

        The programs round-robin with the base program to a joint fixed
        point.  Role-hierarchy-growing deltas (new roles, and ``r ⊑ s``
        between EXISTING roles) also stay on the fast path: new roles
        are invisible to the base program by construction, and a grown
        closure between base roles is swapped into the compiled program
        by ``rebind_role_closure``'s masks-only partial rebuild (no
        recompile).  Only deltas the rebind structurally cannot express
        (a build-time-dead chunk revived, window slots exhausted) or
        that overflow a padding reservation take the full-rebuild
        path."""
        base, b = self._base_engine, self._base_idx
        if base is None or self._state is None:
            return None
        if b.n_concepts < self._FAST_PATH_MIN_CONCEPTS:
            # below the configured floor the full rebuild is cheaper
            # than the fast path's fixed costs.  With EXACT-shape delta
            # programs (every delta a fresh XLA compile) the crossover
            # measured at ~32k (16k: rebuild 9.3 s vs fast 13.1 s; 48k:
            # rebuild 13.5-14.3 s vs fast 7.0-10.6 s); with BUCKETED
            # delta programs the steady state is compile-free and the
            # crossover drops to ~2k (see
            # ClassifierConfig.fast_path_min_concepts)
            return None
        import dataclasses

        from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine

        links_grew = idx.n_links > b.n_links
        # Role-ADDING deltas stay on the fast path (r3 verdict item 8 —
        # the reference accepts T4/T5 axioms as plain inserts over live
        # stores, ``init/AxiomLoader.java:1051-1132``): a new role is
        # invisible to the base program by construction — its links park
        # in the reserved link rows where the base's stale tables hold
        # the sentinel role (factored-mask column 0) and ⊤ fillers — and
        # the delta/cross programs are built from the NEW index, whose
        # closure includes the new role everywhere it matters: new rows
        # × all links (B), full tables × new links (A).  A delta that
        # changes the closure between EXISTING roles (r ⊑ s added, or an
        # old→old pair introduced THROUGH a new role) is handled by the
        # MASKS-ONLY PARTIAL REBUILD (r4 verdict task 5): the closure
        # reaches the base program only through runtime arguments, so
        # ``rebind_role_closure`` swaps the factored masks + live-window
        # tables under the same compiled program (attempted below, after
        # the cheap structural guards) and the old embedded closure
        # stays a sound warm start by monotonicity.  Only when the
        # rebind reports the program structurally can't express the
        # grown closure does the delta fall back to the full rebuild.
        if (
            idx.n_concepts > base.nc
            or idx.n_links < b.n_links
            or idx.n_links > base.nl  # new links must fit the reserved rows
            or idx.n_roles < b.n_roles
            or len(idx.chain_pairs) < len(b.chain_pairs)
        ):
            return None
        clo_new = idx.role_closure[: b.n_roles, : b.n_roles]
        closure_changed = not np.array_equal(clo_new, b.role_closure)
        # Prefix/containment integrity guards: the slicing below assumes
        # the re-indexed accumulated ontology keeps every base row.  That
        # is the indexer's append-only contract, but nothing enforces it
        # at runtime — a future dedup/reorder would silently drop axioms
        # from BOTH programs (incomplete closure) or leave the reused
        # base program reading rows under a stale order (unsound).
        for new, old in (
            (idx.nf1, b.nf1),
            (idx.nf2, b.nf2),
            (idx.nf3, b.nf3),
            (idx.links, b.links),
        ):
            if len(new) < len(old) or not np.array_equal(
                new[: len(old)], old
            ):
                return None

        # nf4 / chain_pairs are globally SORTED by the indexer, so their
        # deltas are SET DIFFERENCES — a positional tail slice would drop
        # a new row that sorts into the prefix from BOTH programs (silent
        # incompleteness) — and every base row must still be present
        span = np.int64(
            max(idx.n_concepts, idx.n_links, idx.n_roles, 2)
        )

        def _sorted_delta(new, old):
            """(delta_rows, base_rows_all_survive)."""
            key = lambda t: (
                t[:, 0].astype(np.int64) * span + t[:, 1]
            ) * span + t[:, 2]
            if len(old) == 0:
                return new, True
            kn, ko = key(new), key(old)
            return new[~np.isin(kn, ko)], bool(np.isin(ko, kn).all())

        nf4_delta, nf4_ok = _sorted_delta(idx.nf4, b.nf4)
        cp_delta, cp_ok = _sorted_delta(idx.chain_pairs, b.chain_pairs)
        if not (nf4_ok and cp_ok):
            return None

        # ---- engine roster.  The delta program (B) carries only the
        # delta's own axiom rows — giving it the full CR1/CR2 tables was
        # measured SLOWER (the per-delta compile of 48k-row plans
        # outweighs the base votes it saves); the base pass closes
        # cross-hierarchy consequences at one level per vote at
        # ~0.35 s/vote of reused compiled program.  When the delta
        # CREATED LINKS (the reference's property-assertion traffic,
        # ``scripts/traffic-data-load-classify.sh``), a third CROSS
        # program (A) joins the FULL nf4/chain tables against ONLY the
        # new-link window — together with B (new rows × all links) these
        # are the two one-sided halves of the reference's T3₂ increment
        # join (``base/Type3_2AxiomProcessorBase.java:100-174``).  The
        # new links live in the base program's reserved link-row padding
        # (``_LINK_PAD``), where its stale tables keep them inert.
        delta_idx = dataclasses.replace(
            idx,
            nf1=idx.nf1[len(b.nf1):],
            nf2=idx.nf2[len(b.nf2):],
            nf3=idx.nf3[len(b.nf3):],
            nf4=nf4_delta,
            chain_pairs=cp_delta,
        )
        rules = set()
        for name, tab in (
            ("CR1", delta_idx.nf1),
            ("CR2", delta_idx.nf2),
            ("CR3", delta_idx.nf3),
            ("CR4", delta_idx.nf4),
            ("CR6", delta_idx.chain_pairs),
        ):
            if len(tab):
                rules.add(name)
        # CR5 sweeps the full link table: the delta program carries it
        # when the base never compiled it, or when new links exist that
        # the base program's stale filler table cannot see
        if idx.has_bottom_axioms and (links_grew or not base._bottom):
            rules.add("CR5")

        # state shapes must match the base program's exactly (pinning
        # the base's L-window width keeps the link-axis chunk evening
        # from drifting nl away from base.nl); in the bucketed posture
        # the programs are additionally pure functions of their bucket
        # signature — steady-state delta traffic compiles once per
        # bucket per process, ever
        bucket_delta = self._bucket_delta_eligible(idx, base)
        shape_kw = delta_program_kwargs(
            self.config, base, mesh=self._mesh, bucket=bucket_delta
        )
        # ``cohort_shape``: normalize the roster to the CANONICAL cohort
        # shape (ISSUE 12) — rule set and table presence determined by
        # the BASE structure, missing families padded with inert replay
        # rows — so heterogeneous same-bucket deltas (class-only, link,
        # mixed) resolve to ONE roster key and share a cohort dispatch.
        # Falls back to the content roster (still cohortable among
        # identical shapes) when the delta carries families canonical
        # padding cannot cover.
        canon = None
        if cohort_shape and bucket_delta:
            canon = self._canonical_delta_tables(
                idx, b, delta_idx, links_grew
            )
        engines = []
        if canon is not None:
            canon_idx, canon_rules, window = canon
            engines.append(
                RowPackedSaturationEngine(
                    canon_idx, rules=frozenset(canon_rules), **shape_kw
                )
            )
            if window is not None:
                cross_rules = set()
                if len(idx.nf4):
                    cross_rules.add("CR4")
                if len(idx.chain_pairs):
                    cross_rules.add("CR6")
                engines.append(
                    RowPackedSaturationEngine(
                        idx,  # FULL tables × the (possibly inert) window
                        rules=frozenset(cross_rules),
                        link_window=window,
                        **shape_kw,
                    )
                )
        else:
            if rules:
                engines.append(
                    RowPackedSaturationEngine(
                        delta_idx, rules=frozenset(rules), **shape_kw
                    )
                )
            if links_grew:
                cross_rules = set()
                if len(idx.nf4):
                    cross_rules.add("CR4")
                if len(idx.chain_pairs):
                    cross_rules.add("CR6")
                if cross_rules:
                    engines.append(
                        RowPackedSaturationEngine(
                            idx,  # FULL tables × the new-link window only
                            rules=frozenset(cross_rules),
                            link_window=(b.n_links, idx.n_links),
                            **shape_kw,
                        )
                    )
        if not engines and not closure_changed:
            return None  # nothing new for the engines: rebuild path
        # (a pure r ⊑ s delta may carry NO new table rows: the rebound
        # base program alone re-derives under the grown closure)
        if any((e.nc, e.nl) != (base.nc, base.nl) for e in engines):
            return None  # layouts still diverge: take the rebuild path
        if closure_changed:
            # masks-only partial rebuild — LAST, after every other
            # fast-path guard has passed, because it mutates the base
            # engine in place: swap the compiled program's
            # closure-derived arguments; on structural refusal (dead
            # chunk revived / window slots exhausted) rebuild instead
            if not base.rebind_role_closure(clo_new):
                return None
            # subsequent deltas must diff against the closure the base
            # program now runs under
            self._base_idx = b = dataclasses.replace(
                b, role_closure=np.asarray(clo_new)
            )
        engines.append(base)
        return DeltaPlan(
            engines=engines, base=base, bucketed=bucket_delta, idx=idx
        )

    def _execute_delta_plan(self, plan: "DeltaPlan") -> SaturationResult:
        """Inline (single-tenant) execution of a fast-path plan: the
        round-robin joint fixed point over the delta/cross programs and
        the base program — one device dispatch per vote per tenant, the
        N-dispatch baseline the cohort path collapses to 1."""
        from distel_tpu.core.engine import _host_bit_total, fetch_global

        engines, base = plan.engines, plan.base
        bucket_delta = plan.bucketed
        self.last_result = None
        # a one-slot box keeps this frame from pinning any state tuple
        # through a saturate call (a held reference would add a full
        # extra S_T+R_T to peak HBM — the same hazard _full_rebuild's
        # _pop_state dance avoids)
        box = [engines[0].embed_state(*self._pop_state())]
        # count through the registry-cached shape program (a fresh
        # per-delta jit here cost ~0.1-0.3 s per increment)
        count = engines[0].count_live_bits
        start_total = _host_bit_total(fetch_global(count(*box[0])))
        iters = 0
        streak = 0
        ei = 0
        # round-robin to the JOINT fixed point: stop once every engine
        # in turn reports a quiet pass.  init_total=0: derivation
        # accounting happens once at the end under the full universe's
        # live mask; termination uses the engines' RAW change signal
        # (``iterations > unroll`` ⇔ some vote derived something) — the
        # base engine's derivation COUNT masks bit lanes past its own
        # concept universe, so a counted zero could lie.
        while streak < len(engines):
            eng = engines[ei % len(engines)]
            ei += 1
            r = eng.saturate(
                self.config.max_iterations, initial=box.pop(), init_total=0
            )
            iters += r.iterations
            unproductive = r.iterations <= eng.unroll
            box.append((r.packed_s, r.packed_r))
            del r
            streak = streak + 1 if unproductive else 0
        final_total = _host_bit_total(fetch_global(count(*box[0])))
        # per-increment program cost: only the freshly compiled delta
        # programs count (the base program's build was charged to the
        # rebuild increment that produced it)
        from distel_tpu.runtime.instrumentation import CompileStats

        agg = CompileStats(
            bucket_signature=getattr(base, "bucket_signature", ""),
            program="delta-programs",
        )
        n_programs = hits = 0
        delta_sig = ""
        for eng in engines:
            if eng is not base:
                agg.merge(eng.compile_stats)
                n_programs += 1
                hits += bool(eng.compile_stats.program_cache_hit)
                if not delta_sig:
                    delta_sig = eng.bucket_signature
        # a delta was COMPILE-FREE only when every program it built hit
        # the registry (merge() ORs the flag — one warm program must
        # not mask a cold one's compile)
        agg.program_cache_hit = n_programs > 0 and hits == n_programs
        self.last_compile = agg
        self.last_delta_stats = {
            "delta_bucketed": bucket_delta,
            "delta_programs": n_programs,
            "delta_program_hits": hits,
            "delta_signature": delta_sig,
        }
        return SaturationResult(
            packed_s=box[0][0],
            packed_r=box[0][1],
            iterations=iters,
            derivations=final_total - start_total,
            idx=plan.idx,
            converged=True,
            transposed=True,
        )
