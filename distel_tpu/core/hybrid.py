"""Per-rule backend routing — the reference's rule→node plugin boundary.

The reference assigns each completion rule to its own set of worker
nodes (``init/AxiomLoader.java:237-493``, weights
``ShardInfo.properties:5-12``); the TPU rebuild fuses all rules into one
XLA program, so the surviving knob is *which backend applies a rule*:
``ClassifierConfig.rule_backends`` maps ``"CR1".."CR6"`` to ``"tpu"``
(default) or ``"host"`` (accepted aliases: ``cpu``, ``oracle``, and the
reference spelling ``redis``).

``HybridSaturator`` alternates global rounds: the TPU engine saturates
its rule subset to a fixed point, then the host applies the routed-out
rules once (vectorized numpy on the transposed bool matrices — the same
formulas as the engines, spec in ``core/oracle.py``); convergence is
reached when a host pass derives nothing new — the same global AND-vote
structure as the reference's cross-rule-group barrier
(``controller/CommunicationHandler.java:49-84``), with the host pass
playing the role of the foreign rule group.

This path exists for the plugin boundary and cross-backend verification,
not speed — routed rules run at host numpy rates.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from distel_tpu.core.engine import SaturationResult
from distel_tpu.core.indexing import BOTTOM_ID, IndexedOntology

ALL_RULES = frozenset(f"CR{i}" for i in range(1, 7))
_HOST_ALIASES = {"host", "cpu", "oracle", "redis"}
_TPU_ALIASES = {"tpu", "xla", "device"}


def split_backends(rule_backends: dict) -> Tuple[frozenset, frozenset]:
    """Validate and split ``rule_backends`` → (tpu_rules, host_rules)."""
    host = set()
    for rule, backend in rule_backends.items():
        if rule not in ALL_RULES:
            raise ValueError(
                f"unknown rule {rule!r}: expected one of {sorted(ALL_RULES)}"
            )
        if backend in _HOST_ALIASES:
            host.add(rule)
        elif backend not in _TPU_ALIASES:
            raise ValueError(
                f"unknown backend {backend!r} for {rule}: "
                f"expected one of {sorted(_TPU_ALIASES | _HOST_ALIASES)}"
            )
    return frozenset(ALL_RULES - host), frozenset(host)


def apply_rules_host(
    idx: IndexedOntology, st: np.ndarray, rt: np.ndarray, rules
) -> int:
    """One host pass of ``rules`` over the transposed bool matrices
    ``st`` [a, x] / ``rt`` [l, x] (mutated in place).  Returns the number
    of new bits in live x columns."""
    n = idx.n_concepts
    before = int(st[:, :n].sum()) + int(rt[:, :n].sum())
    h = idx.role_closure
    link_roles = idx.links[:, 0] if idx.n_links else None
    fillers = idx.links[:, 1] if idx.n_links else None
    if "CR1" in rules and len(idx.nf1):
        np.logical_or.at(st, idx.nf1[:, 1], st[idx.nf1[:, 0]])
    if "CR2" in rules and len(idx.nf2):
        np.logical_or.at(
            st, idx.nf2[:, 2], st[idx.nf2[:, 0]] & st[idx.nf2[:, 1]]
        )
    if "CR3" in rules and len(idx.nf3):
        np.logical_or.at(rt, idx.nf3[:, 1], st[idx.nf3[:, 0]])
    if "CR4" in rules and len(idx.nf4) and idx.n_links:
        m4 = h[link_roles][:, idx.nf4[:, 0]].T          # [K4, L]
        f4 = st[idx.nf4[:, 1]][:, fillers]              # [K4, L]
        out = ((m4 & f4).astype(np.float32) @ rt[: len(fillers)].astype(np.float32)) > 0
        np.logical_or.at(st, idx.nf4[:, 2], out)
    if "CR6" in rules and len(idx.chain_pairs) and idx.n_links:
        cp = idx.chain_pairs
        m6 = h[link_roles][:, cp[:, 0]].T               # [P, L]
        f6 = rt[cp[:, 1]][:, fillers]                   # [P, L]
        out = ((m6 & f6).astype(np.float32) @ rt[: len(fillers)].astype(np.float32)) > 0
        np.logical_or.at(rt, cp[:, 2], out)
    if "CR5" in rules and idx.has_bottom_axioms and idx.n_links:
        botf = st[BOTTOM_ID][fillers]                   # [L]
        if botf.any():
            st[BOTTOM_ID] |= rt[: len(fillers)][botf].any(axis=0)
    after = int(st[:, :n].sum()) + int(rt[:, :n].sum())
    return after - before


class HybridSaturator:
    """Saturates with the TPU engine applying ``tpu_rules`` and the host
    applying ``host_rules``, alternating to a global fixed point.  API
    matches the engines' ``saturate``."""

    #: delegates embedding to the row-packed engine
    accepts_wire_state = True

    def __init__(
        self,
        idx: IndexedOntology,
        rule_backends: dict,
        *,
        engine_kw: Optional[dict] = None,
    ):
        from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine

        self.idx = idx
        self.tpu_rules, self.host_rules = split_backends(rule_backends)
        self.engine = RowPackedSaturationEngine(
            idx, rules=self.tpu_rules, **(engine_kw or {})
        )

    def saturate(
        self,
        max_iters: int = 10_000,
        *,
        initial=None,
        allow_incomplete: bool = False,
        max_rounds: int = 256,
    ) -> SaturationResult:
        state = initial
        iterations = 0
        derivations = 0
        result = None
        converged = False
        for _ in range(max_rounds):
            result = self.engine.saturate(
                max_iters, initial=state, allow_incomplete=allow_incomplete
            )
            iterations += result.iterations
            derivations += result.derivations
            if not self.host_rules:
                converged = True
                break
            st = np.ascontiguousarray(result.s.T)
            rt = np.ascontiguousarray(result.r.T)
            # host-local fixed point of the routed rules (cheap numpy) —
            # one application per round would make deep host-routed
            # chains need one global round per level
            new = 0
            while True:
                got = apply_rules_host(self.idx, st, rt, self.host_rules)
                new += got
                if got == 0:
                    break
            if new == 0:
                converged = True
                break
            derivations += new
            state = (st.T, rt.T)
        if not converged and not allow_incomplete:
            raise RuntimeError(
                f"hybrid saturation did not converge within {max_rounds} rounds"
            )
        result.iterations = iterations
        result.derivations = derivations
        result.converged = converged
        return result
