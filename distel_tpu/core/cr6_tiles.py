"""CR6 live-tile schedule: the structure-packed role-chain join.

BENCH_r03 put CR6 at 67% of the device step with a live-MAC fraction of
0.068: the scanned window formulation contracts each row chunk against
every L-window its role UNION can touch, so a chunk mixing role runs
pays every run's links for every row — >93% of the executed MACs are
dead on the factored mask alone (``m6[p, role(l)] = 0`` whenever link
``l``'s role is not a subrole of ROW ``p``'s chain role).  No gating on
that formulation can recover the loss: the dead MACs are *inside* the
windows it executes.

This module rebuilds the CR6 contraction around the join's live
structure instead (the reference's per-role hash-join partitioning,
``RolePairHandler.java:396-444``, taken to row granularity):

* the role-sorted ``chain_pairs`` table splits into **role-run row
  tiles** (≤ ``tile_m`` rows, runs merged only while the merged tile's
  rows × union-live-links MAC volume stays near the parts' sum), so
  each row tile's rows agree about which links can satisfy them;
* each row tile's live links — links whose role is a transitive subrole
  of some row's chain role — are **packed densely into ``tile_l``-slot
  link tiles** (live-row gather → tile): the contraction runs
  ``[tile_m, tile_l] @ [tile_l, W]`` only over occupied tiles, and the
  off-role interior the window schedule still sweeps never exists;
* the window-term operand (factored mask ∧ bit-table ∧ liveness) is
  built per tile and the outputs flow into the engine's existing
  deferred **segmented-OR write groups** — the S/R bit-tables never
  round-trip HBM per rule, and the write cascade (group boundaries,
  target sets) is bit-compatible with the scanned window formulation,
  which is what makes the tiled closure byte-identical to dense per
  round (``tests/test_cr6_tiles.py`` pins it).

Backend split: the schedule is backend-agnostic bit-algebra.  The
pure-jax path (gather + ``PackedColsMatmulPlan`` XLA contraction) runs
and wins on CPU — it is what the r5 int8 probe was sizing before the
tunnel outage killed it.  On a TPU host the same per-tile contraction
lowers through the Mosaic packed-columns kernel
(``ops/bitmatmul._packed_cols_sparse_kernel``): operands stay packed in
VMEM and the per-tile skip flags drop the DMA + MXU work of tiles the
liveness multiplier zeroed.  :func:`pallas_mosaic_supported` is the
capability probe the tests (and any caller) guard on — it attempts a
real lowering once and caches the answer, so the Pallas-path tests
auto-skip on CPU hosts and un-skip the moment a TPU appears (the
``tests/sharding_support.py`` pattern).

Bucket-mode purity: every ontology-derived array built here (row ids,
mask rows, link-tile ids/validity, write-plan order/targets) rides in
the engine's runtime-argument pytree; only the quantized tile COUNTS
(row tiles, link tiles per row tile, write-plan structure) reach the
traced program, via the bucket signature — same-rung ontologies share
one compiled executable through ``PROGRAMS`` and the persistent HLO
cache, exactly like the window formulation.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from distel_tpu.ops.bitmatmul import PackedColsMatmulPlan
from distel_tpu.ops.bitpack import SegmentedRowOr

#: default knobs (mirrored by ``ClassifierConfig.cr6_tiles_*`` /
#: ``cr6.tiles.*`` properties keys — the engine normalizes through
#: these, so config-plane and direct-construction defaults agree)
TILE_DEFAULTS = {
    "enable": True,
    "tile_m": 512,
    "tile_l": 256,
    "density_threshold": 0.5,
}

#: occupancy-histogram bin edges (fraction of a link tile's slots
#: holding live links) — the bench ``cr6_tiles`` section records this
OCCUPANCY_BINS = (0.25, 0.5, 0.75, 1.0)


@functools.lru_cache(maxsize=1)
def pallas_mosaic_supported() -> bool:
    """Can ``jax.experimental.pallas`` lower a real (non-interpret)
    TPU kernel on the current default backend?  False on CPU hosts
    ("Only interpret mode is supported on CPU backend"); True when a
    TPU host appears — the capability guard the Pallas-path tests and
    the engine's kernel selection key on."""
    import jax
    import jax.numpy as jnp

    try:
        plan = PackedColsMatmulPlan(
            8, 32, 4, tm=8, tl=32, tw=4, use_xla=False, interpret=False
        )
        a = jnp.zeros((8, 32), jnp.int8)
        b = jnp.zeros((32, 4), jnp.uint32)
        jax.block_until_ready(plan(a, b))
        return True
    except Exception:
        return False


@dataclass
class Cr6TileSchedule:
    """One engine's static live-tile schedule (host arrays; the engine
    converts the slab fields to device arrays in its argument pytree).

    Shapes: ``n_rt`` row tiles of ``tile_m`` rows; ``nt`` link tiles of
    ``tile_l`` slots per row tile (both counts quantized in bucket
    mode, padded entries inert).  ``groups`` mirrors the window
    formulation's deferred write-group row boundaries exactly, so the
    intra-step read/write cascade — and with it per-round byte
    identity — is preserved."""

    tile_m: int
    tile_l: int
    n_rt: int
    nt: int
    #: [n_rt, tile_m] int32 — l2 (second-leg) R-row ids, padded dead
    rows: np.ndarray
    #: [n_rt, tile_m, n_roles_pad+1] int8 — factored mask rows
    mrows: np.ndarray
    #: [n_rt, tile_m] int32 — per-row fd source (l2 // lc; pad = the
    #: appended always-False dirty slot)
    fdx: np.ndarray
    #: [n_rt, nt, tile_l] int32 — live link ids (padded dead)
    tids: np.ndarray
    #: [n_rt, nt, tile_l] bool — slot validity (False = padding)
    tval: np.ndarray
    #: [(rt0, rt1, SegmentedRowOr, order_np, targets_np)] — deferred
    #: write groups over row-tile ranges; order/targets are the plan's
    #: data content (runtime args in bucket mode, constants otherwise)
    groups: List[tuple]
    #: row spans [(a0, a1, roles)] per row tile, persisted for
    #: ``rebind_role_closure`` (re-deriving them would risk desync)
    spans: List[tuple]
    #: live link ids per row tile (host copy, pre-padding) — rebind
    #: fit checks and the occupancy stats read these
    live_per_span: List[np.ndarray]
    #: schedule statistics (occupancy histogram, MAC volumes)
    stats: dict = field(default_factory=dict)

    def signature_parts(self) -> tuple:
        """Traced-structure record for the engine's bucket signature:
        everything that shapes the jaxpr (counts, write-plan
        structure), nothing that is argument content."""
        return (
            self.tile_m,
            self.tile_l,
            self.n_rt,
            self.nt,
            tuple(
                (rt0, rt1, plan.structure())
                for rt0, rt1, plan, _o, _t in self.groups
            ),
        )


def _role_run_spans(
    tab_roles: np.ndarray,
    bounds: List[int],
    tile_m: int,
    live_count,
) -> List[Tuple[int, int]]:
    """Row spans of the role-sorted table: split at the write-group
    ``bounds`` (cascade preservation) and at role-run boundaries, then
    greedily re-merged while the merged span's rows × union-live MAC
    volume stays within 1.25x of the parts' sum and under ``tile_m``
    rows — role-poor tables still get few big MXU-friendly tiles,
    role-rich ones stay role-pure."""
    n = len(tab_roles)
    spans: List[Tuple[int, int]] = []
    for b0, b1 in zip(bounds[:-1], bounds[1:]):
        b1r = min(b1, n)
        if b0 >= b1r:
            continue
        seg = tab_roles[b0:b1r]
        starts = np.flatnonzero(np.r_[True, seg[1:] != seg[:-1]]) + b0
        ends = np.r_[starts[1:], b1r]
        pieces = []
        for s, e in zip(starts, ends):
            for o in range(s, e, tile_m):
                pieces.append((o, min(o + tile_m, e)))
        cur = None
        for s, e in pieces:
            macs = (e - s) * live_count(tab_roles[s:e])
            if cur is None:
                cur = [s, e, macs]
                continue
            nrows = e - cur[0]
            nmacs = nrows * live_count(tab_roles[cur[0]:e])
            if nrows <= tile_m and nmacs <= 1.25 * (cur[2] + macs):
                cur[1], cur[2] = e, cur[2] + macs
            else:
                spans.append((cur[0], cur[1]))
                cur = [s, e, macs]
        if cur is not None:
            spans.append((cur[0], cur[1]))
    return spans


def build_cr6_tile_schedule(
    tab_roles: np.ndarray,
    l2_rows: np.ndarray,
    targets: np.ndarray,
    mask_tab: np.ndarray,
    link_roles: np.ndarray,
    role_closure: np.ndarray,
    *,
    lc: int,
    n_lchunks: int,
    tile_m: int,
    tile_l: int,
    group_bounds: List[int],
    link_window: Optional[Tuple[int, int]] = None,
    n_rows: Optional[int] = None,
    dead_link: int,
    pad_target: int,
    tile_headroom: int = 0,
    q1=None,
    qn=None,
    h_override: Optional[np.ndarray] = None,
    fit_schedule: Optional["Cr6TileSchedule"] = None,
) -> Optional[Cr6TileSchedule]:
    """Build (or re-fit) the live-tile schedule for one CR6 table.

    ``group_bounds``: ROW indices of the window formulation's deferred
    write-group boundaries (``[0, g0·rk, g1·rk, ..., n_grid]``) — row
    tiles never straddle one, and the tile write groups cover exactly
    the same row ranges, so the tiled step's intra-step cascade matches
    the window step's bit for bit.  ``n_rows``: quantized row-grid
    length of a bucketed engine (rows past the real table become inert
    pad tiles so the tile counts stay rung-determined).  ``q1``/``qn``:
    the engine's structure-count / segment-histogram quantizers (None =
    exact mode).  ``h_override``: recompute liveness under a GROWN role
    closure — the ``rebind_role_closure`` path; combined with
    ``fit_schedule`` (the compiled schedule being re-bound) the builder
    reuses its spans/slots and returns None when the grown closure
    needs more link tiles than the compiled program has slots for (the
    caller then falls back to a rebuild).

    Returns None when re-fitting fails; an all-inert schedule (zero
    live links anywhere) is returned as a schedule with ``nt`` slots
    all invalid — the engine treats it like the window path treats an
    all-dead slab."""
    h = np.asarray(
        role_closure if h_override is None else h_override
    ).astype(bool)
    n_real = len(tab_roles)
    n_grid = n_real if n_rows is None else int(n_rows)
    link_roles = np.asarray(link_roles)

    def live_links(roles) -> np.ndarray:
        roles = np.unique(np.asarray(roles))
        roles = roles[roles < h.shape[1]]
        if roles.size == 0:
            return np.zeros(0, np.int64)
        rel = np.flatnonzero(h[:, roles].any(axis=1))
        live = np.flatnonzero(np.isin(link_roles, rel))
        if link_window is not None:
            w0, w1 = link_window
            live = live[(live >= w0) & (live < w1)]
        return live

    if fit_schedule is None:
        bounds = sorted({0, n_grid, *(min(b, n_grid) for b in group_bounds)})
        # link_window engines (the incremental CROSS programs) keep the
        # row-span grid VALUE-independent: the windowed live counts are
        # per-delta content, and letting them steer the greedy merge
        # would fold each delta's link positions into the span count —
        # i.e. into the bucket signature — re-opening the serve-time
        # recompiles PR 10 closed.  A constant live count merges runs
        # up to tile_m rows at role/group boundaries only, which are
        # corpus-static.
        live_count = (
            (lambda r: 0)
            if link_window is not None
            else (lambda r: len(live_links(r)))
        )
        spans = _role_run_spans(
            tab_roles, [b for b in bounds if b <= n_real] + [n_real],
            tile_m, live_count,
        )
        # quantization-pad rows (past the real table) become inert pad
        # spans so the row-tile count is a pure function of the grid
        pad_bounds = [b for b in bounds if b >= n_real]
        if pad_bounds and pad_bounds[0] < n_grid:
            lo = n_real
            for b in pad_bounds[1:] + [n_grid]:
                for o in range(lo, b, tile_m):
                    spans.append((o, min(o + tile_m, b)))
                lo = b
        spans = [
            (a0, a1, np.unique(tab_roles[a0:min(a1, n_real)]))
            for a0, a1 in spans
        ]
    else:
        spans = fit_schedule.spans

    live_per_span = [live_links(roles) for _a0, _a1, roles in spans]
    max_tiles = max(
        [-(-len(lv) // tile_l) for lv in live_per_span], default=0
    )
    if fit_schedule is not None:
        nt = fit_schedule.nt
        if max_tiles > nt:
            return None  # grown closure overflows the compiled slots
        n_rt = fit_schedule.n_rt
    else:
        nt = max_tiles + int(tile_headroom)
        if q1 is not None:
            nt = q1(nt) if nt else 0
        n_rt = len(spans)
        if q1 is not None:
            n_rt = q1(max(n_rt, 1))

    rows = np.full((n_rt, tile_m), dead_link, np.int32)
    mrows = np.zeros((n_rt, tile_m, mask_tab.shape[1]), np.int8)
    # fd pad = n_lchunks: the engine appends one always-False slot to
    # dirty_l before the gather, so pad rows never re-dirty a tile
    fdx = np.full((n_rt, tile_m), n_lchunks, np.int32)
    # the target grid only feeds the write plans, which a re-fit
    # reuses verbatim — skip the allocation there
    tgt = (
        np.full((n_rt, tile_m), pad_target, np.int64)
        if fit_schedule is None
        else None
    )
    tids = np.full((n_rt, nt, tile_l), dead_link, np.int32)
    tval = np.zeros((n_rt, nt, tile_l), bool)
    occupancy = []
    for i, ((a0, a1, _roles), lv) in enumerate(zip(spans, live_per_span)):
        a1r = min(a1, n_real)
        k = a1r - a0
        if k > 0:
            rows[i, :k] = l2_rows[a0:a1r]
            mrows[i, :k] = mask_tab[a0:a1r]
            fdx[i, :k] = l2_rows[a0:a1r] // lc
            if tgt is not None:
                tgt[i, :k] = targets[a0:a1r]
        for t in range(-(-len(lv) // tile_l)):
            seg = lv[t * tile_l : (t + 1) * tile_l]
            tids[i, t, : len(seg)] = seg
            tval[i, t, : len(seg)] = True
            occupancy.append(len(seg) / tile_l)

    def tile_stats() -> dict:
        total_live = int(sum(len(lv) for lv in live_per_span))
        occupied_slots = int(tval.sum())
        hist = [0] * len(OCCUPANCY_BINS)
        for o in occupancy:
            for bi, edge in enumerate(OCCUPANCY_BINS):
                if o <= edge:
                    hist[bi] += 1
                    break
        return {
            "tile_m": tile_m,
            "tile_l": tile_l,
            "n_row_tiles": int(n_rt),
            "n_link_tiles": int(nt),
            "live_links": total_live,
            "occupied_slots": occupied_slots,
            "tile_macs": occupied_slots * tile_m,
            "occupancy_histogram": {
                f"<= {edge}": hist[bi]
                for bi, edge in enumerate(OCCUPANCY_BINS)
            },
            "mean_occupancy": (
                round(float(np.mean(occupancy)), 4) if occupancy else 0.0
            ),
        }

    # deferred write groups over the SAME row ranges as the window
    # formulation's groups (cascade preservation — see the module
    # docstring); pad row-tile slots target the dead row, pad seg-OR
    # slots gather the appended all-zero output row.  A re-fit
    # (``fit_schedule``) reuses the compiled schedule's groups verbatim
    # — the closure changes liveness and masks, never rows or targets.
    if fit_schedule is not None:
        return Cr6TileSchedule(
            tile_m=tile_m,
            tile_l=tile_l,
            n_rt=int(n_rt),
            nt=int(nt),
            rows=rows,
            mrows=mrows,
            fdx=fdx,
            tids=tids,
            tval=tval,
            groups=fit_schedule.groups,
            spans=spans,
            live_per_span=live_per_span,
            # fully recomputed — a rebound schedule's occupancy and MAC
            # volume must describe the GROWN closure, not the build-time
            # one (stale figures would contradict occupied_slots)
            stats=tile_stats(),
        )
    span_starts = [a0 for a0, _a1, _r in spans] + [n_grid]
    groups = []
    bound_list = sorted({0, n_grid, *(min(b, n_grid) for b in group_bounds)})
    for b0, b1 in zip(bound_list[:-1], bound_list[1:]):
        rt0 = int(np.searchsorted(span_starts, b0))
        rt1 = int(np.searchsorted(span_starts, b1))
        rt1 = max(rt1, rt0)
        if fit_schedule is None and rt1 == rt0 and b1 > b0:
            continue  # bound past every span (all-pad tail, no tiles)
        if rt1 > n_rt:
            rt1 = n_rt
        tg = tgt[rt0:rt1].reshape(-1)
        if qn is not None:
            plan = SegmentedRowOr.quantized(
                tg, qn, pad_target, (rt1 - rt0) * tile_m
            )
        else:
            plan = SegmentedRowOr(tg)
        groups.append(
            (
                rt0, rt1, plan,
                plan.order.astype(np.int32),
                plan.targets.astype(np.int32),
            )
        )
    if fit_schedule is None and n_rt > len(spans):
        # bucket quantization pad row tiles: fold them into the LAST
        # group (inert rows targeting the dead row keep the plan a
        # no-op) so every row tile is covered by exactly one write
        rt0, rt1, _p, _o, _t = groups[-1]
        tg = tgt[rt0:n_rt].reshape(-1)
        plan = (
            SegmentedRowOr.quantized(
                tg, qn, pad_target, (n_rt - rt0) * tile_m
            )
            if qn is not None
            else SegmentedRowOr(tg)
        )
        groups[-1] = (
            rt0, n_rt, plan,
            plan.order.astype(np.int32), plan.targets.astype(np.int32),
        )

    stats = tile_stats()
    return Cr6TileSchedule(
        tile_m=tile_m,
        tile_l=tile_l,
        n_rt=int(n_rt),
        nt=int(nt),
        rows=rows,
        mrows=mrows,
        fdx=fdx,
        tids=tids,
        tval=tval,
        groups=groups,
        spans=spans,
        live_per_span=live_per_span,
        stats=stats,
    )


def make_tile_matmul(
    tile_m: int, tile_l: int, words: int, mm_kw: dict
) -> PackedColsMatmulPlan:
    """The one per-tile contraction plan a tile schedule runs under:
    ``[tile_m, tile_l] @ [tile_l, words]`` in the packed-columns AND-OR
    semiring.  On the XLA (CPU) path the m-axis pads to 8 — the
    Mosaic grid tile would be pure wasted MACs there; on the Pallas
    path the kernel's per-tile skip flags are forced ON (the liveness
    multiplier zeroes whole dead tiles, and skipping their DMA + MXU
    work is the TPU half of the live-tile win)."""
    kw = dict(mm_kw)
    if kw.get("use_xla"):
        kw.setdefault("tm", max(((tile_m + 7) // 8) * 8, 8))
    else:
        kw.setdefault("skip_zero_tiles", True)
        kw.setdefault("tl", tile_l)
    return PackedColsMatmulPlan(tile_m, tile_l, words, **kw)
