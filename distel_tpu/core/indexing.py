"""Indexing: normalized IR → dense integer tensors for the TPU engine.

The analog of the reference's load plane (``init/AxiomLoader.java``):
  * every entity gets a dense int id (replacing the packed string ids of
    ``misc/Util.java:95-103``), with ⊥=0 / ⊤=1 exactly like the reference's
    ``BOTTOM_ID=0 / TOP_ID=1`` (``misc/Constants.java:30-31``);
  * axioms are categorized by normal form into flat numpy arrays (replacing
    the per-rule Redis shard layout of ``init/AxiomLoader.java:495-577``);
  * n-ary conjunctions are binarized with shared auxiliary concepts so CR2
    becomes a fixed-arity column AND (the reference instead runs an n-way
    ZINTERSTORE Lua, ``base/Type1_2AxiomProcessorBase.java:45-66``).

TPU-first representation — the **link table**: during EL+ saturation every
role pair (X,Y) ∈ R(r) has Y drawn from the finite set of existential
fillers, so instead of per-role boolean matrices ``R[r][X,Y]`` (the naive
translation of the reference's ``Yr → {X}`` key layout,
``RolePairHandler.java:396-444``) we materialize the set of *links*
L = {(r, B)} closed under role-chain targets, and keep one boolean matrix
``R[x, l]``.  All rule applications then become column gathers/scatters or
matmuls over the link axis (see ``core/engine.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from distel_tpu.frontend.normalizer import NormalizedOntology
from distel_tpu.owl import syntax as S

BOTTOM_ID = 0  # reference misc/Constants.java:31
TOP_ID = 1     # reference misc/Constants.java:30

AUX_PREFIX = "distel:aux#"


def atom_key(atom: S.ClassExpression) -> str:
    if atom is S.OWL_THING or atom == S.OWL_THING:
        return "owl:Thing"
    if atom is S.OWL_NOTHING or atom == S.OWL_NOTHING:
        return "owl:Nothing"
    if isinstance(atom, S.Individual):
        return f"ind:{atom.iri}"
    return atom.iri


@dataclass
class IndexedOntology:
    """Flat tensor form of a normalized ontology.

    Array conventions (all int32):
      nf1        [K1, 2]  rows (a, b)          : a ⊑ b
      nf2        [K2, 3]  rows (a1, a2, b)     : a1 ⊓ a2 ⊑ b (binarized)
      nf3        [K3, 2]  rows (a, l)          : a ⊑ ∃role(l).filler(l)
      nf4        [K4, 3]  rows (s, a, b)       : ∃s.a ⊑ b
      links      [L, 2]   rows (role, filler)
      chain_pairs[P, 3]   rows (r_first, l2, lt): precomputed second-leg
                 expansion of every chain axiom r∘s⊑t — for a link l2 whose
                 role ⊑* s, a pair over l2 starting at filler(l1) extends any
                 l1-pair (role(l1) ⊑* r_first) to the link lt=(t, filler(l2)).
      role_closure [Nr, Nr] bool: H[r, s] = r ⊑* s (reflexive-transitive)
    """

    n_concepts: int
    n_roles: int
    concept_names: List[str]
    concept_ids: Dict[str, int]
    role_names: List[str]
    role_ids: Dict[str, int]
    nf1: np.ndarray
    nf2: np.ndarray
    nf3: np.ndarray
    nf4: np.ndarray
    links: np.ndarray
    chain_pairs: np.ndarray
    role_closure: np.ndarray
    #: ids of original (non-gensym, non-aux) named classes — the signature
    #: the taxonomy/export layer projects onto
    original_classes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    has_bottom_axioms: bool = False
    #: out-of-profile axiom kinds dropped during loading (populated by the
    #: native load plane; the Python path reports via NormalizedOntology.removed)
    removed: Dict[str, int] = field(default_factory=dict)

    @property
    def n_links(self) -> int:
        return len(self.links)

    def stats(self) -> Dict[str, int]:
        return {
            "concepts": self.n_concepts,
            "roles": self.n_roles,
            "links": self.n_links,
            "nf1": len(self.nf1),
            "nf2": len(self.nf2),
            "nf3": len(self.nf3),
            "nf4": len(self.nf4),
            "chain_pairs": len(self.chain_pairs),
        }


class Indexer:
    """Entity interning + axiom tensorization.

    An Indexer instance is *persistent*: calling ``index`` again with a
    grown axiom set keeps every previously-assigned concept/role/link id
    stable — the property incremental classification relies on to embed a
    saturated S/R state into the larger arrays (the reference's analog is
    the Redis stores simply persisting across ``CURRENT_INCREMENT`` bumps,
    ``init/AxiomLoader.java:119-129``).
    """

    def __init__(self) -> None:
        self.concept_ids: Dict[str, int] = {"owl:Nothing": BOTTOM_ID, "owl:Thing": TOP_ID}
        self.concept_names: List[str] = ["owl:Nothing", "owl:Thing"]
        self.role_ids: Dict[str, int] = {}
        self.role_names: List[str] = []
        self.link_ids: Dict[Tuple[int, int], int] = {}
        self.link_rows: List[Tuple[int, int]] = []
        self._aux_counter = 0
        self._aux_memo: Dict[Tuple[int, int], int] = {}

    def concept(self, atom: S.ClassExpression) -> int:
        k = atom_key(atom)
        cid = self.concept_ids.get(k)
        if cid is None:
            cid = len(self.concept_names)
            self.concept_ids[k] = cid
            self.concept_names.append(k)
        return cid

    def role(self, r: S.ObjectProperty) -> int:
        rid = self.role_ids.get(r.iri)
        if rid is None:
            rid = len(self.role_names)
            self.role_ids[r.iri] = rid
            self.role_names.append(r.iri)
        return rid

    def _aux_concept(self, a1: int, a2: int) -> int:
        """Shared auxiliary concept for binarization: aux ⊒ a1 ⊓ a2."""
        key = (a1, a2) if a1 <= a2 else (a2, a1)
        cid = self._aux_memo.get(key)
        if cid is None:
            cid = len(self.concept_names)
            name = f"{AUX_PREFIX}{self._aux_counter}"
            self._aux_counter += 1
            self.concept_ids[name] = cid
            self.concept_names.append(name)
            self._aux_memo[key] = cid
        return cid

    def index(self, norm: NormalizedOntology) -> IndexedOntology:
        # intern all atoms first so original classes get stable low ids
        for atom in sorted(norm.atoms(), key=atom_key):
            self.concept(atom)
        for r in sorted(norm.roles(), key=lambda r: r.iri):
            self.role(r)

        nf1_rows: List[Tuple[int, int]] = []
        nf2_rows: List[Tuple[int, int, int]] = []
        nf3_rows: List[Tuple[int, int]] = []  # (a, link)
        nf4_rows: List[Tuple[int, int, int]] = []
        nf5_rows: List[Tuple[int, int]] = []
        nf6_rows: List[Tuple[int, int, int]] = []

        for a, b in norm.nf1:
            nf1_rows.append((self.concept(a), self.concept(b)))
        for ops, b in norm.nf2:
            ids = [self.concept(o) for o in ops]
            # left-fold with shared aux concepts: a1⊓a2⊑x12, x12⊓a3⊑x123, ...
            acc = ids[0]
            for i in range(1, len(ids) - 1):
                aux = self._aux_concept(acc, ids[i])
                nf2_rows.append((acc, ids[i], aux))
                acc = aux
            nf2_rows.append((acc, ids[-1], self.concept(b)))
        for r, s in norm.nf5:
            nf5_rows.append((self.role(r), self.role(s)))
        for r, s, t in norm.nf6:
            nf6_rows.append((self.role(r), self.role(s), self.role(t)))

        n_roles = len(self.role_names)
        closure = _role_closure(n_roles, nf5_rows)

        # link table: distinct (role, filler) from NF3, then closed under
        # chain targets (t, filler(l2)) — the finite universe of R-columns.
        link_ids = self.link_ids
        links = self.link_rows

        def link(r: int, f: int) -> int:
            lid = link_ids.get((r, f))
            if lid is None:
                lid = len(links)
                link_ids[(r, f)] = lid
                links.append((r, f))
            return lid

        # intern NEW links sorted by role: with link rows grouped by role,
        # the CR4/CR6 operand's nonzeros (closure-mask ∧ bit-table)
        # cluster into role-diagonal tiles, which the tile-skipping matmul
        # kernel then skips (measured 0.94 → 0.066 live-tile fraction on a
        # 66-role corpus).  Previously-interned ids stay put — increments
        # only append, preserving the stable-id contract above.
        new_pairs = set()
        for a, r, b in norm.nf3:
            pair = (self.role(r), self.concept(b))
            if pair not in link_ids:
                new_pairs.add(pair)
        for r, f in sorted(new_pairs):
            link(r, f)

        for a, r, b in norm.nf3:
            nf3_rows.append((self.concept(a), link(self.role(r), self.concept(b))))

        # close links under chain heads; compute chain_pairs.  Links are
        # bucketed by role with a per-(chain, role) cursor so every
        # (chain axiom, link) pair is visited ONCE — the naive rescan of
        # the whole link table per chain per round is O(chains x links x
        # rounds), which is quadratic in copies on multiplied corpora
        # (measured: 17 s to index 512 GALEN copies, dominated by this
        # loop).  Same output set; chain_pairs are sorted below, so the
        # emitted order is unchanged.
        chain_pairs: List[Tuple[int, int, int]] = []
        if nf6_rows:
            by_role: Dict[int, List[int]] = {}
            for li, (r2, _f2) in enumerate(links):
                by_role.setdefault(r2, []).append(li)

            def link_b(r: int, f: int) -> int:
                """link() that also maintains the role buckets."""
                n_before = len(links)
                lid = link(r, f)
                if lid == n_before:
                    by_role.setdefault(r, []).append(lid)
                return lid

            seen_pairs = set()
            cursors: Dict[Tuple[int, int], int] = {}
            # relevant source roles per chain row: rho ⊑* s
            relevant = [
                np.flatnonzero(closure[:, s]) for (_r, s, _t) in nf6_rows
            ]
            changed = True
            while changed:
                changed = False
                for ci, (r, s, t) in enumerate(nf6_rows):
                    for rho in relevant[ci]:
                        bucket = by_role.get(int(rho))
                        if not bucket:
                            continue
                        cur = cursors.get((ci, int(rho)), 0)
                        while cur < len(bucket):
                            l2 = bucket[cur]
                            cur += 1
                            lt = link_b(t, links[l2][1])
                            key2 = (r, l2, lt)
                            if key2 not in seen_pairs:
                                seen_pairs.add(key2)
                                chain_pairs.append(key2)
                                changed = True
                        cursors[(ci, int(rho))] = cur

        for r, a, b in norm.nf4:
            nf4_rows.append((self.role(r), self.concept(a), self.concept(b)))
        # same tile-clustering for the operand ROW axis: the engines'
        # matmul rows follow these arrays' order, so group them by role
        nf4_rows.sort()
        chain_pairs.sort()

        n_concepts = len(self.concept_names)
        # original classes = concepts the CURRENT corpus still mentions.
        # The name roster is append-only (stable-id contract), so after a
        # retraction a dead concept keeps its id and its row in
        # ``concept_names`` — membership in the live atom set is what
        # decides whether the taxonomy should speak for it.  Add-only
        # histories are unaffected: every interned non-aux name came from
        # some batch's atoms, and the accumulated corpus only grows.
        live = {atom_key(a) for a in norm.atoms()}
        live.add("owl:Nothing")
        live.add("owl:Thing")
        original = [
            i
            for i, name in enumerate(self.concept_names)
            if name in live
            and not name.startswith(("distel:gensym#", AUX_PREFIX, "ind:"))
        ]

        has_bottom = any(b == BOTTOM_ID for _, b in nf1_rows) or any(
            b == BOTTOM_ID for _, _, b in nf2_rows
        ) or any(b == BOTTOM_ID for _, _, b in nf4_rows)

        def arr(rows, width):
            if not rows:
                return np.zeros((0, width), np.int32)
            return np.asarray(rows, np.int32)

        return IndexedOntology(
            n_concepts=n_concepts,
            n_roles=max(n_roles, 1),
            concept_names=self.concept_names,
            concept_ids=self.concept_ids,
            role_names=self.role_names,
            role_ids=self.role_ids,
            nf1=arr(nf1_rows, 2),
            nf2=arr(nf2_rows, 3),
            nf3=arr(nf3_rows, 2),
            nf4=arr(nf4_rows, 3),
            links=arr(links, 2),
            chain_pairs=arr(chain_pairs, 3),
            role_closure=closure,
            original_classes=np.asarray(original, np.int32),
            has_bottom_axioms=has_bottom,
        )


def _role_closure(n_roles: int, edges: List[Tuple[int, int]]) -> np.ndarray:
    """Reflexive-transitive closure H[r, s] = r ⊑* s by repeated
    squaring: log₂(diameter) boolean matmuls (BLAS for normal role
    counts, scipy sparse beyond 4096 — multiplied corpora reach tens of
    thousands of roles, where the old per-k Warshall outer-product loop
    was O(n³) in Python and ran for hours)."""
    n = max(n_roles, 1)
    if not edges:
        return np.eye(n, dtype=bool)
    if n <= 4096:
        h = np.eye(n, dtype=bool)
        e = np.asarray(edges, np.int64)
        h[e[:, 0], e[:, 1]] = True
        while True:
            # f32 accumulation: a uint8 product wraps mod 256, and a
            # witness count that lands on exactly 0 mod 256 would drop
            # a true reachability bit; f32 is exact below 2^24
            h2 = (
                h.astype(np.float32) @ h.astype(np.float32) > 0
            ) | h
            if np.array_equal(h2, h):
                return h
            h = h2
    from scipy.sparse import csr_matrix, eye as speye

    e = np.asarray(edges, np.int64)
    h = (
        csr_matrix(
            (np.ones(len(e), np.float32), (e[:, 0], e[:, 1])), shape=(n, n)
        )
        + speye(n, dtype=np.float32, format="csr")
    )
    h.data[:] = 1.0  # idempotent weights: products count paths, not wrap
    while True:
        h2 = h @ h + h
        h2.data[:] = 1.0
        h2.eliminate_zeros()
        if h2.nnz == h.nnz:
            return h.toarray().astype(bool)
        h = h2


def index_ontology(norm: NormalizedOntology) -> IndexedOntology:
    return Indexer().index(norm)


def role_sort_links(idx: IndexedOntology) -> IndexedOntology:
    """Renumber link ids into role-grouped order and sort the CR4/CR6
    row arrays by role — the tile-clustering contract the Python Indexer
    establishes at interning time, applied as a post-pass for load
    planes that intern in encounter order (the native loader).  NOT for
    the incremental path: renumbering breaks the Indexer's stable-id
    contract that lets a previous closure embed verbatim."""
    import dataclasses

    if idx.n_links == 0:
        return idx
    perm = np.argsort(idx.links[:, 0], kind="stable")
    if (perm == np.arange(len(perm))).all() and _rows_sorted(
        idx.nf4
    ) and _rows_sorted(idx.chain_pairs):
        return idx
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm), dtype=perm.dtype)
    nf3 = idx.nf3.copy()
    if len(nf3):
        nf3[:, 1] = inv[nf3[:, 1]]
    cp = idx.chain_pairs.copy()
    if len(cp):
        cp[:, 1] = inv[cp[:, 1]]
        cp[:, 2] = inv[cp[:, 2]]
        cp = cp[np.lexsort((cp[:, 2], cp[:, 1], cp[:, 0]))]
    nf4 = idx.nf4
    if len(nf4):
        nf4 = nf4[np.lexsort((nf4[:, 2], nf4[:, 1], nf4[:, 0]))]
    return dataclasses.replace(
        idx, links=idx.links[perm], nf3=nf3, nf4=nf4, chain_pairs=cp
    )


def _rows_sorted(a: np.ndarray) -> bool:
    """Role-grouped check: first column (the role) non-decreasing."""
    return len(a) < 2 or bool((np.diff(a[:, 0]) >= 0).all())
