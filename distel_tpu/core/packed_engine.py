"""Bit-packed saturation engine: EL+ completion on uint32 bitset state.

Same rule semantics as the dense engine (``core/engine.py`` — the spec is
``core/oracle.py``), different state representation: S and R live as
uint32 words, 32 concepts each, end to end — in HBM, through every rule,
across the whole fixed point.  XLA's bool arrays burn a byte per bit, so
packing multiplies the single-chip concept ceiling by ~8 and cuts the
step's HBM traffic by the same factor (the usual TPU bottleneck;
SURVEY.md §7 step 6).

Rules map onto the packed ops (``distel_tpu/ops``):

  CR1/CR2/CR3   gather_bit_columns → bool columns → ColumnScatter OR-packs
                them back (the scatter-add trick: distinct (word,bit)
                targets never carry)
  CR4/CR6       PackedMatmulPlan — the Pallas MXU kernel contracting the
                *packed* R against the per-step axiom operand
                (reference: the CR4 two-stage join ``RolePairHandler.java:421-425``
                and the chain join of ``base/Type5AxiomProcessorBase.java:99-153``)
  CR5 (⊥)       one VPU pass: any(rp & botf_packed) per row

The fixed-point loop, convergence vote, and derivation accounting mirror
the dense engine (reference barrier AND-vote
``controller/CommunicationHandler.java:78-83``).  Sharded-mesh execution
stays with the dense engine for now — this engine is the single-chip
scale path.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distel_tpu.core.engine import (
    SaturationResult,
    _host_bit_total,
    _pad_up,
)
from distel_tpu.core.indexing import BOTTOM_ID, TOP_ID, IndexedOntology
from distel_tpu.ops.bitmatmul import PackedMatmulPlan
from distel_tpu.ops.bitpack import ColumnScatter, gather_bit_columns


class PackedSaturationEngine:
    """Compiles an indexed ontology into a jitted fixed point over packed
    state.  API mirrors ``SaturationEngine`` for the paths the runtime
    uses: ``initial_state`` / ``step`` / ``saturate``."""

    def __init__(
        self,
        idx: IndexedOntology,
        *,
        pad_multiple: int = 128,
        matmul_dtype=None,
        unroll: int = 4,
        use_pallas: Optional[bool] = None,
    ):
        self.idx = idx
        self.unroll = max(int(unroll), 1)
        pad_multiple = _pad_up(max(pad_multiple, 32), 32)
        self.nc = _pad_up(max(idx.n_concepts, 2), pad_multiple)
        self.nl = max(_pad_up(idx.n_links, 32), 32)
        self.wc = self.nc // 32
        self.wl = self.nl // 32

        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        kw = {} if matmul_dtype is None else {"dtype": matmul_dtype}
        k4 = len(idx.nf4)
        p6 = len(idx.chain_pairs)
        self._plan4 = (
            PackedMatmulPlan(self.nc, self.wl, k4, use_xla=not use_pallas, **kw)
            if k4
            else None
        )
        self._plan6 = (
            PackedMatmulPlan(self.nc, self.wl, p6, use_xla=not use_pallas, **kw)
            if p6
            else None
        )

        h = idx.role_closure
        link_roles = (
            idx.links[:, 0] if idx.n_links else np.zeros(0, np.int64)
        )
        fillers = np.zeros(self.nl, np.int64)
        if idx.n_links:
            fillers[: idx.n_links] = idx.links[:, 1]

        # static per-rule index/mask tables, laid out in each matmul plan's
        # kernel contraction order (ops/bitmatmul.py docstring) so nothing
        # is permuted at runtime
        def kernel_tables(plan):
            order = plan.bit_order                       # [k_p] link ids
            valid = order < idx.n_links
            f = np.where(valid, fillers[np.minimum(order, self.nl - 1)], 0)
            roles = np.where(valid, link_roles[np.minimum(order, max(idx.n_links - 1, 0))], 0)
            return f.astype(np.int32), roles, valid

        if self._plan4 is not None:
            f4, roles4, valid4 = kernel_tables(self._plan4)
            self._fillers4 = f4
            # M4[rho, j] = valid(rho) & H[role(rho), s_j]
            self._m4 = (valid4[:, None] & h[roles4][:, idx.nf4[:, 0]]).astype(
                np.int8
            )
        if self._plan6 is not None:
            f6, roles6, valid6 = kernel_tables(self._plan6)
            self._fillers6 = f6
            self._m6 = (
                valid6[:, None] & h[roles6][:, idx.chain_pairs[:, 0]]
            ).astype(np.int8)

        # plain-layout filler rows for the ⊥ rule
        self._fillers = fillers.astype(np.int32)
        self._live_row = None  # built lazily inside jit

        # scatter plans: one per state matrix, combining every rule that
        # writes it (reference: the per-rule Lua writers of
        # misc/ScriptsCollection.java collapsed into two scatters)
        s_targets = [idx.nf1[:, 1], idx.nf2[:, 2]]
        if len(idx.nf4):
            s_targets.append(idx.nf4[:, 2])
        if idx.has_bottom_axioms and idx.n_links:
            s_targets.append(np.array([BOTTOM_ID]))
        self._s_scatter = ColumnScatter(
            np.concatenate(s_targets) if s_targets else np.zeros(0, np.int64),
            self.wc,
        )
        r_targets = [idx.nf3[:, 1]]
        if p6:
            r_targets.append(idx.chain_pairs[:, 2])
        self._r_scatter = ColumnScatter(np.concatenate(r_targets), self.wl)

        self._step_jit = jax.jit(self._step)
        self._initial_jit = None
        self._run_jit = jax.jit(self._run, static_argnums=(2,))

    # ------------------------------------------------------------- state

    def _initial_arrays(self) -> Tuple[jax.Array, jax.Array]:
        """S(X) = {X, ⊤}, R empty — packed form of the reference's init
        (``init/AxiomLoader.java:1237-1245``)."""
        rows = jnp.arange(self.nc)
        sp = jnp.zeros((self.nc, self.wc), jnp.uint32)
        sp = sp.at[rows, rows >> 5].set(
            jnp.asarray(1, jnp.uint32) << (rows & 31).astype(jnp.uint32)
        )
        top = jnp.asarray(np.uint32(1 << (TOP_ID & 31)))
        sp = sp.at[:, TOP_ID >> 5].set(sp[:, TOP_ID >> 5] | top)
        rp = jnp.zeros((self.nc, self.wl), jnp.uint32)
        return sp, rp

    def initial_state(self) -> Tuple[jax.Array, jax.Array]:
        if self._initial_jit is None:
            self._initial_jit = jax.jit(self._initial_arrays)
        return self._initial_jit()

    # ------------------------------------------------------------- rules

    def _step(self, sp: jax.Array, rp: jax.Array):
        idx = self.idx
        s_sources = []
        # CR1: a ⊑ b
        s_sources.append(gather_bit_columns(sp, idx.nf1[:, 0]))
        # CR2: a1 ⊓ a2 ⊑ b
        s_sources.append(
            gather_bit_columns(sp, idx.nf2[:, 0])
            & gather_bit_columns(sp, idx.nf2[:, 1])
        )
        # CR3: a ⊑ ∃link
        r_sources = [gather_bit_columns(sp, idx.nf3[:, 0])]
        # CR4: ∃s.a ⊑ b — packed MXU matmul over the link axis
        if self._plan4 is not None:
            sf = gather_bit_columns(sp[self._fillers4], idx.nf4[:, 1])
            w4 = jnp.asarray(self._m4) * sf.astype(jnp.int8)
            s_sources.append(self._plan4(rp, w4).astype(bool))
        # CR6: chains — same kernel over precomputed chain pairs
        if self._plan6 is not None:
            rf = gather_bit_columns(rp[self._fillers6], idx.chain_pairs[:, 1])
            d6 = jnp.asarray(self._m6) * rf.astype(jnp.int8)
            r_sources.append(self._plan6(rp, d6).astype(bool))
        # CR5: ⊥ back-propagation — one AND+any pass over packed words
        if idx.has_bottom_axioms and idx.n_links:
            botf = gather_bit_columns(
                sp[self._fillers], np.full(1, BOTTOM_ID)
            )[:, 0]
            # pack the [nl] bool vector: scatter-ADD of distinct powers of
            # two per word is bitwise OR (no carries)
            links = jnp.arange(self.nl)
            botf_packed = (
                jnp.zeros((1, self.wl), jnp.uint32)
                .at[0, links >> 5]
                .add(
                    botf.astype(jnp.uint32)
                    << (links & 31).astype(jnp.uint32)
                )
            )
            newbot = jnp.any(rp & botf_packed != 0, axis=1)
            s_sources.append(newbot[:, None])

        sp = self._s_scatter.apply(sp, jnp.concatenate(s_sources, axis=1))
        rp = self._r_scatter.apply(rp, jnp.concatenate(r_sources, axis=1))
        return sp, rp

    def step(self, sp, rp):
        return self._step_jit(sp, rp)

    # -------------------------------------------------------- fixed point

    def _live_bits(self, sp: jax.Array, rp: jax.Array) -> jax.Array:
        live = jnp.arange(self.nc) < self.idx.n_concepts
        pop = jnp.sum(
            lax.population_count(sp), axis=1, dtype=jnp.int32
        ) + jnp.sum(lax.population_count(rp), axis=1, dtype=jnp.int32)
        return jnp.where(live, pop, 0)

    def _run(self, sp0, rp0, max_iters: int):
        unroll = self.unroll

        def cond(st):
            sp, rp, it, changed = st
            return changed & (it < max_iters)

        def body(st):
            sp, rp, it, _ = st
            sp2, rp2 = sp, rp
            for _ in range(unroll):
                sp2, rp2 = self._step(sp2, rp2)
            changed = jnp.any(sp2 != sp) | jnp.any(rp2 != rp)
            return (sp2, rp2, it + unroll, changed)

        init_bits = self._live_bits(sp0, rp0)
        sp, rp, it, changed = lax.while_loop(
            cond, body, (sp0, rp0, jnp.asarray(0, jnp.int32), jnp.asarray(True))
        )
        return sp, rp, it, changed, self._live_bits(sp, rp), init_bits

    def saturate(
        self,
        max_iters: int = 10_000,
        *,
        initial: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        allow_incomplete: bool = False,
    ) -> SaturationResult:
        budget = _pad_up(max_iters, self.unroll)
        if initial is None:
            sp0, rp0 = self.initial_state()
        else:
            sp0, rp0 = self.embed_state(*initial)
        out = self._run_jit(sp0, rp0, budget)
        sp, rp, it, changed, bits, init_bits = jax.device_get(out)
        converged = not bool(changed)
        if not converged and not allow_incomplete:
            raise RuntimeError(
                f"saturation did not converge within {budget} iterations"
            )
        return SaturationResult(
            packed_s=sp,
            packed_r=rp,
            iterations=int(it),
            derivations=_host_bit_total(bits) - _host_bit_total(init_bits),
            idx=self.idx,
            converged=converged,
        )

    def embed_state(self, s_old, r_old) -> Tuple[jax.Array, jax.Array]:
        """Embed an *unpacked* bool state (e.g. from a snapshot) into this
        engine's packed arrays — the incremental/resume path."""
        s_old = np.asarray(s_old, bool)
        r_old = np.asarray(r_old, bool)
        s = np.zeros((self.nc, self.nc), bool)
        np.fill_diagonal(s, True)
        s[:, TOP_ID] = True
        nn = min(s_old.shape[0], self.nc)
        s[:nn, : min(s_old.shape[1], self.nc)] |= s_old[
            :nn, : min(s_old.shape[1], self.nc)
        ]
        r = np.zeros((self.nc, self.nl), bool)
        r[:nn, : min(r_old.shape[1], self.nl)] = r_old[
            :nn, : min(r_old.shape[1], self.nl)
        ]
        sp = np.packbits(s, axis=1, bitorder="little").view(np.uint32)
        rp = np.packbits(r, axis=1, bitorder="little").view(np.uint32)
        return jnp.asarray(sp), jnp.asarray(rp)
