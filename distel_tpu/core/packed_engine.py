"""Bit-packed saturation engine: EL+ completion on uint32 bitset state.

Same rule semantics as the dense engine (``core/engine.py`` — the spec is
``core/oracle.py``), different state representation: S and R live as
uint32 words, 32 concepts each, end to end — in HBM, through every rule,
across the whole fixed point.  XLA's bool arrays burn a byte per bit, so
packing multiplies the single-chip concept ceiling by ~8 and cuts the
step's HBM traffic by the same factor (the usual TPU bottleneck;
SURVEY.md §7 step 6).

Rules map onto the packed ops (``distel_tpu/ops``):

  CR1/CR2/CR3   gather_bit_columns → bool columns → ColumnScatter OR-packs
                them back (the scatter-add trick: distinct (word,bit)
                targets never carry)
  CR4/CR6       PackedMatmulPlan — the Pallas MXU kernel contracting the
                *packed* R against the per-step axiom operand
                (reference: the CR4 two-stage join ``RolePairHandler.java:421-425``
                and the chain join of ``base/Type5AxiomProcessorBase.java:99-153``)
  CR5 (⊥)       one VPU pass: any(rp & botf_packed) per row

The fixed-point loop, convergence vote, and derivation accounting mirror
the dense engine (reference barrier AND-vote
``controller/CommunicationHandler.java:78-83``).

Sharded execution (``mesh=``): S and R rows are sharded over the concept
axis of the mesh and the whole fixed point runs inside one ``shard_map``.
Each step all-reduces only the **distinct existential-filler rows** of
S/R (the finite set of concepts that ever appear as a link filler —
typically a small fraction of the concept universe), the packed analog of
the reference's cross-node delta reads against the result node
(``base/Type2AxiomProcessorBase.java:101-116``); everything else —
column gathers, scatters, the MXU matmuls — is shard-local.  The
convergence vote is a ``psum`` inside the ``lax.while_loop`` cond — the
reference's Redis BLPOP barrier + AND-vote
(``controller/CommunicationHandler.java:49-84``) as one ICI collective.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distel_tpu.core.engine import (
    SaturationResult,
    _host_bit_total,
    _pad_up,
    check_embed_fits,
    fetch_global,
    finish_device_run,
    fresh_init_total,
)
from distel_tpu.core.indexing import BOTTOM_ID, TOP_ID, IndexedOntology
from distel_tpu.ops.bitmatmul import PackedMatmulPlan
from distel_tpu.parallel.shard_compat import shard_map
from distel_tpu.ops.bitpack import (
    ColumnScatter,
    gather_bit_columns,
    gather_bit_matrix,
)


class PackedSaturationEngine:
    """Compiles an indexed ontology into a jitted fixed point over packed
    state.  API mirrors ``SaturationEngine`` for the paths the runtime
    uses: ``initial_state`` / ``step`` / ``saturate``; pass ``mesh=`` for
    concept-axis row sharding (see module docstring)."""

    def __init__(
        self,
        idx: IndexedOntology,
        *,
        pad_multiple: int = 128,
        matmul_dtype=None,
        unroll: int = 4,
        use_pallas: Optional[bool] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        concept_axis: str = "c",
        bucket: bool = False,
        bucket_ratio: float = 1.25,
    ):
        """``bucket``: SHAPE-ONLY bucketing — concept/link padding rides
        the same geometric ladder as the row-packed engine, collapsing
        the distinct state shapes nearby corpora compile for.  Unlike
        the row-packed engine this one still traces its axiom tables as
        constants, so cross-ontology program reuse needs identical
        content; the ladder only helps the persistent cache across
        repeat runs and keeps checkpoint layouts interchangeable with a
        bucketed row-packed engine of the same corpus."""
        from distel_tpu.core.program_cache import bucket_dim

        self.idx = idx
        self.unroll = max(int(unroll), 1)
        self.mesh = mesh
        self.concept_axis = concept_axis
        self.n_shards = int(mesh.shape[concept_axis]) if mesh is not None else 1
        pad_multiple = _pad_up(max(pad_multiple, 32), 32) * self.n_shards
        base_c = max(idx.n_concepts, 2)
        base_l = idx.n_links
        if bucket:
            base_c = bucket_dim(base_c + 1, bucket_ratio)
            base_l = bucket_dim(base_l + 1, bucket_ratio)
        self.nc = _pad_up(base_c, pad_multiple)
        self.nl = max(_pad_up(base_l, 32), 32)
        self.wc = self.nc // 32
        self.wl = self.nl // 32
        self.rows_per_shard = self.nc // self.n_shards

        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        kw = {} if matmul_dtype is None else {"dtype": matmul_dtype}
        k4 = len(idx.nf4)
        p6 = len(idx.chain_pairs)
        # plan m = the shard-local row count: the matmuls run inside
        # shard_map on local blocks
        m = self.rows_per_shard
        # CR4/CR6 can only fire over existing links; without links the
        # kernel tables below have nothing to index (and R stays empty)
        self._plan4 = (
            PackedMatmulPlan(m, self.wl, k4, use_xla=not use_pallas, **kw)
            if k4 and idx.n_links
            else None
        )
        self._plan6 = (
            PackedMatmulPlan(m, self.wl, p6, use_xla=not use_pallas, **kw)
            if p6 and idx.n_links
            else None
        )

        h = idx.role_closure
        link_roles = (
            idx.links[:, 0] if idx.n_links else np.zeros(0, np.int64)
        )
        fillers = np.zeros(self.nl, np.int64)
        if idx.n_links:
            fillers[: idx.n_links] = idx.links[:, 1]

        # the distinct filler universe: the only rows of S/R any rule reads
        # non-locally.  dindex maps concept id → distinct-row position.
        self._distinct_fillers = (
            np.unique(idx.links[:, 1]) if idx.n_links else np.zeros(0, np.int64)
        ).astype(np.int32)
        dindex = np.zeros(self.nc, np.int64)
        dindex[self._distinct_fillers] = np.arange(len(self._distinct_fillers))

        # static per-rule index/mask tables, laid out in each matmul plan's
        # kernel contraction order (ops/bitmatmul.py docstring) so nothing
        # is permuted at runtime
        def kernel_tables(plan):
            order = plan.bit_order                       # [k_p] link ids
            valid = order < idx.n_links
            f = np.where(valid, fillers[np.minimum(order, self.nl - 1)], 0)
            roles = np.where(valid, link_roles[np.minimum(order, max(idx.n_links - 1, 0))], 0)
            return dindex[f], roles, valid

        if self._plan4 is not None:
            d4, roles4, valid4 = kernel_tables(self._plan4)
            self._drows4 = d4
            # M4[rho, j] = valid(rho) & H[role(rho), s_j]
            self._m4 = (valid4[:, None] & h[roles4][:, idx.nf4[:, 0]]).astype(
                np.int8
            )
        if self._plan6 is not None:
            d6, roles6, valid6 = kernel_tables(self._plan6)
            self._drows6 = d6
            self._m6 = (
                valid6[:, None] & h[roles6][:, idx.chain_pairs[:, 0]]
            ).astype(np.int8)

        # distinct-row position of every (plain-layout) link filler, for ⊥
        self._dplain = dindex[fillers]

        # scatter plans: one per state matrix, combining every rule that
        # writes it (reference: the per-rule Lua writers of
        # misc/ScriptsCollection.java collapsed into two scatters)
        s_targets = [idx.nf1[:, 1], idx.nf2[:, 2]]
        if len(idx.nf4):
            s_targets.append(idx.nf4[:, 2])
        if idx.has_bottom_axioms and idx.n_links:
            s_targets.append(np.array([BOTTOM_ID]))
        self._s_scatter = ColumnScatter(
            np.concatenate(s_targets) if s_targets else np.zeros(0, np.int64),
            self.wc,
        )
        r_targets = [idx.nf3[:, 1]]
        if p6:
            r_targets.append(idx.chain_pairs[:, 2])
        self._r_scatter = ColumnScatter(np.concatenate(r_targets), self.wl)

        if mesh is not None:
            P = jax.sharding.PartitionSpec
            ns = jax.sharding.NamedSharding
            self._row_sharding = ns(mesh, P(concept_axis, None))
        else:
            self._row_sharding = None
        self._step_jit = jax.jit(self._step)
        self._initial_jit = None
        self._live_bits_jit = None
        if mesh is None:
            self._run_jit = jax.jit(self._run, static_argnums=(2,))
        else:
            self._run_jit = functools.lru_cache(maxsize=4)(self._sharded_run)

    # ------------------------------------------------------------- state

    def _initial_arrays(self) -> Tuple[jax.Array, jax.Array]:
        """S(X) = {X, ⊤}, R empty — packed form of the reference's init
        (``init/AxiomLoader.java:1237-1245``)."""
        rows = jnp.arange(self.nc)
        sp = jnp.zeros((self.nc, self.wc), jnp.uint32)
        sp = sp.at[rows, rows >> 5].set(
            jnp.asarray(1, jnp.uint32) << (rows & 31).astype(jnp.uint32)
        )
        top = jnp.asarray(np.uint32(1 << (TOP_ID & 31)))
        sp = sp.at[:, TOP_ID >> 5].set(sp[:, TOP_ID >> 5] | top)
        rp = jnp.zeros((self.nc, self.wl), jnp.uint32)
        return sp, rp

    def initial_state(self) -> Tuple[jax.Array, jax.Array]:
        if self._initial_jit is None:
            out_shardings = (
                None
                if self._row_sharding is None
                else (self._row_sharding, self._row_sharding)
            )
            self._initial_jit = jax.jit(
                self._initial_arrays, out_shardings=out_shardings
            )
        return self._initial_jit()

    # ------------------------------------------------------------- rules

    def _filler_rows(self, x_loc: jax.Array, axis_name: Optional[str]):
        """The distinct-filler rows of the (possibly shard-local) packed
        matrix ``x_loc``, replicated: the only cross-shard reads of the
        whole step.  Each row lives on exactly one shard, so the masked
        gather + psum IS the row exchange — one all-reduce over ICI."""
        rows = self._distinct_fillers
        if axis_name is None:
            return x_loc[rows]
        i = lax.axis_index(axis_name)
        local = jnp.asarray(rows) - i * self.rows_per_shard
        ok = (local >= 0) & (local < self.rows_per_shard)
        part = jnp.where(
            ok[:, None],
            x_loc[jnp.clip(local, 0, self.rows_per_shard - 1)],
            jnp.asarray(0, x_loc.dtype),
        )
        return lax.psum(part, axis_name)

    def _step(
        self,
        sp: jax.Array,
        rp: jax.Array,
        axis_name: Optional[str] = None,
    ):
        idx = self.idx
        need_s_rows = self._plan4 is not None or (
            idx.has_bottom_axioms and idx.n_links
        )
        sf_rows = self._filler_rows(sp, axis_name) if need_s_rows else None
        s_sources = []
        # CR1: a ⊑ b
        s_sources.append(gather_bit_columns(sp, idx.nf1[:, 0]))
        # CR2: a1 ⊓ a2 ⊑ b
        s_sources.append(
            gather_bit_columns(sp, idx.nf2[:, 0])
            & gather_bit_columns(sp, idx.nf2[:, 1])
        )
        # CR3: a ⊑ ∃link
        r_sources = [gather_bit_columns(sp, idx.nf3[:, 0])]
        # CR4: ∃s.a ⊑ b — packed MXU matmul over the link axis
        if self._plan4 is not None:
            sf = gather_bit_matrix(sf_rows, self._drows4, idx.nf4[:, 1])
            w4 = jnp.asarray(self._m4) * sf.astype(jnp.int8)
            s_sources.append(self._plan4(rp, w4).astype(bool))
        # CR6: chains — same kernel over precomputed chain pairs
        if self._plan6 is not None:
            rf_rows = self._filler_rows(rp, axis_name)
            rf = gather_bit_matrix(rf_rows, self._drows6, idx.chain_pairs[:, 1])
            d6 = jnp.asarray(self._m6) * rf.astype(jnp.int8)
            r_sources.append(self._plan6(rp, d6).astype(bool))
        # CR5: ⊥ back-propagation — one AND+any pass over packed words
        if idx.has_bottom_axioms and idx.n_links:
            botd = gather_bit_columns(sf_rows, np.full(1, BOTTOM_ID))[:, 0]
            botf = botd[self._dplain]                    # [nl] bool
            # pack the [nl] bool vector: scatter-ADD of distinct powers of
            # two per word is bitwise OR (no carries)
            links = jnp.arange(self.nl)
            botf_packed = (
                jnp.zeros((1, self.wl), jnp.uint32)
                .at[0, links >> 5]
                .add(
                    botf.astype(jnp.uint32)
                    << (links & 31).astype(jnp.uint32)
                )
            )
            newbot = jnp.any(rp & botf_packed != 0, axis=1)
            s_sources.append(newbot[:, None])

        sp = self._s_scatter.apply(sp, jnp.concatenate(s_sources, axis=1))
        rp = self._r_scatter.apply(rp, jnp.concatenate(r_sources, axis=1))
        return sp, rp

    def step(self, sp, rp):
        return self._step_jit(sp, rp)

    # -------------------------------------------------------- fixed point

    def _live_bits(
        self, sp: jax.Array, rp: jax.Array, axis_name: Optional[str] = None
    ) -> jax.Array:
        n_local = sp.shape[0]
        rows = jnp.arange(n_local)
        if axis_name is not None:
            rows = rows + lax.axis_index(axis_name) * n_local
        live = rows < self.idx.n_concepts
        pop = jnp.sum(
            lax.population_count(sp), axis=1, dtype=jnp.int32
        ) + jnp.sum(lax.population_count(rp), axis=1, dtype=jnp.int32)
        return jnp.where(live, pop, 0)

    def _run(
        self, sp0, rp0, max_iters: int, axis_name: Optional[str] = None
    ):
        unroll = self.unroll

        def cond(st):
            sp, rp, it, changed = st
            return changed & (it < max_iters)

        def body(st):
            sp, rp, it, _ = st
            sp2, rp2 = sp, rp
            for _ in range(unroll):
                sp2, rp2 = self._step(sp2, rp2, axis_name)
            changed = jnp.any(sp2 != sp) | jnp.any(rp2 != rp)
            if axis_name is not None:
                # the reference's global AND-vote
                # (controller/CommunicationHandler.java:78-83) as one psum
                changed = lax.psum(changed.astype(jnp.int32), axis_name) > 0
            return (sp2, rp2, it + unroll, changed)

        sp, rp, it, changed = lax.while_loop(
            cond, body, (sp0, rp0, jnp.asarray(0, jnp.int32), jnp.asarray(True))
        )
        return sp, rp, it, changed, self._live_bits(sp, rp, axis_name)

    def _sharded_run(self, max_iters: int):
        """Build (and cache per iteration budget) the jitted shard_map of
        the whole fixed point."""
        P = jax.sharding.PartitionSpec
        axis = self.concept_axis

        def run(sp0, rp0):
            sp, rp, it, changed, bits = self._run(sp0, rp0, max_iters, axis)
            # scalars leave the shard_map as one lane per shard (their
            # values are replicated by construction — psum'd vote,
            # lockstep counter)
            return sp, rp, it[None], changed[None], bits

        return jax.jit(
            shard_map(
                run,
                mesh=self.mesh,
                in_specs=(P(axis, None), P(axis, None)),
                out_specs=(
                    P(axis, None),
                    P(axis, None),
                    P(axis),
                    P(axis),
                    P(axis),
                ),
                check_vma=False,
            )
        )

    def saturate(
        self,
        max_iters: int = 10_000,
        *,
        initial: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        allow_incomplete: bool = False,
    ) -> SaturationResult:
        budget = _pad_up(max_iters, self.unroll)
        if initial is None:
            sp0, rp0 = self.initial_state()
            init_total = fresh_init_total(self.idx)
        else:
            sp0, rp0 = self.embed_state(*initial)
            if self._live_bits_jit is None:
                self._live_bits_jit = jax.jit(self._live_bits)
            init_total = _host_bit_total(
                fetch_global(self._live_bits_jit(sp0, rp0))
            )
        if self.mesh is None:
            out = self._run_jit(sp0, rp0, budget)
        else:
            out = self._run_jit(budget)(sp0, rp0)
        return finish_device_run(
            out, self.idx, budget, allow_incomplete, transposed=False,
            init_total=init_total,
        )

    def embed_state(
        self, s_old, r_old, *, allow_shrink: bool = False
    ) -> Tuple[jax.Array, jax.Array]:
        """Embed an *unpacked* bool state (e.g. from a snapshot) into this
        engine's packed arrays — the incremental/resume path."""
        if np.asarray(s_old).dtype == np.uint32:
            raise TypeError(
                "packed transposed state (uint32) is only understood by "
                "the row-packed engine; pass unpacked bool arrays (e.g. "
                "load_snapshot_state(path, unpack=True))"
            )
        s_old = np.asarray(s_old, bool)
        r_old = np.asarray(r_old, bool)
        check_embed_fits(
            allow_shrink,
            concepts=(s_old.shape[0], self.nc),
            subsumers=(s_old.shape[1], self.nc),
            link_rows=(r_old.shape[0], self.nc),
            links=(r_old.shape[1], self.nl),
        )
        s = np.zeros((self.nc, self.nc), bool)
        np.fill_diagonal(s, True)
        s[:, TOP_ID] = True
        nn = min(s_old.shape[0], self.nc)
        s[:nn, : min(s_old.shape[1], self.nc)] |= s_old[
            :nn, : min(s_old.shape[1], self.nc)
        ]
        r = np.zeros((self.nc, self.nl), bool)
        r[:nn, : min(r_old.shape[1], self.nl)] = r_old[
            :nn, : min(r_old.shape[1], self.nl)
        ]
        sp = np.packbits(s, axis=1, bitorder="little").view(np.uint32)
        rp = np.packbits(r, axis=1, bitorder="little").view(np.uint32)
        if self._row_sharding is not None:
            return (
                jax.device_put(sp, self._row_sharding),
                jax.device_put(rp, self._row_sharding),
            )
        return jnp.asarray(sp), jnp.asarray(rp)
