"""Row-packed saturation engine: transposed, scatter-free, the flagship.

Same rule semantics as ``core/engine.py`` (spec: ``core/oracle.py``),
third state representation — chosen from measured TPU behavior:

  * XLA's scatter op serializes per target index on TPU (~1.3 µs per
    scattered *column* at 20k concepts — two orders of magnitude under
    HBM speed), and it is the dominant cost of both earlier engines:
    every completion rule ends in a scatter into S or R columns.
  * Bit-packing the state 32-to-a-uint32 cuts HBM traffic 8x vs bool
    (the usual TPU bottleneck) and is the single-chip scale lever.

So this engine stores the state **transposed and packed**:

    S_T [a, xw]  uint32 — bit x of word xw set iff a ∈ S(x)
    R_T [l, xw]  uint32 — bit x set iff (x, filler(l)) ∈ R(role(l))

(the reference's *inverted* result zsets ``A → {X : A ∈ S(X)}``,
``init/AxiomLoader.java:1237-1245``, are exactly the rows of S_T — the
reference's storage was row-packed-shaped all along).  Every completion
rule now *writes whole rows*, and every row write becomes:

  gather source rows → bucketed segmented OR
  (``ops/bitpack.SegmentedRowOr``: reshape + OR-reduce per
  power-of-two length bucket) → scatter-*set* at the distinct target rows

which XLA lowers to dense fast ops — no scatter-max anywhere.  Measured
on a v5e: CR1 at 20k concepts drops 34 ms → 1.3 ms.

Rules (CR names per SURVEY.md §7; reference kernels in
``misc/ScriptsCollection.java``):

  CR1  S_T[b]  ∨= S_T[a]                       row gather + seg-OR
  CR2  S_T[b]  ∨= S_T[a1] ∧ S_T[a2]            two gathers + seg-OR
  CR3  R_T[l]  ∨= S_T[a]                       row gather + seg-OR
  CR4  S_T[b_j] ∨= pack(W[j,:] ⊙ unpack(R_T))  int8 MXU matmul [K4,L]@[L,Nc]
         W[j,l] = H[role(l), s_j] ∧ S_T[a_j, bit filler(l)]
  CR6  R_T[lt_p] ∨= pack(D[p,:] ⊙ unpack(R_T)) int8 MXU matmul [P,L]@[L,Nc]
         D[p,l] = H[role(l), r_p] ∧ R_T[l2_p, bit filler(l)]
  CR5  S_T[⊥]  ∨= OR_l botf(l) ? R_T[l]        masked packed OR-reduce

(int8 matmul with int32 accumulation runs 2x bf16 on the v5e MXU and is
exact.)  Role hierarchy (CR5' / ``base/Type4AxiomProcessorBase.java``)
never materializes — consumers read through the closure masks in W/D.

Sharded execution (``mesh=``): the packed **word axis** is sharded — each
device owns a contiguous x-slice of every row of S_T and R_T, so row
gathers, segment-ORs, row writes, and the matmuls (whose output x-axis is
the sharded one) are all shard-local.  The only cross-shard data are the
tiny bit-lookup tables W, D and botf (bits at filler columns, which live
on one shard each): a masked local extract + ``psum`` — the packed analog
of the reference's cross-node delta reads against the result node
(``base/Type2AxiomProcessorBase.java:101-116``).  The convergence vote is
a ``psum`` in the ``lax.while_loop`` cond — the reference's Redis BLPOP
barrier + AND-vote (``controller/CommunicationHandler.java:49-84``).
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distel_tpu.core.engine import (
    SaturationResult,
    check_embed_fits,
    _host_bit_total,
    _pad_up,
    fetch_global,
    finish_device_run,
    fresh_init_total,
    observed_loop,
)
from distel_tpu.core.cr6_tiles import (
    TILE_DEFAULTS as _CR6_TILE_DEFAULTS,
    build_cr6_tile_schedule,
    make_tile_matmul,
)
from distel_tpu.core.indexing import BOTTOM_ID, TOP_ID, IndexedOntology
from distel_tpu.core.program_cache import (
    PROGRAMS,
    bucket_dim,
    signature_of,
)
from distel_tpu.ops.bitmatmul import PackedColsMatmulPlan
from distel_tpu.parallel.shard_compat import shard_map
from distel_tpu.ops.bitpack import (
    SegmentedRowOr,
    bit_lookup,
    bit_lookup_from,
)
from distel_tpu.runtime.instrumentation import (
    COHORT_EVENTS,
    DISPATCH_EVENTS,
    FRONTIER_EVENTS,
    CompileStats,
    FrontierStats,
    compile_watch,
)


def _state_donation() -> tuple:
    """Donation indices for the saturation programs' two state buffers
    (``donate_argnums``-style), or ``()`` on the CPU backend.

    Donating the state halves peak state memory where it matters — HBM:
    every run embeds into fresh arrays, so the input copy XLA would
    otherwise retain across the fixed point is pure waste.  On PJRT-CPU
    the saving is host RAM (irrelevant at these scales) and donation is
    actively unsafe in warm serving processes: the in-place aliasing of
    donated while-loop state intermittently recycles the aliased pages
    while host-side reads of the produced closure are still pending,
    observed as garbage or empty closures (told subsumptions missing
    from freshly repaired taxonomies) and glibc heap corruption
    ("corrupted double-linked list" replica aborts) in fleet replicas —
    reproduced at ~1/3 rate under ``MALLOC_PERTURB_``, zero with
    donation off.  The warm-process restore/resume closure flake
    (ROADMAP) has the same signature.  ``DISTEL_DONATE_RUN_STATE=0/1``
    forces either posture (bisection knob)."""
    forced = os.environ.get("DISTEL_DONATE_RUN_STATE")
    if forced is not None:
        return (0, 1) if forced == "1" else ()
    return () if jax.default_backend() == "cpu" else (0, 1)


#: budget-floor chunk count past which the CR4/CR6 contractions compile
#: as uniform scanned chunks (O(1) traced bodies) instead of one traced
#: body per chunk — see ``scan_chunks`` in the engine constructor
_SCAN_CHUNK_THRESHOLD = 24


def _factored_closure_tables(h, nf4_roles, chain_roles, n_pad=None):
    """``(h2, m4, m6)``: the factored-mask encoding — ``h`` extended
    with one all-zero SENTINEL role row (padded links carry the
    sentinel id, so their mask column is dead), then gathered per table
    row: ``m4[j, ρ] = H[ρ, s_j]`` / ``m6[p, ρ] = H[ρ, r_p]``.  The ONE
    place this encoding lives: ``__init__`` builds the compile-time
    masks through it and :meth:`RowPackedSaturationEngine.
    rebind_role_closure` rebuilds them under a grown closure — a drift
    between the two would bind wrong masks onto a compiled program.
    ``nf4_roles`` / ``chain_roles`` are the per-row role columns, or
    None when the rule is off (empty table).  ``n_pad``: quantized role
    count of a shape-bucketed engine — the ρ axis widens to ``n_pad +
    1`` (rows past the real roles stay all-zero, and the sentinel id
    becomes ``n_pad``) so the mask-table SHAPES depend only on the
    bucket rung, never on the exact role count."""
    n_roles = h.shape[0]
    if n_pad is None:
        n_pad = n_roles
    h2 = np.zeros((n_pad + 1, n_roles), np.int8)
    h2[:n_roles] = h

    def tab(roles):
        if roles is None:
            return np.zeros((0, n_pad + 1), np.int8)
        return np.ascontiguousarray(h2[:, roles].T)

    return h2, tab(nf4_roles), tab(chain_roles)


def _fill_window_slabs(offs_l, c01_l, nch, T):
    """[nch, T]-padded window tables ``(offs, c01, tval)`` — ``tval``
    False marks pad slots, which the scan body's live multiplier zeroes
    (and the Pallas per-tile skip then drops).  The ONE slab layout,
    shared by ``build_scan`` and :meth:`RowPackedSaturationEngine.
    rebind_role_closure` so it cannot drift between compile time and a
    later mask rebind."""
    offs_s = np.zeros((nch, T), np.int32)
    c01_s = np.zeros((nch, T, 2), np.int32)
    tval_s = np.zeros((nch, T), bool)
    for i, (o, c) in enumerate(zip(offs_l, c01_l)):
        offs_s[i, : len(o)] = o
        c01_s[i, : len(o)] = c
        tval_s[i, : len(o)] = True
    return offs_s, c01_s, tval_s


def _pad_to_slots(offs, c01, slots, p_off, p_c01):
    """Tail-pad an unrolled-mode window list to ``slots`` entries with
    copies of the inert reserve window ``(p_off, p_c01)`` (see
    ``_pad_window`` in ``__init__``).  The ONE pad-window encoding,
    shared by ``build_tiles`` and ``rebind_role_closure``'s unrolled
    branch — see :func:`_fill_window_slabs` for why sharing matters."""
    pad = slots - len(offs)
    if pad <= 0:
        return offs, c01
    offs = np.concatenate([offs, np.full(pad, p_off, np.int32)])
    c01 = np.concatenate(
        [c01, np.tile(np.asarray(p_c01, np.int32), (pad, 1))]
    )
    return offs, c01


def _stack_span_masks(mask_tab, spans, rk):
    """[nch, rk, n_roles+1] per-chunk factored-mask slab: each kept
    span's rows tail-padded to ``rk`` with all-zero mask rows (pad rows
    contribute nothing).  Spans of a bucket-quantized row grid may
    extend past the real table — the clamped slice pads all the way to
    ``rk``, so quantization pad rows are all-zero mask rows too.
    Shared by ``build_scan`` and ``rebind_role_closure`` — see
    :func:`_fill_window_slabs`."""
    out = []
    for a0, a1 in spans:
        seg = mask_tab[a0:a1]
        out.append(np.pad(seg, ((0, rk - len(seg)), (0, 0))))
    return np.stack(out)


def _chunk_spans(n_rows, rk):
    """Row spans ``[(a0, a1), ...]`` of a role-sorted CR4/CR6 table
    split into exactly-``rk``-row chunks — THE span decomposition shared
    by ``build_scan`` (which compiles one padded slab per live span) and
    ``rebind_role_closure`` (which re-derives span liveness for rules
    the program never compiled).  Both sides must iterate identical
    spans: if they desynchronized, the rebind would misjudge which table
    rows the compiled program carries and could bless a closure the
    program cannot derive under (silent under-derivation)."""
    return [(a0, min(a0 + rk, n_rows)) for a0 in range(0, n_rows, rk)]


def _pos_maps(writers, n_rows, dead_rows=(), quantize=None):
    """Layered row → concat-position maps; position ``sentinel`` indexes
    a trailing always-False slot.  Rows written by k writers occupy k
    layers (k ≤ number of rules writing that state matrix).  Turns
    per-plan change vectors into a global changed-row mask with gathers
    only — a scatter would serialize per index on TPU.
    ``dead_rows``: reserved dummy rows of a shape-bucketed engine (the
    quantization pad segments' shared targets) — excluded from the maps
    so (a) their always-no-op writes never surface in the frontier and
    (b) the many pad segments aiming at one dead row don't inflate the
    layer count.  ``quantize``: ladder function padding the LAYER COUNT
    (extra layers are all-sentinel — harmless gathers) so the traced
    layer structure collides across same-bucket ontologies."""
    offs = np.cumsum([0] + [len(t) for t in writers])
    sentinel = int(offs[-1])  # trailing always-False concat slot
    if not writers or n_rows == 0:
        return []
    live = []
    for t in writers:
        t = np.asarray(t)
        keep = ~np.isin(t, dead_rows) if len(dead_rows) else slice(None)
        live.append((t[keep], (offs[len(live)] + np.arange(len(t)))[keep]))
    mult = np.zeros(n_rows, np.int64)
    for t, _pos in live:
        mult[t] += 1
    n_layers = int(mult.max()) if len(mult) else 0
    if quantize is not None:
        n_layers = min(quantize(n_layers), len(writers))
    layers = [np.full(n_rows, sentinel, np.int64) for _ in range(n_layers)]
    level = np.zeros(n_rows, np.int64)
    for t, pos in live:
        lv = level[t]
        for li in range(n_layers):
            sel = lv == li
            if sel.any():
                layers[li][t[sel]] = pos[sel]
        level[t] += 1
    return layers


def _window_term(
    subt, rp_state, fills, lroles, off, live, mask_rows, mm, lcw, dt,
    wlw, axis_name=None, base=None,
):
    """One live L-window's contribution to a CR4/CR6 chunk: the
    [rk, wlw] packed AND-OR product of the (factored-mask ∧ bit-table ∧
    ``live``) operand against the window's R rows.  ``lcw`` is the
    rule's window length (CR4 may run finer windows than CR6 — see
    ``lc4`` in ``__init__``).  ``live`` zeroes the operand when nothing
    the window reads changed last step — OR-monotone, so skipping only
    delays; the Pallas kernel's per-tile skip flags then drop the MXU
    work.  THE one window-term formulation, shared verbatim by the
    unrolled, scanned AND sparse-tier step programs (the parity tests
    pin them bit-identical).  Window contents slice the SHARED
    filler/link-role tables (stacked per-chunk copies would replicate
    them ×n_chunks in the run arguments)."""
    fcols = lax.dynamic_slice(fills, (off,), (lcw,))
    lrole = lax.dynamic_slice(lroles, (off,), (lcw,))
    with jax.named_scope("bit_table"):
        if axis_name is None:
            f = bit_lookup_from(subt, fcols, dtype=dt)
        else:
            f = lax.psum(
                bit_lookup_from(
                    subt, fcols, word_offset=base, dtype=jnp.int32,
                ),
                axis_name,
            ).astype(dt)                                  # [lc, rk]
    # factored mask tile: mask[j, l] = mask_rows[j, role(l)]
    w = (
        jnp.take(mask_rows, lrole, axis=1).astype(dt)
        * f.T
        * live.astype(dt)
    )
    b = lax.dynamic_slice(rp_state, (off, 0), (lcw, wlw))
    return mm(w, b)


class RowPackedSaturationEngine:
    """Compiles an indexed ontology into a jitted fixed point over
    transposed row-packed state.  API mirrors ``SaturationEngine``:
    ``initial_state`` / ``step`` / ``saturate`` / ``embed_state``; pass
    ``mesh=`` to shard the packed word axis (see module docstring)."""

    #: this engine's embed_state understands the wire-packed (transposed
    #: uint32) snapshot form — see runtime/checkpoint.load_snapshot_state
    accepts_wire_state = True

    def __init__(
        self,
        idx: IndexedOntology,
        *,
        pad_multiple: int = 128,
        matmul_dtype=None,
        # None = auto: 2 steps per vote (measured best on a v5e —
        # unroll=1 pays loop overhead per step, unroll=4 doubles compile
        # time and overshoots the fixed point), dropping to 1 at
        # very-large state where the second unrolled body's live chunk
        # buffers are the difference between fitting one chip and OOM
        # (measured at 112k many-role classes: 15.96 GB vs 12.35 GB)
        unroll: Optional[int] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        word_axis: str = "c",
        temp_budget_bytes: Optional[int] = None,
        use_pallas: Optional[bool] = None,
        rules: Optional[frozenset] = None,
        mm_opts: Optional[dict] = None,
        l_chunk: Optional[int] = None,
        l_chunk_cr4: Optional[int] = None,
        gate_chunks: Optional[bool] = None,
        min_links_pad: int = 0,
        min_concepts: int = 0,
        link_window: Optional[Tuple[int, int]] = None,
        scan_chunks: Optional[bool] = None,
        scan_group_bytes: Optional[int] = None,
        window_headroom: int = 0,
        bucket: bool = False,
        bucket_ratio: float = 1.25,
        state_dims: Optional[Tuple[int, int]] = None,
        sparse_tail: Optional[dict] = None,
        pipeline: Optional[dict] = None,
        cr6_tiles: Optional[dict] = None,
        fused_rounds: Optional[dict] = None,
    ):
        """``rules``: subset of {"CR1".."CR6"} this engine applies (None =
        all) — the per-rule backend plugin boundary: rules routed to
        another backend (``core/hybrid.py``) are excluded here.
        ``mm_opts``: extra keyword overrides for the CR4/CR6
        :class:`PackedColsMatmulPlan` (tiling, ``skip_zero_tiles``,
        ``interpret``) — the test hook for pinning a kernel variant.
        ``link_window``: restrict the CR4/CR6 contractions to links in
        ``[start, stop)`` — the incremental cross-term path runs the
        full axiom tables against ONLY the delta's new links (the
        one-sided halves of the reference's two-sided T3₂ increment
        join, ``base/Type3_2AxiomProcessorBase.java:100-174``).  Row
        rules (CR1-CR3) and CR5 are unaffected (CR5 re-deriving over
        old links is idempotent).
        ``gate_chunks``: frontier-gated chunk skipping (None = auto:
        enabled from 32k concepts, where skipped work outweighs the
        per-chunk branch, up to the large-state threshold — past ~2.5 GB
        of per-shard packed state the auto posture disables gating and
        halves ``temp_budget_bytes``, trading the skip speedup for the
        ~3 GB of cond pass-through copies that otherwise OOM one chip;
        see the measured figures at the threshold computation in
        ``__init__``).
        ``scan_chunks``: contract the CR4/CR6 row chunks as UNIFORM
        padded chunks under one ``lax.scan`` body per rule, with a few
        deferred target-sorted segmented-OR writes — traced program size
        O(1) in chunk count instead of one body per chunk (None = auto:
        engaged once the budget-driven chunk count exceeds
        ``_SCAN_CHUNK_THRESHOLD``, the regime where XLA pass scaling
        over per-chunk bodies dominates compile time: measured r3 at
        300k classes, 925 s step compile from ~10^3 chunk bodies).
        ``window_headroom``: extra live-window slots reserved per CR4/CR6
        chunk so a LATER role-closure growth (an ``r ⊑ s`` delta between
        existing roles) can be re-bound onto this engine's compiled
        program via :meth:`rebind_role_closure` instead of a full
        rebuild.  Reserved slots are inert until used: scan-mode slots
        carry ``tval=False`` (the live multiplier zeroes the operand and
        the Pallas per-tile skip drops the MXU work); unrolled-mode
        slots point at the padded link-table tail, whose sentinel link
        roles hit the factored mask's all-zero column.
        ``bucket``: shape-bucketed program mode — every compile-relevant
        static dimension quantizes onto the geometric ladder
        (``core/program_cache.bucket_dim``, ×``bucket_ratio`` steps) and
        every ontology-derived array (rule gather indices, seg-OR
        targets, window slabs, frontier maps, the live-column mask)
        rides in the runtime-argument pytree instead of being traced as
        a constant.  The traced program is then a pure function of
        ``self.bucket_signature``: two ontologies on the same rungs
        share one compiled executable (the in-process ``PROGRAMS``
        registry) and produce byte-identical HLO for the persistent
        disk cache.  Quantization padding is closure-invisible: padded
        rows/words are masked dead, pad segments of the quantized
        seg-OR plans reduce an all-zero source into a reserved dead
        state row (``nc-1`` / the pre-evening ``nl-1``), and CR4/CR6
        gains padded table rows with all-zero factored-mask rows.
        Bucket mode forces ``scan_chunks`` for CR4/CR6 (the unrolled
        per-chunk formulation's structure is not canonicalized) and
        plain row-budget chunk spans (role-aware splitting is
        data-dependent).
        ``state_dims``: pin the packed state layout ``(nc, nl)``
        VERBATIM instead of deriving it from the corpus — the
        cooperating-engine interlock of the incremental delta fast
        path, whose delta/cross programs must interchange packed state
        with the compiled BASE program byte-for-byte.  Combine with
        ``l_chunk=<base.lc>`` so the link-axis chunk evening cannot
        drift ``nl``.  With ``bucket=True`` the pinned dims come from a
        bucketed base engine, so they are rung-derived and the delta
        program stays a pure function of its bucket signature; the
        caller must leave the LAST concept/link row free (``nc >
        idx.n_concepts``, ``nl > idx.n_links``) because bucketed plans
        reserve it as the quantization pad segments' dead target.
        ``sparse_tail``: adaptive sparse-tail execution config (None =
        off): ``saturate_observed`` then runs a host-side controller
        that switches low-frontier-density rounds onto a
        frontier-compacted step program — active rule rows/chunks
        gathered into a small capacity-quantized workspace, all indices
        carried as runtime args so sparse programs share executables
        through ``core/program_cache.PROGRAMS`` exactly like dense
        ones.  Keys: ``enable``, ``density_threshold``,
        ``capacity_buckets``, ``hysteresis_rounds``, plus the
        workspace floor ``capacity_floor``.  Single-device,
        scanned-CR4/CR6 engines only (the controller quietly stays
        dense otherwise); overflow past the largest workspace rung
        falls back to the dense step for that round — work is delayed
        at most, never dropped.
        ``pipeline``: pipelined-observation config for
        ``saturate_observed`` (keys ``enable``, ``depth``; None = the
        defaults, enabled at depth 2): dense observed rounds depend
        only on device-carried state, so up to ``depth`` rounds stay
        in flight while the host retires earlier rounds'
        ``changed``/bits/frontier folds from a queue.  Byte-identical
        per retired round to the depth-1 synchronous loop; the
        adaptive controller drains the queue before any sparse tier
        switch, so a switch can shift later by up to depth-1 rounds
        (within the hysteresis slack) without changing what any round
        derives.
        ``cr6_tiles``: live-tile CR6 formulation (``core/cr6_tiles.py``;
        None = off): the role-chain contraction runs over role-run row
        tiles × densely packed live-link tiles instead of the scanned
        role-union windows — same deferred write-group cascade, so the
        closure stays byte-identical to the window formulation per
        round.  Keys: ``enable``, ``tile_m``/``tile_l`` (tile shape),
        ``density_threshold`` (tiled-vs-window MAC-volume ratio above
        which the engine quietly keeps the window formulation — tiles
        only pay when the live structure is sparse).  Scanned-CR6
        single-device engines only (the window formulation stays the
        mesh/unrolled path); the tile indices ride as runtime args, so
        bucket-mode program sharing survives with the tile COUNTS
        folded into the bucket signature."""
        if rules is not None:
            unknown = set(rules) - {f"CR{i}" for i in range(1, 7)}
            if unknown:
                raise ValueError(f"unknown rules: {sorted(unknown)}")
        self._rules = rules
        self._window_headroom = int(window_headroom)
        #: live-tile CR6 config (None = window formulation everywhere);
        #: normalized up front — the scan-mode plan build consults it
        self._cr6_tiles_cfg = self._normalize_cr6_tiles_cfg(cr6_tiles)
        #: retained for rebind_role_closure's tile re-fit (the tile
        #: schedule recomputes liveness under the grown closure against
        #: the same link window the compiled program was built with)
        self._link_window = link_window
        self.idx = idx
        self.mesh = mesh
        self.word_axis = word_axis
        self.n_shards = int(mesh.shape[word_axis]) if mesh is not None else 1
        self._bucket = bool(bucket)
        self._bucket_ratio = float(bucket_ratio)
        #: corpus-axis ladder (floor 32) and small-structure ladder
        #: (floor 1 — window slots, frontier layers) — see bucket_dim
        self._q = lambda n: bucket_dim(n, self._bucket_ratio)
        self._q1 = lambda n: bucket_dim(n, self._bucket_ratio, floor=1)
        pad_multiple = _pad_up(max(pad_multiple, 32), 32)
        if state_dims is not None:
            # pinned layout (see the docstring): nc/nl verbatim from a
            # cooperating engine.  Bucket mode additionally needs the
            # last concept/link row past the corpus — it is the
            # reserved dead row the quantized plans' pad segments
            # target (see _dead_c below)
            nc_pin, nl_pin = (int(d) for d in state_dims)
            reserve = 1 if self._bucket else 0
            if nc_pin % (32 * self.n_shards) or nl_pin % 32:
                raise ValueError(
                    f"state_dims {state_dims} must be 32-aligned "
                    f"({32 * self.n_shards} on the concept axis under "
                    f"{self.n_shards} shards)"
                )
            if nc_pin < max(idx.n_concepts + reserve, 2) or nl_pin < max(
                idx.n_links + reserve, 32
            ):
                raise ValueError(
                    f"state_dims {state_dims} too small for "
                    f"{idx.n_concepts} concepts / {idx.n_links} links"
                    + (" (+1 bucket dead-row reserve)" if reserve else "")
                )
            self.nc, self.nl = nc_pin, nl_pin
        else:
            # the packed word axis must divide evenly across shards
            # min_concepts: a cooperating caller (the incremental path)
            # can force concept-lane headroom beyond the corpus so later
            # class-only deltas fit the compiled program's padding even
            # when n_concepts lands exactly on a pad_multiple boundary
            base_c = max(idx.n_concepts, min_concepts, 2)
            if self._bucket:
                # +1 before quantizing: the last concept row must be
                # PAST the corpus — it is the reserved dead row the
                # quantized plans' pad segments target (see _dead_c)
                base_c = self._q(max(idx.n_concepts + 1, min_concepts, 2))
            self.nc = _pad_up(
                _pad_up(base_c, pad_multiple),
                32 * self.n_shards,
            )
            # min_links_pad: a cooperating engine (the incremental delta
            # fast path) can force this engine's link-row padding up to
            # another engine's, so their packed states interchange
            # verbatim
            if self._bucket:
                self.nl = _pad_up(
                    self._q(max(idx.n_links + 1, min_links_pad, 32)), 32
                )
            else:
                self.nl = max(
                    _pad_up(idx.n_links, 32), 32, _pad_up(min_links_pad, 32)
                )
        # reserved dead rows of the bucketed plans' pad segments: the
        # last concept row and the last PRE-EVENING link row (the link
        # axis may still grow below when lc evens out the chunk grid;
        # row nl-1 here stays a padding row either way).  Exact-mode
        # engines never reference them.
        self._dead_c = self.nc - 1
        self._dead_l = self.nl - 1
        self.wc = self.nc // 32
        # ---- size-adaptive memory posture (measured on a 16 GB v5e with
        # the 96k-class many-role corpus, state = S_T 2.2 GB + R_T 1.6 GB):
        # * 512 MB chunk temporaries + per-chunk frontier gating peak at
        #   14.2 GB of XLA temp and OOM;
        # * the lax.cond gate itself costs ~3.1 GB (state-valued branches
        #   force pass-through copies, and the tunnel XLA does not reuse
        #   cond-branch buffers across sequential chunks);
        # * ungated with 256 MB chunks the same program peaks at 12.6 GB
        #   total-live and runs.
        # So past ~2.5 GB of packed state (the 64k-class regime that was
        # round 1's single-chip ceiling) the engine drops to the tight
        # budget and disables gating unless the caller pinned either.
        state_bytes = (self.nc + self.nl) * self.wc * 4 // max(self.n_shards, 1)
        # mesh runs tip earlier: the cond pass-through copies scale with
        # the per-shard state and the 16 GB v5e budget must also hold the
        # replicated plan constants (measured: 200k-class/8-shard at
        # 2.06 GB per-shard state compiled to 14.1 GB gated temp vs well
        # under that ungated)
        large = state_bytes > (
            (3 << 29) if mesh is not None else (5 << 29)
        )
        # third tier: past ~4.5 GB of per-shard state only a 64 MB
        # chunk budget leaves room for the scheduler's concurrent chunk
        # temporaries (measured at 128k many-role on a 16 GB v5e: 2^26
        # runs at 8.2 GB temp, 2^27+ OOMs).  Mesh runs tip at ~3.8 GB —
        # below the single-chip threshold because the per-shard budget
        # must also hold the replicated plan constants: the r3
        # calibration caught the 300k/8 shape (4.32 GB/shard, narrowly
        # under the single-chip threshold) compiling 29.9 GB of
        # per-shard temp under tier 2 (SCALE_r03.json
        # calibration.300k_fit), while the 200k/8 shape (1.92 GB/shard)
        # measures fine under tier 2 and must not regress to
        # serialized 64 MB chunks
        tier3 = state_bytes > (
            (7 << 29) if mesh is not None else (9 << 29)
        )
        if unroll is None:
            # second tier: past ~4.8 GB of per-shard state the second
            # unrolled body's live chunk buffers alone break one chip
            # (112k many-role: 12.35 GB at unroll=1 vs 15.96 GB at 2).
            # Mesh engines drop to 1 already at the `large` threshold:
            # there the per-step vote the second body amortizes is noise
            # next to the step itself, while the doubled traced body is
            # one of the biggest factors in the XLA compile wall (the
            # SNOMED-scale shapes are mesh-only, and their compile is
            # the deploy-time cost the reference never pays —
            # ``scripts/run-all.sh`` relaunches in minutes)
            if mesh is not None and large:
                unroll = 1
            else:
                unroll = 1 if state_bytes > (9 << 29) else 2
        self.unroll = max(int(unroll), 1)
        if temp_budget_bytes is None:
            if tier3:
                temp_budget_bytes = 1 << 26
            else:
                temp_budget_bytes = (1 << 28) if large else (1 << 29)
        # past the third tier, also pin the CR4/CR6 chunk order with
        # optimization barriers: XLA otherwise overlaps independent
        # chunks' contraction temporaries and the peak is both higher
        # and run-to-run variable — 128k single-chip measured flaky at
        # 8.2 GB temp without, stable with
        self._serialize_chunks = tier3
        if gate_chunks is None and large:
            gate_chunks = False
        # int8 × int8 → int32 runs 2x bf16 on the MXU and is exact
        self.matmul_dtype = jnp.int8 if matmul_dtype is None else matmul_dtype

        def on(rule: str) -> bool:
            return rules is None or rule in rules

        empty2 = np.zeros((0, 2), np.int64)
        empty3 = np.zeros((0, 3), np.int64)

        # --- per-rule static plans: sources permuted into seg-OR order.
        # Bucket mode canonicalizes each plan's segment-length histogram
        # (SegmentedRowOr.quantized): pad segments gather the reserved
        # dead row and OR it into itself (CR1/CR2) or into the dead link
        # row (CR3) — pure no-ops under OR, invisible to counts (the
        # dead rows' live-column bits never change and CR3's one
        # diagonal bit lands in a masked pad column).
        def _rule_plan(tab, tgt_col, src_cols, pad_target):
            plan = (
                SegmentedRowOr.quantized(
                    tab[:, tgt_col], self._qn, pad_target, len(tab)
                )
                if self._bucket
                else SegmentedRowOr(tab[:, tgt_col])
            )
            srcs = [
                np.append(tab[:, c], self._dead_c)[plan.order]
                if self._bucket
                else tab[plan.order, c]
                for c in src_cols
            ]
            return (plan, *srcs)

        #: segment/structure-count ladder: power-of-two rungs from 8 —
        #: deliberately coarser than the corpus-axis ladder, because a
        #: histogram has many entries and EVERY one must land on the
        #: same rung for two programs to collide (pad segments are
        #: near-free no-ops, so doubling a count costs little)
        self._qn = lambda n: bucket_dim(n, 2.0, floor=8)
        nf1 = idx.nf1 if on("CR1") else empty2
        self._p1, self._src1 = _rule_plan(nf1, 1, (0,), self._dead_c)
        nf2 = idx.nf2 if on("CR2") else empty3
        self._p2, self._src2a, self._src2b = _rule_plan(
            nf2, 2, (0, 1), self._dead_c
        )
        nf3 = idx.nf3 if on("CR3") else empty2
        self._p3, self._src3 = _rule_plan(nf3, 1, (0,), self._dead_l)
        # raw (unpermuted) CR1-CR3 tables: the sparse tier's host-side
        # active-set compaction selects rows against these — the plans
        # above are emission-permuted and quantization-padded
        self._sp_nf1, self._sp_nf2, self._sp_nf3 = nf1, nf2, nf3

        # CR4/CR6 row tables (chunking, masks and link-table arrays are
        # built later, once the final padded link-axis width is known).
        # Bucket mode quantizes the ROW COUNT each rule's scanned chunk
        # grid is laid out over; rows past the real table are handled by
        # the span builders' tail clamping (all-zero mask rows, dead
        # targets) and contribute nothing.
        self._has4 = bool(len(idx.nf4) and idx.n_links and on("CR4"))
        if self._has4:
            self._a4 = idx.nf4[:, 1]
        self._has6 = bool(
            len(idx.chain_pairs) and idx.n_links and on("CR6")
        )
        if self._has6:
            self._l26 = idx.chain_pairs[:, 1]
        k4_rows = len(idx.nf4) if self._has4 else 0
        k6_rows = len(idx.chain_pairs) if self._has6 else 0
        if self._bucket:
            k4_rows = self._q(k4_rows)
            k6_rows = self._q(k6_rows)
        self._k4_rows, self._k6_rows = k4_rows, k6_rows

        self._bottom = bool(
            idx.has_bottom_axioms and idx.n_links and on("CR5")
        )

        # CR1-CR3 (and CR5) are NOT split into per-axiom chunks: their
        # full static plans are swept over WORD BLOCKS of the state
        # instead (see the block loop in :meth:`_step`), which bounds the
        # per-rule temporaries to O(K·bw) while keeping the traced
        # program size independent of the corpus — one traced block body
        # instead of one body per chunk.  Round 2 unrolled one traced
        # body per chunk, and XLA compile time grew super-linearly in
        # chunk count (measured: 8/32/64 CR1 chunks at a fixed 128k
        # corpus compile in 1.7/11.7/18.3 s; the 300k-class superstep
        # took 74 minutes).  CR4/CR6 stay row-chunked: their contraction
        # needs the full word axis per row chunk (the bit-table reads
        # filler columns anywhere in the row), so they cannot word-block.
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self._use_pallas = use_pallas
        gather_rows = max(temp_budget_bytes // (self.wc * 4), 1)
        # the XLA fallback materializes the unpacked [rk, 32·wl] i32
        # product per SHARD-LOCAL word width (the Pallas kernel keeps
        # everything packed, so there only the packed output counts)
        wl_words = self.wc // self.n_shards
        mm_rows = (
            gather_rows
            if use_pallas
            else max(temp_budget_bytes // 2 // (128 * wl_words), 1)
        )

        # ---- ROLE-AWARE row chunking for CR4/CR6.  The axiom tables
        # arrive role-sorted (core/indexing: nf4 by s, chain_pairs by
        # first-leg role), so chunks cut at role-run boundaries keep
        # each chunk's relevant link set — links whose role is a
        # subrole of some chunk row's role — small, and the static
        # live-tile schedule (build_tiles below) then contracts only
        # those links: the reference's per-role hash-join partitioning
        # (RolePairHandler.java:396-444) as a static schedule.  Runs
        # merge greedily while the merged chunk's (rows × live links)
        # MAC volume stays within ``waste`` of the parts' sum, so
        # role-poor corpora still get few big MXU-friendly chunks; the
        # waste factor relaxes until the chunk count (≈ traced program
        # size) fits the compile budget.
        h_clo = idx.role_closure
        n_roles_all = h_clo.shape[0]
        link_cnt = (
            np.bincount(idx.links[:, 0], minlength=n_roles_all)
            if idx.n_links
            else np.zeros(n_roles_all, np.int64)
        )
        # role-aware splitting (and the role-resolution L-window cap
        # below) engage only when a table's DENSE contraction volume is
        # super-TFLOP — below that, pruning saves sub-0.1s of chip time
        # while growing the traced program (≈ compile time)
        rows_max = max(self._k4_rows, self._k6_rows)
        big_tables = rows_max * self.nl * self.nc >= 5e11

        def role_chunks(tab_roles, tab_targets):
            """[(raw_ids, inv, piece)] — ``raw_ids`` a contiguous
            role-sorted row range, ``piece`` a LOCAL seg-OR plan over
            the chunk's targets, ``inv`` its emission order."""

            def materialize(spans):
                out = []
                for a0, a1 in spans:
                    piece = SegmentedRowOr(tab_targets[a0:a1])
                    out.append((np.arange(a0, a1), piece.order, piece))
                return out

            n = len(tab_roles)
            if n == 0:
                return []
            if not big_tables:
                # the whole table's DENSE contraction is sub-TFLOP —
                # role-splitting would only grow the traced program
                # (≈ compile time); plain row-budget spans suffice
                spans = [
                    (o, min(o + mm_rows, n)) for o in range(0, n, mm_rows)
                ]
                return materialize(spans)
            starts = np.flatnonzero(
                np.r_[True, tab_roles[1:] != tab_roles[:-1]]
            )
            ends = np.r_[starts[1:], n]
            # oversized single-role runs split by the row budget alone
            pieces = []
            for s, e in zip(starts, ends):
                rho = int(tab_roles[s])
                for o in range(s, e, mm_rows):
                    pieces.append((o, min(o + mm_rows, e), rho))

            def greedy(waste):
                out, cur = [], None
                for s, e, rho in pieces:
                    rset = h_clo[:, rho] > 0
                    rmacs = (e - s) * int(link_cnt[rset].sum())
                    if cur is None:
                        cur = [s, e, rset.copy(), rmacs]
                        continue
                    nrows = e - cur[0]
                    nset = cur[2] | rset
                    nmacs = nrows * int(link_cnt[nset].sum())
                    if nrows <= mm_rows and nmacs <= waste * (
                        cur[3] + rmacs
                    ):
                        cur[1], cur[2], cur[3] = e, nset, cur[3] + rmacs
                    else:
                        out.append((cur[0], cur[1]))
                        cur = [s, e, rset.copy(), rmacs]
                if cur is not None:
                    out.append((cur[0], cur[1]))
                return out

            for waste in (1.25, 2.0, 4.0, float("inf")):
                spans = greedy(waste)
                if len(spans) <= 48:
                    break
            return materialize(spans)

        # ---- scan-mode decision: the budget floor on chunk count is
        # ceil(rows / mm_rows) per rule; once the total crosses the
        # threshold, per-chunk traced bodies dominate XLA compile time
        # (super-linear pass scaling — r3 measured 925 s at the 300k
        # shape) and the uniform-chunk lax.scan formulation takes over.
        k4 = self._k4_rows
        k6 = self._k6_rows
        est_spans = -(-k4 // mm_rows) + -(-k6 // mm_rows)
        if scan_chunks is None:
            scan_chunks = est_spans > _SCAN_CHUNK_THRESHOLD
        if self._bucket:
            # the scanned formulation is the only canonicalized CR4/CR6
            # structure (per-chunk unrolled bodies embed data-dependent
            # plans) — always scan under bucketing
            scan_chunks = True
        self._scan_mode = bool(scan_chunks) and (k4 + k6) > 0
        if self._scan_mode:
            self._cr4_chunks, self._cr6_chunks = [], []
            max_rk = max(min(mm_rows, max(k4, k6)), 1)
            self._scan_rk = (
                min(mm_rows, k4) if k4 else 0,
                min(mm_rows, k6) if k6 else 0,
            )
        else:
            self._cr4_chunks = (
                role_chunks(idx.nf4[:, 0], idx.nf4[:, 2])
                if self._has4
                else []
            )
            self._cr6_chunks = (
                role_chunks(idx.chain_pairs[:, 0], idx.chain_pairs[:, 2])
                if self._has6
                else []
            )
            max_rk = max(
                [
                    len(raw)
                    for raw, _, _ in self._cr4_chunks + self._cr6_chunks
                ],
                default=1,
            )
        # The contraction (link) axis is chunked too: a realistic
        # many-role corpus at 96k classes has ~100k links, so the
        # per-step [rk, nl] i8 operand (mask ∧ bit-table) alone would
        # be gigabytes.  An AND-OR product ORs over L, so the step
        # contracts one L-chunk at a time inside a ``lax.fori_loop`` —
        # sequencing matters: as a Python loop XLA schedules every
        # chunk's gathers concurrently and peak memory is back to the
        # unchunked figure.  The link axis pads up to a whole number of
        # equal chunks (padded links have all-zero mask bits — inert).
        if l_chunk is not None:
            lc = min(_pad_up(max(l_chunk, 32), 32), self.nl)
        else:
            lc = min(
                _pad_up(max(temp_budget_bytes // 2 // max(max_rk, 1), 32), 32),
                self.nl,
            )
            if big_tables:
                # resolve the link table's role runs: windows near the
                # mean run size let the static live-tile schedule skip
                # off-role links (256 floor keeps the MXU contraction
                # dimension efficient)
                n_link_roles = int(
                    len(np.unique(idx.links[:, 0])) if idx.n_links else 1
                )
                if self._bucket:
                    # the window length must be a pure function of the
                    # bucket rung, not the exact distinct-role count
                    n_link_roles = bucket_dim(
                        n_link_roles, self._bucket_ratio, floor=1
                    )
                role_lc = _pad_up(
                    -(-self.nl // max(n_link_roles, 1)), 32
                )
                lc = min(lc, max(role_lc, 256))
        self.n_lchunks = -(-self.nl // lc)
        # even the chunks out: taking the budget maximum as-is can round
        # nl up by almost a whole chunk of inert links (R rows + mask
        # bits); re-deriving lc from the chunk count bounds the padding
        # at 32 * n_lchunks links
        lc = _pad_up(-(-self.nl // self.n_lchunks), 32)
        self.nl = self.n_lchunks * lc
        self.lc = lc
        # CR4 gets its OWN (finer) window length: its per-chunk live
        # link runs are much shorter than CR6's (one existential's role
        # vs a chain head's whole subrole closure), so windows quantized
        # at the CR6-sized lc overshoot badly — measured slack at the
        # 96k many-role shape: 1.63x at lc=1600 vs 1.10x at lc=800
        # (CR6: 1.17x vs 1.06x, but finer CR6 windows double the
        # accumulator read-modify-write traffic of its much larger row
        # chunks, a bad trade).  The window table maps its c01 entries
        # onto the GLOBAL lc grid, so the L-frontier granularity is
        # unchanged.  lc4 clamps to lc: the c01 table records only a
        # window's FIRST and LAST global-lc chunk, which covers every
        # overlapped chunk only while the window is no wider than one
        # chunk — a coarser window could straddle a middle chunk whose
        # dirtiness would then never re-activate it (missed derivations
        # with a clean convergence vote).
        if l_chunk_cr4 is None:
            lc4 = lc
        else:
            lc4 = min(_pad_up(max(l_chunk_cr4, 32), 32), lc)
        self.lc4 = lc4

        # ---- word-block sweep plan for CR1-CR3 + CR5: the block width
        # bounds each rule's gather/reduce temporaries (the widest live
        # buffer is [K, bw]); blocks tile the shard-local word axis and
        # the LAST block overlaps its predecessor instead of padding
        # (off = min(i*bw, wl-bw)) — re-deriving a word twice in one
        # step is sound because every rule is an idempotent monotone OR.
        # Overlap instead of padding keeps nc/nl independent of the
        # block plan, which the incremental fast path's state-layout
        # interlock (core/incremental.py) relies on.
        wl = self.wc // self.n_shards
        emission_max = max(
            self._p1.k,
            2 * self._p2.k,  # two gathers live at once
            self._p3.k,
            1,
        )
        bw = temp_budget_bytes // (4 * emission_max)
        if bw >= 128:
            bw = bw // 128 * 128  # lane-aligned slices when affordable
        bw = max(min(bw, wl), 1)
        n_sblocks = -(-wl // bw)
        # even the blocks out (cf. the lc plan above): taking the budget
        # maximum as-is can leave the overlapping last block re-deriving
        # almost a whole block of words every superstep
        bw = -(-wl // n_sblocks)
        if bw >= 128:
            bw = min(_pad_up(bw, 128), wl)
        self._bw = bw
        self._n_sblocks = -(-wl // bw)

        # link-table arrays at the final width
        h = idx.role_closure
        link_roles = idx.links[:, 0] if idx.n_links else np.zeros(0, np.int64)
        # padded link rows get filler ⊤, NOT 0 (= ⊥): with filler 0,
        # CR5's ⊥-filler mask is true for padded rows (⊥ ∈ S(⊥)), and a
        # cooperating program that parks NEW links in this engine's
        # padding (the incremental cross-term path) would have their R
        # bits OR-ed into the ⊥ row by THIS engine's stale CR5.  ⊥ ∈
        # S(⊤) only when the whole ontology is inconsistent — where
        # every concept is already unsatisfiable, so the OR is sound.
        fillers = np.full(self.nl, TOP_ID, np.int64)
        if idx.n_links:
            fillers[: idx.n_links] = idx.links[:, 1]
        self._fillers = fillers

        # The CR4/CR6 closure masks are FACTORED, never materialized:
        # mask[j, l] = H[role(l), s_j] depends on l only through role(l),
        # so the step gathers one [rk, lc] tile per L-chunk from a
        # [K, n_roles+1] table (h[j, ρ] = H[ρ, s_j], one extra all-zero
        # sentinel role for padded links).  Round 1 stored the mask
        # bit-packed along the link axis ([K, nl/32] u32) — 8.6 GB at
        # the 300k-class SNOMED shape, REPLICATED per shard under the
        # word-axis sharding; the factored tables are ~15 MB there.
        # They stay *arguments* to the jitted run (embedded constants
        # get serialized into every remote compile request).
        n_roles = h.shape[0]
        # bucket mode widens the factored-mask ρ axis to a quantized
        # role count (extra rows all-zero) so the mask-table shapes are
        # rung-determined; the sentinel id moves to the padded end
        self._n_roles_pad = (
            bucket_dim(n_roles, self._bucket_ratio, floor=8)
            if self._bucket
            else n_roles
        )
        self._link_roles = np.full(self.nl, self._n_roles_pad, np.int32)
        if idx.n_links:
            self._link_roles[: idx.n_links] = link_roles

        # m4[j, ρ] = H[ρ, s_j] (link role must be a transitive subrole
        # of the axiom's s); m6[p, ρ] = H[ρ, r_p] (first-leg closure) —
        # shared encoding with rebind_role_closure
        _h2, m4, m6 = _factored_closure_tables(
            h,
            idx.nf4[:, 0] if self._has4 else None,
            idx.chain_pairs[:, 0] if self._has6 else None,
            n_pad=self._n_roles_pad,
        )
        # host copies for the sparse tier's row-granular CR4/CR6
        # activity fold (rebind_role_closure refreshes them): the full
        # factored-mask tables AS BOOL (the fold runs every round —
        # converting per round would copy the whole table each time),
        # plus which roles each L-chunk carries (dirty chunks -> dirty
        # roles -> rows whose masks cover one)
        self._m4_full = m4.astype(bool)
        self._m6_full = m6.astype(bool)
        self._chunk_roles_np = np.zeros(
            (self.n_lchunks, self._n_roles_pad + 1), bool
        )
        self._chunk_roles_np[
            np.arange(self.nl) // self.lc, self._link_roles
        ] = True
        # maximal dirty-role fold (every L-chunk dirty) and each
        # table's row activity under it, precomputed so the all-dirty
        # early rounds of every observed run — which are certain to
        # stay dense — skip the O(rows × roles) masked fold
        self._max_dirty_roles = self._chunk_roles_np.any(axis=0)
        self._m4_any = (self._m4_full & self._max_dirty_roles).any(axis=1)
        self._m6_any = (self._m6_full & self._max_dirty_roles).any(axis=1)

        # ---- static live-tile schedule: each CR4/CR6 row chunk
        # contracts ONLY the L-windows containing links whose role is a
        # (transitive) subrole of some axiom role in the chunk.  Roles
        # are fixed at index time and links are interned role-grouped
        # (core/indexing.role_sort_links), so the relevant links form a
        # few contiguous runs and the windows covering them are a static
        # per-chunk table — the contraction loop drives over it with
        # traced offsets (dynamic slices), skipping the ~93-98% of the
        # (rows × links) plane the role-block structure keeps dead
        # (the reference's per-role hash join partitioning,
        # RolePairHandler.java:396-444, as a static schedule).  Window
        # edges may include off-role links: their factored-mask entries
        # are 0, so they contribute nothing (and windows clamped at the
        # link-table tail re-derive earlier links — OR is idempotent).
        # Chunks with NO relevant links are dropped outright.
        def live_windows(role_list, lcn, h_arg=None):
            """Static live L-window offsets (offs, c01) for a row span
            whose axiom roles are ``role_list`` — shared by the per-chunk
            and the scanned-slab builders; None when no link can satisfy
            the span's roles.  ``lcn`` is the rule's window length (CR4
            may run finer windows than the global ``lc``).  ``c01`` holds
            the aligned GLOBAL-lc dirty_l chunks a window overlaps; the
            filler/link-role window contents are dynamic slices of the
            SHARED [nl] tables at runtime — stacking copies here would
            replicate them up to n_chunks times in the jitted-run
            arguments.  ``h_arg`` overrides the build-time role closure —
            :meth:`rebind_role_closure` recomputes the schedule under a
            GROWN closure against the same link table."""
            croles = np.unique(role_list)
            hh = h if h_arg is None else h_arg
            rel = np.flatnonzero(hh[:, croles].any(axis=1))
            live = np.flatnonzero(np.isin(self._link_roles, rel))
            if link_window is not None:
                w0, w1 = link_window
                live = live[(live >= w0) & (live < w1)]
            if live.size == 0:
                return None
            offs = []
            i = 0
            while i < live.size:
                off = min(int(live[i]), self.nl - lcn)
                offs.append(off)
                i = int(np.searchsorted(live, off + lcn))
            offs = np.asarray(offs, np.int32)
            c01 = np.stack(
                [
                    offs // self.lc,
                    np.minimum(
                        (offs + lcn - 1) // self.lc, self.n_lchunks - 1
                    ),
                ],
                axis=1,
            ).astype(np.int32)
            return offs, c01

        def _pad_window(lcn):
            """(offset, c01) of an inert reserve window: parked at the
            link-table tail, where padded rows carry the sentinel link
            role — the factored mask's all-zero column — so the window's
            operand is zero and the Pallas per-tile skip drops it.  (A
            tail window may also cover real trailing links; re-deriving
            them is idempotent under OR.)"""
            off = max(self.nl - lcn, 0)
            return off, (
                off // self.lc,
                min((off + lcn - 1) // self.lc, self.n_lchunks - 1),
            )

        def build_tiles(chunks, role_of, lcn):
            kept, tiles, dropped_roles = [], [], []
            hw = self._window_headroom
            p_off, p_c01 = _pad_window(lcn)
            for raw, inv, piece in chunks:
                win = live_windows(role_of(raw), lcn)
                if win is None:
                    # record the dead chunk's roles: rebind must refuse
                    # if a grown closure would make it live (its rows
                    # are absent from the compiled program)
                    dropped_roles.append(np.unique(role_of(raw)))
                    continue
                offs, c01 = win
                offs, c01 = _pad_to_slots(
                    offs, c01, len(offs) + hw, p_off, p_c01
                )
                kept.append((raw, inv, piece))
                tiles.append((jnp.asarray(offs), jnp.asarray(c01)))
            return kept, tiles, dropped_roles

        def build_scan(rk, lcn, tab_roles, rows_src, tab_targets,
                       mask_tab, fd_idx, fd_pad, want_readers=True,
                       n_rows=None, pad_target=0):
            """Uniform padded chunk slabs for one rule's scanned
            contraction: the role-sorted table splits into spans of
            exactly ``rk`` rows (tail zero-padded — padded rows have
            all-zero mask rows, so they contribute nothing), each span
            keeps its role-aware live-window table padded to the common
            window count, and chunks are batched into GROUPS whose
            padded matmul outputs are then OR-combined by ONE deferred
            target-sorted segmented-OR write per group (``SegmentedRowOr``
            over the group's padded target list — pad targets land in
            row 0's segment with zero rows, a no-op under OR).  The
            traced program is one ``lax.scan`` body + one write per
            group — O(1) in chunk count.  ``fd_idx``/``fd_pad``: per-row
            indices into the rule's change-source vector (S-row mask for
            CR4, dirty_l for CR6; pad = the appended always-False slot),
            folded to a per-chunk dirty scalar by one vectorized gather.
            ``n_rows``: bucket-quantized row-grid length (None = the
            real table) — spans past the real table slice short/empty
            and pad out exactly like per-span tail padding; bucket mode
            KEEPS spans with no live windows (all-inert slots, so a
            later ``rebind_role_closure`` can revive them) instead of
            dropping them, because the chunk count must be a pure
            function of the bucket rung.  ``pad_target``: row the pad
            slots' seg-OR targets aim at (the bucketed dead row; 0 — a
            no-op duplicate of the BOTTOM segment — for exact mode)."""
            spans = _chunk_spans(
                len(tab_roles) if n_rows is None else n_rows, rk
            )
            rows_l, fdx_l = [], []
            offs_l, c01_l, tgt_l, reader_rows = [], [], [], []
            spans_kept, spans_dropped = [], []
            for a0, a1 in spans:
                win = live_windows(tab_roles[a0:a1], lcn)
                if win is None:
                    if not self._bucket:
                        spans_dropped.append((a0, a1))
                        continue
                    win = (
                        np.zeros(0, np.int32), np.zeros((0, 2), np.int32)
                    )
                spans_kept.append((a0, a1))
                seg = rows_src[a0:a1]
                rows_l.append(np.pad(seg, (0, rk - len(seg))))
                seg = fd_idx[a0:a1]
                fdx_l.append(
                    np.pad(
                        seg, (0, rk - len(seg)), constant_values=fd_pad
                    )
                )
                offs_l.append(win[0])
                c01_l.append(win[1])
                seg = tab_targets[a0:a1]
                tgt_l.append(
                    np.pad(
                        seg, (0, rk - len(seg)),
                        constant_values=pad_target,
                    )
                )
                if want_readers:
                    reader_rows.append(rows_src[a0:a1])
            if not rows_l:
                # every span dead: no program structure to build, but
                # PERSIST the span grid — rebind_role_closure must check
                # exactly these boundaries when a grown closure could
                # revive one (re-deriving them later risks desync with
                # the grid actually used here)
                return {"empty": True, "spans_dropped": spans_dropped}
            n_grid = len(tab_roles) if n_rows is None else n_rows
            pos_of = np.full(n_grid, -1, np.int64)
            for i, (a0, a1) in enumerate(spans_kept):
                pos_of[a0:a1] = i * rk + np.arange(a1 - a0)
            nch = len(rows_l)
            n_windows = np.asarray([len(o) for o in offs_l])
            # reserve slots stay tval=False until rebind_role_closure
            # fills them for a grown closure
            T = int(n_windows.max()) + self._window_headroom
            if self._bucket:
                T = self._q1(T)  # window slab slots ride the ladder too
            offs_s, c01_s, tval_s = _fill_window_slabs(
                offs_l, c01_l, nch, T
            )
            # group size bounds the deferred per-group output buffer
            # ([gch·rk, wlw] u32 — the memory cost of deferring the
            # seg-OR).  256 MB measured best at the 300k/8-shard shape:
            # vs 128 MB groups it cuts step compile 407 → 294 s with
            # per-shard temp UNCHANGED at 4.4 GB (the serialized groups
            # reuse the same peak); 512 MB only reaches 254 s while
            # nudging temp up — the residual compile lives outside the
            # group bodies.  ``scan_group_bytes`` is the test hook for
            # forcing multi-group splits at small corpus sizes.
            group_bytes = scan_group_bytes or (1 << 28)
            wlw = self.wc // self.n_shards
            gch = max(int(group_bytes // max(rk * wlw * 4, 1)), 1)
            groups = []
            group_args = []
            for g0 in range(0, nch, gch):
                g1 = min(g0 + gch, nch)
                tg = np.concatenate(tgt_l[g0:g1])
                if self._bucket:
                    # canonical write plan: pad segments gather the
                    # appended all-zero row of the group's (padded) scan
                    # output — index (g1-g0)*rk — into the dead row
                    plan = SegmentedRowOr.quantized(
                        tg, self._qn, pad_target, (g1 - g0) * rk
                    )
                else:
                    plan = SegmentedRowOr(tg)
                # gate-reader rows: only the CR4 flags consult
                # them (CR6 groups re-dirty on ANY R change)
                rows = (
                    np.unique(np.concatenate(reader_rows[g0:g1]))
                    if want_readers
                    else None
                )
                if self._bucket:
                    if rows is not None:
                        rows = np.pad(
                            rows,
                            (0, self._qn(len(rows)) - len(rows)),
                            constant_values=self._dead_c,
                        )
                    # runtime copies of the plan's data content — the
                    # compiled program gathers/writes through THESE so
                    # the jaxpr stays ontology-independent
                    group_args.append(
                        (
                            jnp.asarray(plan.order.astype(np.int32)),
                            jnp.asarray(plan.targets.astype(np.int32)),
                            jnp.asarray(rows.astype(np.int32))
                            if rows is not None
                            else (),
                        )
                    )
                groups.append((g0, g1, plan, rows))
            slabs = tuple(
                jnp.asarray(x)
                for x in (
                    np.stack(rows_l).astype(np.int32),
                    np.stack(fdx_l).astype(np.int32),
                    _stack_span_masks(mask_tab, spans_kept, rk),
                    offs_s,
                    c01_s,
                    tval_s,
                )
            )
            return {
                "rk": rk,
                "lcn": lcn,
                "nch": nch,
                "T": T,
                "groups": groups,
                "slabs": slabs,
                "n_windows": n_windows,
                # rebind_role_closure's structural record: which row
                # spans the compiled program carries (and which it
                # dropped as dead — a grown closure reviving one forces
                # the rebuild path; bucket mode drops nothing, so its
                # rebind can revive any span within the T slots)
                "spans_kept": spans_kept,
                "spans_dropped": spans_dropped,
                "group_args": tuple(group_args),
                "pad_target": pad_target,
                # host copies for the sparse tier's per-round activity
                # fold (the slabs above are device arrays);
                # rebind_role_closure refreshes tval alongside the
                # slab swap
                "tval_np": tval_s,
                "tgt_rows_np": np.stack(tgt_l),
                # table row -> flat slab position (chunk*rk + offset);
                # -1 for rows of dropped spans (absent from the program
                # — and provably inert: a dropped span's roles satisfy
                # no link, so no frontier can activate its rows)
                "pos_of_row": pos_of,
            }

        # the whole plan-table pytree (closure masks + live-tile
        # schedules) stays an ARGUMENT to the jitted run — embedded
        # constants get serialized into every remote compile request
        # and replicated per shard
        if self._scan_mode:
            rk4, rk6 = self._scan_rk

            def _settle(d):
                """(scan dict | None, persisted all-dropped spans)."""
                if d is None:
                    return None, []
                if d.get("empty"):
                    return None, d["spans_dropped"]
                return d, []

            self._scan4, self._scan4_dropped = _settle(
                build_scan(
                    rk4, self.lc4, idx.nf4[:, 0], self._a4,
                    idx.nf4[:, 2], m4, self._a4, self.nc,
                    n_rows=self._k4_rows if self._bucket else None,
                    pad_target=self._dead_c if self._bucket else 0,
                )
                if self._has4
                else None
            )
            self._scan6, self._scan6_dropped = _settle(
                build_scan(
                    rk6, self.lc, idx.chain_pairs[:, 0], self._l26,
                    idx.chain_pairs[:, 2], m6,
                    self._l26 // self.lc, self.n_lchunks,
                    want_readers=False,
                    n_rows=self._k6_rows if self._bucket else None,
                    pad_target=self._dead_l if self._bucket else 0,
                )
                if self._has6
                else None
            )
            self._cr4_tiles, self._cr6_tiles = [], []
            self._cr4_dropped_roles = self._cr6_dropped_roles = []
            # ---- CR6 live-tile schedule (core/cr6_tiles.py): pack the
            # role-run row tiles against their own live links and keep
            # the window formulation only as the sparse tier's (and the
            # rebind bookkeeping's) structure.  Build-time decision —
            # tiled vs window MAC volume under the configured
            # threshold — so it folds into the bucket signature below.
            self._tiles6 = None
            self.cr6_tiles_stats = {"active": False, "reason": "off"}
            if (
                self._cr6_tiles_cfg is not None
                and self._scan6 is not None
                and mesh is None
            ):
                tcfg = self._cr6_tiles_cfg
                d6 = self._scan6
                gb = [g0 * rk6 for g0, _g1, _p, _r in d6["groups"]]
                gb.append(d6["groups"][-1][1] * rk6)
                # tile_m clamps to the (padded) table height: a tiny
                # chain table under a 512-row tile would charge the MAC
                # volume (and the density decision) for pad rows that
                # outnumber the real ones.  Bucket mode clamps against
                # the rung-quantized grid, keeping it rung-derived.
                n_grid6 = (
                    self._k6_rows
                    if self._bucket
                    else len(idx.chain_pairs)
                )
                tm_eff = max(
                    min(tcfg["tile_m"], _pad_up(max(n_grid6, 1), 8)), 8
                )
                sched = build_cr6_tile_schedule(
                    idx.chain_pairs[:, 0], self._l26,
                    idx.chain_pairs[:, 2], m6,
                    self._link_roles, h,
                    lc=self.lc, n_lchunks=self.n_lchunks,
                    tile_m=tm_eff, tile_l=tcfg["tile_l"],
                    group_bounds=gb, link_window=link_window,
                    n_rows=self._k6_rows if self._bucket else None,
                    dead_link=self.nl - 1,
                    pad_target=self._dead_l if self._bucket else 0,
                    tile_headroom=self._window_headroom,
                    q1=self._q1 if self._bucket else None,
                    qn=self._qn if self._bucket else None,
                )
                window_macs = int(d6["n_windows"].sum()) * self.lc * rk6
                tile_macs = (
                    sched.stats["occupied_slots"] * sched.tile_m
                )
                density = tile_macs / max(float(window_macs), 1.0)
                self.cr6_tiles_stats = {
                    "active": False,
                    "density": round(density, 4),
                    "window_slot_rows": window_macs,
                    "tile_slot_rows": tile_macs,
                    **sched.stats,
                }
                # link_window engines (the incremental cross programs)
                # take tiles unconditionally: their contraction is tiny
                # either way, and a per-delta density flip would fold
                # the delta's link CONTENT into the bucket signature —
                # the steady-state recompile hazard the value-
                # independent span grid above exists to prevent
                if (
                    density <= tcfg["density_threshold"]
                    or link_window is not None
                ):
                    self._tiles6 = sched
                    self.cr6_tiles_stats["active"] = True
                else:
                    # live structure too dense for packing to pay:
                    # keep the window formulation, loudly in the stats
                    self.cr6_tiles_stats["reason"] = (
                        "density above threshold"
                    )
            elif self._cr6_tiles_cfg is not None:
                self.cr6_tiles_stats["reason"] = (
                    "no scanned CR6" if self._scan6 is None else "mesh"
                )
            self._masks = (
                jnp.asarray(self._fillers.astype(np.int32)),
                jnp.asarray(self._link_roles),
                self._scan4["slabs"] if self._scan4 else (),
                self._scan6["slabs"] if self._scan6 else (),
                self._t6_device_slabs(),
            )
        else:
            self._scan4 = self._scan6 = None
            self._scan4_dropped = self._scan6_dropped = []
            self._tiles6 = None
            self.cr6_tiles_stats = {
                "active": False, "reason": "unrolled CR6 formulation",
            }
            self._cr4_chunks, self._cr4_tiles, self._cr4_dropped_roles = (
                build_tiles(
                    self._cr4_chunks, lambda raw: idx.nf4[raw, 0], self.lc4
                )
            )
            self._cr6_chunks, self._cr6_tiles, self._cr6_dropped_roles = (
                build_tiles(
                    self._cr6_chunks, lambda raw: idx.chain_pairs[raw, 0],
                    self.lc,
                )
            )
            self._masks = (
                jnp.asarray(m4),
                jnp.asarray(m6),
                jnp.asarray(self._fillers.astype(np.int32)),
                jnp.asarray(self._link_roles),
                tuple(self._cr4_tiles),
                tuple(self._cr6_tiles),
            )

        # rebind_role_closure re-derives window schedules under a grown
        # closure through the same builders the compile-time plan used
        self._live_windows = live_windows
        self._make_pad_window = _pad_window

        #: density denominator of the sparse-tail controller: total
        #: REAL rule-table rows a fully-dirty round re-evaluates
        self._sp_total_rows = (
            len(nf1) + len(nf2) + len(nf3)
            + (len(idx.nf4) if self._has4 else 0)
            + (len(idx.chain_pairs) if self._has6 else 0)
            + (1 if self._bottom else 0)
        )

        # one packed-output matmul plan per row-chunk, shared by every
        # (equal-sized) L-window.  dtype: forwarded only when the caller
        # pinned one — the Pallas kernel's own default (bf16 on TPU) wins
        # otherwise; the engine's int8 preference applies to the
        # XLA-formulated lookups/tables
        mm_kw = {"use_xla": not use_pallas}
        if matmul_dtype is not None:
            mm_kw["dtype"] = matmul_dtype
        if mm_opts:
            mm_kw.update(mm_opts)
        self._mm_kw = dict(mm_kw)  # the sparse tier builds its own plans
        wl = self.wc // self.n_shards
        if self._scan_mode:

            def scan_mm(rk, lcn):
                # the ONE plan all scanned chunks share; under the XLA
                # fallback the m-axis pad is pure wasted MACs, so align
                # it to 8 instead of the Pallas grid tile
                kw2 = dict(mm_kw)
                if kw2.get("use_xla") and "tm" not in kw2:
                    kw2["tm"] = max(_pad_up(rk, 8), 8)
                return PackedColsMatmulPlan(rk, lcn, wl, **kw2)

            self._cr4_mm = (
                [scan_mm(self._scan_rk[0], self.lc4)]
                if self._scan4
                else []
            )
            if self._tiles6 is not None:
                # the ONE per-tile plan of the live-tile formulation:
                # [tile_m, tile_l] against the packed gathered rows
                # (cr6_tiles.make_tile_matmul forces the Pallas skip
                # flags on when the Mosaic kernel is in play)
                self._cr6_mm = [
                    make_tile_matmul(
                        self._tiles6.tile_m, self._tiles6.tile_l, wl,
                        mm_kw,
                    )
                ]
            else:
                self._cr6_mm = (
                    [scan_mm(self._scan_rk[1], lc)] if self._scan6 else []
                )
        else:
            self._cr4_mm = [
                PackedColsMatmulPlan(len(raw), self.lc4, wl, **mm_kw)
                for raw, _, _ in self._cr4_chunks
            ]
            self._cr6_mm = [
                PackedColsMatmulPlan(len(raw), lc, wl, **mm_kw)
                for raw, _, _ in self._cr6_chunks
            ]

        # live-column word mask: bits for x < n_concepts only
        wmask = np.zeros(self.wc, np.uint32)
        full, rem = divmod(idx.n_concepts, 32)
        wmask[:full] = 0xFFFFFFFF
        if rem:
            wmask[full] = (1 << rem) - 1
        self._wmask = wmask

        if gate_chunks is None:
            gate_chunks = self.nc >= 32_768
        self._gate = self._build_gate() if gate_chunks else None

        # ---- L-frontier bookkeeping: the two-sided semi-naive join of
        # the reference (base/Type3_2AxiomProcessorBase.java:100-174 —
        # part 1 re-joins keys whose B-side grew, part 2 keys whose
        # R-side grew) in tensor form.  Each CR4/CR6 L-iteration's
        # contribution is OR-monotone, so it only needs re-contracting
        # when one of its inputs changed since it last ran:
        #   * an R row inside that L-chunk           (dirty_l[i]), or
        #   * a bit-table source row — S rows a4[raw] for CR4 (exact,
        #     via the changed-S-row mask), R rows l2[raw] for CR6
        #     (conservative at L-chunk granularity).
        # A stale iteration's w operand is multiplied to zero, which the
        # Pallas kernel's per-tile skip flags turn into skipped MXU work
        # — no lax.cond, so no state- or acc-valued branch copies.
        # Skipped contributions are only DELAYED: flags are folded from
        # this step's write change-vectors, and the fixed point exits
        # only after a full no-change step, so convergence detection is
        # unaffected.
        # writer target lists in the EXACT order _step appends change
        # vectors (CR1, CR2, CR4, CR5 / CR3, CR6); turned into layered
        # row → concat-position gather maps (_pos_maps — a scatter would
        # serialize per index on TPU) shared by the rule gate and the
        # L-frontier fold
        if self._scan_mode:
            w4_targets = [
                g[2].targets
                for g in (self._scan4["groups"] if self._scan4 else [])
            ]
            if self._tiles6 is not None:
                # live-tile CR6: the change vectors come from the tile
                # write groups, so the layered maps must index THEIR
                # emission order, not the window grid's
                w6_targets = [g[2].targets for g in self._tiles6.groups]
            else:
                w6_targets = [
                    g[2].targets
                    for g in (self._scan6["groups"] if self._scan6 else [])
                ]
        else:
            w4_targets = [
                piece.targets for _, _, piece in self._cr4_chunks
            ]
            w6_targets = [
                piece.targets for _, _, piece in self._cr6_chunks
            ]
        s_writers = (
            ([self._p1.targets] if self._p1.k else [])
            + ([self._p2.targets] if self._p2.k else [])
            + w4_targets
            + ([np.asarray([BOTTOM_ID])] if self._bottom else [])
        )
        r_writers = ([self._p3.targets] if self._p3.k else []) + w6_targets
        pm_kw = (
            {"quantize": self._q1}
            if self._bucket
            else {}
        )
        self._s_layers = _pos_maps(
            s_writers, self.nc,
            dead_rows=(self._dead_c,) if self._bucket else (),
            **pm_kw,
        )
        self._r_layers = _pos_maps(
            r_writers, self.nl,
            dead_rows=(self._dead_l,) if self._bucket else (),
            **pm_kw,
        )
        self._l2chunks6 = [
            np.unique(self._l26[raw] // self.lc)
            for raw, _, _ in self._cr6_chunks
        ]
        self._a4rows = [self._a4[raw] for raw, _, _ in self._cr4_chunks]

        # ---- bucketed argument pytree + bucket signature.  Every
        # ontology-derived array the step reads becomes a runtime
        # argument here; the traced program is then a pure function of
        # the structural metadata hashed into ``bucket_signature``, so
        # same-bucket ontologies share one compiled executable (the
        # process-global PROGRAMS registry) and identical persistent-
        # cache HLO.
        if self._bucket:

            def i32(a):
                return jnp.asarray(np.asarray(a, np.int32))

            gate_rows = []
            if self._gate is not None:
                for kind, rows in self._gate["readers"]:
                    if kind == "SR":
                        gate_rows.append(
                            i32(rows if rows is not None else
                                np.zeros(0, np.int32))
                        )
            self._masks = {
                "wmask": jnp.asarray(self._wmask),
                "fills": i32(self._fillers),
                "lroles": jnp.asarray(self._link_roles),
                "src1": i32(self._src1),
                "tgt1": i32(self._p1.targets),
                "src2a": i32(self._src2a),
                "src2b": i32(self._src2b),
                "tgt2": i32(self._p2.targets),
                "src3": i32(self._src3),
                "tgt3": i32(self._p3.targets),
                "s4": self._scan4["slabs"] if self._scan4 else (),
                "s6": self._scan6["slabs"] if self._scan6 else (),
                "g4": self._scan4["group_args"] if self._scan4 else (),
                "g6": self._scan6["group_args"] if self._scan6 else (),
                # live-tile CR6 content (empty when window formulation
                # is active): slab arrays + per-group write-plan args
                "t6": self._t6_device_slabs(),
                "gt6": tuple(
                    (i32(order), i32(tgts))
                    for _rt0, _rt1, _p, order, tgts in (
                        self._tiles6.groups if self._tiles6 else ()
                    )
                ),
                "sl": tuple(i32(pm) for pm in self._s_layers),
                "rl": tuple(i32(pm) for pm in self._r_layers),
                "gate_rows": tuple(gate_rows),
            }
        #: build-knob record folded into the signature (options that
        #: steer tracing without leaving a distinct shape attribute).
        #: Bucket mode records only link_window's PRESENCE, not its
        #: bounds: the window reaches the traced program exclusively
        #: through the runtime-arg window slabs (offs/c01/tval), so the
        #: incremental cross program — full CR4/CR6 tables × the
        #: new-link window — compiles once per bucket and every later
        #: delta's (start, stop) rides in as argument content
        self._sig_knobs = repr(
            (
                mm_opts, l_chunk, l_chunk_cr4, temp_budget_bytes,
                scan_group_bytes,
                (link_window is not None) if self._bucket else link_window,
                gate_chunks,
            )
        )
        self.bucket_signature = self._compute_signature()
        #: per-budget AOT executables (single-device; populated by
        #: precompile()/saturate, shared across engines via PROGRAMS in
        #: bucket mode)
        self._aot_runs: dict = {}
        self._aot_step = None
        #: sparse-tail tier state: normalized controller config,
        #: per-capacity AOT executables, build telemetry, per-round
        #: frontier records of the last saturate_observed run
        self._sparse_cfg = self._normalize_sparse_cfg(sparse_tail)
        #: pipelined-observation config (runtime-only: never part of the
        #: traced program, so it stays out of the bucket signature)
        self._pipeline_cfg = self._normalize_pipeline_cfg(pipeline)
        self._aot_sparse: dict = {}
        self._sparse_builds: list = []
        self._sparse_const_cache = None
        self._sparse_mm: dict = {}
        #: fused multi-round tier state (ISSUE 17): normalized window
        #: config, per-(K, capacity) AOT executables, build telemetry,
        #: and the cached device plan tables of the on-device round
        #: decision (dropped by rebind_role_closure — the factored
        #: masks and live-window validity change under a grown closure)
        self._fused_cfg = self._normalize_fused_cfg(fused_rounds)
        self._aot_fused: dict = {}
        self._fused_builds: list = []
        self._fused_tab_cache = None
        self.frontier_rounds: list = []
        self._stats_lock = threading.Lock()
        #: accumulated program-build telemetry for this engine
        self.compile_stats = CompileStats(
            bucket_signature=self.bucket_signature, program="total"
        )
        self.last_compile: Optional[CompileStats] = None

        if mesh is not None:
            P = jax.sharding.PartitionSpec
            self._state_sharding = jax.sharding.NamedSharding(
                mesh, P(None, word_axis)
            )
        else:
            self._state_sharding = None
        # jit over a [:2] slice so the change flag is dead code inside
        # the trace (the public step() discards it)
        self._step_jit = jax.jit(
            lambda sp, rp, masks: self._step(sp, rp, masks)[:2]
        )
        self._step_sharded = None
        self._initial_jit = None
        self._observe_jit = None
        self._live_bits_jit = None
        self._embed_dev_jit = None
        # donate the state buffers where safe (see _state_donation): every
        # saturate() builds fresh arrays (initial_state / embed_state), and
        # without donation XLA keeps a full input copy alive across the loop
        # — 2x state memory
        if mesh is None:
            self._run_jit = jax.jit(
                self._run, static_argnums=(3,), donate_argnums=_state_donation()
            )
        else:
            self._run_jit = functools.lru_cache(maxsize=4)(self._sharded_run)

    # ------------------------------------------------------------- state

    def _initial_arrays(self) -> Tuple[jax.Array, jax.Array]:
        """S(X) = {X, ⊤}, R empty (reference init
        ``init/AxiomLoader.java:1237-1245``): the diagonal plus a full ⊤
        row — padded x columns evolve inertly and are masked from counts."""
        rows = jnp.arange(self.nc)
        sp = jnp.zeros((self.nc, self.wc), jnp.uint32)
        sp = sp.at[rows, rows >> 5].set(
            jnp.asarray(1, jnp.uint32) << (rows & 31).astype(jnp.uint32)
        )
        sp = sp.at[TOP_ID].set(jnp.full((self.wc,), 0xFFFFFFFF, jnp.uint32))
        rp = jnp.zeros((self.nl, self.wc), jnp.uint32)
        return sp, rp

    def initial_state(self) -> Tuple[jax.Array, jax.Array]:
        if self._initial_jit is None:
            out_shardings = (
                None
                if self._state_sharding is None
                else (self._state_sharding, self._state_sharding)
            )
            self._initial_jit = jax.jit(
                self._initial_arrays, out_shardings=out_shardings
            )
        return self._initial_jit()

    def embed_state(
        self, s_old, r_old, *, allow_shrink: bool = False
    ) -> Tuple[jax.Array, jax.Array]:
        """Embed a previous closure into this engine's (possibly larger)
        transposed packed arrays — the incremental/resume path.

        Accepts either *unpacked x-major* bool arrays
        (``SaturationResult.s`` / ``.r`` from any engine) or *packed
        transposed* uint32 arrays (``SaturationResult.packed_s`` /
        ``.packed_r`` of a row-packed result, dispatched on dtype) — the
        packed form never densifies and is 32x smaller end to end.
        Packed-row reuse is sound because concept ids are append-only and
        an old run's padded x-columns evolve exactly as fresh concepts
        with S(x)={x,⊤} and no axioms — i.e. the correct warm start for
        ids later assigned to new concepts."""
        if isinstance(s_old, jax.Array) and s_old.dtype == jnp.uint32:
            # device-resident wire state (the incremental path): embed on
            # device — at 64k scale a host round trip of the closure costs
            # minutes over the remote-attach tunnel
            return self._embed_packed_device(
                s_old, r_old, allow_shrink=allow_shrink
            )
        if np.asarray(s_old).dtype == np.uint32:
            return self._embed_packed(
                np.asarray(s_old),
                np.asarray(r_old),
                allow_shrink=allow_shrink,
            )
        s_old = np.asarray(s_old, bool)
        r_old = np.asarray(r_old, bool)
        check_embed_fits(
            allow_shrink,
            concepts=(s_old.shape[0], self.nc),
            subsumers=(s_old.shape[1], self.nc),
            link_rows=(r_old.shape[0], self.nc),
            links=(r_old.shape[1], self.nl),
        )

        def pack_rows(m: np.ndarray) -> np.ndarray:
            pad = (-m.shape[1]) % 32
            if pad:
                m = np.pad(m, ((0, 0), (0, pad)))
            b = np.ascontiguousarray(
                np.packbits(m, axis=1, bitorder="little")
            )
            return b.view(np.uint32)

        rows = np.arange(self.nc)
        sp = np.zeros((self.nc, self.wc), np.uint32)
        sp[rows, rows >> 5] = np.uint32(1) << (rows & 31).astype(np.uint32)
        sp[TOP_ID, :] = np.uint32(0xFFFFFFFF)
        na = min(s_old.shape[1], self.nc)
        nx = min(s_old.shape[0], self.nc)
        ps = pack_rows(s_old[:nx, :na].T)  # [na, ceil32(nx)] words
        sp[:na, : ps.shape[1]] |= ps
        rp = np.zeros((self.nl, self.wc), np.uint32)
        nl = min(r_old.shape[1], self.nl)
        pr = pack_rows(r_old[:nx, :nl].T)
        rp[:nl, : pr.shape[1]] |= pr
        if self._state_sharding is not None:
            return (
                jax.device_put(sp, self._state_sharding),
                jax.device_put(rp, self._state_sharding),
            )
        return jnp.asarray(sp), jnp.asarray(rp)

    def _embed_packed_device(
        self,
        sp_old: jax.Array,
        rp_old: jax.Array,
        *,
        allow_shrink: bool = False,
    ) -> Tuple[jax.Array, jax.Array]:
        """Device-side :meth:`_embed_packed`: pad the wire rows into the
        (possibly grown) arrays and OR in the fresh-concept init, without
        the closure ever visiting the host.  Always emits FRESH buffers
        (one fused HBM pass, ~ms) — ``saturate`` donates its initial
        state into the run, and passing a caller's buffers through would
        silently invalidate the result they came from."""
        check_embed_fits(
            allow_shrink,
            subsumer_rows=(sp_old.shape[0], self.nc),
            x_words=(sp_old.shape[1], self.wc),
            link_rows=(rp_old.shape[0], self.nl),
            link_x_words=(rp_old.shape[1], self.wc),
        )

        def embed(sp_old, rp_old):
            sp, rp = self._initial_arrays()
            na = min(sp_old.shape[0], self.nc)
            nw = min(sp_old.shape[1], self.wc)
            sp = sp.at[:na, :nw].set(
                sp[:na, :nw] | sp_old[:na, :nw]
            )
            nlr = min(rp_old.shape[0], self.nl)
            rp = rp.at[:nlr, :nw].set(rp_old[:nlr, :nw])
            return sp, rp

        if self.mesh is None:
            # shape-keyed registry program: the incremental fast path
            # builds FRESH delta engines every increment, and a
            # per-instance jit would re-trace+compile this (tiny)
            # embed per delta — ~0.1-0.3 s of pure steady-state
            # overhead on CPU.  The traced body depends only on the
            # shapes and TOP_ID, so shape keying is exact.
            key = (
                "shape:embed", self.nc, self.nl, self.wc,
                tuple(sp_old.shape), tuple(rp_old.shape),
            )

            def build():
                return (
                    jax.jit(embed)
                    .lower(
                        jax.ShapeDtypeStruct(sp_old.shape, jnp.uint32),
                        jax.ShapeDtypeStruct(rp_old.shape, jnp.uint32),
                    )
                    .compile()
                )

            exe, _hit = PROGRAMS.get_or_build(key, build)
            return exe(sp_old, rp_old)
        if self._embed_dev_jit is None:
            self._embed_dev_jit = jax.jit(
                embed,
                out_shardings=(
                    self._state_sharding, self._state_sharding
                ),
            )
        return self._embed_dev_jit(sp_old, rp_old)

    def _embed_packed(
        self,
        sp_old: np.ndarray,
        rp_old: np.ndarray,
        *,
        allow_shrink: bool = False,
    ) -> Tuple[jax.Array, jax.Array]:
        """Copy packed transposed state into the (grown) arrays: stable
        ids mean old words land verbatim in the low words of each row."""
        check_embed_fits(
            allow_shrink,
            subsumer_rows=(sp_old.shape[0], self.nc),
            x_words=(sp_old.shape[1], self.wc),
            link_rows=(rp_old.shape[0], self.nl),
            link_x_words=(rp_old.shape[1], self.wc),
        )
        rows = np.arange(self.nc)
        sp = np.zeros((self.nc, self.wc), np.uint32)
        sp[rows, rows >> 5] = np.uint32(1) << (rows & 31).astype(np.uint32)
        sp[TOP_ID, :] = np.uint32(0xFFFFFFFF)
        na = min(sp_old.shape[0], self.nc)
        nw = min(sp_old.shape[1], self.wc)
        sp[:na, :nw] |= sp_old[:na, :nw]
        rp = np.zeros((self.nl, self.wc), np.uint32)
        nl = min(rp_old.shape[0], self.nl)
        rp[:nl, :nw] = rp_old[:nl, :nw]
        if self._state_sharding is not None:
            return (
                jax.device_put(sp, self._state_sharding),
                jax.device_put(rp, self._state_sharding),
            )
        return jnp.asarray(sp), jnp.asarray(rp)

    # ------------------------------------------------------------- rules

    def _shard_jit(self, fn, out_specs, donate=(), with_dirty=False):
        """Shared shard_map+jit scaffolding for every mesh entry point
        (fixed point, public step, observed round): state sharded on the
        packed word axis, masks replicated; ``with_dirty`` adds the
        replicated 3-tuple frontier carry between state and masks."""
        P = jax.sharding.PartitionSpec
        state = P(None, self.word_axis)
        # plan tables (masks + live-tile schedules): replicated leaves
        masks = jax.tree.map(lambda _: P(), self._masks)
        in_specs = (
            (state, state, P(None), masks)
            if with_dirty
            else (state, state, masks)
        )
        return jax.jit(
            shard_map(
                fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ),
            donate_argnums=donate,
        )

    def _shard_word_base(self, axis_name):
        """This shard's word offset into the packed word axis (the ONE
        place the even-split layout invariant — ``wc`` divisible by
        ``n_shards``, arranged by construction padding — is encoded)."""
        return lax.axis_index(axis_name) * (self.wc // self.n_shards)

    def _bit_table(
        self, p: jax.Array, rows: np.ndarray, axis_name: Optional[str],
        cols=None,
    ) -> jax.Array:
        """``out[j, i] = bit(p[rows[i], column fillers[j]])`` as the
        matmul dtype, [nl, len(rows)] (transposed — callers fold the
        transpose into their next op).  Linear-cost lookup via
        ``ops.bitpack.bit_lookup``; under sharding each filler column
        lives on exactly one shard, so a masked local lookup + psum IS
        the exchange — the only cross-shard data of the whole step (the
        packed analog of the reference's delta reads against the result
        node, ``base/Type2AxiomProcessorBase.java:101-116``).  The
        CR4/CR6 L-chunk loop uses ``bit_lookup_from`` directly; this
        full-width variant serves CR5's ⊥-filler mask.  ``cols``: a
        bucketed engine passes its argument-carried filler table so the
        column ids never trace as constants."""
        dt = self.matmul_dtype
        if cols is None:
            cols = self._fillers
        if axis_name is None:
            return bit_lookup(p, rows, cols, dtype=dt)
        base = self._shard_word_base(axis_name)
        bits = bit_lookup(p, rows, cols, word_offset=base, dtype=jnp.int32)
        return lax.psum(bits, axis_name).astype(dt)

    def _build_gate(self):
        """Static structures for frontier-gated chunk skipping — the
        tensor analog of the reference's semi-naive score cursors
        (``misc/Util.java:68-93``: every worker re-reads only keys whose
        score grew): a rule chunk re-runs only when a row it reads
        changed in the previous superstep.  Writers emit per-target
        change vectors; *layered permutation gathers* turn them into
        global changed-row masks (a scatter would serialize per index on
        TPU); each reader's dirty flag is then a static gather + any().
        CR4/CR6 contract over the whole R matrix, so any R change
        re-dirties them.  Flag order == chunk execution order in
        :meth:`_step`."""
        readers = []
        if self._scan_mode:
            # flag granularity in scan mode is the write GROUP (the
            # per-chunk signal lives in the scanned live/f_dirty
            # multipliers instead of a cond)
            for g in self._scan4["groups"] if self._scan4 else []:
                readers.append(("SR", g[3]))
            cr6_groups = (
                self._tiles6.groups
                if self._tiles6 is not None
                else (self._scan6["groups"] if self._scan6 else [])
            )
            for _g in cr6_groups:
                readers.append(("RR", None))
        else:
            for raw, _inv, plan in self._cr4_chunks:
                readers.append(("SR", np.unique(self._a4[raw])))
            for raw, _inv, plan in self._cr6_chunks:
                readers.append(("RR", None))
        if self._bottom:
            # CR5's masked OR-reduce sweeps all of R_T (scales with
            # nl·wc, unlike CR1-3's axiom-count-bound gathers), so it
            # keeps its gate; always the LAST flag
            readers.append(("CR5", None))

        # R-side masks are unnecessary for the GATE: every R reader
        # (CR4/CR6 contract the whole matrix, CR5 reduces it) re-dirties
        # on ANY R change.  The layered maps themselves are built once in
        # __init__ (_pos_maps) and shared with the L-frontier fold.
        if not readers:
            return None
        return {
            "readers": readers,
            "n_flags": len(readers),
        }

    def initial_dirty(self):
        """All-dirty frontier carry (everything runs on the first
        superstep): ``(rule-chunk gate flags, per-L-chunk R dirty flags,
        changed-S-row mask)``."""
        n = self._gate["n_flags"] if self._gate else 0
        return (
            jnp.ones(max(n, 1), bool),
            jnp.ones(max(self.n_lchunks, 1), bool),
            jnp.ones(self.nc, bool),
        )

    # ---------------------------------------------- sparse-tail tier
    #
    # Semi-naive saturation means late rounds derive little, yet the
    # dense step still sweeps every rule table and the full CR4/CR6
    # chunk grid each round (gating zeroes the operands but the
    # gathers, window slices and scan bodies all execute).  The sparse
    # tier makes tail rounds cost what they derive: a host-side
    # controller (see ``saturate_observed``) folds each round's
    # frontier to the host, compacts the ACTIVE rule rows (CR1-CR3 at
    # row granularity) and active CR4/CR6 chunks (the dense program's
    # own gating granularity) into small capacity-quantized workspace
    # arrays, and runs them through ``_sparse_exec`` — a second step
    # program whose jaxpr depends only on the workspace capacities and
    # the engine's structural shapes.  All indices ride as runtime
    # args, so sparse programs share executables through PROGRAMS
    # exactly like dense ones.  Selection mirrors the dense step's
    # gating semantics exactly (same masks, same granularity, same
    # intra-step read/write order), so an adaptive run is
    # byte-identical PER ROUND to a dense-only run — the property
    # tests/test_sparse_tail.py pins.

    _SPARSE_DEFAULTS = {
        "enable": True,
        "density_threshold": 0.05,
        "capacity_buckets": 8,
        "hysteresis_rounds": 2,
        "capacity_floor": 64,
    }

    @classmethod
    def _normalize_sparse_cfg(cls, raw) -> Optional[dict]:
        if not raw:
            return None
        cfg = dict(cls._SPARSE_DEFAULTS)
        if raw is not True:
            unknown = set(raw) - set(cfg)
            if unknown:
                raise ValueError(
                    f"unknown sparse_tail keys: {sorted(unknown)}"
                )
            cfg.update(raw)
        if not cfg["enable"]:
            return None
        # reject degenerate values at load, not rounds deep into a run:
        # capacity_buckets < 1 would shift by a negative count in
        # _sparse_rung, capacity_floor < 1 breaks the rung ladder, and
        # hysteresis < 1 silently means "always eligible" — the
        # controller would pick the sparse tier regardless of density
        if int(cfg["capacity_buckets"]) < 1 or int(cfg["capacity_floor"]) < 1:
            raise ValueError(
                "sparse_tail capacity_buckets and capacity_floor must "
                f"be >= 1 (got {cfg['capacity_buckets']!r}, "
                f"{cfg['capacity_floor']!r})"
            )
        if int(cfg["hysteresis_rounds"]) < 1:
            raise ValueError(
                "sparse_tail hysteresis_rounds must be >= 1 "
                f"(got {cfg['hysteresis_rounds']!r})"
            )
        return cfg

    _CR6_TILE_DEFAULTS = dict(_CR6_TILE_DEFAULTS)

    @classmethod
    def _normalize_cr6_tiles_cfg(cls, raw) -> Optional[dict]:
        """Resolved live-tile CR6 config (None = window formulation).
        Degenerate tile shapes are rejected at construction, not rounds
        into a run: a sub-8-row or sub-32-slot tile would break the
        packed contraction's alignment assumptions silently."""
        if not raw:
            return None
        cfg = dict(cls._CR6_TILE_DEFAULTS)
        if raw is not True:
            unknown = set(raw) - set(cfg)
            if unknown:
                raise ValueError(
                    f"unknown cr6_tiles keys: {sorted(unknown)}"
                )
            cfg.update(raw)
        if not cfg["enable"]:
            return None
        cfg["tile_m"] = int(cfg["tile_m"])
        cfg["tile_l"] = int(cfg["tile_l"])
        if cfg["tile_m"] < 8 or cfg["tile_l"] < 32:
            raise ValueError(
                "cr6_tiles tile_m must be >= 8 and tile_l >= 32 "
                f"(got {cfg['tile_m']!r}, {cfg['tile_l']!r})"
            )
        if not (0.0 < float(cfg["density_threshold"])):
            raise ValueError(
                "cr6_tiles density_threshold must be > 0 "
                f"(got {cfg['density_threshold']!r})"
            )
        return cfg

    def _t6_device_slabs(self):
        """Device copies of the live-tile slab arrays — the CR6 tile
        content of the runtime-argument pytree (empty when the window
        formulation is active)."""
        t = self._tiles6
        if t is None:
            return ()
        return (
            jnp.asarray(t.rows),
            jnp.asarray(t.mrows),
            jnp.asarray(t.fdx),
            jnp.asarray(t.tids),
            jnp.asarray(t.tval),
        )

    _PIPELINE_DEFAULTS = {"enable": True, "depth": 2}

    @classmethod
    def _normalize_pipeline_cfg(cls, raw) -> dict:
        """Resolved pipelined-observation config.  Unlike
        ``sparse_tail`` (where None means off), None means the
        DEFAULTS — pipelining replays the synchronous loop's rounds
        byte-for-byte with only the host fetch deferred, so it is safe
        on by default.  ``False`` / ``{"enable": False}`` / depth 1
        restore the strictly synchronous loop."""
        cfg = dict(cls._PIPELINE_DEFAULTS)
        if raw is None or raw is True:
            return cfg
        if raw is False:
            cfg["enable"] = False
            return cfg
        unknown = set(raw) - set(cfg)
        if unknown:
            raise ValueError(f"unknown pipeline keys: {sorted(unknown)}")
        cfg.update(raw)
        if int(cfg["depth"]) < 1:
            raise ValueError(
                f"pipeline depth must be >= 1 (got {cfg['depth']!r})"
            )
        cfg["depth"] = int(cfg["depth"])
        cfg["enable"] = bool(cfg["enable"])
        return cfg

    _FUSED_DEFAULTS = {"enable": True, "rounds": 1, "adaptive": False}

    @classmethod
    def _normalize_fused_cfg(cls, raw) -> Optional[dict]:
        """Resolved device-resident fused-rounds config (ISSUE 17).
        ``rounds`` (K) is the surfacing period: the observed controller
        runs up to K saturation rounds inside ONE device dispatch — a
        ``lax.while_loop`` with the tier decision on device — and
        surfaces to the host only at window edges.  ``None``/``True``
        mean the DEFAULTS (K=1: the per-round controllers run
        untouched, so the posture is behavior-preserving by default);
        K>1 turns the fused window path on wherever the adaptive
        controller would run.  Returns None when disabled."""
        if raw is None or raw is True:
            return dict(cls._FUSED_DEFAULTS)
        if raw is False:
            return None
        cfg = dict(cls._FUSED_DEFAULTS)
        unknown = set(raw) - set(cfg)
        if unknown:
            raise ValueError(f"unknown fused_rounds keys: {sorted(unknown)}")
        cfg.update(raw)
        if not cfg["enable"]:
            return None
        if int(cfg["rounds"]) < 1:
            raise ValueError(
                f"fused_rounds rounds must be >= 1 (got {cfg['rounds']!r})"
            )
        cfg["rounds"] = int(cfg["rounds"])
        cfg["adaptive"] = bool(cfg["adaptive"])
        return cfg

    @staticmethod
    def _fused_k_ladder(K: int, adaptive: bool) -> list:
        """The window sizes this config can dispatch: just K, or — with
        the K-adaptive terminal window on — the halving ladder K, K/2,
        ..., 2 (each rung is its own registry program; the precompile
        roster and the artifact farm warm them all)."""
        ks = [int(K)]
        if adaptive:
            k = int(K)
            while k > 2:
                k //= 2
                ks.append(k)
        return ks

    def _fused_eligible(self) -> bool:
        """Whether this engine's config actually routes the fused
        multi-round tier (K > 1 configured AND the adaptive sparse-tail
        machinery the on-device round decision is built from is both
        configured and supported) — the precompile roster warms the
        fused window program only then."""
        return bool(
            self._fused_cfg
            and self._fused_cfg["rounds"] > 1
            and self._sparse_cfg is not None
            and self._sparse_supported()
        )

    def _sparse_supported(self) -> bool:
        """The tier's support matrix: CR4/CR6 — when present — must be
        in the scanned-chunk formulation (the sparse program rides
        their slabs; bucket mode always scans).  Mesh engines are
        supported: the sparse program builds inside the same shard_map
        structure as the dense step (see :meth:`_sparse_aot`), so the
        adaptive controller — including pipelined dense dispatch —
        drives single-device and sharded engines identically."""
        if (self._has4 or self._has6) and not self._scan_mode:
            return False
        return True

    @staticmethod
    def _sparse_rung(cfg: dict, n: int, floor: int) -> Optional[int]:
        """Smallest workspace rung >= ``n`` on the power-of-two family
        of the program-cache ladder (``bucket_dim``, ratio 2), or None
        when ``n`` overflows the largest of the ``capacity_buckets``
        configured rungs — the caller then falls back to the dense
        step for the round."""
        rung = bucket_dim(max(int(n), 1), 2.0, floor=floor)
        if rung > floor << (int(cfg["capacity_buckets"]) - 1):
            return None
        return rung

    def _sparse_round_plan(self, cfg, s_chg, dirty_l, any_r):
        """Host-side measure + active-set selection for one round.
        Returns ``(rows_touched, density, measure, overflow)``;
        ``measure`` holds the selected row sets + workspace key and is
        None on workspace overflow (``overflow`` True) — the round then
        runs dense, never dropping work.  The controller turns a
        measure into program arguments with :meth:`_sparse_round_args`
        only once it actually picks the sparse tier (dense rounds pay
        just the selection fold, not the workspace padding).

        Selection replicates the dense step's gating EXACTLY, extended
        with its intra-step cascade: CR1 selects on the previous
        round's changed-S mask (dense CR1 reads pre-step S); CR2 also
        covers readers of active CR1 targets (dense CR2 reads S after
        CR1's writes — potential targets whose write turns out clean
        contribute nothing new under monotone OR); CR3 covers CR1/CR2
        targets likewise.  CR4/CR6 select at ROW granularity: a row is
        active iff its bit-table source row changed (CR4: the S row
        ``a4[j]``; CR6: the chunk of R row ``l2[p]``) or its factored
        mask covers a role present in a dirty L-chunk — rows outside
        that set provably contribute nothing new even in the dense
        step (their operand inputs are unchanged), so per-round
        derivation counts stay byte-identical to a dense-only run
        while the tail's cost tracks the true frontier, not the dense
        chunk grid."""
        nf1, nf2, nf3 = self._sp_nf1, self._sp_nf2, self._sp_nf3
        empty = np.zeros(0, np.int64)
        act1 = np.flatnonzero(s_chg[nf1[:, 0]]) if len(nf1) else empty
        s1 = s_chg
        if act1.size:
            s1 = s_chg.copy()
            s1[nf1[act1, 1]] = True
        act2 = (
            np.flatnonzero(s1[nf2[:, 0]] | s1[nf2[:, 1]])
            if len(nf2)
            else empty
        )
        s2 = s1
        if act2.size:
            s2 = s1.copy() if s1 is s_chg else s1
            s2[nf2[act2, 2]] = True
        act3 = np.flatnonzero(s2[nf3[:, 0]]) if len(nf3) else empty

        # dirty chunks -> dirty roles: the role-granular over-
        # approximation of "some link this row's mask covers changed"
        dirty_roles = self._chunk_roles_np[dirty_l].any(axis=0)

        def row_act(d, mask_tab, mask_any, fd_rows):
            """Active CR4/CR6 rows: source changed (``fd_rows``) or
            mask covers a dirty role; rows of dropped spans (slab
            position -1) and of chunks with no live windows are inert
            in the compiled program and excluded."""
            if np.array_equal(dirty_roles, self._max_dirty_roles):
                # all roles dirty (early rounds): the precomputed
                # per-row activity, no table-sized temporary
                masked = mask_any[: len(fd_rows)]
            else:
                masked = (
                    mask_tab[: len(fd_rows)] & dirty_roles
                ).any(axis=1)
            act = fd_rows | masked
            pos = d["pos_of_row"][: len(fd_rows)]
            has_win = d["tval_np"].any(axis=1)
            ok = (pos >= 0) & has_win[np.clip(pos, 0, None) // d["rk"]]
            return np.flatnonzero(act & ok)

        act4 = act6 = empty
        fd4 = fd6 = None
        if self._scan4 is not None:
            fd4 = s_chg[self._a4]
            act4 = row_act(self._scan4, self._m4_full, self._m4_any, fd4)
        if self._scan6 is not None:
            fd6 = dirty_l[self._l26 // self.lc]
            act6 = row_act(self._scan6, self._m6_full, self._m6_any, fd6)
        run5 = bool(self._bottom and (any_r or s_chg[BOTTOM_ID]))
        rows_touched = int(
            act1.size + act2.size + act3.size + act4.size + act6.size
            + (1 if run5 else 0)
        )
        density = rows_touched / max(self._sp_total_rows, 1)
        floor = cfg["capacity_floor"]
        c123 = self._sparse_rung(
            cfg, max(act1.size, act2.size, act3.size), floor
        )
        a4 = self._sparse_rung(cfg, act4.size, floor) if act4.size else 0
        a6 = self._sparse_rung(cfg, act6.size, floor) if act6.size else 0
        if c123 is None or a4 is None or a6 is None:
            return rows_touched, density, None, True
        measure = {
            "act1": act1, "act2": act2, "act3": act3,
            "act4": act4, "act6": act6, "fd4": fd4, "fd6": fd6,
            "run5": run5, "key": (c123, a4, a6),
        }
        return rows_touched, density, measure, False

    def _sparse_round_args(self, measure, dirty_l):
        """Compact one round's selected row sets (a
        :meth:`_sparse_round_plan` measure) into the padded workspace
        arrays of the sparse program — called only on rounds the
        controller actually runs sparse."""
        nf1, nf2, nf3 = self._sp_nf1, self._sp_nf2, self._sp_nf3
        empty = np.zeros(0, np.int64)
        act1, act2, act3 = (
            measure["act1"], measure["act2"], measure["act3"],
        )
        c123, a4, a6 = measure["key"]

        def pad_idx(a, n, fill=0):
            out = np.full(n, fill, np.int32)
            out[: len(a)] = a
            return out

        def val_mask(k, n):
            v = np.zeros(n, np.uint32)
            v[:k] = 0xFFFFFFFF
            return v

        args = {
            "src1": pad_idx(nf1[act1, 0] if act1.size else empty, c123),
            "tgt1": pad_idx(nf1[act1, 1] if act1.size else empty, c123),
            "val1": val_mask(act1.size, c123),
            "src2a": pad_idx(nf2[act2, 0] if act2.size else empty, c123),
            "src2b": pad_idx(nf2[act2, 1] if act2.size else empty, c123),
            "tgt2": pad_idx(nf2[act2, 2] if act2.size else empty, c123),
            "val2": val_mask(act2.size, c123),
            "src3": pad_idx(nf3[act3, 0] if act3.size else empty, c123),
            "tgt3": pad_idx(nf3[act3, 1] if act3.size else empty, c123),
            "val3": val_mask(act3.size, c123),
            "dirty_l": np.asarray(dirty_l, bool),
        }
        if self._bottom:
            args["run5"] = np.bool_(measure["run5"])

        def row_args(d, act, fd_rows, prefix, a):
            g_of = d.get("g_of")
            if g_of is None:
                g_of = np.zeros(d["nch"], np.int32)
                for gi, (g0, g1, _p, _r) in enumerate(d["groups"]):
                    g_of[g0:g1] = gi
                d["g_of"] = g_of
            pos = d["pos_of_row"][act]
            args["sel" + prefix] = pad_idx(pos, a)
            fdp = np.zeros(a, bool)
            fdp[: act.size] = fd_rows[act]
            args["fd" + prefix] = fdp
            # pad slots park on position 0 with wave -1: no group pass
            # matches, so their operands zero out and writes are no-ops
            args["wave" + prefix] = pad_idx(
                g_of[(pos // d["rk"]).astype(np.int64)], a, fill=-1
            )

        if a4:
            row_args(self._scan4, measure["act4"], measure["fd4"], "4", a4)
        if a6:
            row_args(self._scan6, measure["act6"], measure["fd6"], "6", a6)
        return {"args": args, "key": measure["key"]}

    def _sparse_consts(self) -> dict:
        """Device-resident sparse-program arguments that are stable
        across rounds (built once; slab leaves are read live because
        ``rebind_role_closure`` swaps them)."""
        c = self._sparse_const_cache
        if c is None:
            c = {
                "wmask": jnp.asarray(self._wmask),
                "fills": jnp.asarray(self._fillers.astype(np.int32)),
                "lroles": jnp.asarray(self._link_roles),
            }
            if self._scan4 is not None:
                c["tgt4_flat"] = jnp.asarray(
                    self._scan4["tgt_rows_np"].reshape(-1).astype(np.int32)
                )
            if self._scan6 is not None:
                c["tgt6_flat"] = jnp.asarray(
                    self._scan6["tgt_rows_np"].reshape(-1).astype(np.int32)
                )
            self._sparse_const_cache = c
        return c

    def _sparse_args(self, plan: dict) -> dict:
        sa = dict(plan["args"])
        c = self._sparse_consts()
        sa["wmask"], sa["fills"], sa["lroles"] = (
            c["wmask"], c["fills"], c["lroles"],
        )
        if "sel4" in sa:
            sa["tgt4_flat"] = c["tgt4_flat"]
            sa["slabs4"] = self._scan4["slabs"]
        if "sel6" in sa:
            sa["tgt6_flat"] = c["tgt6_flat"]
            sa["slabs6"] = self._scan6["slabs"]
        return sa

    def _sparse_mm_plan(self, lcn: int):
        """Single-row matmul plan of the sparse tier's per-row
        contraction (cf. ``scan_mm`` in ``__init__`` — same kwargs,
        rk=1)."""
        plan = self._sparse_mm.get(lcn)
        if plan is None:
            kw2 = dict(self._mm_kw)
            if kw2.get("use_xla") and "tm" not in kw2:
                kw2["tm"] = 8
            plan = PackedColsMatmulPlan(
                1, lcn, self.wc // self.n_shards, **kw2
            )
            self._sparse_mm[lcn] = plan
        return plan

    def _sparse_avals(self, c123: int, a4: int, a6: int) -> dict:
        def av(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype)

        sa = {
            "wmask": av((self.wc,), jnp.uint32),
            "fills": av((self.nl,), jnp.int32),
            "lroles": av((self.nl,), jnp.int32),
            "dirty_l": av((self.n_lchunks,), jnp.bool_),
        }
        for k in ("src1", "tgt1", "src2a", "src2b", "tgt2", "src3",
                  "tgt3"):
            sa[k] = av((c123,), jnp.int32)
        for k in ("val1", "val2", "val3"):
            sa[k] = av((c123,), jnp.uint32)
        if self._bottom:
            sa["run5"] = av((), jnp.bool_)
        for a, d, prefix in (
            (a4, self._scan4, "4"), (a6, self._scan6, "6"),
        ):
            if not a or d is None:
                continue
            sa["sel" + prefix] = av((a,), jnp.int32)
            sa["fd" + prefix] = av((a,), jnp.bool_)
            sa["wave" + prefix] = av((a,), jnp.int32)
            sa["tgt" + prefix + "_flat"] = av(
                (d["nch"] * d["rk"],), jnp.int32
            )
            sa["slabs" + prefix] = jax.tree_util.tree_map(
                lambda x: av(np.shape(x), jnp.asarray(x).dtype),
                d["slabs"],
            )
        return sa

    def _sparse_exec(self, sp, rp, sa, axis_name=None):
        """One frontier-compacted superstep — the sparse tier's traced
        program.  Rule order and intra-step read/write structure mirror
        :meth:`_step` verbatim (CR1 → CR2 → CR3 → CR4 groups in dense
        group order → CR6 groups → CR5, each reading exactly the state
        its dense counterpart reads), which is what makes an adaptive
        run byte-identical per round to a dense-only run.  Every
        ontology-derived value arrives in ``sa`` — compacted active-row
        indices + validity masks, selected chunk ids over the scanned
        slabs, the shared filler/link-role tables — so the jaxpr is a
        pure function of the workspace capacities and the engine's
        structural shapes (bucket mode shares executables across
        same-bucket ontologies through PROGRAMS).  Pad workspace slots
        carry ``val=0`` / wave ``-1`` and reduce to OR-identity no-op
        writes.  Returns ``(sp, rp, changed, delta_bits, mask_s,
        any_r, dirty_l_next)`` — the frontier fold the host controller
        carries into the next round; ``delta_bits`` counts new
        live-column bits so tail rounds skip the full live-bits sweep.

        With ``axis_name`` the body runs inside the mesh engines'
        shard_map structure (see :meth:`_sparse_aot`): state arrives as
        the shard-local word window, the compacted row gathers/writes
        stay shard-local (row indices address every shard's full row
        axis), the CR4/CR6/CR5 bit-table lookups use the dense step's
        masked-local-extract + ``psum`` exchange, and the round's
        frontier fold (changed vote, delta popcount, changed-row masks)
        is ``psum``-folded ONCE at the end — the per-round analog of
        the fixed point's AND-vote, so the host controller reads one
        replicated fold regardless of mesh size."""
        width = sp.shape[1]
        wmask = sa["wmask"]
        base = None
        if axis_name is not None:
            # shard-local views: `base` is this shard's word offset
            # (all column/word bookkeeping below is in word units),
            # `wmask` narrows to the local window so the delta popcount
            # counts each live bit on exactly one shard
            base = self._shard_word_base(axis_name)
            wmask = lax.dynamic_slice(wmask, (base,), (width,))
        dt = self.matmul_dtype
        delta = jnp.asarray(0, jnp.int32)
        changed = jnp.asarray(False)
        mask_s = jnp.zeros(self.nc, bool)
        mask_r = jnp.zeros(self.nl, bool)

        def write_seq(state, mask_vec, tgts, contribs, delta, changed):
            """Sequential OR-writes of ``contribs`` [n, width] into
            ``state`` rows ``tgts`` with per-write change tracking —
            the sparse analog of the dense seg-OR write.  Sequencing
            makes duplicate targets exact under OR, and n is workspace-
            bounded, so the per-row scatter cost the dense engine
            avoids stays microseconds here."""

            def body(i, car):
                st, mv, d, ch = car
                t = tgts[i]
                old = st[t]
                gained = contribs[i] & ~old
                chg = jnp.any(gained != 0)
                st = st.at[t].set(old | contribs[i])
                mv = mv.at[t].set(mv[t] | chg)
                d = d + jnp.sum(
                    lax.population_count(gained & wmask),
                    dtype=jnp.int32,
                )
                return st, mv, d, ch | chg

            return lax.fori_loop(
                0, tgts.shape[0], body,
                (state, mask_vec, delta, changed),
            )

        # CR1/CR2/CR3 over the compacted row workspace; gathers happen
        # before each rule's writes and after the previous rule's —
        # the dense block sweep's effective read/write order
        if len(self._sp_nf1):
            contrib = sp[sa["src1"]] & sa["val1"][:, None]
            sp, mask_s, delta, changed = write_seq(
                sp, mask_s, sa["tgt1"], contrib, delta, changed
            )
        if len(self._sp_nf2):
            contrib = (sp[sa["src2a"]] & sp[sa["src2b"]]) \
                & sa["val2"][:, None]
            sp, mask_s, delta, changed = write_seq(
                sp, mask_s, sa["tgt2"], contrib, delta, changed
            )
        if len(self._sp_nf3):
            contrib = sp[sa["src3"]] & sa["val3"][:, None]
            rp, mask_r, delta, changed = write_seq(
                rp, mask_r, sa["tgt3"], contrib, delta, changed
            )

        dl = sa["dirty_l"]

        def scan_sel(d, slabs, sel, fd, wave, mm, src_state, rp_state,
                     gi):
            """Contract the SELECTED rows of one rule at single-row
            shapes over their chunks' window tables, one group pass:
            rows outside group ``gi`` (and pad slots, wave -1) zero out
            via the live multiplier, preserving the dense
            group-sequential cascade.  ``sel`` holds flat slab
            positions (chunk*rk + offset); ``live`` is the dense
            formula with fd at ROW granularity — a strict refinement
            of the chunk flag that derives the identical new facts."""
            rows_s, _fdx_s, m_s, offs_s, c01_s, tval_s = slabs
            rk, T, lcn = d["rk"], d["T"], d["lcn"]
            ch_of = sel // rk
            xs = (
                rows_s.reshape(-1)[sel],
                m_s.reshape(-1, m_s.shape[-1])[sel],
                offs_s[ch_of], c01_s[ch_of], tval_s[ch_of],
                fd, wave,
            )

            def one_row(_, xs):
                row_k, m_k, offs_k, c01_k, tval_k, fd_k, w_k = xs
                subt = src_state[row_k][:, None]      # [width, 1]

                def one(i, acc):
                    live = (
                        (w_k == gi)
                        & tval_k[i]
                        & (dl[c01_k[i, 0]] | dl[c01_k[i, 1]] | fd_k)
                    )
                    return acc | _window_term(
                        subt, rp_state, sa["fills"], sa["lroles"],
                        offs_k[i], live, m_k[None], mm, lcn, dt,
                        width, axis_name, base,
                    )

                z = jnp.zeros((1, width), jnp.uint32)
                # T is the STATIC window-slot count; 0 = all-dead slab
                # (see scan_contract) — contribute nothing, and never
                # trace `one` against the empty window tables
                acc = (
                    z
                    if T == 0
                    else one(0, z)
                    if T == 1
                    else lax.fori_loop(0, T, one, z)
                )
                return (), acc[0]

            _, ys = lax.scan(one_row, (), xs)
            return ys

        if "sel4" in sa:
            d4 = self._scan4
            mm4 = self._sparse_mm_plan(d4["lcn"])
            tg4 = sa["tgt4_flat"][sa["sel4"]]
            with jax.named_scope("cr4"):
                for gi in range(len(d4["groups"])):
                    contrib = scan_sel(
                        d4, sa["slabs4"], sa["sel4"], sa["fd4"],
                        sa["wave4"], mm4, sp, rp, gi,
                    )
                    sp, mask_s, delta, changed = write_seq(
                        sp, mask_s, tg4, contrib, delta, changed
                    )
        if "sel6" in sa:
            d6 = self._scan6
            mm6 = self._sparse_mm_plan(d6["lcn"])
            tg6 = sa["tgt6_flat"][sa["sel6"]]
            with jax.named_scope("cr6"):
                for gi in range(len(d6["groups"])):
                    contrib = scan_sel(
                        d6, sa["slabs6"], sa["sel6"], sa["fd6"],
                        sa["wave6"], mm6, rp, rp, gi,
                    )
                    rp, mask_r, delta, changed = write_seq(
                        rp, mask_r, tg6, contrib, delta, changed
                    )

        if self._bottom:

            def red5(ops):
                s, r = ops
                # same masked-local-extract + psum exchange as the
                # dense step's ⊥-filler mask (see _bit_table)
                botf = self._bit_table(
                    s, np.full(1, BOTTOM_ID), axis_name, cols=sa["fills"]
                )
                bmask = botf[:, 0].astype(bool)
                masked = jnp.where(
                    bmask[:, None], r, jnp.asarray(0, jnp.uint32)
                )
                return lax.reduce(
                    masked, np.uint32(0), lax.bitwise_or, (0,)
                )[None]

            with jax.named_scope("cr5"):
                red = lax.cond(
                    sa["run5"],
                    red5,
                    lambda _ops: jnp.zeros((1, width), jnp.uint32),
                    (sp, rp),
                )
                old5 = sp[BOTTOM_ID]
                gained = red[0] & ~old5
                chg = jnp.any(gained != 0)
                sp = sp.at[BOTTOM_ID].set(old5 | red[0])
                mask_s = mask_s.at[BOTTOM_ID].set(
                    mask_s[BOTTOM_ID] | chg
                )
                delta = delta + jnp.sum(
                    lax.population_count(gained & wmask),
                    dtype=jnp.int32,
                )
                changed = changed | chg

        with jax.named_scope("frontier"):
            if axis_name is not None:
                # ONE per-round exchange folds every shard's view of
                # the frontier (a row's new bits may land on a single
                # shard's word window): the changed vote, the delta
                # popcount partials, and the changed-row masks leave
                # replicated — the sharded analog of the reference's
                # per-iteration barrier read, paid once per round
                # instead of once per rule
                changed = lax.psum(changed.astype(jnp.int32), axis_name) > 0
                delta = lax.psum(delta, axis_name)
                mask_s = lax.psum(mask_s.astype(jnp.int32), axis_name) > 0
                mask_r = lax.psum(mask_r.astype(jnp.int32), axis_name) > 0
            any_r = jnp.any(mask_r)
            dirty_l_next = mask_r.reshape(
                self.n_lchunks, self.lc
            ).any(axis=1)
        return sp, rp, changed, delta, mask_s, any_r, dirty_l_next

    def _sparse_aot(self, c123: int, a4: int, a6: int):
        """Compiled sparse-step executable for one workspace-capacity
        triple — same registry/caching story as :meth:`_run_aot`: in
        bucket mode same-bucket engines share the executable through
        PROGRAMS (capacities ride in the key), and the XLA compile of
        the byte-identical HLO is normally a persistent-cache hit."""
        key = (c123, a4, a6)
        exe = self._aot_sparse.get(key)
        if exe is not None:
            return exe
        stats = CompileStats(
            bucket_signature=self.bucket_signature,
            program=f"sparse[{c123},{a4},{a6}]",
        )
        sp_av = jax.ShapeDtypeStruct((self.nc, self.wc), jnp.uint32)
        rp_av = jax.ShapeDtypeStruct((self.nl, self.wc), jnp.uint32)
        sa_av = self._sparse_avals(c123, a4, a6)
        if self.mesh is None:
            fn = jax.jit(self._sparse_exec, donate_argnums=_state_donation())
        else:
            # the mesh variant runs the SAME body inside the same
            # shard_map structure as the dense step: state sharded on
            # the packed word axis, the compacted workspace arguments
            # replicated (they are row indices + tiny masks — byte-
            # scale next to the state), and every output replicated by
            # the body's end-of-round psum fold, so the host controller
            # is mesh-agnostic (out_specs P() hand it the same scalars/
            # masks the single-device program returns)
            P = jax.sharding.PartitionSpec
            axis = self.word_axis
            state = P(None, axis)
            fn = jax.jit(
                shard_map(
                    functools.partial(self._sparse_exec, axis_name=axis),
                    mesh=self.mesh,
                    in_specs=(
                        state, state, jax.tree.map(lambda _: P(), sa_av)
                    ),
                    out_specs=(state, state, P(), P(), P(), P(), P()),
                    check_vma=False,
                ),
                donate_argnums=_state_donation(),
            )

        def build():
            t0 = time.perf_counter()
            lowered = fn.lower(sp_av, rp_av, sa_av)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            stats.trace_lower_s = t1 - t0
            stats.compile_s = time.perf_counter() - t1
            return compiled

        with compile_watch(stats):
            if self._bucket:
                exe, hit = PROGRAMS.get_or_build(
                    (self.bucket_signature, "sparse", key), build
                )
                stats.program_cache_hit = hit
            else:
                exe = build()
        self._aot_sparse[key] = exe
        self._sparse_builds.append(stats)
        self._note_compile(stats)
        return exe

    # ------------------------------------ fused multi-round tier (ISSUE 17)
    #
    # The per-round controllers pay a host dispatch + frontier fold per
    # retired round — the analog of the reference's per-iteration Redis
    # barrier (``controller/CommunicationHandler.java:78-83``).  The
    # fused tier moves the ROUND LOOP itself onto the device: one
    # ``lax.while_loop`` runs up to K rounds per dispatch, re-deriving
    # the adaptive controller's per-round decision (frontier measure,
    # density/hysteresis, dense-vs-sparse tier pick, convergence vote)
    # from device-resident copies of the same carries, and surfaces to
    # the host only at window edges.  Every decision input rides as a
    # runtime argument (the device analog of ``_sparse_round_plan``'s
    # host tables), so the fused program is a pure function of
    # ``bucket_signature`` + K + the traced sparse workspace capacities
    # and shares executables through PROGRAMS like every other program.
    # A round whose frontier overflows the traced capacity rung EXITS
    # the window before running (status 2) and replays on the host path
    # — fused runs stay byte-identical per retired round to the
    # synchronous adaptive controller, the property
    # tests/test_fused_rounds.py pins.

    def _fused_below_cutoff(self, thr: float) -> int:
        """Largest ``rows_touched`` for which the HOST controller's f64
        test ``rows / max(total_rows, 1) < thr`` holds — the exact
        integer form of the density test the fused program evaluates on
        device (an on-device f32 division could disagree with the host
        at the threshold boundary, silently desyncing hysteresis)."""
        total = max(self._sp_total_rows, 1)
        start = int(np.floor(float(thr) * total)) + 2
        for cand in range(start, -1, -1):
            if cand / total < thr:
                return cand
        return -1

    def _fused_tables(self) -> dict:
        """Device-resident plan tables of the on-device round decision —
        the runtime-argument analog of :meth:`_sparse_round_plan`'s host
        arrays (rule tables, factored role masks, slab positions,
        live-window validity).  Cached per engine;
        ``rebind_role_closure`` drops the cache."""
        fa = self._fused_tab_cache
        if fa is not None:
            return fa
        i32 = np.int32
        nf1, nf2, nf3 = self._sp_nf1, self._sp_nf2, self._sp_nf3
        c = self._sparse_consts()
        fa = {
            "croles": jnp.asarray(self._chunk_roles_np),
            "wmask": c["wmask"],
            "fills": c["fills"],
            "lroles": c["lroles"],
        }
        if len(nf1):
            fa["nf1s"] = jnp.asarray(nf1[:, 0].astype(i32))
            fa["nf1t"] = jnp.asarray(nf1[:, 1].astype(i32))
        if len(nf2):
            fa["nf2a"] = jnp.asarray(nf2[:, 0].astype(i32))
            fa["nf2b"] = jnp.asarray(nf2[:, 1].astype(i32))
            fa["nf2t"] = jnp.asarray(nf2[:, 2].astype(i32))
        if len(nf3):
            fa["nf3s"] = jnp.asarray(nf3[:, 0].astype(i32))
            fa["nf3t"] = jnp.asarray(nf3[:, 1].astype(i32))

        def row_tables(d, rows_src, mask_tab, prefix):
            n = len(rows_src)
            g_of = d.get("g_of")
            if g_of is None:
                g_of = np.zeros(d["nch"], np.int32)
                for gi, (g0, g1, _p, _r) in enumerate(d["groups"]):
                    g_of[g0:g1] = gi
                d["g_of"] = g_of
            fa["src" + prefix] = jnp.asarray(rows_src.astype(i32))
            fa["m" + prefix] = jnp.asarray(mask_tab[:n])
            fa["pos" + prefix] = jnp.asarray(
                d["pos_of_row"][:n].astype(i32)
            )
            fa["gof" + prefix] = jnp.asarray(g_of)
            # live-window validity per chunk — derived from tval_np,
            # which rebind_role_closure refreshes (hence the cache drop)
            fa["hw" + prefix] = jnp.asarray(d["tval_np"].any(axis=1))
            fa["tgt" + prefix + "_flat"] = c["tgt" + prefix + "_flat"]

        if self._scan4 is not None:
            row_tables(self._scan4, np.asarray(self._a4), self._m4_full, "4")
        if self._scan6 is not None:
            row_tables(
                self._scan6,
                np.asarray(self._l26 // self.lc),
                self._m6_full,
                "6",
            )
        self._fused_tab_cache = fa
        return fa

    def _fused_run_args(self, cfg: dict, budget: int) -> dict:
        """One run's full fused-program argument pytree: the cached
        plan tables + this run's controller scalars + the engine's
        dense-step argument pytree (slab leaves read live, so a rebind
        between runs is picked up)."""
        fa = dict(self._fused_tables())
        if self._scan4 is not None:
            fa["slabs4"] = self._scan4["slabs"]
        if self._scan6 is not None:
            fa["slabs6"] = self._scan6["slabs"]
        fa["mk"] = self._masks
        fa["below_cut"] = jnp.asarray(
            self._fused_below_cutoff(cfg["density_threshold"]), jnp.int32
        )
        fa["hyst"] = jnp.asarray(int(cfg["hysteresis_rounds"]), jnp.int32)
        fa["budget"] = jnp.asarray(int(budget), jnp.int32)
        return fa

    def _fused_round_plan_dev(self, sc, dl, fa):
        """Device replica of :meth:`_sparse_round_plan`'s measure —
        per-rule activity masks + counts over the full rule tables, no
        compaction.  Must derive the IDENTICAL active sets the host
        fold derives from the same carries: the per-round tier choice,
        hysteresis and rows_touched records all hang off it."""
        i32 = jnp.int32
        nf1, nf2, nf3 = self._sp_nf1, self._sp_nf2, self._sp_nf3
        zero = jnp.asarray(0, i32)
        plan = {"n1": zero, "n2": zero, "n3": zero, "n4": zero, "n6": zero}

        def scatter_or(base, tgts, act):
            hit = (
                jnp.zeros(self.nc, i32).at[tgts].max(act.astype(i32)) > 0
            )
            return base | hit

        s1 = sc
        if len(nf1):
            act1 = sc[fa["nf1s"]]
            plan["act1"] = act1
            plan["n1"] = jnp.sum(act1, dtype=i32)
            s1 = scatter_or(sc, fa["nf1t"], act1)
        s2 = s1
        if len(nf2):
            act2 = s1[fa["nf2a"]] | s1[fa["nf2b"]]
            plan["act2"] = act2
            plan["n2"] = jnp.sum(act2, dtype=i32)
            s2 = scatter_or(s1, fa["nf2t"], act2)
        if len(nf3):
            act3 = s2[fa["nf3s"]]
            plan["act3"] = act3
            plan["n3"] = jnp.sum(act3, dtype=i32)

        dirty_roles = jnp.any(fa["croles"] & dl[:, None], axis=0)

        def row_act(d, prefix, fd):
            masked = jnp.any(fa["m" + prefix] & dirty_roles[None, :], axis=1)
            pos = fa["pos" + prefix]
            ok = (pos >= 0) & fa["hw" + prefix][
                jnp.maximum(pos, 0) // d["rk"]
            ]
            return (fd | masked) & ok

        if self._scan4 is not None:
            fd4 = sc[fa["src4"]]
            act4 = row_act(self._scan4, "4", fd4)
            plan["fd4"], plan["act4"] = fd4, act4
            plan["n4"] = jnp.sum(act4, dtype=i32)
        if self._scan6 is not None:
            fd6 = dl[fa["src6"]]
            act6 = row_act(self._scan6, "6", fd6)
            plan["fd6"], plan["act6"] = fd6, act6
            plan["n6"] = jnp.sum(act6, dtype=i32)
        any_r = jnp.any(dl)
        rows = plan["n1"] + plan["n2"] + plan["n3"] + plan["n4"] + plan["n6"]
        if self._bottom:
            run5 = any_r | sc[BOTTOM_ID]
            plan["run5"] = run5
            rows = rows + run5.astype(i32)
        plan["rows"] = rows
        return plan

    def _fused_sparse_args_dev(self, plan, dl, fa, caps):
        """Device compaction of one round's active sets into the padded
        sparse workspace — the traced analog of
        :meth:`_sparse_round_args` (``jnp.nonzero(..., size, fill=0)``
        matches ``np.flatnonzero``'s ascending order; pad slots carry
        the host path's exact fills: index 0, val 0, wave -1)."""
        c123, a4c, a6c = caps
        i32, u32 = jnp.int32, jnp.uint32
        full = jnp.asarray(0xFFFFFFFF, u32)

        def compact(mask, n, cap):
            idx = jnp.nonzero(mask, size=cap, fill_value=0)[0]
            return idx, jnp.arange(cap) < n

        def picked(tab, idx, valid, fill=0):
            return jnp.where(valid, tab[idx], fill).astype(i32)

        sa = {
            "wmask": fa["wmask"],
            "fills": fa["fills"],
            "lroles": fa["lroles"],
            "dirty_l": dl,
        }
        if len(self._sp_nf1):
            idx, v = compact(plan["act1"], plan["n1"], c123)
            sa["src1"] = picked(fa["nf1s"], idx, v)
            sa["tgt1"] = picked(fa["nf1t"], idx, v)
            sa["val1"] = jnp.where(v, full, jnp.asarray(0, u32))
        if len(self._sp_nf2):
            idx, v = compact(plan["act2"], plan["n2"], c123)
            sa["src2a"] = picked(fa["nf2a"], idx, v)
            sa["src2b"] = picked(fa["nf2b"], idx, v)
            sa["tgt2"] = picked(fa["nf2t"], idx, v)
            sa["val2"] = jnp.where(v, full, jnp.asarray(0, u32))
        if len(self._sp_nf3):
            idx, v = compact(plan["act3"], plan["n3"], c123)
            sa["src3"] = picked(fa["nf3s"], idx, v)
            sa["tgt3"] = picked(fa["nf3t"], idx, v)
            sa["val3"] = jnp.where(v, full, jnp.asarray(0, u32))
        if self._bottom:
            sa["run5"] = plan["run5"]

        def row_args(d, prefix, cap):
            idx, v = compact(plan["act" + prefix], plan["n" + prefix], cap)
            pos = picked(fa["pos" + prefix], idx, v)
            sa["sel" + prefix] = pos
            sa["fd" + prefix] = jnp.where(v, plan["fd" + prefix][idx], False)
            sa["wave" + prefix] = jnp.where(
                v, fa["gof" + prefix][pos // d["rk"]], -1
            ).astype(i32)
            sa["tgt" + prefix + "_flat"] = fa["tgt" + prefix + "_flat"]
            sa["slabs" + prefix] = fa["slabs" + prefix]

        if a4c and self._scan4 is not None:
            row_args(self._scan4, "4", a4c)
        if a6c and self._scan6 is not None:
            row_args(self._scan6, "6", a6c)
        return sa

    def _fused_exec(
        self, sp, rp, gate, dl, sc, below, it, fa, K, caps, axis_name=None,
    ):
        """Up to K rounds of the adaptive controller inside ONE traced
        program — ``lax.while_loop`` with the tier decision on device.
        Carries mirror the host controller exactly: the frontier
        3-tuple (gate flags, per-L-chunk dirty, changed-S mask), the
        hysteresis counter and the iteration cursor.  Per round the
        body re-derives the host decision (plan → density/hysteresis →
        idle / sparse / dense), executes the picked tier through the
        SAME traced bodies the per-round programs use (``_step`` /
        ``_sparse_exec``), and appends the round's telemetry to the
        window buffers.  Exit status: 0 = K rounds retired (or budget
        hit), 1 = converged, 2 = capacity fallout — the round's sparse
        frontier overflowed the traced workspace ``caps`` and DID NOT
        RUN; the host replays that one round on the per-round path and
        resumes windows, so no work is ever dropped or double-run.

        Under a mesh the body runs inside the engines' shard_map
        structure: per-round psum folds (the dense step's frontier
        fold, the sparse program's end-of-round fold) stay INSIDE the
        loop, so every carry the decision reads is replicated and only
        the window-edge fold reaches the host — K reference barriers
        collapse into one surfacing."""
        i32 = jnp.int32
        nbits = self.nc + self.nl
        width = sp.shape[1]
        below_cut, hyst, budget = fa["below_cut"], fa["hyst"], fa["budget"]
        mk = fa["mk"]
        sparse_on = bool(caps[0])
        gating = self._gate is not None

        def cond(carry):
            _sp, _rp, _g, _dl, _sc, _b, it_, rdone, status = carry[:9]
            return (status == 0) & (rdone < K) & (it_ < budget)

        def body(carry):
            (sp, rp, gate, dl, sc, below, it_, rdone, status,
             tb, rb, db, cb, bb) = carry
            plan = self._fused_round_plan_dev(sc, dl, fa)
            rows = plan["rows"]
            below_next = jnp.where(
                rows <= below_cut, below + 1, jnp.asarray(0, i32)
            )
            idle = rows == 0
            if sparse_on:
                want_sparse = (it_ > 0) & (below_next >= hyst)
                fits = (
                    jnp.maximum(
                        jnp.maximum(plan["n1"], plan["n2"]), plan["n3"]
                    )
                    <= caps[0]
                )
                if self._scan4 is not None:
                    fits = fits & (plan["n4"] <= caps[1])
                if self._scan6 is not None:
                    fits = fits & (plan["n6"] <= caps[2])
                use_sparse = want_sparse & fits & ~idle
                fallout = want_sparse & ~fits & ~idle
            else:
                use_sparse = fallout = jnp.asarray(False)

            ops = (sp, rp, gate, dl, sc)

            def run_dense(ops):
                sp, rp, gate, dl, sc = ops
                ch = jnp.asarray(False)
                dirty = (gate, dl, sc)
                for _ in range(self.unroll):
                    sp, rp, c, dirty = self._step(
                        sp, rp, mk, axis_name, dirty
                    )
                    ch |= c
                if axis_name is not None:
                    ch = lax.psum(ch.astype(i32), axis_name) > 0
                bits = self._live_bits(
                    sp, rp, axis_name,
                    wmask=mk["wmask"] if self._bucket else None,
                )
                gate, dl, sc = dirty
                return (
                    sp, rp, gate, dl, sc, ch, jnp.asarray(0, i32), bits
                )

            def run_noop(ops):
                sp, rp, gate, dl, sc = ops
                return (
                    sp, rp, gate, dl, sc, jnp.asarray(False),
                    jnp.asarray(0, i32), jnp.zeros(nbits, i32),
                )

            branches = [run_dense, run_noop]
            if sparse_on:

                def run_sparse(ops):
                    sp, rp, gate, dl, sc = ops
                    sa = self._fused_sparse_args_dev(plan, dl, fa, caps)
                    sp, rp, ch, delta, mask_s, any_r, dl2 = (
                        self._sparse_exec(sp, rp, sa, axis_name)
                    )
                    if gating:
                        gate = self._next_dirty(
                            mask_s, any_r, axis_name, mk
                        )
                    return (
                        sp, rp, gate, dl2, mask_s, ch, delta,
                        jnp.zeros(nbits, i32),
                    )

                branches = [run_dense, run_sparse, run_noop]
                bix = jnp.where(
                    idle | fallout, 2, jnp.where(use_sparse, 1, 0)
                )
            else:
                bix = jnp.where(idle, 1, 0).astype(i32)

            sp, rp, gate, dl, sc, ch, delta, bits = lax.switch(
                bix, branches, ops
            )
            noop_ix = len(branches) - 1
            tier = jnp.where(
                idle, 2, jnp.where(bix == noop_ix, 0, bix)
            ).astype(i32)
            keep = fallout  # the round did not run: record nothing

            def upd(buf, new):
                old_row = buf[rdone]
                return buf.at[rdone].set(jnp.where(keep, old_row, new))

            tb = upd(tb, tier)
            rb = upd(rb, rows)
            db = upd(db, delta)
            cb = upd(cb, ch)
            bb = upd(bb, bits)
            step_it = jnp.where(idle | use_sparse, 1, self.unroll)
            it_next = jnp.where(fallout, it_, it_ + step_it)
            rdone_next = jnp.where(fallout, rdone, rdone + 1)
            status_next = jnp.where(
                fallout, 2, jnp.where(ch, 0, 1)
            ).astype(i32)
            below_out = jnp.where(fallout, below, below_next)
            return (
                sp, rp, gate, dl, sc, below_out, it_next, rdone_next,
                status_next, tb, rb, db, cb, bb,
            )

        init = (
            sp, rp, gate, dl, sc, below.astype(i32), it.astype(i32),
            jnp.asarray(0, i32), jnp.asarray(0, i32),
            jnp.full(K, -1, i32), jnp.zeros(K, i32), jnp.zeros(K, i32),
            jnp.zeros(K, bool), jnp.zeros((K, nbits), i32),
        )
        return lax.while_loop(cond, body, init)

    def _fused_sig(self, fa_av) -> str:
        """Aval signature of the fused argument pytree — the fused
        analog of the dense program's aval hash inside
        ``bucket_signature``.  The rule-table lengths the plan tables
        carry are NOT bucket-quantized, so two same-bucket engines may
        trace different fused programs; the registry key carries this
        hash alongside the bucket signature to keep sharing sound."""
        parts = jax.tree_util.tree_map(
            lambda a: (tuple(a.shape), str(a.dtype)), fa_av
        )
        leaves, treedef = jax.tree_util.tree_flatten(parts)
        return signature_of((tuple(leaves), str(treedef)), "fa")

    def _fused_aot(self, K: int, caps: Tuple[int, int, int], fa: dict):
        """Compiled fused-window executable for one (K, workspace
        capacities) pair — same registry/caching story as
        :meth:`_sparse_aot`: bucket-mode engines share executables
        through PROGRAMS (K, the capacity triple and the fused argument
        avals ride in the key), and the XLA compile of byte-identical
        HLO is normally a persistent-cache hit."""
        key = (int(K),) + tuple(int(x) for x in caps)
        exe = self._aot_fused.get(key)
        if exe is not None:
            return exe
        stats = CompileStats(
            bucket_signature=self.bucket_signature,
            program=f"fused[K={K};{caps[0]},{caps[1]},{caps[2]}]",
        )
        u32 = jnp.uint32
        aval = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
            np.shape(x), jnp.asarray(x).dtype
        )
        fa_av = jax.tree_util.tree_map(aval, fa)
        sp_av = jax.ShapeDtypeStruct((self.nc, self.wc), u32)
        rp_av = jax.ShapeDtypeStruct((self.nl, self.wc), u32)
        n_flags = self._gate["n_flags"] if self._gate else 0
        carry_av = (
            jax.ShapeDtypeStruct((max(n_flags, 1),), jnp.bool_),
            jax.ShapeDtypeStruct((self.n_lchunks,), jnp.bool_),
            jax.ShapeDtypeStruct((self.nc,), jnp.bool_),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
        )
        if self.mesh is None:
            fn = jax.jit(
                functools.partial(self._fused_exec, K=K, caps=caps),
                donate_argnums=_state_donation(),
            )
        else:
            # same shard_map structure as the per-round programs: state
            # on the packed word axis, plan tables + carries replicated,
            # every output replicated by the in-loop psum folds except
            # the per-shard live-bit partials
            P = jax.sharding.PartitionSpec
            axis = self.word_axis
            state = P(None, axis)
            fn = jax.jit(
                shard_map(
                    functools.partial(
                        self._fused_exec, K=K, caps=caps, axis_name=axis
                    ),
                    mesh=self.mesh,
                    in_specs=(
                        state, state, P(), P(), P(), P(), P(),
                        jax.tree.map(lambda _: P(), fa_av),
                    ),
                    out_specs=(
                        (state, state) + (P(),) * 11 + (P(None, axis),)
                    ),
                    check_vma=False,
                ),
                donate_argnums=_state_donation(),
            )

        def build():
            t0 = time.perf_counter()
            lowered = fn.lower(sp_av, rp_av, *carry_av, fa_av)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            stats.trace_lower_s = t1 - t0
            stats.compile_s = time.perf_counter() - t1
            return compiled

        with compile_watch(stats):
            if self._bucket:
                exe, hit = PROGRAMS.get_or_build(
                    (
                        self.bucket_signature, "fused", key,
                        self._fused_sig(fa_av),
                    ),
                    build,
                )
                stats.program_cache_hit = hit
            else:
                exe = build()
        self._aot_fused[key] = exe
        self._fused_builds.append(stats)
        self._note_compile(stats)
        return exe

    # ------------------------------------------- programs & compilation

    def _compute_signature(self) -> str:
        """Signature of the traced program: every structural determinant
        (shapes, plan structures, chunk/group/gate layout, backend) plus
        the argument pytree's avals, hashed.  For a bucketed engine two
        equal signatures imply the same jaxpr — the soundness condition
        for sharing a compiled executable across ontologies.  Exact
        engines get an ``exact…`` signature (their program additionally
        embeds ontology constants, so it is only ever reused by the
        same engine instance / the persistent cache's HLO keying)."""

        def scan_sig(d):
            if d is None:
                return None
            return (
                d["rk"], d["lcn"], d["nch"], d["T"], d["pad_target"],
                tuple(
                    (g0, g1, plan.structure(),
                     -1 if rows is None else len(np.asarray(rows)))
                    for g0, g1, plan, rows in d["groups"]
                ),
                len(d["spans_kept"]), len(d["spans_dropped"]),
            )

        gate = None
        if self._gate is not None:
            gate = tuple(
                (kind, -1 if rows is None else len(np.asarray(rows)))
                for kind, rows in self._gate["readers"]
            )
        avals = jax.tree_util.tree_map(
            lambda a: (tuple(np.shape(a)), str(jnp.asarray(a).dtype)),
            self._masks,
        )
        parts = (
            1,  # signature schema version
            jax.default_backend(),
            self.n_shards,
            tuple(self.mesh.shape.items()) if self.mesh is not None else None,
            self._bucket, self._bucket_ratio,
            self.nc, self.nl, self.wc, self.unroll,
            self.lc, self.lc4, self.n_lchunks, self._bw, self._n_sblocks,
            self._serialize_chunks, self._use_pallas,
            str(self.matmul_dtype),
            tuple(sorted(self._rules)) if self._rules is not None else None,
            self._bottom, self._n_roles_pad,
            self._k4_rows, self._k6_rows, self._scan_mode,
            getattr(self, "_scan_rk", None),
            self._p1.structure(), self._p2.structure(),
            self._p3.structure(),
            scan_sig(self._scan4), scan_sig(self._scan6),
            # live-tile CR6 structure: the formulation choice AND the
            # quantized tile counts shape the jaxpr, so two engines
            # share a program only when both resolved identically
            self._tiles6.signature_parts()
            if self._tiles6 is not None
            else None,
            len(self._s_layers), len(self._r_layers),
            self._window_headroom, gate,
            self._dead_c, self._dead_l,
            len(self._cr4_chunks), len(self._cr6_chunks),
            self._sig_knobs,
            avals,
        )
        prefix = ("b" if self._bucket else "exact") + f"{self.nc}x{self.nl}"
        return signature_of(parts, prefix)

    def _mask_avals(self):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                           jnp.asarray(a).dtype),
            self._masks,
        )

    def _note_compile(self, stats: CompileStats) -> None:
        with self._stats_lock:
            self.compile_stats.merge(stats)
            self.last_compile = stats

    def _run_aot(self, budget: int):
        """Compiled fixed-point executable for ``budget`` iterations
        (single-device path).  Bucket mode consults the process-global
        ``PROGRAMS`` registry first: a same-signature engine built
        earlier in this process hands its executable over outright (no
        trace, no lower, no XLA), and on a registry miss the XLA
        compile of the byte-identical HLO is normally a persistent
        disk-cache hit.  Exact mode AOT-compiles per engine — the same
        walls the old jit dispatch paid, but split into measured
        ``compile_stats``."""
        exe = self._aot_runs.get(budget)
        if exe is not None:
            return exe
        stats = CompileStats(
            bucket_signature=self.bucket_signature,
            program=f"run[{budget}]",
        )
        sp_av = jax.ShapeDtypeStruct((self.nc, self.wc), jnp.uint32)
        rp_av = jax.ShapeDtypeStruct((self.nl, self.wc), jnp.uint32)
        mk_av = self._mask_avals()

        def build():
            t0 = time.perf_counter()
            lowered = self._run_jit.lower(sp_av, rp_av, mk_av, budget)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            stats.trace_lower_s = t1 - t0
            stats.compile_s = time.perf_counter() - t1
            return compiled

        with compile_watch(stats):
            if self._bucket:
                key = (self.bucket_signature, "run", budget)
                exe, hit = PROGRAMS.get_or_build(key, build)
                stats.program_cache_hit = hit
            else:
                exe = build()
        self._aot_runs[budget] = exe
        self._note_compile(stats)
        return exe

    def _step_aot(self):
        """Compiled public-step executable (single-device) — same
        registry/caching story as :meth:`_run_aot`."""
        if self._aot_step is not None:
            return self._aot_step
        stats = CompileStats(
            bucket_signature=self.bucket_signature, program="step"
        )
        sp_av = jax.ShapeDtypeStruct((self.nc, self.wc), jnp.uint32)
        rp_av = jax.ShapeDtypeStruct((self.nl, self.wc), jnp.uint32)
        mk_av = self._mask_avals()

        def build():
            t0 = time.perf_counter()
            lowered = self._step_jit.lower(sp_av, rp_av, mk_av)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            stats.trace_lower_s = t1 - t0
            stats.compile_s = time.perf_counter() - t1
            return compiled

        with compile_watch(stats):
            if self._bucket:
                key = (self.bucket_signature, "step")
                exe, hit = PROGRAMS.get_or_build(key, build)
                stats.program_cache_hit = hit
            else:
                exe = build()
        self._aot_step = exe
        self._note_compile(stats)
        return exe

    def precompile(
        self,
        max_iters: int = 10_000,
        *,
        programs: Tuple[str, ...] = (
            "run", "step", "sparse", "fused", "helpers",
        ),
        parallel: Optional[bool] = None,
        max_workers: Optional[int] = None,
    ) -> CompileStats:
        """AOT-build this engine's program roster before any request
        needs it — the warmup half of the cold-start overhaul.  The
        roster is the per-program split of the superstep machinery this
        engine will execute: the fixed-point ``run`` program (the XLA
        heavyweight — the scanned per-rule group bodies live inside it)
        and the public single-``step`` program; their ``.lower()``
        ``.compile()`` pairs are driven concurrently on a thread pool
        (XLA compiles release the GIL), overlapping pass time instead
        of serializing it.  ``runtime/warmup.py`` layers cross-bucket
        concurrency on top (one roster per configured bucket).

        Mesh engines lower+compile through the sharded dispatch path —
        that populates the persistent disk cache (the later dispatch
        compile becomes a cache deserialization) without touching the
        lru-cached jit wrappers.

        Returns this engine's cumulative :class:`CompileStats` (equal
        to this call's cost on a freshly built engine)."""
        budget = _pad_up(max_iters, self.unroll)
        if self.mesh is None:
            roster = {
                "run": lambda: self._run_aot(budget),
                "step": self._step_aot,
            }
            if self._sparse_cfg is not None and self._sparse_supported():
                # the sparse tier's floor-rung programs — the
                # capacities tail rounds actually resolve to: the
                # S-rules-only key (a4 = a6 = 0, the subclass-chain
                # tail regime) and, when CR4/CR6 exist, the mixed key
                # with their row workspaces at the floor.  Larger
                # rungs compile lazily (and usually hit the
                # persistent cache).
                cfg = self._sparse_cfg

                def sparse_floor():
                    floor = cfg["capacity_floor"]
                    self._sparse_aot(floor, 0, 0)
                    mixed = (
                        floor,
                        floor if self._scan4 else 0,
                        floor if self._scan6 else 0,
                    )
                    if mixed != (floor, 0, 0):
                        self._sparse_aot(*mixed)

                roster["sparse"] = sparse_floor
            if self._fused_eligible():

                def fused_floor():
                    scfg = self._sparse_cfg
                    floor = scfg["capacity_floor"]
                    caps = (
                        floor,
                        floor if self._scan4 else 0,
                        floor if self._scan6 else 0,
                    )
                    fargs = self._fused_run_args(scfg, budget)
                    for k in self._fused_k_ladder(
                        self._fused_cfg["rounds"],
                        self._fused_cfg.get("adaptive", False),
                    ):
                        self._fused_aot(k, caps, fargs)

                roster["fused"] = fused_floor

            def helpers():
                # the delta plane's shape-keyed helper programs
                # (same-bucket embed + live-bit counts): tiny builds,
                # but a consumer fed by the AOT artifact farm should
                # build NOTHING — running them here puts their keys on
                # the farm wire alongside the heavyweights
                z_sp = jnp.zeros((self.nc, self.wc), jnp.uint32)
                z_rp = jnp.zeros((self.nl, self.wc), jnp.uint32)
                self.count_live_bits(z_sp, z_rp)
                self._embed_packed_device(z_sp, z_rp)

            roster["helpers"] = helpers
            tasks = [roster[name] for name in programs if name in roster]
        else:

            def mesh_run():
                stats = CompileStats(
                    bucket_signature=self.bucket_signature,
                    program=f"run[{budget}]",
                )
                with compile_watch(stats):
                    sp0, rp0 = self.initial_state()
                    t0 = time.perf_counter()
                    lowered = self._run_jit(budget).lower(
                        sp0, rp0, self._masks
                    )
                    t1 = time.perf_counter()
                    lowered.compile()
                    stats.trace_lower_s = t1 - t0
                    stats.compile_s = time.perf_counter() - t1
                self._note_compile(stats)

            tasks = [mesh_run]
            if "fused" in programs and self._fused_eligible():

                def mesh_fused():
                    scfg = self._sparse_cfg
                    floor = scfg["capacity_floor"]
                    caps = (
                        floor,
                        floor if self._scan4 else 0,
                        floor if self._scan6 else 0,
                    )
                    fargs = self._fused_run_args(scfg, budget)
                    for k in self._fused_k_ladder(
                        self._fused_cfg["rounds"],
                        self._fused_cfg.get("adaptive", False),
                    ):
                        self._fused_aot(k, caps, fargs)

                tasks.append(mesh_fused)
        if parallel is None:
            parallel = len(tasks) > 1
        if parallel and len(tasks) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=max_workers or len(tasks)
            ) as pool:
                for f in list(pool.map(lambda fn: fn(), tasks)):
                    pass
        else:
            for fn in tasks:
                fn()
        return self.compile_stats

    def rebind_role_closure(self, new_closure) -> bool:
        """Re-bind this engine's COMPILED program to a grown role
        closure without recompiling — the masks-only partial rebuild for
        deltas that add ``r ⊑ s`` between existing roles (the last
        delta shape that previously forced a full rebuild; reference
        parity: role-hierarchy axioms are uniform inserts over live
        stores, ``init/AxiomLoader.java:1051-1132``, with downstream
        re-emission ``RolePairHandler.java:380-444``).

        Sound because the closure reaches the compiled program only
        through runtime ARGUMENTS with static shapes: the factored
        CR4/CR6 masks (``m4``/``m6`` or the scanned ``m`` slabs) and the
        live-window offset/validity tables.  This method recomputes all
        of them under ``new_closure`` through the same builders the
        compile-time plan used and swaps them into ``self._masks``; the
        traced program (row chunks, seg-OR write plans, gate readers)
        is untouched.  The caller re-enters the fixed point from the
        old embedded state — monotonicity makes that a sound warm start
        under a grown closure.

        Returns False — with the engine UNTOUCHED — when the new
        closure needs structure the program lacks: a row chunk that was
        dead at build time (no live links) coming alive, or a chunk
        needing more live windows than its static slots (including the
        ``window_headroom`` reserve).  Requires same role count and a
        SUPERSET closure (EL+ deltas only grow it; shrinking is belief
        revision, out of scope — and a shrunk mask under stale S/R bits
        would be unsound anyway).
        """
        idx = self.idx
        h_old = np.asarray(idx.role_closure)
        h_new = np.asarray(new_closure, dtype=h_old.dtype)
        if h_new.shape != h_old.shape:
            return False
        ob, nb = h_old.astype(bool), h_new.astype(bool)
        if np.any(ob & ~nb):
            return False  # not a superset: refuse
        if np.array_equal(ob, nb):
            return True  # nothing to do

        _h2, m4_new, m6_new = _factored_closure_tables(
            h_new,
            idx.nf4[:, 0] if self._has4 else None,
            idx.chain_pairs[:, 0] if self._has6 else None,
            n_pad=self._n_roles_pad,
        )

        def windows_fit(role_list, lcn, slots):
            """New live windows for a span, or None when they exceed
            ``slots`` (the program's static capacity)."""
            win = self._live_windows(role_list, lcn, h_arg=h_new)
            if win is None:
                # superset closure: a live span cannot go dead; an
                # all-dead span is vacuously fit (no live links)
                return np.zeros(0, np.int32), np.zeros((0, 2), np.int32)
            offs, c01 = win
            if len(offs) > slots:
                return None
            return offs, c01

        if self._scan_mode:
            new_slabs = {}
            for key, d, tab_roles, mask_tab in (
                ("s4", self._scan4,
                 idx.nf4[:, 0] if self._has4 else None, m4_new),
                ("s6", self._scan6,
                 idx.chain_pairs[:, 0] if self._has6 else None, m6_new),
            ):
                if d is None:
                    # the rule had NO live chunk at build (or no rows):
                    # a grown closure reviving any span needs a program
                    # this engine never compiled.  Consume the spans
                    # PERSISTED by build_scan (the shared _chunk_spans
                    # grid) — re-deriving boundaries here from
                    # self._scan_rk could silently desync from the grid
                    # the build actually dropped, misjudging liveness.
                    dropped = (
                        self._scan4_dropped
                        if key == "s4"
                        else self._scan6_dropped
                    )
                    if tab_roles is not None and len(tab_roles):
                        lcn = self.lc4 if key == "s4" else self.lc
                        for a0, a1 in dropped:
                            if self._live_windows(
                                tab_roles[a0:a1], lcn, h_arg=h_new
                            ) is not None:
                                return False
                    continue
                for a0, a1 in d["spans_dropped"]:
                    if self._live_windows(
                        tab_roles[a0:a1], d["lcn"], h_arg=h_new
                    ) is not None:
                        return False  # dead chunk came alive
                nch, T, rk = d["nch"], d["T"], d["rk"]
                offs_l, c01_l = [], []
                for a0, a1 in d["spans_kept"]:
                    fit = windows_fit(tab_roles[a0:a1], d["lcn"], T)
                    if fit is None:
                        return False
                    offs_l.append(fit[0])
                    c01_l.append(fit[1])
                # same slab layout + mask padding as build_scan, via the
                # shared helpers
                offs_s, c01_s, tval_s = _fill_window_slabs(
                    offs_l, c01_l, nch, T
                )
                old = d["slabs"]
                new_slabs[key] = (
                    old[0], old[1],
                    jnp.asarray(
                        _stack_span_masks(mask_tab, d["spans_kept"], rk)
                    ),
                    jnp.asarray(offs_s),
                    jnp.asarray(c01_s),
                    jnp.asarray(tval_s),
                )
                new_slabs[key + "_nw"] = np.asarray(
                    [len(o) for o in offs_l]
                )
                # host copy for the sparse tier's chunk-activity fold
                # must track the slab swap
                new_slabs[key + "_np"] = tval_s
            # ---- live-tile CR6: re-fit the tile schedule under the
            # grown closure (same spans, same write groups, same slot
            # counts) BEFORE any swap — a grown closure needing more
            # link tiles than the compiled program has slots refuses
            # the rebind with the engine untouched
            new_tiles6 = None
            if self._tiles6 is not None:
                new_tiles6 = build_cr6_tile_schedule(
                    idx.chain_pairs[:, 0], self._l26,
                    idx.chain_pairs[:, 2], m6_new,
                    self._link_roles, idx.role_closure,
                    lc=self.lc, n_lchunks=self.n_lchunks,
                    tile_m=self._tiles6.tile_m,
                    tile_l=self._tiles6.tile_l,
                    group_bounds=[],
                    link_window=self._link_window,
                    n_rows=self._k6_rows if self._bucket else None,
                    dead_link=self.nl - 1,
                    pad_target=self._dead_l if self._bucket else 0,
                    q1=self._q1 if self._bucket else None,
                    qn=self._qn if self._bucket else None,
                    h_override=h_new,
                    fit_schedule=self._tiles6,
                )
                if new_tiles6 is None:
                    return False  # tile slots exhausted: full rebuild
            # ---- all checks passed: swap atomically
            if new_tiles6 is not None:
                self._tiles6 = new_tiles6
                # refresh the derived MAC figures too — occupied_slots
                # grew under the new closure, and the stale density
                # would contradict it
                win_macs = self.cr6_tiles_stats.get("window_slot_rows")
                tile_macs = new_tiles6.stats["tile_macs"]
                self.cr6_tiles_stats = dict(
                    self.cr6_tiles_stats,
                    **new_tiles6.stats,
                    tile_slot_rows=tile_macs,
                    **(
                        {"density": round(
                            tile_macs / max(float(win_macs), 1.0), 4
                        )}
                        if win_macs
                        else {}
                    ),
                )
            if self._scan4 is not None:
                self._scan4["slabs"] = new_slabs["s4"]
                self._scan4["n_windows"] = new_slabs["s4_nw"]
                self._scan4["tval_np"] = new_slabs["s4_np"]
            if self._scan6 is not None:
                self._scan6["slabs"] = new_slabs["s6"]
                self._scan6["n_windows"] = new_slabs["s6_nw"]
                self._scan6["tval_np"] = new_slabs["s6_np"]
            if self._bucket:
                # same compiled program, new argument content: only the
                # slab leaves change — shapes (and so the signature and
                # any registry-shared executable) are untouched
                self._masks = dict(
                    self._masks,
                    s4=self._scan4["slabs"] if self._scan4 else (),
                    s6=self._scan6["slabs"] if self._scan6 else (),
                    t6=self._t6_device_slabs(),
                )
            else:
                self._masks = (
                    self._masks[0],
                    self._masks[1],
                    self._scan4["slabs"] if self._scan4 else (),
                    self._scan6["slabs"] if self._scan6 else (),
                    self._t6_device_slabs(),
                )
        else:
            new_tiles = {}
            for key, chunks, tiles, dropped, role_of, lcn in (
                ("t4", self._cr4_chunks, self._cr4_tiles,
                 self._cr4_dropped_roles,
                 lambda raw: idx.nf4[raw, 0], self.lc4),
                ("t6", self._cr6_chunks, self._cr6_tiles,
                 self._cr6_dropped_roles,
                 lambda raw: idx.chain_pairs[raw, 0], self.lc),
            ):
                for roles in dropped:
                    if self._live_windows(roles, lcn, h_arg=h_new) \
                            is not None:
                        return False  # dead chunk came alive
                p_off, p_c01 = self._make_pad_window(lcn)
                rebuilt = []
                for (raw, _inv, _piece), (offs_old, _c01_old) in zip(
                    chunks, tiles
                ):
                    slots = int(offs_old.shape[0])
                    fit = windows_fit(role_of(raw), lcn, slots)
                    if fit is None:
                        return False
                    offs, c01 = fit
                    # inert reserve windows at the padded tail (the
                    # tile loop's window count is static)
                    offs, c01 = _pad_to_slots(
                        offs, c01, slots, p_off, p_c01
                    )
                    rebuilt.append((jnp.asarray(offs), jnp.asarray(c01)))
                new_tiles[key] = rebuilt
            self._cr4_tiles = new_tiles["t4"]
            self._cr6_tiles = new_tiles["t6"]
            self._masks = (
                jnp.asarray(m4_new),
                jnp.asarray(m6_new),
                self._masks[2],
                self._masks[3],
                tuple(self._cr4_tiles),
                tuple(self._cr6_tiles),
            )
        import dataclasses

        # the sparse tier's host-side activity fold reads the full
        # factored-mask tables — refresh them with the grown closure,
        # as bool like the build-time cache, along with the derived
        # all-dirty row activity (chunk→role coverage is
        # closure-independent and stays put)
        self._m4_full = m4_new.astype(bool)
        self._m6_full = m6_new.astype(bool)
        self._m4_any = (self._m4_full & self._max_dirty_roles).any(axis=1)
        self._m6_any = (self._m6_full & self._max_dirty_roles).any(axis=1)
        # the fused tier's device plan tables mirror these host arrays
        # (factored masks, live-window validity, slab leaves) — rebuild
        # them lazily under the grown closure
        self._fused_tab_cache = None
        self.idx = dataclasses.replace(idx, role_closure=h_new)
        return True

    def step_cost_model(self) -> dict:
        """Analytic per-superstep cost from the static plan shapes, for
        roofline reporting (SURVEY §6 / BASELINE.md ask throughput to be
        relatable to what the chip could do):

        * ``hbm_bytes`` — packed-state HBM traffic of one ungated
          superstep: per rule, source-row gathers + target-row
          read-modify-writes (CR1-CR3), the per-chunk R_T sweep +
          bit-table gathers of the L-loop (CR4/CR6), and the CR5
          OR-reduce sweep.  Gating only reduces this, so the figure is
          an upper bound per step.
        * ``mm_dense_equiv_macs`` — the CR4/CR6 contraction size as a
          DENSE matmul ([Σrk, nl] @ [nl, nc]): the dense-equivalent
          work the tile-skipping kernel competes against; achieved
          ops/s above the MXU's dense peak means the skip logic is
          winning, not that silicon broke physics.
        """
        w4 = 4 * self.wc  # bytes per packed row
        rw = 0
        for p in (self._p1, self._p3):
            rw += p.k * w4                               # gathered sources
            rw += 2 * p.n_targets * w4                   # target RMW
        rw += 2 * self._p2.k * w4
        rw += 2 * self._p2.n_targets * w4
        if self._n_sblocks > 1:
            # block slice + write-back traffic of the word sweep
            rw += 2 * (self.nc + self.nl) * w4
        macs = 0
        live_macs = 0
        for chunks, tiles, lcn in (
            (self._cr4_chunks, self._cr4_tiles, self.lc4),
            (self._cr6_chunks, self._cr6_tiles, self.lc),
        ):
            for (raw, _inv, piece), tile in zip(chunks, tiles):
                n_t = int(tile[0].shape[0])
                rw += n_t * lcn * w4                     # live R windows
                rw += len(raw) * w4                      # subt gather
                rw += 2 * piece.n_targets * w4           # target RMW
                macs += len(raw) * self.nl * self.nc
                live_macs += len(raw) * n_t * lcn * self.nc
        for d in (self._scan4, self._scan6):
            if d is None:
                continue
            rk, lcn = d["rk"], d["lcn"]
            if d is self._scan6 and self._tiles6 is not None:
                # live-tile CR6: the contraction touches the packed
                # live-link tiles only — gathered rows, subt gathers,
                # and the tile write plans' RMW + re-gather traffic
                t6 = self._tiles6
                rw += t6.n_rt * t6.nt * t6.tile_l * w4   # link-tile rows
                rw += t6.n_rt * t6.tile_m * w4           # subt gathers
                for _rt0, _rt1, plan, _o, _t in t6.groups:
                    rw += 2 * plan.n_targets * w4
                    rw += 2 * plan.k * w4
                macs += d["nch"] * rk * self.nl * self.nc
                live_macs += (
                    t6.stats["occupied_slots"] * t6.tile_m * self.nc
                )
                continue
            n_t_total = int(d["n_windows"].sum())
            # every chunk executes T = max(n_windows) slots; padded
            # slots still issue their R-window dynamic_slice read (only
            # the MXU work is zeroed), so the traffic bound charges the
            # padded plane, not just the live windows
            rw += d["nch"] * d["T"] * lcn * w4           # R window reads
            rw += d["nch"] * rk * w4                     # subt gathers
            # deferred per-group output buffers: one write + the
            # emission-order re-gather on top of the target RMW
            for _g0, _g1, plan, _rows in d["groups"]:
                rw += 2 * plan.n_targets * w4
                rw += 2 * plan.k * w4
            macs += d["nch"] * rk * self.nl * self.nc
            live_macs += n_t_total * rk * lcn * self.nc
        if self._bottom:
            rw += (self.nl + 2) * w4
        return {
            "hbm_bytes": rw,
            "mm_dense_equiv_macs": macs,
            # the statically-scheduled portion actually contracted (live
            # role windows only) — what the chip really has to beat
            "mm_live_macs": live_macs,
        }

    def _next_dirty(self, mask_s, any_r, axis_name, mk=None):
        """End-of-step rule-gate flags from the shared changed-S-row
        mask and the any-R-change scalar; one tiny psum makes the flags
        globally uniform under sharding (the cond predicates must agree
        across shards).  Bucket mode reads the SR readers' row lists
        from the argument pytree (``mk["gate_rows"]``, padded with the
        dead row — which the pos-maps keep permanently clean)."""
        g = self._gate
        flags = []
        si = 0
        for kind, rows in g["readers"]:
            if kind == "SR":
                if self._bucket:
                    rows_t = mk["gate_rows"][si]
                    si += 1
                else:
                    rows_t = jnp.asarray(rows) if rows.size else None
                d = any_r
                if rows_t is not None and rows_t.shape[0]:
                    d = d | jnp.any(mask_s[rows_t])
            elif kind == "RR":
                d = any_r
            else:  # CR5
                d = any_r | mask_s[BOTTOM_ID]
            flags.append(d)
        dirty = jnp.stack(flags)
        if axis_name is not None:
            dirty = lax.psum(dirty.astype(jnp.int32), axis_name) > 0
        return dirty

    def _next_frontier(self, s_vecs, r_vecs, mk=None):
        """Fold this step's write change-vectors into
        ``(changed-S-row mask [nc], any_r, per-L-chunk R dirty flags)``
        via the layered permutation gathers of ``_pos_maps`` (a scatter
        would serialize per index on TPU).  The caller psums the parts
        it carries across shards.  Bucket mode gathers through the
        argument-pytree layer maps (``mk["sl"]``/``mk["rl"]``)."""
        cs = jnp.concatenate(
            [v.astype(bool) for v in s_vecs] + [jnp.zeros(1, bool)]
        )
        cr = jnp.concatenate(
            [v.astype(bool) for v in r_vecs] + [jnp.zeros(1, bool)]
        )
        s_layers = (
            mk["sl"]
            if self._bucket
            else [jnp.asarray(pm) for pm in self._s_layers]
        )
        r_layers = (
            mk["rl"]
            if self._bucket
            else [jnp.asarray(pm) for pm in self._r_layers]
        )
        mask_s = jnp.zeros(self.nc, bool)
        for pm in s_layers:
            mask_s = mask_s | cs[pm]
        mask_r = jnp.zeros(self.nl, bool)
        for pm in r_layers:
            mask_r = mask_r | cr[pm]
        dirty_l = mask_r.reshape(self.n_lchunks, self.lc).any(axis=1)
        return mask_s, jnp.any(cr), dirty_l

    def _step(
        self,
        sp: jax.Array,
        rp: jax.Array,
        masks: Optional[tuple] = None,  # the self._masks plan-table pytree
        axis_name: Optional[str] = None,
        dirty: Optional[jax.Array] = None,
    ):
        """One superstep → ``(sp, rp, changed, dirty_next)``.
        ``dirty`` is the 3-tuple frontier carry ``(rule-chunk gate
        flags, per-L-chunk R dirty flags, changed-S-row mask)`` — see
        :meth:`initial_dirty`; ``None`` means all-dirty (the stateless
        public ``step()``).  The gate flags are consulted only when
        chunk gating is on; the L-frontier parts always gate the
        CR4/CR6 contractions and are always refolded at the end of the
        step.  ``changed`` is tracked at
        each rule's write (on the touched rows only) rather than by a
        whole-array post-comparison, so the pre-step state is dead as
        soon as the last rule reads it — without this the fixed-point
        loop carries two full copies of S and OOMs ~2x earlier."""
        mk = self._masks if masks is None else masks
        if self._bucket:
            # bucketed engines carry EVERY ontology-derived array in the
            # argument pytree — nothing below may close over self.* data
            # content (structure only), or the compiled program would
            # stop being shareable across same-bucket ontologies
            fills, lroles = mk["fills"], mk["lroles"]
            s4slabs, s6slabs = mk["s4"], mk["s6"]
            t6slabs = mk["t6"]
            m4 = m6 = t4 = t6 = None
        elif self._scan_mode:
            fills, lroles, s4slabs, s6slabs, t6slabs = mk
            m4 = m6 = t4 = t6 = None
        else:
            m4, m6, fills, lroles, t4, t6 = mk
            s4slabs = s6slabs = None
        bucket = self._bucket
        gating = self._gate is not None
        if dirty is None:  # stateless public step(): all-dirty
            dirty = self.initial_dirty()
        gate_flags, dirty_l, s_changed = dirty
        ch = jnp.asarray(False)
        s_vecs, r_vecs = [], []
        flag = iter(range(self._gate["n_flags"])) if gating else None
        width = sp.shape[1]  # shard-local word width

        def gated_rows(n_targets, operand, compute):
            """``compute(operand) → reduced`` [n_targets, width] under
            this chunk's dirty flag (zeros when clean).  Only the
            chunk-bounded reduced rows cross the cond boundary; the
            caller ORs them in unconditionally (OR with zeros is the
            identity), so the state stays a linear scatter chain the
            compiler aliases in place.  Wrapping the whole rule in the
            cond instead forces a pass-through copy of the multi-GB
            state per skipped branch — measured +3.1 GB peak at 96k
            many-role classes, the difference between fitting one chip
            and OOM.  The flag iterator consumes indices in
            ``_build_gate``'s reader order."""
            if not gating:
                return compute(operand)
            return lax.cond(
                gate_flags[next(flag)],
                compute,
                lambda _ops: jnp.zeros((n_targets, width), jnp.uint32),
                operand,
            )

        # ---- CR1/CR2/CR3: full static plans, swept over word blocks.
        # Each rule is column-local (a row write's word w depends only
        # on its sources' word w), so a [rows, bw] block is a complete
        # sub-problem; the sweep bounds temporaries to O(K·bw) with ONE
        # traced body regardless of corpus size — per-axiom chunking
        # compiled one body per chunk and XLA compile time grew
        # super-linearly in chunk count (74 min at 300k classes).
        # CR5 stays a full-width op after CR6 (its ⊥-filler mask reads
        # bit columns anywhere in the row, and its masked-reduce
        # temporary is O(nl·width) regardless of blocking).
        if self._p1.k or self._p2.k or self._p3.k:

            def block_rules(sb, rb):
                # named_scope: phase attribution for the step profiler
                # (runtime/profiling.py reads scopes out of hlo_stats).
                # Bucket mode swaps every gather/target constant for its
                # argument-pytree copy (quantized-plan pad segments are
                # dead-row self-loops — no-ops under OR).
                cvs = []
                if self._p1.k:  # CR1: a ⊑ b
                    with jax.named_scope("cr1"):
                        src = mk["src1"] if bucket else jnp.asarray(self._src1)
                        red = self._p1.reduce(sb[src])
                        sb, cv = self._p1.write(
                            sb, red, track="rows",
                            targets=mk["tgt1"] if bucket else None,
                        )
                    cvs.append(cv)
                if self._p2.k:  # CR2: a1 ⊓ a2 ⊑ b
                    with jax.named_scope("cr2"):
                        if bucket:
                            sa, sb2 = mk["src2a"], mk["src2b"]
                        else:
                            sa = jnp.asarray(self._src2a)
                            sb2 = jnp.asarray(self._src2b)
                        red = self._p2.reduce(sb[sa] & sb[sb2])
                        sb, cv = self._p2.write(
                            sb, red, track="rows",
                            targets=mk["tgt2"] if bucket else None,
                        )
                    cvs.append(cv)
                if self._p3.k:  # CR3: a ⊑ ∃link — reads S, writes R
                    with jax.named_scope("cr3"):
                        src = mk["src3"] if bucket else jnp.asarray(self._src3)
                        red = self._p3.reduce(sb[src])
                        rb, cv = self._p3.write(
                            rb, red, track="rows",
                            targets=mk["tgt3"] if bucket else None,
                        )
                    cvs.append(cv)
                return sb, rb, cvs

            if self._n_sblocks == 1:
                sp, rp, cvs = block_rules(sp, rp)
            else:
                bw = self._bw
                nrows_s, nrows_r = sp.shape[0], rp.shape[0]
                zeros = []
                if self._p1.k:
                    zeros.append(jnp.zeros(self._p1.n_targets, bool))
                if self._p2.k:
                    zeros.append(jnp.zeros(self._p2.n_targets, bool))
                if self._p3.k:
                    zeros.append(jnp.zeros(self._p3.n_targets, bool))

                def body(bi, carry):
                    sp, rp, cvs = carry
                    off = jnp.minimum(bi * bw, width - bw)
                    sb = lax.dynamic_slice(sp, (0, off), (nrows_s, bw))
                    # slice/write back only the matrices the active
                    # rules touch (an inert R copy per block otherwise)
                    rb = (
                        lax.dynamic_slice(rp, (0, off), (nrows_r, bw))
                        if self._p3.k
                        else rp
                    )
                    sb, rb, cv = block_rules(sb, rb)
                    if self._p1.k or self._p2.k:
                        sp = lax.dynamic_update_slice(sp, sb, (0, off))
                    if self._p3.k:
                        rp = lax.dynamic_update_slice(rp, rb, (0, off))
                    return sp, rp, [a | b for a, b in zip(cvs, cv)]

                sp, rp, cvs = lax.fori_loop(
                    0, self._n_sblocks, body, (sp, rp, zeros)
                )
            cvs = iter(cvs)
            if self._p1.k:
                cv = next(cvs)
                s_vecs.append(cv)
                ch |= jnp.any(cv)
            if self._p2.k:
                cv = next(cvs)
                s_vecs.append(cv)
                ch |= jnp.any(cv)
            if self._p3.k:
                cv = next(cvs)
                r_vecs.append(cv)
                ch |= jnp.any(cv)
        # CR4: ∃s.a ⊑ b — packed-columns MXU matmul: R_T stays uint32 in
        # HBM end to end (the Pallas kernel unpacks/repacks per VMEM tile;
        # the XLA fallback materializes the wide operands instead).  The
        # matmul contracts over the chunk's unique raw axioms and OR-
        # accumulates over L-chunks inside a ``fori_loop`` (partial
        # AND-OR products just OR; sequencing bounds peak memory to one
        # chunk's temporaries — see __init__).  Per chunk the factored
        # role mask gathers to a [rk, Lc] i8 tile.  The packed output rows are
        # then gathered into the seg-OR's repeat-padded emission order
        # (packed-row copies are ~free next to MXU work)
        dt = self.matmul_dtype
        lc = self.lc
        wlw = rp.shape[1]
        base = (
            None
            if axis_name is None
            else self._shard_word_base(axis_name)
        )

        def window_term(subt, rp_state, off, live, mask_rows, mm, lcw):
            # the shared module-level formulation (also the sparse
            # tier's), bound to this step's tables and shard context
            return _window_term(
                subt, rp_state, fills, lroles, off, live, mask_rows,
                mm, lcw, dt, wlw, axis_name, base,
            )

        def contract_from(
            bits_state, rp_state, rows, mask_rows, mm, f_dirty, tiles,
            lcw,
        ):
            """``f_dirty``: scalar — did any bit-table SOURCE row of this
            chunk change last step?  A live window whose R slice is also
            clean (``dirty_l`` over the aligned chunks it overlaps)
            re-derives nothing (OR-monotone) — see ``window_term``.
            ``tiles`` is this chunk's static live-window table
            (see ``build_tiles`` in ``__init__``): the loop contracts
            only windows whose link roles can satisfy the chunk's
            axiom roles."""
            offs, c01 = tiles
            n_t = int(offs.shape[0])
            rk = len(rows)
            subt = bits_state[jnp.asarray(rows)].T        # [W, rk], hoisted

            def one(i, acc):
                live = dirty_l[c01[i, 0]] | dirty_l[c01[i, 1]] | f_dirty
                return acc | window_term(
                    subt, rp_state, offs[i], live, mask_rows, mm, lcw
                )

            if n_t == 1:
                return one(0, jnp.zeros((rk, wlw), jnp.uint32))
            return lax.fori_loop(
                0, n_t, one, jnp.zeros((rk, wlw), jnp.uint32)
            )

        # ---- scanned CR4/CR6: uniform padded chunks under ONE lax.scan
        # body per rule; per-chunk dirtiness arrives as scanned operands
        # (live-window validity × dirty_l × the vectorized f_dirty
        # gather) instead of per-chunk conds, and the write is a few
        # deferred target-sorted seg-OR writes over the stacked scan
        # output — traced program size O(1) in chunk count (see
        # ``build_scan`` in ``__init__``)
        if self._scan_mode:

            def scan_contract(d, slabs, mm, state_src, rp_state,
                              fd_src, g0, g1):
                rows_s, fdx_s, m_s, offs_s, c01_s, tval_s = slabs
                T, rk = d["T"], d["rk"]
                fd_all = fd_src[fdx_s[g0:g1]].any(axis=1)   # [gch]

                def body(_, xs):
                    rows_k, m_k, offs_k, c01_k, tval_k, fd_k = xs
                    subt = state_src[rows_k].T              # [width, rk]

                    def one(i, acc):
                        live = tval_k[i] & (
                            dirty_l[c01_k[i, 0]]
                            | dirty_l[c01_k[i, 1]]
                            | fd_k
                        )
                        return acc | window_term(
                            subt, rp_state, offs_k[i], live, m_k, mm,
                            d["lcn"],
                        )

                    z = jnp.zeros((rk, wlw), jnp.uint32)
                    # T == 0: a bucketed slab whose spans have NO live
                    # window anywhere (e.g. the cross program when the
                    # new-link window satisfies none of this rule's
                    # roles) — contribute nothing; a 0-trip fori_loop
                    # would still TRACE `one` against the empty slabs
                    if T == 0:
                        acc = z
                    else:
                        acc = one(0, z) if T == 1 else lax.fori_loop(
                            0, T, one, z
                        )
                    return (), acc

                xs = (
                    rows_s[g0:g1], m_s[g0:g1], offs_s[g0:g1],
                    c01_s[g0:g1], tval_s[g0:g1], fd_all,
                )
                _, ys = lax.scan(body, (), xs)
                return ys.reshape(-1, wlw)

            if self._scan4 is not None:
                s_changed_ext = jnp.concatenate(
                    [s_changed, jnp.zeros(1, bool)]
                )
                mm4 = self._cr4_mm[0]
                for gi, (g0, g1, gplan, _rows) in enumerate(
                    self._scan4["groups"]
                ):

                    def red4s(ops, g0=g0, g1=g1, gplan=gplan, gi=gi):
                        s, r = ops
                        out = scan_contract(
                            self._scan4, s4slabs, mm4, s, r,
                            s_changed_ext, g0, g1,
                        )
                        if bucket:
                            # quantized-plan pad segments gather the
                            # appended all-zero row via the runtime
                            # order argument
                            out = jnp.pad(out, ((0, 1), (0, 0)))
                            return gplan.reduce(out[mk["g4"][gi][0]])
                        return gplan.reduce(out[jnp.asarray(gplan.order)])

                    with jax.named_scope("cr4"):
                        red = gated_rows(gplan.n_targets, (sp, rp), red4s)
                        sp, cv = gplan.write(
                            sp, red, track="rows",
                            targets=mk["g4"][gi][1] if bucket else None,
                        )
                    s_vecs.append(cv)
                    ch |= jnp.any(cv)
                    if self._serialize_chunks:
                        sp, rp = lax.optimization_barrier((sp, rp))
            if self._scan6 is not None and self._tiles6 is None:
                dirty_l_ext = jnp.concatenate(
                    [dirty_l, jnp.zeros(1, bool)]
                )
                mm6 = self._cr6_mm[0]
                for gi, (g0, g1, gplan, _rows) in enumerate(
                    self._scan6["groups"]
                ):

                    def red6s(r, g0=g0, g1=g1, gplan=gplan, gi=gi):
                        out = scan_contract(
                            self._scan6, s6slabs, mm6, r, r,
                            dirty_l_ext, g0, g1,
                        )
                        if bucket:
                            out = jnp.pad(out, ((0, 1), (0, 0)))
                            return gplan.reduce(out[mk["g6"][gi][0]])
                        return gplan.reduce(out[jnp.asarray(gplan.order)])

                    with jax.named_scope("cr6"):
                        red = gated_rows(gplan.n_targets, rp, red6s)
                        rp, cv = gplan.write(
                            rp, red, track="rows",
                            targets=mk["g6"][gi][1] if bucket else None,
                        )
                    r_vecs.append(cv)
                    ch |= jnp.any(cv)
                    if self._serialize_chunks:
                        sp, rp = lax.optimization_barrier((sp, rp))
            if self._tiles6 is not None:
                # ---- live-tile CR6 (core/cr6_tiles.py): role-run row
                # tiles contract ONLY their densely packed live links —
                # the [tile_m, tile_l] operand is (factored mask ∧
                # bit-table ∧ per-link liveness), so the off-role
                # interior the window schedule sweeps never exists.
                # Write groups mirror the window formulation's row
                # ranges, keeping the intra-step cascade (and per-round
                # byte identity) intact.
                dirty_l_ext = jnp.concatenate(
                    [dirty_l, jnp.zeros(1, bool)]
                )
                mm6 = self._cr6_mm[0]
                t6s = self._tiles6
                rows_s, m_s, fdx_s, tids_s, tval_s = t6slabs
                lc_g = self.lc

                def tile_contract(rp_state, rt0, rt1):
                    fd_all = dirty_l_ext[fdx_s[rt0:rt1]].any(axis=1)

                    def body(_, xs):
                        rows_k, m_k, tid_k, tva_k, fd_k = xs
                        subt = rp_state[rows_k].T      # [width, tile_m]

                        def one(t, acc):
                            ids = tid_k[t]
                            live = (
                                dirty_l_ext[ids // lc_g] | fd_k
                            ) & tva_k[t]
                            with jax.named_scope("bit_table"):
                                f = bit_lookup_from(
                                    subt, fills[ids], dtype=dt
                                )                      # [tile_l, tile_m]
                            w = (
                                jnp.take(
                                    m_k, lroles[ids], axis=1
                                ).astype(dt)
                                * f.T
                                * live.astype(dt)
                            )
                            b = rp_state[ids]          # [tile_l, width]
                            return acc | mm6(w, b)

                        z = jnp.zeros((t6s.tile_m, wlw), jnp.uint32)
                        # nt == 0: an all-inert schedule (e.g. a cross
                        # program whose link window satisfies no chain
                        # role) — contribute nothing; a 0-trip
                        # fori_loop would still trace `one` against
                        # the empty slabs
                        if t6s.nt == 0:
                            acc = z
                        elif t6s.nt == 1:
                            acc = one(0, z)
                        else:
                            acc = lax.fori_loop(0, t6s.nt, one, z)
                        return (), acc

                    xs = (
                        rows_s[rt0:rt1], m_s[rt0:rt1], tids_s[rt0:rt1],
                        tval_s[rt0:rt1], fd_all,
                    )
                    _, ys = lax.scan(body, (), xs)
                    return ys.reshape(-1, wlw)

                for gi, (rt0, rt1, gplan, order_np, _tgts) in enumerate(
                    t6s.groups
                ):

                    def red6t(r, rt0=rt0, rt1=rt1, gplan=gplan,
                              order_np=order_np, gi=gi):
                        out = tile_contract(r, rt0, rt1)
                        if bucket:
                            out = jnp.pad(out, ((0, 1), (0, 0)))
                            return gplan.reduce(out[mk["gt6"][gi][0]])
                        return gplan.reduce(out[jnp.asarray(order_np)])

                    with jax.named_scope("cr6"):
                        red = gated_rows(gplan.n_targets, rp, red6t)
                        rp, cv = gplan.write(
                            rp, red, track="rows",
                            targets=mk["gt6"][gi][1] if bucket else None,
                        )
                    r_vecs.append(cv)
                    ch |= jnp.any(cv)
                    if self._serialize_chunks:
                        sp, rp = lax.optimization_barrier((sp, rp))

        if self._has4 and not self._scan_mode:
            for k, ((raw, inv, plan), mm) in enumerate(
                zip(self._cr4_chunks, self._cr4_mm)
            ):
                a4rows = self._a4rows[k]
                tiles = t4[k]

                def red4(ops, raw=raw, inv=inv, plan=plan, mm=mm,
                         a4rows=a4rows, tiles=tiles):
                    s, r = ops
                    f_dirty = (
                        jnp.any(s_changed[jnp.asarray(a4rows)])
                        if len(a4rows)
                        else jnp.asarray(False)
                    )
                    out = contract_from(
                        s, r, self._a4[raw], m4[raw], mm, f_dirty,
                        tiles, self.lc4,
                    )
                    return plan.reduce(out[inv])

                with jax.named_scope("cr4"):
                    red = gated_rows(plan.n_targets, (sp, rp), red4)
                    sp, cv = plan.write(sp, red, track="rows")
                s_vecs.append(cv)
                ch |= jnp.any(cv)
                if self._serialize_chunks:
                    sp, rp = lax.optimization_barrier((sp, rp))
        # CR6: role chains
        if self._has6 and not self._scan_mode:
            for k, ((raw, inv, plan), mm) in enumerate(
                zip(self._cr6_chunks, self._cr6_mm)
            ):
                l2c = self._l2chunks6[k]
                tiles = t6[k]

                def red6(r, raw=raw, inv=inv, plan=plan, mm=mm, l2c=l2c,
                         tiles=tiles):
                    f_dirty = (
                        jnp.any(dirty_l[jnp.asarray(l2c)])
                        if len(l2c)
                        else jnp.asarray(False)
                    )
                    out = contract_from(
                        r, r, self._l26[raw], m6[raw], mm, f_dirty,
                        tiles, self.lc,
                    )
                    return plan.reduce(out[inv])

                with jax.named_scope("cr6"):
                    red = gated_rows(plan.n_targets, rp, red6)
                    rp, cv = plan.write(rp, red, track="rows")
                r_vecs.append(cv)
                ch |= jnp.any(cv)
                if self._serialize_chunks:
                    sp, rp = lax.optimization_barrier((sp, rp))
        # CR5: ⊥ back-propagation — one masked packed OR-reduce (its
        # gate flag is always the LAST one, after the CR4/CR6 chunks)
        if self._bottom:

            def red5(ops):
                s, r = ops
                botf = self._bit_table(
                    s, np.full(1, BOTTOM_ID), axis_name,
                    cols=fills if bucket else None,
                )
                mask = botf[:, 0].astype(bool)              # [nl]
                masked = jnp.where(
                    mask[:, None], r, jnp.asarray(0, jnp.uint32)
                )
                return lax.reduce(
                    masked, np.uint32(0), lax.bitwise_or, (0,)
                )[None]

            with jax.named_scope("cr5"):
                red = gated_rows(1, (sp, rp), red5)
                old5 = sp[BOTTOM_ID]
                merged5 = old5 | red[0]
                sp = sp.at[BOTTOM_ID].set(merged5)
                cv = jnp.any(merged5 != old5)[None]
            s_vecs.append(cv)
            ch |= jnp.any(cv)
        with jax.named_scope("frontier"):
            mask_s, any_r, dirty_l_next = self._next_frontier(
                s_vecs, r_vecs, mk
            )
            gate_next = (
                self._next_dirty(mask_s, any_r, axis_name, mk)
                if gating
                else gate_flags
            )
            if axis_name is not None:
                dirty_l_next = (
                    lax.psum(dirty_l_next.astype(jnp.int32), axis_name) > 0
                )
                mask_s = lax.psum(mask_s.astype(jnp.int32), axis_name) > 0
        return sp, rp, ch, (gate_next, dirty_l_next, mask_s)

    def step(self, sp, rp):
        """One superstep.  On a mesh engine the matmul plans are sized to
        the shard-local word width, so the step runs inside the same
        shard_map structure as the fixed point."""
        if self.mesh is None:
            return self._step_aot()(sp, rp, self._masks)
        if self._step_sharded is None:
            P = jax.sharding.PartitionSpec
            axis = self.word_axis
            self._step_sharded = self._shard_jit(
                lambda sp, rp, masks: self._step(sp, rp, masks, axis)[:2],
                out_specs=(P(None, axis), P(None, axis)),
            )
        return self._step_sharded(sp, rp, self._masks)

    # -------------------------------------------------------- fixed point

    def _live_bits(
        self, sp: jax.Array, rp: jax.Array, axis_name: Optional[str] = None,
        wmask=None,
    ) -> jax.Array:
        """Per-row popcount over live x columns, [nc + nl] i32 (partial
        per shard under sharding — the host total sums all partials).
        ``wmask``: the bucketed run program passes its argument-carried
        live-column mask (the exact concept count varies within a
        bucket); the eager per-engine jit keeps the constant."""
        if wmask is None:
            wmask = jnp.asarray(self._wmask)
        if axis_name is not None:
            wmask = lax.dynamic_slice(
                wmask,
                (self._shard_word_base(axis_name),),
                (self.wc // self.n_shards,),
            )
        bs = jnp.sum(
            lax.population_count(sp & wmask[None, :]), axis=1, dtype=jnp.int32
        )
        br = jnp.sum(
            lax.population_count(rp & wmask[None, :]), axis=1, dtype=jnp.int32
        )
        return jnp.concatenate([bs, br])

    def count_live_bits(self, sp, rp) -> jax.Array:
        """Eager per-row live-bit counts (``_live_bits`` outside any run
        program).  Single-device engines route it through a SHAPE-KEYED
        registry program with the live-column mask as a runtime
        argument: the incremental fast path counts start/final bits
        with freshly built delta engines every increment, and a
        per-instance ``jax.jit`` would re-trace+compile per delta —
        measured ~0.1-0.3 s of steady-state overhead on CPU."""
        if self.mesh is not None:
            if self._live_bits_jit is None:
                self._live_bits_jit = jax.jit(self._live_bits)
            return self._live_bits_jit(sp, rp)
        key = ("shape:live_bits", self.nc, self.nl, self.wc)

        def build():
            u32 = jnp.uint32
            return (
                jax.jit(
                    lambda sp, rp, w: self._live_bits(sp, rp, wmask=w)
                )
                .lower(
                    jax.ShapeDtypeStruct((self.nc, self.wc), u32),
                    jax.ShapeDtypeStruct((self.nl, self.wc), u32),
                    jax.ShapeDtypeStruct((self.wc,), u32),
                )
                .compile()
            )

        exe, _hit = PROGRAMS.get_or_build(key, build)
        return exe(sp, rp, jnp.asarray(self._wmask))

    def _run(
        self, sp0, rp0, masks, max_iters: int,
        axis_name: Optional[str] = None,
    ):
        unroll = self.unroll

        def cond(st):
            return st[3] & (st[2] < max_iters)

        def body(st):
            sp, rp, it, _, dirty = st
            changed = jnp.asarray(False)
            for _ in range(unroll):
                sp, rp, c, dirty = self._step(sp, rp, masks, axis_name, dirty)
                changed |= c
            if axis_name is not None:
                # the reference's global AND-vote
                # (controller/CommunicationHandler.java:78-83) as one psum
                changed = lax.psum(changed.astype(jnp.int32), axis_name) > 0
            return (sp, rp, it + unroll, changed, dirty)

        sp, rp, it, changed, _d = lax.while_loop(
            cond,
            body,
            (
                sp0,
                rp0,
                jnp.asarray(0, jnp.int32),
                jnp.asarray(True),
                self.initial_dirty(),
            ),
        )
        return sp, rp, it, changed, self._live_bits(
            sp, rp, axis_name,
            wmask=masks["wmask"] if self._bucket else None,
        )

    def _sharded_run(self, max_iters: int):
        """Build (and cache per iteration budget) the jitted shard_map of
        the whole fixed point over the packed word axis."""
        P = jax.sharding.PartitionSpec
        axis = self.word_axis

        def run(sp0, rp0, masks):
            sp, rp, it, changed, bits = self._run(
                sp0, rp0, masks, max_iters, axis
            )
            # scalars leave as one lane per shard (replicated by
            # construction); bits leave as per-shard partial sums
            return sp, rp, it[None], changed[None], bits

        return self._shard_jit(
            run,
            out_specs=(
                P(None, axis),
                P(None, axis),
                P(axis),
                P(axis),
                P(axis),
            ),
            donate=_state_donation(),
        )

    def _observe_round(self, sp, rp, dirty, masks, axis_name=None):
        changed = jnp.asarray(False)
        for _ in range(self.unroll):
            sp, rp, c, dirty = self._step(sp, rp, masks, axis_name, dirty)
            changed |= c
        if axis_name is not None:
            changed = lax.psum(changed.astype(jnp.int32), axis_name) > 0
        bits = self._live_bits(
            sp, rp, axis_name,
            wmask=masks["wmask"] if self._bucket else None,
        )
        return sp, rp, changed, bits, dirty

    def _ensure_observe_jit(self):
        if self._observe_jit is None:
            # old sp/rp are dead after each round — donate the buffers
            # (where safe, see _state_donation)
            if self.mesh is None:
                self._observe_jit = jax.jit(
                    self._observe_round, donate_argnums=_state_donation()
                )
            else:
                P = jax.sharding.PartitionSpec
                axis = self.word_axis

                def fn(sp, rp, dirty, masks):
                    sp, rp, ch, bits, dirty = self._observe_round(
                        sp, rp, dirty, masks, axis
                    )
                    # scalar leaves as one lane per shard (replicated by
                    # the psum); bits leave as per-shard partials; dirty
                    # is replicated (psum'd inside the step)
                    return sp, rp, ch[None], bits, dirty

                inner = self._shard_jit(
                    fn,
                    out_specs=(
                        P(None, axis),
                        P(None, axis),
                        P(axis),
                        P(axis),
                        P(None),
                    ),
                    donate=_state_donation(),
                    with_dirty=True,
                )

                def observe(sp, rp, dirty, masks):
                    sp, rp, lanes, bits, dirty = inner(sp, rp, dirty, masks)
                    return sp, rp, lanes.max(), bits, dirty

                self._observe_jit = observe
        return self._observe_jit

    def _host_gate_flags(self, mask_s, any_r) -> np.ndarray:
        """Host replication of :meth:`_next_dirty` — the controller
        enters a dense round with a host-built carry after sparse
        rounds, and the flags must match what the device fold would
        have produced from the same masks."""
        if self._gate is None:
            return np.ones(1, bool)
        flags = []
        for kind, rows in self._gate["readers"]:
            if kind == "SR":
                d = any_r or (
                    rows is not None
                    and len(rows) > 0
                    and bool(mask_s[rows].any())
                )
            elif kind == "RR":
                d = any_r
            else:  # CR5
                d = any_r or bool(mask_s[BOTTOM_ID])
            flags.append(d)
        return np.asarray(flags, bool)

    def _saturate_adaptive(
        self, cfg, sp, rp, init_total, budget, observer, state_observer,
        frontier_observer, pipeline_depth: int = 1,
    ):
        """The dense/sparse controller loop, with pipelined dense
        dispatch.  Runs single-device and mesh engines identically:
        the dense rounds go through the (shard_map-structured, on a
        mesh) ``_observe_jit`` and the sparse rounds through the
        matching ``_sparse_aot`` program, both of which hand back
        replicated folds — so the host logic below never branches on
        the mesh.  On a mesh the deferred per-shard frontier folds are
        where pipelining pays most: each retire's host fold replaces a
        per-round all-shard sync (the reference's per-iteration Redis
        barrier, ``controller/CommunicationHandler.java:78-83``,
        multiplied by shards), overlapped behind the next speculative
        round's device execution.  Per retired round: measure density
        from the frontier the round consumed, track hysteresis, and
        pick the tier — dense (the regular ``unroll``-step observed
        round) above ``density_threshold`` or on workspace overflow;
        sparse (one frontier-compacted superstep) once
        ``hysteresis_rounds`` consecutive rounds measured below it
        (switching back is immediate).  The host carries the full
        frontier (changed-S mask, per-L-chunk dirty flags, gate flags),
        so the tiers interleave freely; sparse rounds return the fold
        directly plus a live-bit delta, skipping the dense round's
        full-state popcount sweep.

        Dense rounds depend only on device-carried state (sp/rp and
        the dirty carry never visit the host between rounds), so while
        nothing suggests a tier switch the controller keeps up to
        ``pipeline_depth`` rounds in flight: round N+1 is dispatched
        immediately after round N and round N's ``changed``/bits/
        frontier fold retires later from the queue — dispatch runs on
        a dedicated single-worker executor, so device execution
        overlaps the host folds even where the backend's dispatch is
        blocking.  Each retire
        replays the synchronous controller's pre-round measure (the
        host copies hold the PREVIOUS round's frontier, because retires
        happen in dispatch order), so per-round records match the
        synchronous controller's.  Sparse rounds need the host
        compaction plan, so the pipeline drains before any tier
        switch: the density/hysteresis decision acts on a frontier
        stale by at most the pipeline depth, which can delay a switch
        by up to depth-1 rounds — within the hysteresis slack, and
        never changing what any round derives (the sparse tier is
        byte-identical per round to the dense step).  On convergence
        the ≤depth-1 speculatively dispatched extra rounds are
        fixed-point no-ops (monotone OR derives nothing new): dropped
        unretired, excluded from iteration/derivation accounting."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        self._ensure_observe_jit()
        depth = max(int(pipeline_depth), 1)
        if state_observer is not None:
            # the snapshot contract hands over live, not-yet-donated
            # round state — incompatible with speculative dispatch
            depth = 1
        n_flags = self._gate["n_flags"] if self._gate else 0
        gate_flags = np.ones(max(n_flags, 1), bool)
        s_chg = np.ones(self.nc, bool)
        dirty_l = np.ones(self.n_lchunks, bool)
        any_r = True
        below = 0
        iteration, total, converged = 0, init_total, False
        dispatched = 0
        pending = deque()  # in-flight dense rounds, oldest first
        # depth > 1: dense rounds run on a dedicated single-worker
        # executor so round N+1's device execution overlaps round N's
        # host retire/fold/observer work even when the backend's
        # dispatch is blocking (the jax CPU runtime executes this
        # program INLINE at dispatch; a true async-dispatch backend
        # pays one cheap indirection).  One worker + FIFO submission
        # keeps the round order byte-identical to the synchronous
        # controller.
        pool = (
            ThreadPoolExecutor(1, thread_name_prefix="observed-pipeline")
            if depth > 1
            else None
        )
        latest = None  # newest dispatched round's future (pool mode)
        self.frontier_rounds = []

        def finish_round(st, changed):
            nonlocal converged
            FRONTIER_EVENTS.record(st)
            self.frontier_rounds.append(st)
            if frontier_observer is not None:
                frontier_observer(st)
            if observer is not None:
                observer(st.iteration, total - init_total, changed)
            if state_observer is not None:
                state_observer(
                    st.iteration, total - init_total, changed, sp, rp
                )
            if not changed:
                converged = True

        def dispatch_dense(dirty_dev, plan):
            """Enqueue one dense round; ``plan`` is the pre-measured
            ``(rows_touched, density, overflow)`` when dispatched from
            the synchronous decision point, None when speculative
            (measured at retire instead — ``dirty_dev`` is None there:
            the round chains on the previous round's device dirty
            carry)."""
            nonlocal sp, rp, dispatched, latest
            t0 = time.perf_counter()
            if pool is None:
                # depth 1: every round dispatches from the synchronous
                # decision point (speculative chaining needs pending
                # rounds, which needs depth > 1 — i.e. a pool)
                assert dirty_dev is not None
                sp, rp, ch_d, bits_d, dirty_d = self._observe_jit(
                    sp, rp, dirty_dev, self._masks
                )
                ent = {"ch": ch_d, "bits": bits_d, "dirty": dirty_d}
            else:
                # producer/consumer split: the worker runs the round
                # AND fetches its observables to the host, so every
                # device-side wait — including the jax CPU runtime's
                # dispatch quirks (dependent dispatch blocks holding
                # the GIL; dispatch may execute the program inline) —
                # lands on the worker thread, overlapped with the main
                # thread's measure/fold/observer work.  The future
                # resolves to HOST values; the single worker runs
                # tasks in order, so ``prev`` is done before the
                # closure starts and result() is instant
                def _run(prev=latest, s0=sp, r0=rp, dirty0=dirty_dev):
                    if prev is None:
                        a, b, d = s0, r0, dirty0
                    else:
                        # [2] is the previous round's DEVICE dirty
                        # carry — the host copies ride behind it
                        a, b, d = prev.result()[:3]
                    a, b, ch_d, bits_d, dirty_d = self._observe_jit(
                        a, b, d, self._masks
                    )
                    return (a, b, dirty_d) + fetch_global(
                        (ch_d, bits_d, dirty_d)
                    )

                latest = pool.submit(_run)
                ent = {"fut": latest}
            dispatched += self.unroll
            DISPATCH_EVENTS.record_dense()
            ent.update({
                "iteration": dispatched,
                "dispatch_s": time.perf_counter() - t0,
                "inflight": len(pending),
                "plan": plan,
            })
            pending.append(ent)

        def retire_dense():
            """Retire the oldest in-flight dense round: replay the
            synchronous pre-round measure if it was dispatched
            speculatively, block on its device results, and fold its
            frontier into the host copies."""
            nonlocal total, below, iteration
            nonlocal gate_flags, dirty_l, s_chg, any_r
            ent = pending.popleft()
            if ent["plan"] is None:
                rows_touched, density, measure, over = (
                    self._sparse_round_plan(cfg, s_chg, dirty_l, any_r)
                )
                if density < cfg["density_threshold"]:
                    below += 1
                else:
                    below = 0
                over = bool(
                    below >= cfg["hysteresis_rounds"]
                    and measure is None and over
                )
            else:
                rows_touched, density, over = ent["plan"]
            t1 = time.perf_counter()
            if pool is None:
                ch, bits, (gf, dl_, ms_) = fetch_global(
                    (ent["ch"], ent["bits"], ent["dirty"])
                )
            else:
                ch, bits, (gf, dl_, ms_) = ent["fut"].result()[3:]
            retire_s = time.perf_counter() - t1
            prev_total = total
            total = _host_bit_total(bits)
            gate_flags = np.asarray(gf)
            dirty_l = np.asarray(dl_)
            s_chg = np.asarray(ms_)
            any_r = bool(dirty_l.any())
            iteration = ent["iteration"]
            finish_round(
                FrontierStats(
                    iteration=iteration,
                    tier="dense",
                    density=float(density),
                    rows_touched=rows_touched,
                    total_rows=self._sp_total_rows,
                    derivations=total - prev_total,
                    overflow=bool(over),
                    wall_s=ent["dispatch_s"] + retire_s,
                    dispatch_s=ent["dispatch_s"],
                    retire_s=retire_s,
                    inflight=ent["inflight"],
                ),
                bool(ch),
            )

        try:
              while True:
                if converged:
                    break  # drop any still-speculative in-flight rounds
                if pending:
                    # speculative regime: while nothing below suggests a
                    # tier switch, keep the device queue full with dense
                    # rounds chained on the DEVICE dirty carry; otherwise
                    # retire toward the next synchronous decision point
                    if (
                        below < cfg["hysteresis_rounds"]
                        and dispatched < budget
                        and len(pending) < depth
                    ):
                        dispatch_dense(None, None)
                    else:
                        retire_dense()
                    continue
                if iteration >= budget:
                    break
                # ---- pipeline drained: the synchronous decision point ----
                if latest is not None:
                    # every dispatched round has retired (pending is
                    # empty), so the newest round's future is resolved:
                    # re-anchor the main-thread state on its outputs for
                    # the sparse/idle paths below
                    sp, rp = latest.result()[:2]
                    latest = None
                t0 = time.perf_counter()
                prev_total = total
                rows_touched, density, measure, over = self._sparse_round_plan(
                    cfg, s_chg, dirty_l, any_r
                )
                if density < cfg["density_threshold"]:
                    below += 1
                else:
                    below = 0
                want_sparse = (
                    iteration > 0 and below >= cfg["hysteresis_rounds"]
                )
                use_sparse = want_sparse and measure is not None
                if rows_touched == 0:
                    # empty frontier: either tier's step derives nothing —
                    # emit the final no-change round without running one
                    iteration += 1
                    dispatched = iteration
                    finish_round(
                        FrontierStats(
                            iteration=iteration,
                            tier="idle",
                            density=float(density),
                            rows_touched=rows_touched,
                            total_rows=self._sp_total_rows,
                            derivations=0,
                            overflow=False,
                            wall_s=time.perf_counter() - t0,
                        ),
                        False,
                    )
                elif use_sparse:
                    plan = self._sparse_round_args(measure, dirty_l)
                    exe = self._sparse_aot(*plan["key"])
                    DISPATCH_EVENTS.record_sparse()
                    sp, rp, ch_d, delta_d, ms_d, ar_d, dl_d = exe(
                        sp, rp, self._sparse_args(plan)
                    )
                    ch, delta, s_chg, ar, dirty_l = fetch_global(
                        (ch_d, delta_d, ms_d, ar_d, dl_d)
                    )
                    any_r = bool(ar)
                    total += int(delta)
                    gate_flags = self._host_gate_flags(s_chg, any_r)
                    iteration += 1
                    dispatched = iteration
                    finish_round(
                        FrontierStats(
                            iteration=iteration,
                            tier="sparse",
                            density=float(density),
                            rows_touched=rows_touched,
                            total_rows=self._sp_total_rows,
                            derivations=total - prev_total,
                            overflow=False,
                            wall_s=time.perf_counter() - t0,
                        ),
                        bool(ch),
                    )
                else:
                    dirty_dev = (
                        jnp.asarray(gate_flags),
                        jnp.asarray(dirty_l),
                        jnp.asarray(s_chg),
                    )
                    dispatch_dense(
                        dirty_dev,
                        (
                            rows_touched, density,
                            bool(want_sparse and measure is None and over),
                        ),
                    )
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
        if latest is not None:
            # pool mode: the main-thread sp/rp are stale — the current
            # state is the newest dispatched round's outputs (on
            # convergence the dropped speculative rounds are fixed-point
            # no-ops, so these are byte-identical to the retired state)
            sp, rp = latest.result()[:2]
        return sp, rp, iteration, total, converged

    _FUSED_TIERS = {0: "dense", 1: "sparse", 2: "idle"}

    def _saturate_fused(
        self, cfg, K, sp, rp, init_total, budget, observer,
        frontier_observer, pipeline_depth: int = 1,
        adaptive: bool = False,
    ):
        """The K-round fused-window controller (ISSUE 17): each
        dispatch runs :meth:`_fused_exec` — up to K rounds of the
        adaptive controller inside one device program — and the host
        work that :meth:`_saturate_adaptive` pays per round (dispatch,
        frontier fetch, fold, observer callbacks) is paid per WINDOW.
        Per-round telemetry is reconstructed at retire from the
        window's on-device round buffers, so observers still see every
        retired round, each stamped ``rounds_in_window = rounds the
        window retired`` with the window walls divided evenly across
        them (the s/round fit must never mistake a window wall for a
        round wall).

        The retired round sequence is byte-identical to the
        synchronous adaptive controller: the device replays its exact
        per-round decision, and the two escapes both hand control back
        without ever running a round differently —

        * capacity fallout (status 2): a round's sparse frontier
          overflowed the workspace rung traced into the window program.
          The round DID NOT RUN; the host replays that one round on the
          per-round path (which can still pick a bigger sparse rung, or
          the dense step with the overflow flag — exactly the
          synchronous decision) and resumes windows.
        * convergence (status 1): the window's last retired round
          derived nothing; any speculative windows behind it retire
          only fixed-point idle rounds and are dropped unretired, like
          the adaptive controller's speculative dense rounds.

        ``adaptive`` (the K-adaptive terminal window): each dispatch
        picks its window size from the halving ladder K, K/2, ..., 2 —
        the full K while the derivation tail is wide, smaller once the
        tail's geometric decay (the OnlineEta signal) predicts fewer
        remaining rounds than half a window would speculate.  Retired
        rounds are byte-identical either way: the window size only
        moves window BOUNDARIES (how many rounds run per dispatch),
        never what any round computes — a wrong prediction costs
        speculative idle rounds or extra window edges, not results.

        Pipelining speculates whole WINDOWS (depth windows in flight,
        chained on the previous window's device carries).  Unlike the
        adaptive controller, speculation never goes stale: the tier
        decision rides inside the device program, so a speculative
        window is wrong only about its workspace capacities — and that
        surfaces as a deterministic fallout, never a divergent round."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        self._ensure_observe_jit()
        depth = max(int(pipeline_depth), 1)
        unroll = self.unroll
        n_flags = self._gate["n_flags"] if self._gate else 0
        gate_flags = np.ones(max(n_flags, 1), bool)
        s_chg = np.ones(self.nc, bool)
        dirty_l = np.ones(self.n_lchunks, bool)
        any_r = True
        below = 0
        iteration, total, converged = 0, init_total, False
        fa = self._fused_run_args(cfg, budget)
        floor = cfg["capacity_floor"]
        i32 = jnp.int32
        pending = deque()  # in-flight fused windows, oldest first
        pool = (
            ThreadPoolExecutor(1, thread_name_prefix="fused-pipeline")
            if depth > 1
            else None
        )
        latest = None  # newest dispatched window's future (pool mode)
        self.frontier_rounds = []
        recent_deltas = deque(maxlen=8)  # K-adaptive decay signal

        def finish_round(st, changed):
            nonlocal converged
            recent_deltas.append(st.derivations)
            FRONTIER_EVENTS.record(st)
            self.frontier_rounds.append(st)
            if frontier_observer is not None:
                frontier_observer(st)
            if observer is not None:
                observer(st.iteration, total - init_total, changed)
            if not changed:
                converged = True

        def pick_caps():
            """Workspace capacities for the next window, measured from
            the host frontier at this sync point.  Later rounds in the
            window may outgrow them — that is the fallout path, never
            an error — so CR4/CR6 get at least the floor rung even
            when currently inactive (the host per-round key would use
            0 and trace the block away; the window program keeps it so
            mid-window activations don't fall out needlessly)."""
            _rows, _den, measure, _over = self._sparse_round_plan(
                cfg, s_chg, dirty_l, any_r
            )
            if measure is None:
                key = (floor, floor, floor)
            else:
                key = measure["key"]
            return (
                key[0],
                max(key[1], floor) if self._scan4 is not None else 0,
                max(key[2], floor) if self._scan6 is not None else 0,
            )

        def host_carry():
            return (
                jnp.asarray(gate_flags),
                jnp.asarray(dirty_l),
                jnp.asarray(s_chg),
                jnp.asarray(below, i32),
                jnp.asarray(iteration, i32),
            )

        def pick_k():
            """Window size for the NEXT dispatch.  Halve K down the
            power-of-two ladder while half a window still covers the
            decay-predicted remaining rounds; floor 2 (a 1-round
            window pays fused overhead for per-round surfacing)."""
            if not adaptive:
                return K
            from distel_tpu.obs.costmodel import geometric_tail_remaining

            rem = geometric_tail_remaining(recent_deltas)
            if rem is None:
                return K
            k = K
            while k > 2 and k // 2 >= rem:
                k //= 2
            return k

        def dispatch_window(caps, kw):
            nonlocal sp, rp, latest
            exe = self._fused_aot(kw, caps, fa)
            t0 = time.perf_counter()
            if pool is None:
                out = exe(sp, rp, *host_carry(), fa)
                sp, rp = out[0], out[1]
                ent = {"out": out}
            else:

                def _run(prev=latest, s0=sp, r0=rp, c0=host_carry()):
                    if prev is None:
                        a, b, c = s0, r0, c0
                    else:
                        # chain on the previous window's DEVICE carries
                        a, b, c = prev.result()[:3]
                    out = exe(a, b, *c, fa)
                    return (
                        out[0], out[1], out[2:7], fetch_global(out[2:])
                    )

                latest = pool.submit(_run)
                ent = {"fut": latest}
            ent.update({
                "dispatch_s": time.perf_counter() - t0,
                "inflight": len(pending),
            })
            pending.append(ent)

        def retire_window():
            """Retire the oldest in-flight window: fetch its exit
            carries + round buffers, reconstruct the retired rounds'
            FrontierStats, and fold the exit frontier into the host
            copies.  Returns the window's exit status."""
            nonlocal total, below, iteration
            nonlocal gate_flags, dirty_l, s_chg, any_r
            ent = pending.popleft()
            t1 = time.perf_counter()
            if pool is None:
                fetched = fetch_global(ent["out"][2:])
            else:
                fetched = ent["fut"].result()[3]
            retire_s = time.perf_counter() - t1
            (gf, dl_, ms_, below_o, it_o, rdone_o, status_o,
             tb, rb, db, cb, bb) = fetched
            rdone, status = int(rdone_o), int(status_o)
            if rdone:
                DISPATCH_EVENTS.record_fused_window(rdone)
                it_r = iteration
                run_total = total
                for r in range(rdone):
                    tier = int(tb[r])
                    rows = int(rb[r])
                    changed = bool(cb[r])
                    if tier == 0:  # dense
                        it_r += unroll
                        new_total = _host_bit_total(bb[r])
                        delta = new_total - run_total
                        run_total = new_total
                    elif tier == 1:  # sparse
                        it_r += 1
                        delta = int(db[r])
                        run_total += delta
                    else:  # idle
                        it_r += 1
                        delta = 0
                    total = run_total
                    finish_round(
                        FrontierStats(
                            iteration=it_r,
                            tier=self._FUSED_TIERS[tier],
                            density=rows / max(self._sp_total_rows, 1),
                            rows_touched=rows,
                            total_rows=self._sp_total_rows,
                            derivations=delta,
                            overflow=False,
                            wall_s=(ent["dispatch_s"] + retire_s) / rdone,
                            dispatch_s=ent["dispatch_s"] / rdone,
                            retire_s=retire_s / rdone,
                            inflight=ent["inflight"],
                            rounds_in_window=rdone,
                        ),
                        changed,
                    )
            gate_flags = np.asarray(gf)
            dirty_l = np.asarray(dl_)
            s_chg = np.asarray(ms_)
            any_r = bool(dirty_l.any())
            below = int(below_o)
            iteration = int(it_o)
            return status, rdone

        def drain_to_host():
            """Drop any still-speculative windows and re-anchor the
            main-thread device state on the newest window's outputs —
            byte-identical to the oldest retired exit: windows behind
            a fallout replay the same decision and exit immediately,
            windows behind convergence retire only idle rounds, both
            pure passthrough on the state."""
            nonlocal sp, rp, latest
            pending.clear()
            if latest is not None:
                sp, rp = latest.result()[:2]
                latest = None

        def replay_host_round():
            """One round of the SYNCHRONOUS adaptive controller on the
            host frontier — the fallout escape.  Replays the full
            decision (the true capacity ladder may still fit a bigger
            sparse rung than the window had traced; otherwise this is
            the dense round the per-round controller would run, with
            its overflow flag)."""
            nonlocal sp, rp, total, below, iteration
            nonlocal gate_flags, dirty_l, s_chg, any_r
            t0 = time.perf_counter()
            prev_total = total
            rows_touched, density, measure, over = self._sparse_round_plan(
                cfg, s_chg, dirty_l, any_r
            )
            if density < cfg["density_threshold"]:
                below += 1
            else:
                below = 0
            want_sparse = (
                iteration > 0 and below >= cfg["hysteresis_rounds"]
            )
            use_sparse = want_sparse and measure is not None
            if rows_touched == 0:
                iteration += 1
                finish_round(
                    FrontierStats(
                        iteration=iteration,
                        tier="idle",
                        density=float(density),
                        rows_touched=rows_touched,
                        total_rows=self._sp_total_rows,
                        derivations=0,
                        overflow=False,
                        wall_s=time.perf_counter() - t0,
                    ),
                    False,
                )
            elif use_sparse:
                plan = self._sparse_round_args(measure, dirty_l)
                exe = self._sparse_aot(*plan["key"])
                DISPATCH_EVENTS.record_sparse()
                sp, rp, ch_d, delta_d, ms_d, ar_d, dl_d = exe(
                    sp, rp, self._sparse_args(plan)
                )
                ch, delta, s_chg, ar, dirty_l = fetch_global(
                    (ch_d, delta_d, ms_d, ar_d, dl_d)
                )
                any_r = bool(ar)
                total += int(delta)
                gate_flags = self._host_gate_flags(s_chg, any_r)
                iteration += 1
                finish_round(
                    FrontierStats(
                        iteration=iteration,
                        tier="sparse",
                        density=float(density),
                        rows_touched=rows_touched,
                        total_rows=self._sp_total_rows,
                        derivations=total - prev_total,
                        overflow=False,
                        wall_s=time.perf_counter() - t0,
                    ),
                    bool(ch),
                )
            else:
                dirty_dev = (
                    jnp.asarray(gate_flags),
                    jnp.asarray(dirty_l),
                    jnp.asarray(s_chg),
                )
                sp, rp, ch_d, bits_d, dirty_d = self._observe_jit(
                    sp, rp, dirty_dev, self._masks
                )
                DISPATCH_EVENTS.record_dense()
                ch, bits, (gf, dl_, ms_) = fetch_global(
                    (ch_d, bits_d, dirty_d)
                )
                total = _host_bit_total(bits)
                gate_flags = np.asarray(gf)
                dirty_l = np.asarray(dl_)
                s_chg = np.asarray(ms_)
                any_r = bool(dirty_l.any())
                iteration += unroll
                finish_round(
                    FrontierStats(
                        iteration=iteration,
                        tier="dense",
                        density=float(density),
                        rows_touched=rows_touched,
                        total_rows=self._sp_total_rows,
                        derivations=total - prev_total,
                        overflow=bool(
                            want_sparse and measure is None and over
                        ),
                        wall_s=time.perf_counter() - t0,
                    ),
                    bool(ch),
                )

        try:
            while True:
                if converged:
                    break  # drop still-speculative windows (idle no-ops)
                if pending:
                    if len(pending) < depth:
                        # speculative window: same capacities as the
                        # last sync measure (wrong guesses surface as
                        # deterministic fallout, never a changed round)
                        dispatch_window(cur_caps, pick_k())
                    else:
                        status, rdone = retire_window()
                        if status == 2:
                            drain_to_host()
                            replay_host_round()
                        elif status == 0 and rdone == 0:
                            # budget exhausted device-side: the window
                            # entered with iteration >= budget
                            drain_to_host()
                            break
                    continue
                if iteration >= budget:
                    break
                # ---- pipeline drained: the synchronous sync point ----
                cur_caps = pick_caps()
                dispatch_window(cur_caps, pick_k())
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
        if latest is not None:
            # pool mode: the current state is the newest window's
            # outputs (dropped speculative windows are passthrough)
            sp, rp = latest.result()[:2]
        return sp, rp, iteration, total, converged

    def saturate_observed(
        self,
        max_iters: int = 10_000,
        *,
        observer=None,
        state_observer=None,
        initial: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        allow_incomplete: bool = False,
        sparse_tail=None,
        frontier_observer=None,
        pipeline=None,
        fused_rounds=None,
    ) -> SaturationResult:
        """Fixed point with per-superstep observation — the observable
        analog of the reference's progress plane (pub-sub gossip consumed
        by ``worksteal/ProgressMessageHandler.java`` and the timed
        completeness snapshots of ``misc/ResultSnapshotter.java``).
        Dense rounds are PIPELINED by default (``pipeline.depth``
        rounds in flight, host folds retired from a queue — see
        ``__init__``), so per-round observation no longer costs a
        blocking host sync per superstep; the retired round sequence
        stays byte-identical to the synchronous loop.  :meth:`saturate`
        remains marginally faster (one fused while_loop program, no
        per-round observability at all).  On a mesh each superstep runs
        in the same shard_map structure as the fixed point.

        ``sparse_tail``: per-call override of the engine's adaptive
        sparse-tail config (see ``__init__``); when active (and the
        engine supports the tier) the adaptive controller replaces the
        plain observed loop — low-density rounds run the
        frontier-compacted step program and per-round
        :class:`~distel_tpu.runtime.instrumentation.FrontierStats`
        land in ``self.frontier_rounds`` (and ``frontier_observer``,
        when given).  The plain path emits per-round dense-tier
        ``FrontierStats`` too (density pinned 1.0 — no frontier fold
        is measured there), so frontier telemetry never goes dark when
        the sparse tail is off.

        ``pipeline``: per-call override of the engine's pipelined-
        observation config (``{"enable": ..., "depth": ...}``).  A
        ``state_observer`` forces the synchronous depth-1 loop — its
        contract hands over live, not-yet-donated round state.

        ``fused_rounds``: per-call override of the engine's
        device-resident fused-rounds config (``{"enable": ...,
        "rounds": K}``).  With K > 1 the round loop itself moves onto
        the device — up to K rounds of the adaptive controller per
        dispatch (see :meth:`_saturate_fused`) — surfacing to the host
        only at window edges; the retired round sequence stays
        byte-identical to the per-round controllers.  K = 1 routes the
        unchanged per-round path.  The fused tier needs the sparse
        tail's frontier machinery for its on-device round decision, so
        it engages only when the adaptive controller would (dense-only
        fused runs: set ``density_threshold: 0.0`` so the density test
        never picks sparse); a ``state_observer`` needs live per-round
        state and forces the per-round path."""
        self._ensure_observe_jit()
        if initial is None:
            sp, rp = self.initial_state()
        else:
            # embed_state always allocates fresh arrays, so donation in
            # _observe_jit cannot invalidate the caller's buffers
            sp, rp = self.embed_state(*initial)
        init_total = _host_bit_total(
            fetch_global(self.count_live_bits(sp, rp))
        )
        budget = _pad_up(max_iters, self.unroll)
        cfg = (
            self._sparse_cfg
            if sparse_tail is None
            else self._normalize_sparse_cfg(sparse_tail)
        )
        pcfg = (
            self._pipeline_cfg
            if pipeline is None
            else self._normalize_pipeline_cfg(pipeline)
        )
        pdepth = pcfg["depth"] if pcfg["enable"] else 1
        kcfg = (
            self._fused_cfg
            if fused_rounds is None
            else self._normalize_fused_cfg(fused_rounds)
        )
        fk = int(kcfg["rounds"]) if kcfg else 1
        if (
            fk > 1
            and cfg is not None
            and self._sparse_supported()
            and state_observer is None
        ):
            sp, rp, iteration, total, converged = self._saturate_fused(
                cfg, fk, sp, rp, init_total, budget, observer,
                frontier_observer, pipeline_depth=pdepth,
                adaptive=bool(kcfg.get("adaptive")),
            )
        elif cfg is not None and self._sparse_supported():
            sp, rp, iteration, total, converged = self._saturate_adaptive(
                cfg, sp, rp, init_total, budget, observer,
                state_observer, frontier_observer,
                pipeline_depth=pdepth,
            )
        else:
            self.frontier_rounds = []
            dirty_box = [self.initial_dirty()]

            def observe_step(s, r):
                s, r, ch, bits, dirty_box[0] = self._observe_jit(
                    s, r, dirty_box[0], self._masks
                )
                return s, r, ch, bits

            def round_stats(it, delta, changed, dispatch_s, retire_s,
                            inflight):
                # dense-tier telemetry from the plain path: no host
                # frontier fold runs here, so density reports the dense
                # sweep itself (every rule-table row re-evaluated) —
                # serve's frontier gauges stay live with the sparse
                # tail disabled
                st = FrontierStats(
                    iteration=it,
                    tier="dense",
                    density=1.0,
                    rows_touched=self._sp_total_rows,
                    total_rows=self._sp_total_rows,
                    derivations=delta,
                    wall_s=dispatch_s + retire_s,
                    dispatch_s=dispatch_s,
                    retire_s=retire_s,
                    inflight=inflight,
                )
                FRONTIER_EVENTS.record(st)
                self.frontier_rounds.append(st)
                if frontier_observer is not None:
                    frontier_observer(st)

            sp, rp, iteration, total, converged = observed_loop(
                observe_step,
                sp, rp, init_total, self.unroll, budget, observer,
                state_observer=state_observer,
                pipeline_depth=pdepth,
                round_stats=round_stats,
            )
        if not converged and not allow_incomplete:
            raise RuntimeError(
                f"saturation did not converge within {budget} iterations"
            )
        return SaturationResult(
            packed_s=sp,
            packed_r=rp,
            iterations=iteration,
            derivations=total - init_total,
            idx=self.idx,
            converged=converged,
            transposed=True,
        )

    def saturate(
        self,
        max_iters: int = 10_000,
        *,
        initial: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        allow_incomplete: bool = False,
        init_total: Optional[int] = None,
    ) -> SaturationResult:
        """``init_total``: callers that track derivation accounting
        themselves (the incremental fast path's alternation loop, which
        recounts under the full universe at the end) can pass a value —
        any value — to skip the eager live-bits round trip; the result's
        ``derivations`` is then only meaningful to that caller."""
        budget = _pad_up(max_iters, self.unroll)
        # the init count never comes from inside the donated run program
        # (see engine.fresh_init_total): fresh runs use the analytic
        # count, resumes pay one eager live-bits round trip
        if initial is None:
            sp0, rp0 = self.initial_state()
            init_total = fresh_init_total(self.idx)
        else:
            sp0, rp0 = self.embed_state(*initial)
            initial = None  # the embed copied it: free the old closure
            if init_total is None:
                init_total = _host_bit_total(
                    fetch_global(self.count_live_bits(sp0, rp0))
                )
        if self.mesh is None:
            # AOT path: the compiled executable comes from the program
            # registry (bucket mode) or this engine's per-budget cache —
            # either way the build cost lands in compile_stats.  The
            # dispatch lands in the process-global solo-vs-cohort tally
            # (the cohort path's N→1 dispatch collapse is asserted
            # against these counters, see core/cohort.py).
            COHORT_EVENTS.record_solo()
            out = self._run_aot(budget)(sp0, rp0, self._masks)
        else:
            out = self._run_jit(budget)(sp0, rp0, self._masks)
        return finish_device_run(
            out, self.idx, budget, allow_incomplete, transposed=True,
            init_total=init_total,
        )
