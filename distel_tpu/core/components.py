"""Connected-component partitioning + batched saturation.

The reference's weak-scaling evaluation multiplies a corpus into n
disjoint renamed copies (``samples/OntologyMultiplier.java:32-88``,
driven to ~10M axioms by ``scripts/run-all.sh:12-39``) and feeds the
union through the full distributed machinery.  A dense bit-packed state
is QUADRATIC in concepts, so the disjoint union hits a representational
wall long before 10M axioms (13M concepts ≈ 21 TB of packed S_T) — but
the union's closure is block-diagonal: concepts of different components
never subsume each other, links never cross components.

The TPU-native answer: **partition at index time, batch the fixed
point.**  ``partition_index`` finds connected components of the
axiom-interaction graph (concepts ∪ roles; ⊤/⊥ excluded — they belong
to every component and would glue the universe together).
``saturate_components`` groups components whose indexed tensors are
bit-identical after local re-indexing (the multiplied-corpus case:
isomorphic copies), compiles ONE engine per group, and runs the whole
group as a leading batch axis via ``jax.vmap`` over the engine's
superstep — every copy's fixed point is genuinely executed on-chip
(state, rule applications, convergence votes per copy; no result-level
deduplication), with per-group state [B, nc_c, wc_c] LINEAR in the
number of copies.

Soundness: EL+ saturation never derives a fact whose participants span
two components (every rule's premises share a concept or a link, links
are component-local, and role hierarchy/chains were unioned into the
component graph), so the per-component closures ARE the closure of the
union restricted to each block — asserted oracle-identical by
tests/test_components.py.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from distel_tpu.core.indexing import BOTTOM_ID, TOP_ID, IndexedOntology


@dataclass
class Component:
    """One block of the partition: a self-contained IndexedOntology plus
    the map from local concept ids (2, 3, ...; 0=⊥, 1=⊤) back to the
    global index."""

    idx: IndexedOntology
    global_concepts: np.ndarray  # [nc_local - 2] int64: local id-2 -> global

    def signature(self) -> bytes:
        """Isomorphism key: components with equal signatures have
        bit-identical indexed tensors and can share one compiled
        engine (the multiplied-corpus case)."""
        i = self.idx
        parts = [
            np.asarray(
                [i.n_concepts, i.n_roles, int(i.has_bottom_axioms)], np.int64
            ).tobytes()
        ]
        for a in (i.nf1, i.nf2, i.nf3, i.nf4, i.links, i.chain_pairs,
                  i.role_closure.astype(np.int8)):
            parts.append(np.ascontiguousarray(a).tobytes())
        return hashlib.sha256(b"|".join(parts)).digest()


def _group_slices(rank: np.ndarray, n_groups: int):
    """(order, starts): ``order`` sorts ids by group rank (stable);
    ``starts[g]:starts[g+1]`` slices group g's ids out of ``order``."""
    order = np.argsort(rank, kind="stable")
    counts = np.bincount(rank, minlength=n_groups)
    starts = np.zeros(n_groups + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    return order, starts


def partition_index(
    idx: IndexedOntology, *, with_names: bool = True
) -> List[Component]:
    """Split an indexed ontology into interaction components.

    Nodes are concepts and roles (roles offset by ``n_concepts``); every
    axiom row unions its participants; the role closure unions related
    roles.  ⊤ and ⊥ are excluded (every component re-creates its own ids
    0/1); concepts touched by no axiom form singleton components only if
    they are original classes (pure helper ids are dropped).
    ``with_names=False`` skips per-component name tables — the
    weak-scaling path over millions of concepts, where 65k dicts of
    name→id would dwarf the tensors."""
    n, r = idx.n_concepts, idx.n_roles
    roff = n

    def live_edges(*cols):
        """Pairwise edges between every two LIVE participants of each
        row.  A participant is a concept column ("c": ⊤/⊥ are NOT live —
        they belong to every component) or a role column ("r": always
        live, offset by ``roff``).  Pairwise-over-live matters: a
        domain-shaped row like nf4 (r, ⊤, b) must still tie b to r —
        chaining adjacent columns and dropping ⊤-edges afterwards would
        silently disconnect b from the component whose links fire it
        (observed: Disease split from its partonomy copy)."""
        prepped = []
        for arr, kind in cols:
            if kind == "r":
                prepped.append((arr + roff, np.ones(len(arr), bool)))
            else:
                prepped.append(
                    (arr, (arr != TOP_ID) & (arr != BOTTOM_ID))
                )
        out = []
        for i in range(len(prepped)):
            for j in range(i + 1, len(prepped)):
                u, ul = prepped[i]
                v, vl = prepped[j]
                m = ul & vl
                if m.any():
                    out.append(np.stack([u[m], v[m]], axis=1))
        return out

    edges: List[np.ndarray] = []
    if len(idx.nf1):
        edges += live_edges((idx.nf1[:, 0], "c"), (idx.nf1[:, 1], "c"))
    if len(idx.nf2):
        edges += live_edges(
            (idx.nf2[:, 0], "c"), (idx.nf2[:, 1], "c"), (idx.nf2[:, 2], "c")
        )
    if len(idx.nf3):
        edges += live_edges(
            (idx.nf3[:, 0], "c"),
            (idx.links[idx.nf3[:, 1], 0], "r"),
            (idx.links[idx.nf3[:, 1], 1], "c"),
        )
    if len(idx.nf4):
        edges += live_edges(
            (idx.nf4[:, 0], "r"), (idx.nf4[:, 1], "c"), (idx.nf4[:, 2], "c")
        )
    if len(idx.links):
        edges += live_edges(
            (idx.links[:, 0], "r"), (idx.links[:, 1], "c")
        )
    if len(idx.chain_pairs):
        # first-leg role ↔ second-leg link role ↔ TARGET link role: the
        # target matters when the produced link's filler is ⊤ (no
        # links-table edge ties its role to anything — a chain like
        # r∘r ⊑ t over ∃r.⊤ would otherwise leave t unassigned and the
        # remapped chain_pairs row indexing a dropped link)
        edges += live_edges(
            (idx.chain_pairs[:, 0], "r"),
            (idx.links[idx.chain_pairs[:, 1], 0], "r"),
            (idx.links[idx.chain_pairs[:, 2], 0], "r"),
        )
    hr, hc = np.nonzero(idx.role_closure)
    keep = hr != hc
    if keep.any():
        edges.append(np.stack([hr[keep] + roff, hc[keep] + roff], axis=1))

    total = n + r
    e = (
        np.concatenate(edges, axis=0).astype(np.int64)
        if edges
        else np.zeros((0, 2), np.int64)
    )

    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components

    adj = coo_matrix(
        (np.ones(len(e), np.int8), (e[:, 0], e[:, 1])), shape=(total, total)
    )
    _, labels = connected_components(adj, directed=False)

    # ---- per-row component labels (vectorized) -----------------------
    def row_labels(tab, concept_cols, role_cols=()):
        """Component label per row via its first participant that is not
        ⊤/⊥ (whose labels are singleton glue, not components).  Roles
        are never ⊤/⊥, so a role column is a safe base; rows whose every
        participant is ⊤/⊥ (e.g. ⊤ ⊑ ⊥) have no home component — the
        caller falls back to whole-corpus classification."""
        if tab is None or not len(tab):
            return None
        lab = np.full(len(tab), -1, np.int64)
        for j in role_cols:
            lab = labels[tab[:, j] + roff].astype(np.int64)
        for j in reversed(concept_cols):
            c = tab[:, j]
            live_c = (c != TOP_ID) & (c != BOTTOM_ID)
            lab = np.where(live_c, labels[c], lab)
        return lab

    row_labs = {
        "nf1": row_labels(idx.nf1, (0, 1)),
        "nf2": row_labels(idx.nf2, (0, 1, 2)),
        "nf3": (
            labels[idx.links[idx.nf3[:, 1], 0] + roff].astype(np.int64)
            if len(idx.nf3) else None
        ),
        "nf4": row_labels(idx.nf4, (1, 2), role_cols=(0,)),
    }
    link_lab = (
        labels[idx.links[:, 0] + roff].astype(np.int64)
        if len(idx.links) else None
    )
    cp_lab = (
        labels[idx.links[idx.chain_pairs[:, 1], 0] + roff].astype(np.int64)
        if len(idx.chain_pairs) else None
    )
    # GLOBAL rows make the partition unsound — classify unpartitioned
    # (identity map: local concept ids ARE global ones, ⊥=0/⊤=1):
    # * a row purely over ⊤/⊥ (label -1) belongs to every component;
    # * an nf1/nf3 row whose LHS is ⊤ fires on EVERY concept column
    #   (S_T[⊤] is all-ones), and one whose LHS is ⊥ fires on every
    #   unsatisfiable column — conclusions land in components that
    #   never see the row.  (nf2/nf4 stay sound when at least ONE
    #   operand is a live anchor premise confining the rule's columns
    #   to the anchor's component — nf4 additionally anchors through
    #   its role's union-find edges; an nf2 row with BOTH operands
    #   ⊤/⊥ has no anchor and fires globally, so it is flagged below
    #   regardless of its conclusion column.  The in-repo normalizer
    #   never emits such a row, but partition_index accepts any
    #   IndexedOntology — native loader, snapshots.)
    unsound = any(
        lab_vec is not None and (lab_vec < 0).any()
        for lab_vec in (row_labs["nf1"], row_labs["nf2"])
    )
    for tab in (idx.nf1, idx.nf3):
        if len(tab) and np.isin(tab[:, 0], (TOP_ID, BOTTOM_ID)).any():
            unsound = True
    if len(idx.nf2) and (
        np.isin(idx.nf2[:, 0], (TOP_ID, BOTTOM_ID))
        & np.isin(idx.nf2[:, 1], (TOP_ID, BOTTOM_ID))
    ).any():
        unsound = True
    if unsound:
        return [Component(idx=idx, global_concepts=np.arange(2, n))]

    # ---- component ranks in copy order (first concept appearance) ----
    live_c = np.ones(n, bool)
    live_c[[TOP_ID, BOTTOM_ID]] = False
    original = np.zeros(n, bool)
    if len(idx.original_classes):
        original[idx.original_classes] = True
    # a concept with axioms is always kept; an isolated one only if it
    # is an original named class (helpers with no axioms are padding)
    touched = np.zeros(total, bool)
    if len(e):
        touched[e[:, 0]] = True
        touched[e[:, 1]] = True
    for key, tab in (("nf1", idx.nf1), ("nf2", idx.nf2)):
        if row_labs[key] is not None:
            for j in range(tab.shape[1]):
                touched[tab[:, j]] = True
    keep_c = live_c & (touched[:n] | original)

    cids = np.flatnonzero(keep_c)
    clabs = labels[cids].astype(np.int64)
    uniq, first_pos, inv = np.unique(
        clabs, return_index=True, return_inverse=True
    )
    rank_of_uniq = np.argsort(np.argsort(first_pos, kind="stable"))
    crank = rank_of_uniq[inv]  # component rank per kept concept
    n_comp = len(uniq)

    if n_comp == 0:
        return []  # nothing but ⊤/⊥ and dropped helpers

    def rank_of(lab_vec):
        """Component rank per label (-1 = label has no kept component);
        vectorized via searchsorted over the sorted unique labels."""
        pos = np.searchsorted(uniq, lab_vec)
        pos = np.clip(pos, 0, len(uniq) - 1)
        ok = uniq[pos] == lab_vec
        return np.where(ok, rank_of_uniq[pos], -1)

    # local concept ids: 2 + position within component (global order)
    corder, cstarts = _group_slices(crank, n_comp)
    local_c = np.full(n, -1, np.int64)
    local_c[BOTTOM_ID] = BOTTOM_ID
    local_c[TOP_ID] = TOP_ID
    pos = np.empty(len(cids), np.int64)
    pos[corder] = np.arange(len(cids)) - np.repeat(
        cstarts[:-1], np.diff(cstarts)
    )
    local_c[cids] = 2 + pos

    # roles grouped by the same ranks (roles in no kept component drop)
    rids = np.arange(r)
    rrank_all = rank_of(labels[roff + rids].astype(np.int64))
    rids = rids[rrank_all >= 0]
    rrank = rrank_all[rrank_all >= 0]
    rorder, rstarts = _group_slices(rrank, n_comp)
    local_r = np.full(r, -1, np.int64)
    rpos = np.empty(len(rids), np.int64)
    rpos[rorder] = np.arange(len(rids)) - np.repeat(
        rstarts[:-1], np.diff(rstarts)
    )
    local_r[rids] = rpos

    # links grouped likewise
    if link_lab is not None:
        lrank = rank_of(link_lab)
        lkeep = lrank >= 0
        lids = np.flatnonzero(lkeep)
        lorder, lstarts = _group_slices(lrank[lkeep], n_comp)
        local_l = np.full(idx.n_links, -1, np.int64)
        lpos = np.empty(len(lids), np.int64)
        lpos[lorder] = np.arange(len(lids)) - np.repeat(
            lstarts[:-1], np.diff(lstarts)
        )
        local_l[lids] = lpos
    else:
        lids = np.zeros(0, np.int64)
        lorder = np.zeros(0, np.int64)
        lstarts = np.zeros(n_comp + 1, np.int64)
        local_l = np.zeros(0, np.int64)

    # rows grouped per table
    def table_slices(tab, lab_vec):
        if lab_vec is None:
            return None
        rrank_ = rank_of(lab_vec)
        kept = rrank_ >= 0
        ids = np.flatnonzero(kept)
        order, starts = _group_slices(rrank_[kept], n_comp)
        return tab, ids, order, starts

    tslices = {
        "nf1": table_slices(idx.nf1, row_labs["nf1"]),
        "nf2": table_slices(idx.nf2, row_labs["nf2"]),
        "nf3": table_slices(idx.nf3, row_labs["nf3"]),
        "nf4": table_slices(idx.nf4, row_labs["nf4"]),
        "cp": table_slices(idx.chain_pairs, cp_lab),
    }

    def comp_rows(key, k):
        ts = tslices[key]
        if ts is None:
            return None
        tab, ids, order, starts = ts
        return tab[ids[order[starts[k] : starts[k + 1]]]]

    out: List[Component] = []
    empty2 = np.zeros((0, 2), np.int32)
    empty3 = np.zeros((0, 3), np.int32)
    for k in range(n_comp):
        gcon = cids[corder[cstarts[k] : cstarts[k + 1]]]
        groles = rids[rorder[rstarts[k] : rstarts[k + 1]]]
        glinks = lids[lorder[lstarts[k] : lstarts[k + 1]]]

        def remap(tab, spec):
            if tab is None or not len(tab):
                return (empty3 if len(spec) == 3 else empty2)
            cols = []
            for j, kind in enumerate(spec):
                src = tab[:, j]
                cols.append(
                    local_c[src] if kind == "c"
                    else local_r[src] if kind == "r"
                    else local_l[src]
                )
            return np.stack(cols, axis=1).astype(np.int32)

        nf1 = remap(comp_rows("nf1", k), "cc")
        nf2 = remap(comp_rows("nf2", k), "ccc")
        nf3 = remap(comp_rows("nf3", k), "cl")
        nf4 = remap(comp_rows("nf4", k), "rcc")
        chain_pairs = remap(comp_rows("cp", k), "rll")
        links = (
            np.stack(
                [local_r[idx.links[glinks, 0]], local_c[idx.links[glinks, 1]]],
                axis=1,
            ).astype(np.int32)
            if len(glinks)
            else empty2
        )
        closure = (
            np.ascontiguousarray(idx.role_closure[np.ix_(groles, groles)])
            if len(groles)
            else np.zeros((1, 1), idx.role_closure.dtype)
        )
        has_bottom = bool(
            (len(nf1) and (nf1[:, 1] == BOTTOM_ID).any())
            or (len(nf2) and (nf2[:, 2] == BOTTOM_ID).any())
            or (len(nf4) and (nf4[:, 2] == BOTTOM_ID).any())
        )
        orig_local = 2 + np.flatnonzero(original[gcon])
        if with_names:
            names = (
                [idx.concept_names[BOTTOM_ID], idx.concept_names[TOP_ID]]
                + [idx.concept_names[g] for g in gcon]
            )
            rnames = [idx.role_names[g] for g in groles]
            cid_map = {nm: i for i, nm in enumerate(names)}
            rid_map = {nm: i for i, nm in enumerate(rnames)}
        else:
            names, rnames, cid_map, rid_map = [], [], {}, {}
        sub = IndexedOntology(
            n_concepts=2 + len(gcon),
            n_roles=max(len(groles), 1),
            concept_names=names,
            concept_ids=cid_map,
            role_names=rnames,
            role_ids=rid_map,
            nf1=nf1,
            nf2=nf2,
            nf3=nf3,
            nf4=nf4,
            links=links,
            chain_pairs=chain_pairs,
            role_closure=closure,
            original_classes=orig_local.astype(np.int32),
            has_bottom_axioms=has_bottom,
        )
        out.append(Component(idx=sub, global_concepts=gcon.astype(np.int64)))
    return out


def saturate_isomorphic(
    idx: IndexedOntology,
    batch: int,
    *,
    max_iters: int = 10_000,
    engine_kw: Optional[dict] = None,
    warm_timing: bool = False,
) -> dict:
    """Run ``batch`` copies of one component's fixed point as a vmapped
    batch — the execution half of the weak-scaling path, used when the
    grouping happened upstream (``frontend/partition_text.py`` discovers
    isomorphic copies at the text level, before any global index
    exists).  Same counters as one ``saturate_components`` group."""
    comps = [Component(idx=idx, global_concepts=np.zeros(0, np.int64))]
    agg = saturate_components(
        comps, max_iters=max_iters, engine_kw=engine_kw, _batch=batch,
        warm_timing=warm_timing,
    )
    return agg["groups"][0] | {"wall_s": agg["wall_s"]}


def saturate_components(
    components: List[Component],
    *,
    max_iters: int = 10_000,
    engine_kw: Optional[dict] = None,
    warm_timing: bool = False,
    _batch: Optional[int] = None,
) -> dict:
    """Classify every component, batching isomorphic ones through one
    compiled vmapped fixed point.  Returns aggregate counters plus the
    per-group breakdown; per-copy closures stay on device (the closure
    of copy i in a group is ``packed_s[i]``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from distel_tpu.core.engine import (
        _host_bit_total,
        fetch_global,
        fresh_init_total,
    )
    from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine

    groups: Dict[bytes, List[Component]] = {}
    for c in components:
        groups.setdefault(c.signature(), []).append(c)

    kw = dict(engine_kw or {})
    # vmapped steps: Pallas-under-vmap and traced-cond gating both
    # pessimize (vmapped cond becomes select = both branches execute);
    # component corpora are far below the gating threshold anyway
    kw.setdefault("use_pallas", False)
    kw.setdefault("gate_chunks", False)

    total_derivations = 0
    total_iters_max = 0
    total_warm = 0.0
    report: List[dict] = []
    wall0 = time.time()
    for comps in groups.values():
        rep = comps[0].idx
        B = _batch if _batch is not None else len(comps)
        if B == 1:
            # singleton group — including the unpartitioned fallback
            # where the "component" is the entire corpus: run the
            # engine's normal fixed point so the tuned auto posture
            # (Pallas kernels, chunk gating, memory tiers) applies.
            # The vmap pessimizations in ``kw`` exist only for true
            # batches, where traced-cond gating and Pallas-under-vmap
            # both pessimize.
            engine = RowPackedSaturationEngine(rep, **(engine_kw or {}))
            t0 = time.time()
            res = engine.saturate(max_iters)
            wall = time.time() - t0
            warm = None
            if warm_timing:
                t0 = time.time()
                res = engine.saturate(max_iters)
                warm = time.time() - t0
            it, derivs = res.iterations, int(res.derivations)
            del res
        else:
            it, derivs, wall, warm = _run_group(
                RowPackedSaturationEngine(rep, **kw),
                rep, B, max_iters, warm_timing,
            )
        total_derivations += int(derivs)
        total_iters_max = max(total_iters_max, int(it))
        entry = {
            "batch": B,
            "n_concepts_each": rep.n_concepts,
            "n_links_each": rep.n_links,
            "iterations": int(it),
            "derivations": int(derivs),
            "wall_s": round(wall, 3),
        }
        if warm is not None:
            total_warm += warm
            entry["wall_warm_s"] = round(warm, 3)
        report.append(entry)
    return {
        "n_components": len(components),
        "n_groups": len(groups),
        "derivations": int(total_derivations),
        "iterations_max": total_iters_max,
        "wall_s": round(time.time() - wall0, 3),
        "wall_warm_s": round(total_warm, 3),
        "groups": report,
    }


def _run_group(engine, rep, B, max_iters, warm_timing):
    """The vmapped-batch execution of one isomorphism group: B copies of
    ``rep``'s fixed point as a leading axis over the engine's superstep.
    Returns ``(iterations, derivations, wall_s, warm_s_or_None)``."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from distel_tpu.core.engine import (
        _host_bit_total,
        fetch_global,
        fresh_init_total,
    )

    budget = max_iters - max_iters % engine.unroll

    def run(spB, rpB, masks):
        vstep = jax.vmap(
            lambda sp, rp, dirty: engine._step(sp, rp, masks, None, dirty)
        )

        def cond(st):
            return st[3] & (st[2] < budget)

        def body(st):
            spB, rpB, it, _, dirtyB = st
            ch = jnp.zeros((spB.shape[0],), bool)
            for _ in range(engine.unroll):
                spB, rpB, c, dirtyB = vstep(spB, rpB, dirtyB)
                ch = ch | c
            return (spB, rpB, it + engine.unroll, jnp.any(ch), dirtyB)

        spB, rpB, it, changed, _ = lax.while_loop(
            cond,
            body,
            (
                spB,
                rpB,
                jnp.asarray(0, jnp.int32),
                jnp.asarray(True),
                jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        x, (spB.shape[0],) + x.shape
                    ),
                    engine.initial_dirty(),
                ),
            ),
        )
        bits = jax.vmap(engine._live_bits)(spB, rpB)
        return spB, rpB, it, changed, bits

    runj = jax.jit(run, donate_argnums=(0, 1))
    zero = jnp.asarray(0, jnp.uint32)

    def batch_init():
        sp0, rp0 = engine.initial_state()
        return (
            jnp.broadcast_to(sp0, (B,) + sp0.shape) | zero,
            jnp.broadcast_to(rp0, (B,) + rp0.shape) | zero,
        )

    t0 = time.time()
    spB, rpB, it, changed, bits = runj(*batch_init(), engine._masks)
    it, changed, bits_host = fetch_global((it, changed, bits))
    wall = time.time() - t0  # includes the one-time jit compile
    if bool(changed):
        # mirror the monolithic engines' contract
        # (engine.finish_device_run): never report a truncated
        # closure as a result
        raise RuntimeError(
            f"component group (B={B}, nc={rep.n_concepts}) did not "
            f"converge within {budget} iterations"
        )
    del spB, rpB
    warm = None
    if warm_timing:
        # opt-in second run (the weak-scaling bench's steady-state
        # wall); library callers pay for ONE fixed point
        t0 = time.time()
        spB, rpB, it2, ch2, bits2 = runj(*batch_init(), engine._masks)
        fetch_global((it2, ch2, bits2))
        warm = time.time() - t0
    derivs = _host_bit_total(bits_host) - B * fresh_init_total(rep)
    return int(it), int(derivs), wall, warm
