"""Core: indexing, the CPU oracle, and the TPU saturation engine."""
