"""DRed-style retraction: delete told rows, overdelete, re-derive.

The reference has no retraction path at all — deletion means wiping the
Redis stores and re-running the full classification
(``scripts/traffic-data-load-classify.sh``).  This module is the
delete-and-rederive core of the retraction subsystem (ISSUE 16), after
Gupta/Mumick/Subrahmanian's *Maintaining Views Incrementally* (DRed):

1. **Locate** the told rows a previously-ingested axiom text produced.
   ``IncrementalClassifier`` appends each batch's normalized rows onto
   the accumulated corpus in order, so every ingest owns one CONTIGUOUS
   span per NF family — provenance is six ``(start, end)`` pairs, and
   contiguity survives earlier retractions (later spans shift down).
2. **Overdelete**: compute the set of concept rows whose derived bits
   could possibly be supported by the dead rows.  We seed with the
   concepts the dead rows touch (the standard DRed overcount — no
   per-bit provenance is kept) and close under the one cross-row data
   flow of CR1–CR6: every rule that moves a bit between rows moves it
   from a link's FILLER row to the link's HOLDER row (CR4 existential
   discharge, CR5/⊥ propagation, CR6 chain composition), so
   ``x`` is affected whenever ``R[x, l]`` holds and ``filler(l)`` is
   affected.  Whole S/R rows of affected concepts are cleared.
3. **Re-derive**: saturate from the surviving told axioms with the
   cleared state as warm start.  Monotone EL+ makes this sound: cleared
   rows re-derive exactly the survivor-supported closure, and bits in
   unaffected rows were survivor-derivable by construction of the
   overcount.  The caller runs the existing rebuild machinery
   (``IncrementalClassifier._full_rebuild``), which under shape buckets
   is a program-registry hit — a small repair compiles nothing.

Provenance is *enough*, not exact: a retraction is REFUSED (a)
when the text was never ingested (or already retracted), (b) when a
normalization gensym/genrole minted by the dying batch is shared with a
surviving batch (the normalizer memo re-uses gensym names without
re-emitting their defining rows, so the defining rows live only in the
minting batch), or (c) when range-elimination machinery is active
(range retrofits emit rows for OLD axioms into LATER batches, breaking
the span-ownership invariant).  Conservative refusal keeps the repair
byte-identical to a from-scratch classify of the survivors.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from distel_tpu.core.indexing import atom_key

#: the NormalizedOntology row families a span covers, in merge order
NF_FAMILIES = ("nf1", "nf2", "nf3", "nf4", "nf5", "nf6")

GENSYM_PREFIXES = ("distel:gensym#", "distel:genrole#")


class RetractionError(ValueError):
    """Base of every refusal the retraction engine can raise; the serve
    plane maps subclasses onto HTTP statuses (404 unknown / 409
    entangled)."""


class UnknownRetraction(RetractionError):
    """The text was never ingested into this classifier (or was already
    retracted) — there are no rows to remove."""


class EntangledRetraction(RetractionError):
    """The batch's rows cannot be removed without breaking surviving
    batches: a shared normalization gensym or active range machinery
    ties them together.  Retracting would silently change survivors'
    semantics, so the engine refuses instead."""


# ------------------------------------------------------------ provenance


def find_ingest(ingests: List[dict], text: str) -> int:
    """Index of the MOST RECENT live ingest of ``text`` (the natural
    reading of "retract what I added"; duplicate ingests retract one at
    a time, newest first)."""
    for k in range(len(ingests) - 1, -1, -1):
        rec = ingests[k]
        if not rec.get("retracted") and rec.get("text") == text:
            return k
    raise UnknownRetraction(
        "text was never ingested (or was already retracted) — "
        "retraction needs the exact text of a live prior add"
    )


def dead_rows(accumulated, spans: Dict[str, Tuple[int, int]]) -> Dict[str, list]:
    """The told rows a span set owns, by NF family (the rows that die)."""
    out = {}
    for fam in NF_FAMILIES:
        start, end = spans[fam]
        out[fam] = list(getattr(accumulated, fam)[start:end])
    return out


def _row_atoms(fam: str, row):
    if fam == "nf1":
        return row
    if fam == "nf2":
        ops, b = row
        return (*ops, b)
    if fam == "nf3":
        a, _r, b = row
        return (a, b)
    if fam == "nf4":
        _r, a, b = row
        return (a, b)
    return ()


def _row_roles(fam: str, row):
    if fam == "nf3":
        return (row[1],)
    if fam == "nf4":
        return (row[0],)
    if fam in ("nf5", "nf6"):
        return tuple(row)
    return ()


def _gensym_names(rows_by_family: Dict[str, list]) -> set:
    names = set()
    for fam, rows in rows_by_family.items():
        for row in rows:
            for a in _row_atoms(fam, row):
                k = atom_key(a)
                if k.startswith(GENSYM_PREFIXES):
                    names.add(k)
            for r in _row_roles(fam, row):
                if r.iri.startswith(GENSYM_PREFIXES):
                    names.add(r.iri)
    return names


def check_entanglement(
    accumulated,
    spans: Dict[str, Tuple[int, int]],
    dead: Dict[str, list],
) -> None:
    """Refuse when a gensym/genrole minted by the dying rows also
    appears in surviving rows: the normalizer memo re-uses gensym names
    across batches WITHOUT re-emitting their defining rows, so removing
    the minting batch would leave survivors referencing an undefined
    name (silent incompleteness).  Conservative by design — shared
    names are rare outside pathological duplicate corpora."""
    dead_syms = _gensym_names(dead)
    if not dead_syms:
        return
    survivors: Dict[str, list] = {}
    for fam in NF_FAMILIES:
        start, end = spans[fam]
        rows = getattr(accumulated, fam)
        survivors[fam] = list(rows[:start]) + list(rows[end:])
    shared = dead_syms & _gensym_names(survivors)
    if shared:
        raise EntangledRetraction(
            "retraction refused: normalization gensyms "
            f"{sorted(shared)[:5]} are shared with surviving batches "
            "(the defining rows live only in the batch being retracted)"
        )


def remove_spans(
    accumulated, ingests: List[dict], k: int
) -> Dict[str, list]:
    """Delete ingest ``k``'s rows from the accumulated corpus, shift
    every LATER ingest's spans down, and mark ``k`` retracted.  Returns
    the removed rows by family.  Caller has already run every refusal
    check — this mutates."""
    spans = ingests[k]["spans"]
    dead = {}
    for fam in NF_FAMILIES:
        start, end = spans[fam]
        rows = getattr(accumulated, fam)
        dead[fam] = list(rows[start:end])
        del rows[start:end]
        removed = end - start
        if removed:
            for later in ingests[k + 1:]:
                if later.get("retracted"):
                    continue
                s2, e2 = later["spans"][fam]
                later["spans"][fam] = (s2 - removed, e2 - removed)
    ingests[k]["retracted"] = True
    ingests[k]["spans"] = None
    dead_syms = _gensym_names(dead)
    for name in dead_syms:
        accumulated.gensyms.pop(name, None)
    return dead


def purge_normalizer_cache(cache: Dict[str, str], dead: Dict[str, list]) -> int:
    """Drop memo entries whose gensym died with the retracted rows, so
    a later re-add of the same text mints a FRESH gensym and re-emits
    its defining rows (the memo contract is "the rows live in the
    corpus the cache came from" — no longer true for dead names).
    Re-use of a dead name's concept id by a future mint is sound: the
    repair cleared the dead concept's S/R row back to the fresh-concept
    init."""
    dead_syms = _gensym_names(dead)
    if not dead_syms:
        return 0
    doomed = [key for key, name in cache.items() if name in dead_syms]
    for key in doomed:
        del cache[key]
    return len(doomed)


# ----------------------------------------------------------- overdeletion


def affected_concepts(idx, s, r, dead: Dict[str, list]) -> np.ndarray:
    """Boolean mask (over the x-major rows of ``s``) of concepts whose
    derived bits could be supported by the dead rows — the DRed
    overdeletion set.

    Seeds per family (``idx``/``s``/``r`` are the PRE-removal index and
    closure — the overcount is over what the old closure could have
    derived):

    - nf1 ``a ⊑ b``: every ``x`` with ``S[x, a]`` (CR1 fired there).
    - nf2 ``a1 ⊓ … ⊓ an ⊑ b``: every ``x`` with ``S[x, a1] ∧ S[x, a2]``
      — every binarized intermediate (shared aux concepts) and the
      final bit all require at least the first two conjuncts.
    - nf3 ``a ⊑ ∃r.b``: every ``x`` with ``S[x, a]`` (CR3 minted links
      there).
    - nf4 ``∃r.a ⊑ b``: every ``x`` holding a link whose role ⊑* r
      (CR4 could have discharged through it).
    - nf5/nf6 (role hierarchy / chains): every ``x`` holding any link —
      coarse, but role-axiom retraction reshapes the whole role closure.

    Plus every concept the dead rows mention (their own rows go back to
    the fresh-concept init — keeps dead gensym/concept ids cleanly
    reusable).  Then the fixpoint: ``x`` is affected whenever
    ``R[x, l]`` with ``filler(l)`` affected — the only cross-row data
    flow in CR1–CR6 (CR4/CR5/CR6 all move bits filler → holder)."""
    s = np.asarray(s, bool)
    r = np.asarray(r, bool)
    nx = s.shape[0]
    aff = np.zeros(nx, bool)

    def cid(atom) -> Optional[int]:
        return idx.concept_ids.get(atom_key(atom))

    for a, _b in dead["nf1"]:
        c = cid(a)
        if c is not None and c < s.shape[1]:
            aff |= s[:, c]
    for ops, _b in dead["nf2"]:
        c0, c1 = cid(ops[0]), cid(ops[1])
        if c0 is not None and c1 is not None:
            aff |= s[:, c0] & s[:, c1]
    for a, _r, _b in dead["nf3"]:
        c = cid(a)
        if c is not None and c < s.shape[1]:
            aff |= s[:, c]
    n_links = len(idx.links)
    rl = r[:, :n_links] if n_links else r[:, :0]
    for ro, _a, _b in dead["nf4"]:
        rid = idx.role_ids.get(ro.iri)
        if rid is None or not n_links:
            continue
        covered = idx.role_closure[idx.links[:, 0], rid].astype(bool)
        if covered.any():
            aff |= rl[:, covered].any(axis=1)
    if (dead["nf5"] or dead["nf6"]) and n_links:
        aff |= rl.any(axis=1)
    for fam in NF_FAMILIES:
        for row in dead[fam]:
            for a in _row_atoms(fam, row):
                c = cid(a)
                if c is not None and c < nx:
                    aff[c] = True
    if n_links:
        fillers = idx.links[:, 1]
        while True:
            hot = aff[fillers]
            if not hot.any():
                break
            grew = rl[:, hot].any(axis=1) & ~aff
            if not grew.any():
                break
            aff |= grew
    return aff


def clear_rows(
    s, r, aff: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Cleared copies of the closure: affected concepts' S and R rows
    zeroed (the saturation engine's embed re-ORs the ``S(x)={x,⊤}``
    init, so a cleared row warm-starts exactly like a fresh concept)."""
    s2 = np.array(s, dtype=bool, copy=True)
    r2 = np.array(r, dtype=bool, copy=True)
    s2[aff, :] = False
    r2[aff, :] = False
    return s2, r2
