"""AOT artifact farm: a distributable compiled-program registry.

The PROGRAMS registry (``core/program_cache.py``) and the persistent
XLA compile cache amortize compilation across *resumes of one host* —
but every fresh process (a respawned fleet replica, an autoscaled
worker, a cold CI runner) still pays the full trace+compile roster
before its first request.  The reference system never had this wall:
DistEL's Redis-side Lua "programs" are source-shipped and loaded in
milliseconds.  Pushing the EL Envelope's fixed rule set means the
canonical program roster is finite and enumerable ahead of time, so a
one-shot **compile farm** (``cli farm-build``) can pre-bake it and
ship the results to every serving process.

Two artifact tiers, recorded per-entry in the manifest:

* ``"exe"`` — the compiled executable itself, serialized through
  ``jax.experimental.serialize_executable`` (the jax AOT export path).
  A consumer deserializes and serves it with ZERO trace/lower/compile:
  ``CompileStats.compile_s == 0.0`` on the first request.
* ``"hlo-cache"`` — for program kinds the pin cannot serialize, the
  farm ships the byte-identical persistent-compile-cache entries
  instead (same keying).  The consumer still pays trace+lower, but the
  XLA pass becomes a disk-cache deserialization.

Keying: an artifact id is a sha256 over the PROGRAMS registry key —
``(bucket_signature, program_kind, rung/capacity extras...)`` — plus
the runtime environment ``(backend, jax_version, n_devices)``.  The
bucket signature already folds every structural determinant of the
traced program (shapes, rule-group presence, mesh axis), so two
processes that would build the same program resolve to the same
artifact, and NOTHING else does.  The manifest is checksummed per-file
and as a whole; a corrupt entry, or a manifest baked on a different
backend / jax pin / device count, is rejected LOUDLY (a warning + a
counted rejection) and the consumer falls back to compiling — stale
artifacts can cost time, never correctness.

Artifact files are pickles (payload bytes + in/out tree defs).  Load
is opt-in (``artifacts.dir`` / ``--artifacts-dir``) and every file's
sha256 is verified against the checksummed manifest before
unpickling — the manifest is the trust root; point it only at farm
output you produced.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
import warnings
from typing import Dict, Optional, Tuple

MANIFEST_NAME = "manifest.json"
FORMAT_VERSION = 1

#: manifest fields covered by the whole-manifest digest, in canonical
#: order (everything except the digest itself)
_DIGEST_FIELDS = (
    "format", "backend", "jax_version", "n_devices", "artifacts",
    "hlo_cache",
)


class ArtifactError(RuntimeError):
    """A farm directory that cannot be trusted: unreadable/corrupt
    manifest, checksum mismatch, or an environment mismatch under
    ``require=True``."""


class ArtifactAggregate:
    """Process-global artifact-event tallies (thread-safe), one per
    process like the dispatch/frontier aggregates in
    ``runtime/instrumentation.py``.  The serve plane renders them as
    the ``distel_artifact_*`` counter families; the farm smoke and the
    cross-process tests assert on THESE — counted hits, never
    wall-clock inference."""

    _FIELDS = (
        "exe_hits", "hlo_hits", "misses", "rejected", "serialized",
        "unserializable",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            for f in self._FIELDS:
                setattr(self, f, 0)

    def record(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {f: getattr(self, f) for f in self._FIELDS}


#: THE process-global tally (one per process, like PROGRAMS)
ARTIFACT_EVENTS = ArtifactAggregate()


def runtime_env() -> Dict[str, object]:
    """The environment half of the artifact key: a serialized
    executable embeds its backend's device assignment and the
    serializer's wire format follows the jax pin, so artifacts are
    valid only under the exact ``(backend, jax_version, n_devices)``
    they were baked with."""
    import jax

    return {
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "n_devices": jax.device_count(),
    }


def artifact_id(key: Tuple) -> str:
    """Stable id from the PROGRAMS registry key.  ``repr`` of the key
    tuple is deterministic here: keys are built from str/int/tuple
    structural metadata only (the same property ``signature_of``
    already leans on)."""
    return hashlib.sha256(repr(key).encode()).hexdigest()[:32]


def describe_key(key: Tuple) -> Dict[str, object]:
    """Human-greppable manifest fields best-effort extracted from a
    registry key ``(bucket_signature, program_kind, extras...)`` —
    reporting only; the id hashes the full key."""
    desc: Dict[str, object] = {"key": repr(key)}
    if isinstance(key, tuple) and key:
        if isinstance(key[0], str):
            desc["bucket_signature"] = key[0]
        if len(key) > 1 and isinstance(key[1], str):
            desc["kind"] = key[1]
            if key[1] == "fused" and len(key) > 2 and isinstance(
                key[2], tuple
            ) and key[2]:
                desc["fused_k"] = int(key[2][0])
            if key[1] == "sparse" and len(key) > 2 and isinstance(
                key[2], tuple
            ):
                desc["rung"] = list(map(int, key[2]))
            if key[1] == "cohort_run" and len(key) > 3:
                desc["rung"] = int(key[3])
    return desc


def _sha256_bytes(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _manifest_digest(doc: dict) -> str:
    body = json.dumps(
        {f: doc.get(f) for f in _DIGEST_FIELDS}, sort_keys=True
    )
    return _sha256_bytes(body.encode())


def _serialize_exe(exe) -> bytes:
    """Compiled executable -> artifact file bytes.  Raises whatever
    the pin raises for unserializable kinds — the caller downgrades
    those keys to the hlo-cache tier."""
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(exe)
    return pickle.dumps(
        {"payload": payload, "in_tree": in_tree, "out_tree": out_tree},
        protocol=4,
    )


def _deserialize_exe(blob: bytes):
    from jax.experimental import serialize_executable as se

    doc = pickle.loads(blob)
    return se.deserialize_and_load(
        doc["payload"], doc["in_tree"], doc["out_tree"]
    )


class ArtifactStore:
    """One farm directory: ``manifest.json`` + ``exe/<id>.bin`` +
    ``xla/`` (shipped persistent-compile-cache entries).

    Read side (a consuming replica): :meth:`load` under the PROGRAMS
    per-key build lock — deserialize on a manifest hit, reject loudly
    on corruption.  Write side (``cli farm-build``): :meth:`save` as
    the registry's post-build sink, :meth:`adopt_hlo_cache` +
    :meth:`flush` at the end of the bake.  Thread-safe: warmup builds
    the roster on a thread pool."""

    def __init__(self, root: str, writable: bool = False):
        self.root = os.path.abspath(root)
        self.writable = bool(writable)
        self._lock = threading.Lock()
        self.written = 0  # artifacts newly serialized by THIS process
        self._warned: set = set()
        mpath = os.path.join(self.root, MANIFEST_NAME)
        if os.path.exists(mpath):
            try:
                with open(mpath, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise ArtifactError(
                    f"unreadable artifact manifest {mpath}: {e}"
                )
            if doc.get("format") != FORMAT_VERSION:
                raise ArtifactError(
                    f"artifact manifest format {doc.get('format')!r} "
                    f"!= supported {FORMAT_VERSION}"
                )
            if _manifest_digest(doc) != doc.get("checksum"):
                raise ArtifactError(
                    f"artifact manifest checksum mismatch in {mpath} "
                    "(tampered or torn write)"
                )
            self._doc = doc
            self._dirty = False
        elif writable:
            os.makedirs(os.path.join(self.root, "exe"), exist_ok=True)
            self._doc = {
                "format": FORMAT_VERSION,
                **runtime_env(),
                "artifacts": {},
                "hlo_cache": {},
            }
            self._dirty = True
        else:
            raise ArtifactError(
                f"no artifact manifest at {mpath} (run `cli farm-build` "
                "first, or fix --artifacts-dir)"
            )

    # ------------------------------------------------------------ env

    def env_mismatch(self) -> Optional[str]:
        """None when this process can consume the store; else the
        human reason it must not (the caller warns and falls back to
        compiling)."""
        env = runtime_env()
        for k, v in env.items():
            if self._doc.get(k) != v:
                return (
                    f"artifact manifest {k}={self._doc.get(k)!r} != "
                    f"this process's {v!r}"
                )
        return None

    # ---------------------------------------------------------- read

    def covers(self, key: Tuple) -> Optional[str]:
        """The manifest tier for a registry key (``"exe"`` /
        ``"hlo-cache"``) or None."""
        ent = self._doc["artifacts"].get(artifact_id(key))
        return ent["tier"] if ent else None

    def load(self, key: Tuple):
        """Deserialize the artifact for ``key``; None on a miss or a
        (counted, warned) rejection.  An ``hlo-cache``-tier entry also
        returns None — the build that follows is served by the shipped
        persistent-cache entries — but counts as an hlo hit so the
        bench and the smoke can attribute the tier."""
        ent = self._doc["artifacts"].get(artifact_id(key))
        if ent is None:
            ARTIFACT_EVENTS.record("misses")
            return None
        if ent["tier"] == "hlo-cache":
            ARTIFACT_EVENTS.record("hlo_hits")
            return None
        path = os.path.join(self.root, ent["file"])
        try:
            with open(path, "rb") as f:
                blob = f.read()
            if _sha256_bytes(blob) != ent["sha256"]:
                raise ArtifactError(
                    f"artifact {ent['file']} sha256 mismatch"
                )
            exe = _deserialize_exe(blob)
        except Exception as e:
            ARTIFACT_EVENTS.record("rejected")
            self._warn_once(
                ent["file"],
                f"rejecting artifact {ent['file']} for key "
                f"{ent.get('kind', '?')}: {e}; falling back to compile",
            )
            return None
        ARTIFACT_EVENTS.record("exe_hits")
        return exe

    def _warn_once(self, token: str, msg: str) -> None:
        with self._lock:
            if token in self._warned:
                return
            self._warned.add(token)
        warnings.warn(msg, RuntimeWarning, stacklevel=3)

    # --------------------------------------------------------- write

    def save(self, key: Tuple, exe) -> str:
        """Registry post-build sink: serialize ``exe`` under ``key``
        (or record the key at the hlo-cache tier when the pin can't
        serialize this executable kind).  Returns the recorded tier;
        idempotent — a key already in the manifest writes nothing."""
        if not self.writable:
            return self._doc["artifacts"].get(
                artifact_id(key), {}
            ).get("tier", "")
        aid = artifact_id(key)
        with self._lock:
            ent = self._doc["artifacts"].get(aid)
        if ent is not None:
            return ent["tier"]
        t0 = time.perf_counter()
        try:
            blob = _serialize_exe(exe)
        except Exception as e:
            ARTIFACT_EVENTS.record("unserializable")
            ent = {
                **describe_key(key),
                "tier": "hlo-cache",
                "file": None,
                "reason": f"{type(e).__name__}: {e}",
            }
            with self._lock:
                self._doc["artifacts"].setdefault(aid, ent)
                self._dirty = True
            return "hlo-cache"
        rel = os.path.join("exe", f"{aid}.bin")
        path = os.path.join(self.root, rel)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        ent = {
            **describe_key(key),
            "tier": "exe",
            "file": rel,
            "sha256": _sha256_bytes(blob),
            "bytes": len(blob),
            "serialize_s": round(time.perf_counter() - t0, 4),
        }
        with self._lock:
            if aid not in self._doc["artifacts"]:
                self._doc["artifacts"][aid] = ent
                self.written += 1
                self._dirty = True
        ARTIFACT_EVENTS.record("serialized")
        return "exe"

    def adopt_hlo_cache(self, cache_dir: str) -> int:
        """Record (and checksum) the persistent-compile-cache entries
        the bake produced under ``cache_dir`` — ``farm-build`` points
        the jax cache INSIDE the farm, so these files ARE the
        byte-identical entries a consumer's compile would produce.
        Returns the number of newly recorded files."""
        new = 0
        if not os.path.isdir(cache_dir):
            return 0
        for name in sorted(os.listdir(cache_dir)):
            path = os.path.join(cache_dir, name)
            if not os.path.isfile(path):
                continue
            with self._lock:
                if name in self._doc["hlo_cache"]:
                    continue
                self._doc["hlo_cache"][name] = {
                    "sha256": _sha256_file(path),
                    "bytes": os.path.getsize(path),
                }
                self._dirty = True
            new += 1
        return new

    def flush(self) -> bool:
        """Write the manifest iff something changed (the idempotence
        contract: a second farm-build over the same roster writes
        nothing).  Returns whether a write happened."""
        with self._lock:
            if not self._dirty:
                return False
            doc = dict(self._doc)
            doc["checksum"] = _manifest_digest(doc)
            path = os.path.join(self.root, MANIFEST_NAME)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
            self._doc = doc
            self._dirty = False
            return True

    # ----------------------------------------------------- hlo ship

    def install_hlo_cache(self, dest_dir: str) -> int:
        """Copy the shipped persistent-cache entries into the
        consumer's live compile-cache directory (checksum-verified;
        corrupt files are skipped loudly).  Returns files copied."""
        src_dir = os.path.join(self.root, "xla")
        names = self._doc.get("hlo_cache") or {}
        if not names or not os.path.isdir(src_dir):
            return 0
        os.makedirs(dest_dir, exist_ok=True)
        copied = 0
        for name, meta in names.items():
            dst = os.path.join(dest_dir, name)
            if os.path.exists(dst):
                continue
            src = os.path.join(src_dir, name)
            try:
                if _sha256_file(src) != meta["sha256"]:
                    raise ArtifactError("sha256 mismatch")
                tmp = f"{dst}.tmp.{os.getpid()}"
                with open(src, "rb") as fi, open(tmp, "wb") as fo:
                    fo.write(fi.read())
                os.replace(tmp, dst)
                copied += 1
            except (OSError, ArtifactError) as e:
                ARTIFACT_EVENTS.record("rejected")
                self._warn_once(
                    name,
                    f"rejecting shipped compile-cache entry {name}: "
                    f"{e}; that program will compile from scratch",
                )
        return copied

    def stats(self) -> dict:
        arts = self._doc["artifacts"]
        return {
            "root": self.root,
            "artifacts": len(arts),
            "exe": sum(1 for a in arts.values() if a["tier"] == "exe"),
            "hlo_cache_keys": sum(
                1 for a in arts.values() if a["tier"] == "hlo-cache"
            ),
            "hlo_cache_files": len(self._doc.get("hlo_cache") or {}),
            "bytes": sum(a.get("bytes") or 0 for a in arts.values()),
            "written": self.written,
        }


# ------------------------------------------------------------ install

_ACTIVE: Optional[ArtifactStore] = None
_ACTIVE_LOCK = threading.Lock()


def active_store() -> Optional[ArtifactStore]:
    return _ACTIVE


def install(root: str, *, require: bool = False) -> dict:
    """Attach a farm directory to THE process-global PROGRAMS registry
    so every bucketed program build first consults the artifact store.
    Validation happens here, once: a missing/corrupt manifest or an
    environment mismatch warns loudly (raises under ``require=True``),
    counts a rejection, and leaves the process compiling as before.
    Returns the install record serve stamps into its startup line."""
    global _ACTIVE
    from distel_tpu.core.program_cache import PROGRAMS

    try:
        store = ArtifactStore(root, writable=False)
    except ArtifactError as e:
        ARTIFACT_EVENTS.record("rejected")
        if require:
            raise
        warnings.warn(
            f"artifact farm NOT installed: {e}", RuntimeWarning,
            stacklevel=2,
        )
        return {"installed": False, "root": root, "reason": str(e)}
    reason = store.env_mismatch()
    if reason is not None:
        ARTIFACT_EVENTS.record("rejected")
        if require:
            raise ArtifactError(reason)
        warnings.warn(
            f"artifact farm NOT installed: {reason}; every program "
            "will compile as if no farm existed",
            RuntimeWarning, stacklevel=2,
        )
        return {"installed": False, "root": root, "reason": reason}
    # shipped hlo-cache entries land in the live jax cache dir before
    # any build can want them
    copied = 0
    try:
        import jax

        dest = jax.config.jax_compilation_cache_dir
        if dest:
            copied = store.install_hlo_cache(os.path.expanduser(dest))
    except Exception as e:  # cache ship is an optimization tier
        warnings.warn(
            f"could not install shipped compile-cache entries: {e}",
            RuntimeWarning, stacklevel=2,
        )
    with _ACTIVE_LOCK:
        _ACTIVE = store
        PROGRAMS.artifact_source = store
    return {
        "installed": True,
        **store.stats(),
        "hlo_files_copied": copied,
    }


def install_from_config(config) -> Optional[dict]:
    """The entry-point hook: install ``config.artifacts_dir`` when set
    (serve/fleet/classify/warmup all funnel through this)."""
    root = getattr(config, "artifacts_dir", None)
    if not root:
        return None
    return install(
        root, require=bool(getattr(config, "artifacts_require", False))
    )


def uninstall() -> None:
    """Detach the active store (tests)."""
    global _ACTIVE
    from distel_tpu.core.program_cache import PROGRAMS

    with _ACTIVE_LOCK:
        _ACTIVE = None
        PROGRAMS.artifact_source = None
