"""Shape-bucket ladder + in-process compiled-program registry.

The cold-start compile problem (BENCH_r03: 66.3 s cold vs 6.56 s warm at
64k; compile300k_512_cold_r5.log: 148-209 s at 300k, ~95% XLA pass time)
exists because every corpus used to compile a unique program: the
engine's static shapes were derived from *exact* corpus sizes, so the
persistent XLA cache almost never hit across ontologies.  Two pieces fix
that:

* :func:`bucket_dim` — quantize a size onto a small geometric ladder
  (default ×1.25 steps), so nearby corpus sizes resolve to the same
  padded static shape.  The ladder is a fixed global sequence (never
  derived from the input), which makes the quantized value — and every
  shape computed from it — a pure function of the bucket rung.

* :class:`ProgramCache` — a process-global registry of compiled XLA
  executables keyed by ``(bucket_signature, program, budget)``.  A
  bucketed engine's traced program depends ONLY on its signature (all
  ontology content rides in runtime arguments), so an executable
  compiled for one ontology is byte-for-byte the right program for any
  other ontology in the same bucket: the registry skips trace+lower+XLA
  entirely on a hit, and on a miss the XLA compile itself is usually a
  persistent-disk-cache hit (identical HLO ⇒ identical cache key).

The registry is the serving plane's warm-program store for *programs*
(the ontology registry in ``serve/registry.py`` stores warm *closures*);
``runtime/warmup.py`` populates it before traffic arrives.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Optional, Tuple

#: default geometric ladder step — coarse enough that similar corpora
#: collide into one bucket, fine enough that padding waste stays ≤ ~25%
#: on any single dimension (and far less after the engine's own 2048-row
#: pad_multiple rounding at incremental-serving scale)
DEFAULT_RATIO = 1.25

#: smallest ladder rung for corpus-sized dimensions — below this every
#: size quantizes to one bucket
_FLOOR = 32


def bucket_dim(n: int, ratio: float = DEFAULT_RATIO, floor: int = _FLOOR) -> int:
    """Smallest rung of the fixed geometric ladder that is >= ``n``.

    The ladder is ``floor * ratio**k`` rounded up to an int, for k = 0,
    1, 2, ... — a global sequence independent of ``n``, so every caller
    that lands between the same two rungs resolves to the identical
    padded size.  ``n <= 0`` maps to 0 (an absent dimension is its own
    bucket).  ``floor`` picks the ladder family: 32 for corpus-sized
    axes (rows, links, table rows), 1 for small structural counts
    (window slots, frontier layers) where a 32-slot floor would
    multiply real per-step work."""
    if not ratio > 1.0:
        # a config typo (bucket.ratio <= 1) would otherwise divide by
        # log(1) or spin the rung walk forever INSIDE a serve worker's
        # engine build — fail loudly at the first quantize instead
        raise ValueError(f"bucket ratio must be > 1, got {ratio}")
    if n <= 0:
        return 0
    if n <= floor:
        return floor
    # k from the closed form, then walk to correct float rounding
    k = max(int(math.floor(math.log(n / floor, ratio))) - 1, 0)
    rung = int(math.ceil(floor * ratio**k))
    while rung < n:
        k += 1
        rung = int(math.ceil(floor * ratio**k))
    return rung


class ProgramCache:
    """Process-global map ``key -> compiled executable`` with hit/miss
    counters.  Keys are ``(bucket_signature, program_name, extra...)``
    tuples; values are the objects returned by
    ``jax.jit(...).lower(...).compile()`` (callable, donation
    semantics preserved from the jit they were lowered from).

    Thread-safe; a concurrent miss on the same key compiles once (the
    per-key lock serializes builders) so parallel warmup threads never
    duplicate an XLA compile.

    Bounded: at most ``capacity`` executables stay resident, evicted
    LRU — a resident server facing a long tail of distinct buckets
    must not grow memory monotonically (the evicted program's next use
    recompiles, normally a cheap persistent-disk-cache deserialization;
    an engine that already holds the executable in its own
    ``_aot_runs`` keeps running it regardless).  ``capacity`` defaults
    to 32 (``DISTEL_PROGRAM_CACHE_CAPACITY`` overrides)."""

    def __init__(self, capacity: Optional[int] = None):
        import os

        if capacity is None:
            capacity = int(
                os.environ.get("DISTEL_PROGRAM_CACHE_CAPACITY", "32")
            )
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        #: insertion/recency-ordered (dict preserves order; hits
        #: re-append) — front = LRU victim
        self._programs: Dict[Tuple, object] = {}
        self._key_locks: Dict[Tuple, threading.Lock] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        #: AOT artifact farm hooks (``core/artifacts.py``): ``source``
        #: is consulted on every registry miss BEFORE ``build`` runs (a
        #: deserialized artifact counts as a hit — no trace/compile
        #: happened); ``sink`` captures every freshly built executable
        #: (``cli farm-build``).  Both survive :meth:`clear` — the
        #: attachment is process posture, not cached state.
        self.artifact_source = None
        self.artifact_sink = None

    def _touch(self, key: Tuple, exe) -> None:
        """Re-append for LRU recency.  Caller holds ``self._lock``."""
        self._programs.pop(key, None)
        self._programs[key] = exe

    def _evict_over_capacity(self) -> None:
        """Drop LRU entries past capacity.  Caller holds
        ``self._lock``."""
        while len(self._programs) > self.capacity:
            victim = next(iter(self._programs))
            self._programs.pop(victim)
            self.evictions += 1

    def lookup(self, key: Tuple):
        with self._lock:
            exe = self._programs.get(key)
            if exe is not None:
                self.hits += 1
                self._touch(key, exe)
            return exe

    def get_or_build(self, key: Tuple, build: Callable[[], object]):
        """Return ``(executable, was_hit)``; ``build`` runs at most once
        per key across threads."""
        with self._lock:
            exe = self._programs.get(key)
            if exe is not None:
                self.hits += 1
                self._touch(key, exe)
                return exe, True
            klock = self._key_locks.setdefault(key, threading.Lock())
        with klock:
            with self._lock:
                exe = self._programs.get(key)
                if exe is not None:
                    self.hits += 1
                    self._touch(key, exe)
                    return exe, True
            src = self.artifact_source
            if src is not None:
                exe = src.load(key)
                if exe is not None:
                    # a farm artifact: no build ran, so the caller's
                    # CompileStats stay at compile_s == 0.0 — the same
                    # contract as an in-process registry hit
                    with self._lock:
                        self._programs[key] = exe
                        self.hits += 1
                        self._key_locks.pop(key, None)
                        self._evict_over_capacity()
                    return exe, True
            exe = build()
            snk = self.artifact_sink
            if snk is not None:
                try:
                    snk.save(key, exe)
                except Exception as e:
                    import warnings

                    # the farm must never break the build it captures
                    warnings.warn(
                        f"artifact capture failed for {key[:2]}: {e}",
                        RuntimeWarning,
                    )
            with self._lock:
                self._programs[key] = exe
                self.misses += 1
                self._key_locks.pop(key, None)
                self._evict_over_capacity()
            return exe, False

    def stats(self) -> dict:
        with self._lock:
            return {
                "programs": len(self._programs),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }

    def clear(self) -> None:
        """Drop every cached executable (tests; also frees the compiled
        programs' device constants)."""
        with self._lock:
            self._programs.clear()
            self._key_locks.clear()
            self.hits = 0
            self.misses = 0
            self.evictions = 0


#: THE process-global registry (one per process, like jax's own caches)
PROGRAMS = ProgramCache()


def signature_of(parts, prefix: str) -> str:
    """Stable short signature string from structural metadata: a
    human-greppable prefix (the headline shapes) + a sha1 over the full
    ``repr`` of ``parts`` (every structural determinant of the traced
    program — belt and suspenders against two engines colliding on the
    headline shapes while differing somewhere subtle)."""
    import hashlib

    digest = hashlib.sha1(repr(parts).encode()).hexdigest()[:12]
    return f"{prefix}-{digest}"
