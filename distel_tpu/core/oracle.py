"""Ground-truth EL+ saturation on the normalized IR (pure Python).

The unit/property-test oracle the reference never had (it tested only
end-to-end against ELK, reference ``test/ELClassifierTest.java:363-446``).
Implements the CR1-CR6 completion rules of "Pushing the EL Envelope"
(the rule set named at reference
``init/AxiomDistributionType.java:3-31``) directly over Python sets, with
R(r) as explicit pair sets — deliberately *different* data structures from
the TPU engine's link-matrix formulation, so differential tests catch
indexing/closure bugs in either side.

Rules (S(X) = subsumer set, R(r) = role pairs):
  CR1  A ⊑ B,        A ∈ S(X)                       → B ∈ S(X)
  CR2  A1⊓...⊓An ⊑ B, Ai ∈ S(X) ∀i                  → B ∈ S(X)
  CR3  A ⊑ ∃r.B,     A ∈ S(X)                       → (X,B) ∈ R(r)
  CR4  ∃r.A ⊑ B,     (X,Y) ∈ R(r), A ∈ S(Y)         → B ∈ S(X)
  CR5  ⊥ ∈ S(Y),     (X,Y) ∈ R(r)                   → ⊥ ∈ S(X)
  CR5' r ⊑ s,        (X,Y) ∈ R(r)                   → (X,Y) ∈ R(s)
  CR6' r∘s ⊑ t,      (X,Y) ∈ R(r), (Y,Z) ∈ R(s)     → (X,Z) ∈ R(t)
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Set, Tuple

from distel_tpu.frontend.normalizer import NormalizedOntology
from distel_tpu.owl import syntax as S

Atom = S.ClassExpression
Role = S.ObjectProperty


class OracleResult:
    def __init__(
        self, subsumers: Dict[Atom, Set[Atom]], role_pairs, converged=True
    ):
        self.subsumers = subsumers
        self.role_pairs = role_pairs
        self.converged = converged

    def is_subsumed(self, sub: Atom, sup: Atom) -> bool:
        sups = self.subsumers.get(sub, set())
        # an unsatisfiable class is subsumed by everything
        return sup in sups or S.OWL_NOTHING in sups

    def unsatisfiable(self) -> Set[Atom]:
        return {
            x for x, sups in self.subsumers.items() if S.OWL_NOTHING in sups
        }

    def derivation_count(self) -> int:
        return sum(len(v) for v in self.subsumers.values()) + sum(
            len(v) for v in self.role_pairs.values()
        )

    def derived_count(self) -> int:
        """Facts beyond the S(X)={X,⊤} initialization — the unit the
        engines' ``derivations`` field uses (total bits − init bits), so
        engine/oracle throughput ratios compare like with like.  The
        init holds 2 facts per atom except ⊤ itself ({⊤} only)."""
        init = 2 * len(self.subsumers) - (
            1 if S.OWL_THING in self.subsumers else 0
        )
        return max(self.derivation_count() - init, 0)


def saturate(
    norm: NormalizedOntology,
    max_iters: int = 10_000,
    time_budget_s: Optional[float] = None,
) -> OracleResult:
    """``time_budget_s`` stops after the first iteration that exceeds
    the budget, returning the partial (sound, possibly incomplete)
    result with ``converged=False`` — for bounded baseline throughput
    measurements (bench.py); correctness consumers must leave it None."""
    universe = set(norm.atoms())
    universe.add(S.OWL_THING)
    universe.add(S.OWL_NOTHING)

    # S stored inverted, like the reference result node
    # (`init/AxiomLoader.java:1237-1245`): inv[a] = {x : a ∈ S(x)}
    inv: Dict[Atom, Set[Atom]] = {a: {a} for a in universe}
    inv[S.OWL_THING] = set(universe)
    rp: Dict[Role, Set[Tuple[Atom, Atom]]] = {}

    def size() -> int:
        return sum(len(v) for v in inv.values()) + sum(len(v) for v in rp.values())

    deadline = (
        time.monotonic() + time_budget_s if time_budget_s is not None else None
    )
    prev = -1
    iters = 0
    while size() != prev:
        prev = size()
        iters += 1
        if iters > max_iters:
            raise RuntimeError("oracle failed to converge")

        # NB: snapshots (list/copy) guard the self-referential cases
        # (a ⊑ a-cycles, transitive r∘r⊑r) where source and target alias.
        for a, b in norm.nf1:
            inv.setdefault(b, set()).update(list(inv.get(a, ())))
        for ops, b in norm.nf2:
            acc = set(inv.get(ops[0], ()))
            for op in ops[1:]:
                acc &= inv.get(op, set())
            inv.setdefault(b, set()).update(acc)
        for a, r, b in norm.nf3:
            pairs = rp.setdefault(r, set())
            for x in list(inv.get(a, ())):
                pairs.add((x, b))
        for r, a, b in norm.nf4:
            tgt = inv.setdefault(b, set())
            amembers = inv.get(a, set())
            for (x, y) in list(rp.get(r, ())):
                if y in amembers:
                    tgt.add(x)
        # CR5 bottom propagation
        bot = inv.setdefault(S.OWL_NOTHING, set())
        for r, pairs in rp.items():
            for (x, y) in list(pairs):
                if y in bot:
                    bot.add(x)
        # role hierarchy
        for r, s in norm.nf5:
            rp.setdefault(s, set()).update(list(rp.get(r, ())))
        # role chains
        for r, s, t in norm.nf6:
            rs = rp.get(r, set())
            ss = rp.get(s, set())
            if not rs or not ss:
                continue
            by_first: Dict[Atom, Set[Atom]] = {}
            for (y, z) in ss:
                by_first.setdefault(y, set()).add(z)
            tgt = rp.setdefault(t, set())
            for (x, y) in list(rs):
                for z in by_first.get(y, ()):
                    tgt.add((x, z))
        if deadline is not None and time.monotonic() > deadline:
            break

    # invert back to direct S(X) form (reference ResultRearranger,
    # `test/ResultRearranger.java:57-105`)
    subs: Dict[Atom, Set[Atom]] = {x: set() for x in universe}
    for a, xs in inv.items():
        for x in xs:
            subs.setdefault(x, set()).add(a)
    converged = size() == prev
    return OracleResult(subs, rp, converged)
