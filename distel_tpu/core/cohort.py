"""Cross-tenant cohort execution: one device dispatch advances N
same-bucket tenants (ISSUE 12).

The serving walls BENCH_SERVE_r01/r02 left standing are host-side:
fleet throughput scales by adding replica processes (one GIL each) and
the steady-delta path still pays ONE device dispatch PER TENANT even
though PR 10 made same-bucket tenants share one compiled executable.
This module adds the missing leading axis: stack the packed states of N
same-bucket tenants and ``jax.vmap`` the bucketed fixed point, so a
single launch saturates or delta-classifies a whole cohort — on a TPU
host that turns "replicas × GIL" into "MXU utilization × batch"; on
this CPU host the measured win is the N→1 dispatch collapse itself
(asserted via :data:`~distel_tpu.runtime.instrumentation.COHORT_EVENTS`,
never inferred from wall clocks).

Why this is sound, and byte-identical to solo execution:

* a BUCKETED engine's traced program is a pure function of its
  ``bucket_signature`` — every ontology-derived array (rule tables,
  gather indices, window slabs, the live-column mask) rides in the
  runtime-argument pytree.  vmapping that program over stacked states
  AND stacked argument pytrees evaluates each tenant's exact solo
  computation elementwise along the leading axis; the state is uint32
  bit-algebra and integer matmuls, so there is no float reassociation
  to diverge under batching.
* divergent per-tenant convergence is handled by jax's ``while_loop``
  batching rule, which IS the live-tenant mask: the loop runs while ANY
  lane's cond holds and the carry is ``select``-masked per lane, so a
  converged member's state rides unchanged (and its iteration counter
  frozen) while the stragglers drain — monotone EL+ saturation makes
  the extra evaluations fixed-point no-ops regardless.
* cohort sizes quantize to a power-of-two ladder (pad members repeat
  the last live tenant, results discarded), so the compiled cohort
  program is a pure function of ``(bucket_signature, rung, budget)`` —
  shared through ``core/program_cache.PROGRAMS`` and the persistent
  HLO cache exactly like the solo programs, and AOT-able by
  ``runtime/warmup.warm_delta_programs``.

The delta-plane entry point (:func:`execute_delta_cohort`) replays the
incremental fast path's round-robin joint fixed point
(``IncrementalClassifier._execute_delta_plan``) with one vmapped
dispatch per vote: every tenant runs the identical vote sequence it
would run solo (same roster positions, same per-vote budgets), with
per-tenant iteration/derivation accounting frozen at the vote where the
solo loop would have retired it — so closures, iteration counts and
history records all match solo execution bit for bit.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distel_tpu.core.engine import (
    SaturationResult,
    _host_bit_total,
    _pad_up,
)
from distel_tpu.core.program_cache import PROGRAMS
from distel_tpu.runtime.instrumentation import (
    COHORT_EVENTS,
    CompileStats,
    compile_watch,
)


def cohort_rung(n: int) -> int:
    """Smallest power of two >= ``n`` — the cohort-size ladder.  A
    fixed global ladder (like ``bucket_dim``'s geometric one) keeps the
    compiled-program population bounded: a cohort of 3 pads to 4, of 5
    to 8, and every rung's program is shared across all cohorts that
    quantize to it."""
    if n < 1:
        raise ValueError(f"cohort needs at least one member, got {n}")
    r = 1
    while r < n:
        r <<= 1
    return r


def cohort_ready(engine) -> bool:
    """Whether ``engine``'s programs can run under a cohort dispatch:
    single-device (the vmapped program has no shard_map port yet) and
    shape-bucketed (an exact-mode program embeds ontology constants, so
    stacking DIFFERENT tenants under it would be unsound)."""
    return engine.mesh is None and getattr(engine, "_bucket", False)


def _stack_masks(engines) -> dict:
    """Stack N same-signature engines' runtime-argument pytrees along a
    new leading axis.  Equal bucket signatures guarantee equal treedefs
    and leaf shapes (the signature hashes the argument avals)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[e._masks for e in engines]
    )


def _cohort_avals(leader, rung: int):
    u32 = jnp.uint32
    sp_av = jax.ShapeDtypeStruct((rung, leader.nc, leader.wc), u32)
    rp_av = jax.ShapeDtypeStruct((rung, leader.nl, leader.wc), u32)
    mk_av = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct((rung,) + tuple(a.shape), a.dtype),
        leader._mask_avals(),
    )
    return sp_av, rp_av, mk_av


def cohort_run_exe(leader, rung: int, budget: int):
    """The compiled cohort fixed point for ``leader``'s bucket at
    ``rung`` tenants and ``budget`` iterations: ``vmap`` of the solo
    run program (same-shape embed fused in front, matching what each
    solo vote's ``embed_state`` does), registry-shared under
    ``(bucket_signature, "cohort_run", budget, rung)``.  Returns
    ``(executable, CompileStats)`` — the stats record whether THIS
    lookup hit the registry (the steady-state compile-free claim is
    asserted off them)."""
    if not cohort_ready(leader):
        raise ValueError(
            "cohort programs need a single-device shape-bucketed engine"
        )
    stats = CompileStats(
        bucket_signature=leader.bucket_signature,
        program=f"cohort_run[{rung}x{budget}]",
    )
    sp_av, rp_av, mk_av = _cohort_avals(leader, rung)

    def one(sp, rp, masks):
        # the same-shape embed every solo vote applies (embed_state on
        # matching dims reduces to: S |= fresh-init diagonal + ⊤ row, R
        # verbatim) — fused here so a cohort vote stays ONE dispatch
        sp0, _ = leader._initial_arrays()
        return leader._run(sp0 | sp, rp, masks, budget)

    def build():
        t0 = time.perf_counter()
        lowered = jax.jit(
            jax.vmap(one), donate_argnums=(0, 1)
        ).lower(sp_av, rp_av, mk_av)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        stats.trace_lower_s = t1 - t0
        stats.compile_s = time.perf_counter() - t1
        return compiled

    with compile_watch(stats):
        key = (leader.bucket_signature, "cohort_run", budget, rung)
        exe, hit = PROGRAMS.get_or_build(key, build)
        stats.program_cache_hit = hit
    leader._note_compile(stats)
    return exe, stats


def _cohort_count_exe(leader, rung: int):
    """Vmapped embed+count program: per-tenant live-bit totals of the
    embedded states in one dispatch (the cohort analog of the solo
    loop's ``count_live_bits`` bracketing).  Shape-keyed like the solo
    count/embed programs: the traced body depends only on the state
    dims and TOP_ID."""
    key = (
        "shape:cohort_embed_count", rung, leader.nc, leader.nl, leader.wc,
    )

    def one(sp, rp, w):
        sp0, _ = leader._initial_arrays()
        return leader._live_bits(sp0 | sp, rp, wmask=w)

    def build():
        u32 = jnp.uint32
        sp_av, rp_av, _ = _cohort_avals(leader, rung)
        w_av = jax.ShapeDtypeStruct((rung, leader.wc), u32)
        return jax.jit(jax.vmap(one)).lower(sp_av, rp_av, w_av).compile()

    exe, _hit = PROGRAMS.get_or_build(key, build)
    return exe


def delta_cohort_ready(inc, plan) -> bool:
    """Whether one tenant's planned increment can join a cohort
    dispatch: bucketed delta programs, single-device bucketed roster,
    and a device-resident packed closure already in the base layout
    (the stacking precondition — a host/numpy or differently shaped
    state takes the solo path)."""
    if plan is None or not plan.bucketed:
        return False
    if not all(cohort_ready(e) for e in plan.engines):
        return False
    state = inc._state
    if state is None:
        return False
    sp, rp = state
    base = plan.base
    return (
        isinstance(sp, jax.Array)
        and sp.dtype == jnp.uint32
        and tuple(sp.shape) == (base.nc, base.wc)
        and tuple(rp.shape) == (base.nl, base.wc)
    )


def execute_delta_cohort(
    members: Sequence[Tuple[object, object, object]],
    max_iters: Optional[int] = None,
) -> List[SaturationResult]:
    """Advance N tenants' planned increments under shared vmapped
    dispatches and complete each increment.

    ``members``: ``(classifier, plan, batch)`` triples — ingested and
    planned (``_ingest`` + ``_delta_fast_plan``) but not yet executed,
    all passing :func:`delta_cohort_ready` with EQUAL
    ``plan.roster_key()`` (the caller groups; this function verifies).
    Each member's closure, iteration count and history record come out
    byte-identical to solo execution of the same plan; returns the
    per-member results in order."""
    if len(members) < 2:
        raise ValueError("a cohort needs at least 2 members")
    incs = [m[0] for m in members]
    plans = [m[1] for m in members]
    batches = [m[2] for m in members]
    key0 = plans[0].roster_key()
    for inc, plan in zip(incs, plans):
        if plan.roster_key() != key0:
            raise ValueError(
                "cohort members must share one roster key "
                f"({plan.roster_key()} != {key0})"
            )
        if not delta_cohort_ready(inc, plan):
            raise ValueError("member not cohort-ready (stale grouping?)")
    n = len(members)
    rung = cohort_rung(n)
    pad = rung - n
    k = len(plans[0].engines)
    if max_iters is None:
        max_iters = incs[0].config.max_iterations
    for inc in incs:
        inc.last_result = None
        inc.last_compile = None
        inc.last_delta_stats = None
    # stack the tenants' packed closures (pad lanes repeat the last
    # live tenant: they converge identically and are sliced away)
    states = [inc._pop_state() for inc in incs]
    sps = jnp.stack([s for s, _ in states] + [states[-1][0]] * pad)
    rps = jnp.stack([r for _, r in states] + [states[-1][1]] * pad)
    del states
    lead0 = plans[0].engines[0]
    wmasks = jnp.stack(
        [jnp.asarray(p.engines[0]._wmask) for p in plans]
        + [jnp.asarray(plans[-1].engines[0]._wmask)] * pad
    )
    count_exe = _cohort_count_exe(lead0, rung)
    start_bits = np.asarray(count_exe(sps, rps, wmasks))
    start_totals = [_host_bit_total(start_bits[i]) for i in range(n)]

    # ---- the joint round-robin fixed point, one dispatch per vote.
    # Per-tenant accounting mirrors _execute_delta_plan exactly: a
    # tenant retires at streak == k and stops counting; its later votes
    # are monotone no-ops riding the batch (the live-tenant mask is
    # jax's while_loop batching select — see module docstring).
    exes: dict = {}
    masks_by_pos: dict = {}
    builds: List[CompileStats] = []
    iters = [0] * n
    streaks = [0] * n
    votes = 0
    ei = 0
    while min(streaks) < k:
        pos = ei % k
        ei += 1
        engines_j = [p.engines[pos] for p in plans]
        if pos not in exes:
            budget_j = _pad_up(max_iters, engines_j[0].unroll)
            exe, stats = cohort_run_exe(engines_j[0], rung, budget_j)
            exes[pos] = exe
            builds.append(stats)
            # the runtime-argument pytrees never change across votes
            # (any closure rebind happened at plan time), so one stack
            # per position serves the whole joint loop
            masks_by_pos[pos] = _stack_masks(
                engines_j + [engines_j[-1]] * pad
            )
        live = sum(1 for s in streaks if s < k)
        sps, rps, its, _changed, _bits = exes[pos](
            sps, rps, masks_by_pos[pos]
        )
        votes += 1
        COHORT_EVENTS.record_cohort(size=live, rung=rung)
        its = np.asarray(its)
        for i in range(n):
            if streaks[i] >= k:
                continue  # retired: this vote is a no-op for tenant i
            it_i = int(its[i])
            iters[i] += it_i
            unproductive = it_i <= engines_j[i].unroll
            streaks[i] = streaks[i] + 1 if unproductive else 0
    final_bits = np.asarray(count_exe(sps, rps, wmasks))

    # ---- program-cost accounting: the cohort programs are built once
    # and shared — the build (if any) is charged to member 0; later
    # members ride registry-resident programs by construction
    all_hit = all(st.program_cache_hit for st in builds)
    results = []
    for i, (inc, plan, batch) in enumerate(members):
        agg = CompileStats(
            bucket_signature=plan.base.bucket_signature,
            program="cohort-delta-programs",
        )
        if i == 0:
            for st in builds:
                agg.trace_lower_s += st.trace_lower_s
                agg.compile_s += st.compile_s
                agg.persistent_cache_hits += st.persistent_cache_hits
                agg.persistent_cache_misses += st.persistent_cache_misses
        agg.program_cache_hit = all_hit if i == 0 else True
        inc.last_compile = agg
        inc.last_delta_stats = {
            "delta_bucketed": True,
            # cohort variants of every roster position, base included
            # (unlike the solo record, whose base program was charged
            # to the rebuild that built it, the base's COHORT program
            # is a product of this path)
            "delta_programs": len(builds),
            # members past the first ride programs that were registry-
            # resident by their execution (the leader's builds) — they
            # report full hits so the fleet-wide hit/miss counters sum
            # one miss per actual build, not one per member
            "delta_program_hits": (
                sum(bool(st.program_cache_hit) for st in builds)
                if i == 0
                else len(builds)
            ),
            "delta_signature": plan.engines[0].bucket_signature,
            "cohort_size": n,
            "cohort_rung": rung,
            "cohort_dispatches": votes,
        }
        result = SaturationResult(
            packed_s=sps[i],
            packed_r=rps[i],
            iterations=iters[i],
            derivations=_host_bit_total(final_bits[i]) - start_totals[i],
            idx=plan.idx,
            converged=True,
            transposed=True,
        )
        results.append(inc._finish_increment(batch, result, "cohort"))
    COHORT_EVENTS.record_deltas(n)
    return results


def warm_cohort_programs(
    engines, sizes: Sequence[int], max_iters: int
) -> List[dict]:
    """AOT the cohort run programs for an engine roster at the given
    cohort sizes (quantized to the pow2 ladder) — the cohort half of
    the warmup precompile: after this, even the FIRST cohort a
    restarted replica forms dispatches compile-free.  Returns one
    record per (engine, rung) build."""
    out = []
    rungs = sorted({cohort_rung(int(s)) for s in sizes if int(s) >= 2})
    for eng in engines:
        if not cohort_ready(eng):
            continue
        budget = _pad_up(max_iters, eng.unroll)
        for rung in rungs:
            _exe, stats = cohort_run_exe(eng, rung, budget)
            rec = stats.as_dict()
            rec["rung"] = rung
            out.append(rec)
    return out
