"""Mesh construction and multi-host initialization.

The reference's cluster topology is a node list plus per-rule weights
(``ShardInfo.properties:5-22``), wired by ssh/pssh fan-out
(``scripts/init.sh``, ``scripts/classify-all.sh``); its communication
backend is Redis TCP.  The TPU-native equivalents:

* **Within a host (ICI):** one ``jax.sharding.Mesh`` over the local
  chips; the engines shard the packed word axis and every collective
  (the filler bit-table ``psum``, the convergence vote) rides ICI.
* **Across hosts (DCN):** JAX's multi-controller runtime —
  ``jax.distributed.initialize`` connects the processes, after which
  ``jax.devices()`` spans every host and the same mesh code produces a
  global mesh.  XLA routes collectives over ICI within a slice and DCN
  across, with no change to the engine (the sharded fixed point is
  topology-agnostic; the word-axis layout keeps per-step traffic to the
  small bit-tables, which is what makes DCN hops tolerable — the analog
  of the reference keeping only barrier votes and delta reads
  cross-node, ``controller/CommunicationHandler.java:42-84``).
* Host-side work (parse/normalize/index) runs on every process over the
  same input — cheap, deterministic, and replica-consistent, matching
  the reference's loader writing identical metadata to every node
  (``init/AxiomLoader.java:365-413``).

Config keys (``ClassifierConfig.from_properties``): ``coordinator.address``,
``process.id``, ``num.processes`` — the ``NODES_LIST`` analog for the
multi-controller world.
"""

from __future__ import annotations

from typing import Optional


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-controller runtime (idempotent).  Returns True if
    distributed mode is active.  With no coordinator configured this is
    a no-op — the single-process path."""
    if coordinator_address is None:
        return False
    import jax

    # the idempotency check must NOT touch the backend (jax.process_count
    # would initialize XLA, after which distributed.initialize refuses to
    # run) — inspect the distributed client state directly
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return True
    except (ImportError, AttributeError):
        pass  # private-API drift: fall through to initialize()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def setup(config):
    """Shared classifier bootstrap: join the multi-controller runtime if
    configured, then build the mesh (or None for single-device).  The
    order matters — ``build_mesh``'s multi-host guard reads
    ``jax.process_count()``, which is only accurate after
    ``init_distributed``."""
    init_distributed(
        config.coordinator_address,
        config.num_processes,
        config.process_id,
    )
    return build_mesh(config.mesh_devices) if config.mesh_devices else None


def build_mesh(
    n_devices: Optional[int] = None, axis: str = "c"
):
    """A 1-D mesh over the (global, under multi-host) device list.
    ``n_devices=None`` takes every device; the engines require the
    packed word axis to divide by the mesh size, which they arrange via
    padding."""
    import jax
    import numpy as np

    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"mesh of {n_devices} devices requested but only "
                f"{len(devs)} present"
            )
        if jax.process_count() > 1 and n_devices != len(devs):
            # a prefix slice of the global list would exclude every chip
            # of the later hosts, whose processes then cannot execute
            # against the mesh — partial meshes are single-host only
            raise ValueError(
                f"partial mesh ({n_devices} of {len(devs)} devices) is not "
                "supported under multi-host; omit mesh_devices to span all"
            )
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), (axis,))
