"""``shard_map`` compatibility across jax pins.

The engines are written against the modern top-level ``jax.shard_map``
API (``check_vma=`` keyword).  The current pin (0.4.37) predates that
export but ships a fully working implementation at
``jax.experimental.shard_map.shard_map`` whose only API delta is the
keyword's name: the replication/varying-manual-axes check is spelled
``check_rep`` there.  This module resolves whichever the pin provides
and normalizes the keyword, so every mesh entry point
(``rowpacked_engine._shard_jit``, ``packed_engine._sharded_run``, the
sharded sparse-tier program) writes one call and runs on either
vintage.

Verified semantics on the 0.4.37 experimental implementation (the
tier-1 sharded suite pins them): ``check_rep=False`` accepts
replicated ``P()``/``P(None)`` out_specs for values made uniform by
construction (psum'd votes, folded frontier masks), collectives inside
``lax.cond`` branches with a replicated predicate, and pytree
in_specs — everything the engines' shard_map structure uses.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):  # modern pins: the top-level export
    _impl = jax.shard_map
    _CHECK_KW = "check_vma"
else:  # 0.4.x pins: the experimental module, check_vma spelled check_rep
    try:
        from jax.experimental.shard_map import shard_map as _impl

        _CHECK_KW = "check_rep"
    except ImportError:  # pragma: no cover - no known pin hits this
        _impl = None
        _CHECK_KW = None

#: True when this pin provides a usable shard_map under either name —
#: the probe ``tests/sharding_support.py`` keys its skips on (NOT
#: ``hasattr(jax, "shard_map")``, which reads False on 0.4.x pins whose
#: experimental implementation is fully functional).
HAS_SHARD_MAP = _impl is not None

#: where the implementation came from, for diagnostics/docs:
#: ``"jax"`` (top-level) or ``"jax.experimental.shard_map"``
SHARD_MAP_SOURCE = (
    "jax"
    if hasattr(jax, "shard_map")
    else ("jax.experimental.shard_map" if HAS_SHARD_MAP else None)
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` with the kwarg normalized for the resolved
    implementation.  ``check_vma`` follows the modern spelling; on an
    experimental-pin resolution it is passed through as ``check_rep``
    (same meaning: verify outputs declared replicated really are)."""
    if _impl is None:  # pragma: no cover - no known pin hits this
        raise RuntimeError(
            "this jax pin provides neither jax.shard_map nor "
            "jax.experimental.shard_map.shard_map"
        )
    return _impl(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )
