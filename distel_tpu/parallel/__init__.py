"""Device-mesh and multi-host topology utilities."""

from distel_tpu.parallel.mesh import (  # noqa: F401
    build_mesh,
    init_distributed,
    setup,
)
from distel_tpu.parallel.shard_compat import (  # noqa: F401
    HAS_SHARD_MAP,
    SHARD_MAP_SOURCE,
    shard_map,
)
