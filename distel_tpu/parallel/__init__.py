"""Device-mesh and multi-host topology utilities."""

from distel_tpu.parallel.mesh import (  # noqa: F401
    build_mesh,
    init_distributed,
    setup,
)
