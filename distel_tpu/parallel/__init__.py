"""Device-mesh and multi-host topology utilities."""

from distel_tpu.parallel.mesh import build_mesh, init_distributed  # noqa: F401
