"""distel_tpu — a TPU-native distributed fixed-point classifier for EL+ ontologies.

A from-scratch rebuild of the capabilities of DistEL (Redis/Java rule-sharded
saturation; see /root/reference) designed TPU-first:

* the subsumption store S(X) becomes a boolean matrix ``S[x, a]`` ("a is a
  subsumer of x"), sharded over a ``jax.sharding.Mesh`` along the concept axis;
* role-pair stores R(r) become a *link matrix* ``R[x, l]`` over the finite set
  of (role, filler) pairs that can ever appear during EL+ saturation;
* every completion rule CR1-CR6 (the "Pushing the EL Envelope" rule set the
  reference implements as Redis Lua kernels, reference
  ``src/knoelab/classification/base/*AxiomProcessorBase.java``) becomes a
  column gather/scatter or a single AND-OR semiring matmul on the MXU;
* the global barrier + convergence vote (reference
  ``controller/CommunicationHandler.java:49-84``) becomes
  ``lax.while_loop(cond=any(changed))`` with a ``psum`` over the mesh.

Layer map (mirrors SURVEY.md section 1 of the rebuild blueprint):

=========  ==========================  =====================================
Layer      Package                     Reference equivalent
=========  ==========================  =====================================
frontend   ``distel_tpu.owl``          OWLAPI + functional-syntax loading
frontend   ``distel_tpu.frontend``     ``init/Normalizer.java``, profile tools
indexing   ``distel_tpu.core.indexing``  ``init/AxiomLoader.java`` (int-IDing,
                                       categorization, shard layout)
kernels    ``distel_tpu.core.engine``  ``base/Type*AxiomProcessorBase.java``
                                       + the ~12 embedded Lua scripts
oracle     ``distel_tpu.core.oracle``  (new: the unit-test oracle the
                                       reference lacked)
parallel   ``distel_tpu.parallel``     Redis sharding + CommunicationHandler
runtime    ``distel_tpu.runtime``      ``ELClassifier.java`` + scripts/
testing    ``distel_tpu.testing``      ``test/ELClassifierTest.java`` et al.
=========  ==========================  =====================================
"""

__version__ = "0.1.0"

from distel_tpu.owl import parser as owl_parser  # noqa: F401
from distel_tpu.owl import syntax as owl_syntax  # noqa: F401
