"""Bitset primitives: boolean matrices packed 32 columns to a uint32 word.

Layout (the "standard" layout, shared with ``core/engine.py``'s wire
packing): logical column ``c`` lives in word ``c >> 5``, bit ``c & 31``
(little-endian within the word).  All ops here are pure jnp — they trace
into the saturation step's XLA program; the MXU contraction over packed
operands is in ``ops/bitmatmul.py``.

These replace the reference's per-key Redis set reads/writes
(``pipeline/PipelineManager.java``) at 32 set-memberships per word.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax


def pack_bool_columns(x) -> jnp.ndarray:
    """bool [N, M] (M % 32 == 0) → uint32 [N, M/32], standard layout."""
    w = x.reshape(x.shape[0], -1, 32).astype(jnp.uint32)
    weights = jnp.left_shift(
        jnp.asarray(1, jnp.uint32), jnp.arange(32, dtype=jnp.uint32)
    )
    return jnp.sum(w * weights, axis=-1, dtype=jnp.uint32)


def unpack_words(p, m: int, dtype=bool) -> jnp.ndarray:
    """uint32 [N, W] → ``dtype`` [N, m] (m <= 32*W), standard layout."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (p[:, :, None] >> shifts) & jnp.asarray(1, jnp.uint32)
    return bits.reshape(p.shape[0], -1)[:, :m].astype(dtype)


def unpack_words_planes(p, dtype=jnp.int8) -> jnp.ndarray:
    """uint32 [N, W] → ``dtype`` [N, 32*W] in **bit-plane-major** order:
    output position ``pl*W + w`` holds bit ``pl`` of word ``w`` (logical
    column ``32*w + pl``).  Unlike :func:`unpack_words`, never builds the
    [N, W, 32] uint32 intermediate (8 bytes/bit — the allocation that
    OOMs at ~100k concepts); each plane narrows to ``dtype`` immediately."""
    one = jnp.asarray(1, jnp.uint32)
    planes = [
        ((p >> jnp.asarray(pl, jnp.uint32)) & one).astype(dtype)
        for pl in range(32)
    ]
    return jnp.concatenate(planes, axis=1)


def pack_planes(bits) -> jnp.ndarray:
    """Inverse companion of :func:`unpack_words_planes`: bool/int [N, 32*W]
    in bit-plane-major order → uint32 [N, W]."""
    n, m = bits.shape
    w = m // 32
    b3 = bits.reshape(n, 32, w).astype(jnp.uint32)
    weights = (
        jnp.asarray(1, jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    )
    return jnp.sum(b3 * weights, axis=1, dtype=jnp.uint32)


def bit_lookup(
    p,
    rows: np.ndarray,
    cols: np.ndarray,
    *,
    word_offset=None,
    dtype=bool,
) -> jnp.ndarray:
    """``out[j, i] = bit(p[rows[i], cols[j]])`` — TRANSPOSED output
    [len(cols), len(rows)] in ``dtype``.

    A direct 2D bit gather (``p[rows[:,None], cols>>5]``) lowers
    elementwise on TPU (~8 ns/element); a one-hot selection matmul is
    O(len(rows)·N·len(cols)) MACs — cubic at ontology scale.  This
    version is linear: contiguous row gather → transpose → contiguous
    row gather on the word axis → per-row shift.

    ``word_offset`` (traced scalar) supports sharded callers whose ``p``
    holds only the word window ``[word_offset, word_offset + W)``:
    out-of-window columns yield 0 (the caller psums the partials)."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    if rows.size == 0 or cols.size == 0:
        return jnp.zeros((cols.size, rows.size), dtype)
    subt = p[jnp.asarray(rows)].T             # [W, R] (one transpose copy)
    w = jnp.asarray(cols >> 5)
    if word_offset is not None:
        w = w - word_offset
    ok = (w >= 0) & (w < subt.shape[0])
    words = subt[jnp.clip(w, 0, subt.shape[0] - 1)]    # [C, R] row gather
    shifts = jnp.asarray((cols & 31).astype(np.uint32))[:, None]
    bits = (words >> shifts) & jnp.asarray(1, jnp.uint32)
    return jnp.where(ok[:, None], bits, 0).astype(dtype)


def gather_bit_columns(p, cols: np.ndarray) -> jnp.ndarray:
    """Extract logical columns ``cols`` from packed ``p`` [N, W] →
    bool [N, len(cols)].  ``cols`` is a static numpy index vector, so the
    word/bit split costs nothing at runtime."""
    cols = np.asarray(cols)
    if cols.size == 0:
        return jnp.zeros((p.shape[0], 0), bool)
    words = p[:, cols >> 5]
    shifts = jnp.asarray((cols & 31).astype(np.uint32))
    return ((words >> shifts) & jnp.asarray(1, jnp.uint32)).astype(bool)


def gather_bit_matrix(p, rows: np.ndarray, cols: np.ndarray) -> jnp.ndarray:
    """Extract the bool matrix ``out[i, j] = bit(p[rows[i], cols[j]])`` from
    packed ``p`` [N, W] → bool [len(rows), len(cols)].  Both index vectors
    are static, so the word/bit split is free and the two gathers fuse —
    no [len(rows), W] row intermediate is materialized."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    if rows.size == 0 or cols.size == 0:
        return jnp.zeros((rows.size, cols.size), bool)
    words = p[rows[:, None], (cols >> 5)[None, :]]
    shifts = jnp.asarray((cols & 31).astype(np.uint32))[None, :]
    return ((words >> shifts) & jnp.asarray(1, jnp.uint32)).astype(bool)


class ColumnScatter:
    """Static plan for OR-scattering source bit vectors into packed columns.

    Given target logical columns ``targets[j]`` (with repeats — many axioms
    share a superclass), precomputes:
      * the distinct target columns ``d`` and the map ``inv: j → d``;
      * each distinct column's word and bit position.

    At runtime :meth:`apply` OR-reduces the per-axiom source columns into
    the distinct targets (scatter-max in bool space — ``max`` is OR on
    0/1), then rebuilds words by scatter-*add*: distinct columns have
    distinct (word, bit) pairs, so the added powers of two never carry —
    addition IS bitwise OR here.  One pass replaces the reference's
    per-axiom ``zadd`` storms against the result node
    (``base/Type1_1AxiomProcessorBase.java:118-143``).
    """

    def __init__(self, targets: np.ndarray, n_words: int):
        targets = np.asarray(targets, np.int64)
        self.n_words = n_words
        self.d_cols, self.inv = np.unique(targets, return_inverse=True)
        self.d_words = (self.d_cols >> 5).astype(np.int32)
        self.d_shifts = (self.d_cols & 31).astype(np.uint32)

    @property
    def n_distinct(self) -> int:
        return len(self.d_cols)

    def apply(self, packed, source_bits) -> jnp.ndarray:
        """OR ``source_bits`` [N, K] (bool, axiom-ordered) into ``packed``
        [N, W] at this plan's target columns; returns the new packed."""
        if self.n_distinct == 0:
            return packed
        n = packed.shape[0]
        u = jnp.zeros((n, self.n_distinct), bool)
        u = u.at[:, self.inv].max(source_bits)
        v = u.astype(jnp.uint32) << jnp.asarray(self.d_shifts)
        upd = jnp.zeros((n, self.n_words), jnp.uint32)
        upd = upd.at[:, self.d_words].add(v)
        return packed | upd


def scatter_or_columns(packed, source_bits, targets: np.ndarray) -> jnp.ndarray:
    """One-shot convenience wrapper over :class:`ColumnScatter`."""
    return ColumnScatter(np.asarray(targets), packed.shape[1]).apply(
        packed, source_bits
    )


class SegmentedRowOr:
    """Static plan for OR-combining packed *rows* that share a target row.

    XLA's scatter op on TPU serializes per index and runs two orders of
    magnitude below HBM speed for thousands of targets (measured ~1.3 µs
    per scattered column at 20k concepts), so the row-packed engine never
    scatter-MAXes.  Instead: sort the sources by target once at build time,
    OR each run of same-target rows with one segmented ``associative_scan``
    at runtime, and write the per-target results with a scatter-*set* over
    the (unique) target rows — which XLA lowers to a fast dense update.

    ``order`` re-sorts the caller's per-axiom rows; ``targets`` are the
    distinct target row ids, aligned with :meth:`reduce`'s output.
    """

    def __init__(self, raw_targets: np.ndarray):
        raw_targets = np.asarray(raw_targets, np.int64)
        self.k = len(raw_targets)
        self.order = np.argsort(raw_targets, kind="stable")
        sorted_t = raw_targets[self.order]
        self.targets, first = np.unique(sorted_t, return_index=True)
        starts = np.zeros(self.k, bool)
        starts[first] = True
        self._starts = starts
        self._last = np.r_[first[1:] - 1, self.k - 1] if self.k else first

    @property
    def n_targets(self) -> int:
        return len(self.targets)

    def reduce(self, rows) -> jnp.ndarray:
        """OR-reduce ``rows`` [K, W] (any integer dtype, already in
        ``order``) within each same-target run → [n_targets, W]."""
        if self.k == 1:
            return rows
        starts = jnp.asarray(self._starts)

        def comb(x, y):
            xs, xv = x
            ys, yv = y
            return ys | xs, jnp.where(ys[:, None], yv, yv | xv)

        _, v = lax.associative_scan(comb, (starts, rows), axis=0)
        return v[jnp.asarray(self._last)]

    def apply(self, state, rows, track: bool = False):
        """OR ``rows`` [K, W] (in ``order``) into ``state`` [N, W] at this
        plan's target rows.  ``track=True`` additionally returns a scalar
        "did any bit change" — computed on the touched rows only, so the
        caller never needs to keep the pre-step state alive for a
        whole-array comparison (which doubles state memory inside the
        fixed-point loop)."""
        if self.k == 0:
            return (state, jnp.asarray(False)) if track else state
        state = jnp.asarray(state)
        t = jnp.asarray(self.targets)
        old = state[t]
        merged = old | self.reduce(rows)
        out = state.at[t].set(merged)
        if track:
            return out, jnp.any(merged != old)
        return out

    def split(self, max_rows: int):
        """Partition into subplans of at most ``max_rows`` source rows
        each (never splitting a same-target run, so each target row is
        written by exactly one subplan).  Returns ``[(slice, subplan)]``
        where ``slice`` indexes the caller's ``order``-permuted source
        arrays.  Used to bound per-rule temporaries: a single fused rule
        application materializes O(K·W) gather + scan buffers, which
        exceeds HBM at ~100k-concept scale."""
        if self.k == 0:
            return []
        max_rows = max(int(max_rows), 1)
        starts = np.nonzero(self._starts)[0]
        sorted_targets = np.repeat(
            self.targets, np.diff(np.r_[starts, self.k])
        )
        pieces = []
        cur = 0
        while cur < self.k:
            if self.k - cur <= max_rows:
                cut = self.k
            else:
                later = starts[(starts > cur) & (starts <= cur + max_rows)]
                # a single run longer than max_rows becomes its own piece
                cut = int(later[-1]) if later.size else int(
                    starts[starts > cur][0]
                ) if (starts > cur).any() else self.k
            pieces.append(
                (slice(cur, cut), SegmentedRowOr(sorted_targets[cur:cut]))
            )
            cur = cut
        return pieces
