"""Bitset primitives: boolean matrices packed 32 columns to a uint32 word.

Layout (the "standard" layout, shared with ``core/engine.py``'s wire
packing): logical column ``c`` lives in word ``c >> 5``, bit ``c & 31``
(little-endian within the word).  All ops here are pure jnp — they trace
into the saturation step's XLA program; the MXU contraction over packed
operands is in ``ops/bitmatmul.py``.

These replace the reference's per-key Redis set reads/writes
(``pipeline/PipelineManager.java``) at 32 set-memberships per word.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def pack_bool_columns(x) -> jnp.ndarray:
    """bool [N, M] (M % 32 == 0) → uint32 [N, M/32], standard layout."""
    w = x.reshape(x.shape[0], -1, 32).astype(jnp.uint32)
    weights = jnp.left_shift(
        jnp.asarray(1, jnp.uint32), jnp.arange(32, dtype=jnp.uint32)
    )
    return jnp.sum(w * weights, axis=-1, dtype=jnp.uint32)


def unpack_words(p, m: int) -> jnp.ndarray:
    """uint32 [N, W] → bool [N, m] (m <= 32*W), standard layout."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (p[:, :, None] >> shifts) & jnp.asarray(1, jnp.uint32)
    return bits.reshape(p.shape[0], -1)[:, :m].astype(bool)


def gather_bit_columns(p, cols: np.ndarray) -> jnp.ndarray:
    """Extract logical columns ``cols`` from packed ``p`` [N, W] →
    bool [N, len(cols)].  ``cols`` is a static numpy index vector, so the
    word/bit split costs nothing at runtime."""
    cols = np.asarray(cols)
    if cols.size == 0:
        return jnp.zeros((p.shape[0], 0), bool)
    words = p[:, cols >> 5]
    shifts = jnp.asarray((cols & 31).astype(np.uint32))
    return ((words >> shifts) & jnp.asarray(1, jnp.uint32)).astype(bool)


def gather_bit_matrix(p, rows: np.ndarray, cols: np.ndarray) -> jnp.ndarray:
    """Extract the bool matrix ``out[i, j] = bit(p[rows[i], cols[j]])`` from
    packed ``p`` [N, W] → bool [len(rows), len(cols)].  Both index vectors
    are static, so the word/bit split is free and the two gathers fuse —
    no [len(rows), W] row intermediate is materialized."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    if rows.size == 0 or cols.size == 0:
        return jnp.zeros((rows.size, cols.size), bool)
    words = p[rows[:, None], (cols >> 5)[None, :]]
    shifts = jnp.asarray((cols & 31).astype(np.uint32))[None, :]
    return ((words >> shifts) & jnp.asarray(1, jnp.uint32)).astype(bool)


class ColumnScatter:
    """Static plan for OR-scattering source bit vectors into packed columns.

    Given target logical columns ``targets[j]`` (with repeats — many axioms
    share a superclass), precomputes:
      * the distinct target columns ``d`` and the map ``inv: j → d``;
      * each distinct column's word and bit position.

    At runtime :meth:`apply` OR-reduces the per-axiom source columns into
    the distinct targets (scatter-max in bool space — ``max`` is OR on
    0/1), then rebuilds words by scatter-*add*: distinct columns have
    distinct (word, bit) pairs, so the added powers of two never carry —
    addition IS bitwise OR here.  One pass replaces the reference's
    per-axiom ``zadd`` storms against the result node
    (``base/Type1_1AxiomProcessorBase.java:118-143``).
    """

    def __init__(self, targets: np.ndarray, n_words: int):
        targets = np.asarray(targets, np.int64)
        self.n_words = n_words
        self.d_cols, self.inv = np.unique(targets, return_inverse=True)
        self.d_words = (self.d_cols >> 5).astype(np.int32)
        self.d_shifts = (self.d_cols & 31).astype(np.uint32)

    @property
    def n_distinct(self) -> int:
        return len(self.d_cols)

    def apply(self, packed, source_bits) -> jnp.ndarray:
        """OR ``source_bits`` [N, K] (bool, axiom-ordered) into ``packed``
        [N, W] at this plan's target columns; returns the new packed."""
        if self.n_distinct == 0:
            return packed
        n = packed.shape[0]
        u = jnp.zeros((n, self.n_distinct), bool)
        u = u.at[:, self.inv].max(source_bits)
        v = u.astype(jnp.uint32) << jnp.asarray(self.d_shifts)
        upd = jnp.zeros((n, self.n_words), jnp.uint32)
        upd = upd.at[:, self.d_words].add(v)
        return packed | upd


def scatter_or_columns(packed, source_bits, targets: np.ndarray) -> jnp.ndarray:
    """One-shot convenience wrapper over :class:`ColumnScatter`."""
    return ColumnScatter(np.asarray(targets), packed.shape[1]).apply(
        packed, source_bits
    )
