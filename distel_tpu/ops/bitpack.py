"""Bitset primitives: boolean matrices packed 32 columns to a uint32 word.

Layout (the "standard" layout, shared with ``core/engine.py``'s wire
packing): logical column ``c`` lives in word ``c >> 5``, bit ``c & 31``
(little-endian within the word).  All ops here are pure jnp — they trace
into the saturation step's XLA program; the MXU contraction over packed
operands is in ``ops/bitmatmul.py``.

These replace the reference's per-key Redis set reads/writes
(``pipeline/PipelineManager.java``) at 32 set-memberships per word.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax


def pack_bool_columns(x) -> jnp.ndarray:
    """bool [N, M] (M % 32 == 0) → uint32 [N, M/32], standard layout."""
    w = x.reshape(x.shape[0], -1, 32).astype(jnp.uint32)
    weights = jnp.left_shift(
        jnp.asarray(1, jnp.uint32), jnp.arange(32, dtype=jnp.uint32)
    )
    return jnp.sum(w * weights, axis=-1, dtype=jnp.uint32)


def unpack_words(p, m: int, dtype=bool) -> jnp.ndarray:
    """uint32 [N, W] → ``dtype`` [N, m] (m <= 32*W), standard layout.
    Bits narrow to ``dtype`` before the reshape so the widest live value
    is the [N, W, 32] ``dtype`` tensor (1 byte/bit for int8), not u32."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((p[:, :, None] >> shifts) & jnp.asarray(1, jnp.uint32)).astype(
        dtype
    )
    return bits.reshape(p.shape[0], -1)[:, :m]


def unpack_words_planes(p, dtype=jnp.int8) -> jnp.ndarray:
    """uint32 [N, W] → ``dtype`` [N, 32*W] in **bit-plane-major** order:
    output position ``pl*W + w`` holds bit ``pl`` of word ``w`` (logical
    column ``32*w + pl``).  Unlike :func:`unpack_words`, never builds the
    [N, W, 32] uint32 intermediate (8 bytes/bit — the allocation that
    OOMs at ~100k concepts); each plane narrows to ``dtype`` immediately."""
    one = jnp.asarray(1, jnp.uint32)
    planes = [
        ((p >> jnp.asarray(pl, jnp.uint32)) & one).astype(dtype)
        for pl in range(32)
    ]
    return jnp.concatenate(planes, axis=1)


def pack_planes(bits) -> jnp.ndarray:
    """Inverse companion of :func:`unpack_words_planes`: bool/int [N, 32*W]
    in bit-plane-major order → uint32 [N, W]."""
    n, m = bits.shape
    w = m // 32
    b3 = bits.reshape(n, 32, w).astype(jnp.uint32)
    weights = (
        jnp.asarray(1, jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    )
    return jnp.sum(b3 * weights, axis=1, dtype=jnp.uint32)


def bit_lookup_from(subt, cols, *, word_offset=None, dtype=bool) -> jnp.ndarray:
    """``out[j, i] = bit(subt[cols[j] >> 5, i] >> (cols[j] & 31))`` —
    the column-lookup half of :func:`bit_lookup` over a precomputed
    transposed row gather ``subt`` [W, R].  ``cols`` may be a *traced*
    index vector, so callers can loop over column chunks with
    ``lax.fori_loop`` (bounding peak memory to one chunk's temporaries).
    """
    cols = jnp.asarray(cols)
    w = lax.shift_right_logical(cols, 5)
    if word_offset is not None:
        w = w - word_offset
    ok = (w >= 0) & (w < subt.shape[0])
    words = subt[jnp.clip(w, 0, subt.shape[0] - 1)]    # [C, R] row gather
    shifts = (cols & 31).astype(jnp.uint32)[:, None]
    bits = (words >> shifts) & jnp.asarray(1, jnp.uint32)
    return jnp.where(ok[:, None], bits, 0).astype(dtype)


def bit_lookup(
    p,
    rows: np.ndarray,
    cols,
    *,
    word_offset=None,
    dtype=bool,
) -> jnp.ndarray:
    """``out[j, i] = bit(p[rows[i], cols[j]])`` — TRANSPOSED output
    [len(cols), len(rows)] in ``dtype``.

    A direct 2D bit gather (``p[rows[:,None], cols>>5]``) lowers
    elementwise on TPU (~8 ns/element); a one-hot selection matmul is
    O(len(rows)·N·len(cols)) MACs — cubic at ontology scale.  This
    version is linear: contiguous row gather → transpose → contiguous
    row gather on the word axis → per-row shift.

    ``word_offset`` (traced scalar) supports sharded callers whose ``p``
    holds only the word window ``[word_offset, word_offset + W)``:
    out-of-window columns yield 0 (the caller psums the partials)."""
    rows = np.asarray(rows)
    n_cols = cols.size if hasattr(cols, "size") else np.asarray(cols).size
    if rows.size == 0 or n_cols == 0:
        return jnp.zeros((n_cols, rows.size), dtype)
    subt = p[jnp.asarray(rows)].T             # [W, R] (one transpose copy)
    return bit_lookup_from(subt, cols, word_offset=word_offset, dtype=dtype)


def gather_bit_columns(p, cols: np.ndarray) -> jnp.ndarray:
    """Extract logical columns ``cols`` from packed ``p`` [N, W] →
    bool [N, len(cols)].  ``cols`` is a static numpy index vector, so the
    word/bit split costs nothing at runtime."""
    cols = np.asarray(cols)
    if cols.size == 0:
        return jnp.zeros((p.shape[0], 0), bool)
    words = p[:, cols >> 5]
    shifts = jnp.asarray((cols & 31).astype(np.uint32))
    return ((words >> shifts) & jnp.asarray(1, jnp.uint32)).astype(bool)


def gather_bit_matrix(p, rows: np.ndarray, cols: np.ndarray) -> jnp.ndarray:
    """Extract the bool matrix ``out[i, j] = bit(p[rows[i], cols[j]])`` from
    packed ``p`` [N, W] → bool [len(rows), len(cols)].  Both index vectors
    are static, so the word/bit split is free and the two gathers fuse —
    no [len(rows), W] row intermediate is materialized."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    if rows.size == 0 or cols.size == 0:
        return jnp.zeros((rows.size, cols.size), bool)
    words = p[rows[:, None], (cols >> 5)[None, :]]
    shifts = jnp.asarray((cols & 31).astype(np.uint32))[None, :]
    return ((words >> shifts) & jnp.asarray(1, jnp.uint32)).astype(bool)


class ColumnScatter:
    """Static plan for OR-scattering source bit vectors into packed columns.

    Given target logical columns ``targets[j]`` (with repeats — many axioms
    share a superclass), precomputes:
      * the distinct target columns ``d`` and the map ``inv: j → d``;
      * each distinct column's word and bit position.

    At runtime :meth:`apply` OR-reduces the per-axiom source columns into
    the distinct targets (scatter-max in bool space — ``max`` is OR on
    0/1), then rebuilds words by scatter-*add*: distinct columns have
    distinct (word, bit) pairs, so the added powers of two never carry —
    addition IS bitwise OR here.  One pass replaces the reference's
    per-axiom ``zadd`` storms against the result node
    (``base/Type1_1AxiomProcessorBase.java:118-143``).
    """

    def __init__(self, targets: np.ndarray, n_words: int):
        targets = np.asarray(targets, np.int64)
        self.n_words = n_words
        self.d_cols, self.inv = np.unique(targets, return_inverse=True)
        self.d_words = (self.d_cols >> 5).astype(np.int32)
        self.d_shifts = (self.d_cols & 31).astype(np.uint32)

    @property
    def n_distinct(self) -> int:
        return len(self.d_cols)

    def apply(self, packed, source_bits) -> jnp.ndarray:
        """OR ``source_bits`` [N, K] (bool, axiom-ordered) into ``packed``
        [N, W] at this plan's target columns; returns the new packed."""
        if self.n_distinct == 0:
            return packed
        n = packed.shape[0]
        u = jnp.zeros((n, self.n_distinct), bool)
        u = u.at[:, self.inv].max(source_bits)
        v = u.astype(jnp.uint32) << jnp.asarray(self.d_shifts)
        upd = jnp.zeros((n, self.n_words), jnp.uint32)
        upd = upd.at[:, self.d_words].add(v)
        return packed | upd


def scatter_or_columns(packed, source_bits, targets: np.ndarray) -> jnp.ndarray:
    """One-shot convenience wrapper over :class:`ColumnScatter`."""
    return ColumnScatter(np.asarray(targets), packed.shape[1]).apply(
        packed, source_bits
    )


def _next_pow2(counts: np.ndarray) -> np.ndarray:
    """Elementwise smallest power of two >= counts (counts >= 1), exact
    for any int64 — float log2 alone misrounds near exact powers."""
    b = (1 << np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64))
    b = np.where(b < counts, b << 1, b)         # log2 rounded down
    half = b >> 1
    return np.where(half >= counts, half, b)    # log2 rounded up


class SegmentedRowOr:
    """Static plan for OR-combining packed *rows* that share a target row.

    XLA's scatter op on TPU serializes per index and runs two orders of
    magnitude below HBM speed for thousands of targets (measured ~1.3 µs
    per scattered column at 20k concepts), so the row-packed engine never
    scatter-MAXes.  And segments are short — ontology superclasses average
    ~1.6 axioms per target — so a segmented ``associative_scan`` (log-depth
    passes over the whole gathered buffer; measured 41 ms for CR1 at 60k
    concepts) wastes almost all its traffic.  Instead this plan is
    **bucketed**: segments are grouped by padded power-of-two length at
    build time, each segment padded *with repeats of its own members* — OR
    is idempotent, so repeats are free — and the runtime reduce is one
    reshape + OR-reduce per bucket: [n_seg, blen, W] → [n_seg, W], pure
    dense ops at HBM speed (measured 5.5 ms for the same CR1).

    ``order`` (length ``k``, with repeats) maps kernel row position →
    caller's raw axiom index; callers gather their per-axiom sources
    through it once at trace time.  ``targets`` are the per-segment target
    row ids in *bucket emission order*, aligned with :meth:`reduce`'s
    output.
    """

    def __init__(self, raw_targets: np.ndarray):
        raw_targets = np.asarray(raw_targets, np.int64)
        if raw_targets.size == 0:
            self.k = 0
            self.order = np.zeros(0, np.int64)
            self.targets = raw_targets
            self._buckets = []
            return
        order0 = np.argsort(raw_targets, kind="stable")
        sorted_t = raw_targets[order0]
        seg_targets, first, counts = np.unique(
            sorted_t, return_index=True, return_counts=True
        )
        blens = _next_pow2(counts)
        self._init_from_segments(seg_targets, counts, blens, first, order0)

    @classmethod
    def quantized(
        cls, raw_targets: np.ndarray, quantize, pad_target: int,
        pad_source: int,
    ) -> "SegmentedRowOr":
        """Canonical-structure plan for shape-bucketed engines: the
        per-power-of-two segment-count histogram is quantized up through
        ``quantize`` (the bucket ladder) by appending inert pad segments
        — ``order`` slot ``pad_source`` (the caller's appended all-zero
        source row) reduced into ``pad_target`` (the caller's reserved
        dead state row), a no-op under OR.  Two same-bucket ontologies
        then share ``self._buckets`` (the structure traced into the
        program) exactly, while ``order``/``targets`` differ only in
        CONTENT — which bucketed callers pass as runtime arguments
        (:meth:`write`'s ``targets=``), keeping the jaxpr
        ontology-independent."""
        raw_targets = np.asarray(raw_targets, np.int64)
        if raw_targets.size == 0:
            return cls(raw_targets)
        order0 = np.argsort(raw_targets, kind="stable")
        sorted_t = raw_targets[order0]
        seg_targets, first, counts = np.unique(
            sorted_t, return_index=True, return_counts=True
        )
        blens = _next_pow2(counts)
        # canonical level set: every power-of-two length from 1 up to
        # min(top level, 64) is ALWAYS materialized (padded to the
        # quantized count, at least quantize(1) segments), so a level
        # that happens to be empty in one corpus and sparse in another
        # still canonicalizes identically — total pad emission for the
        # always-on range is O(8·127) rows, a constant.  Levels ABOVE
        # 64 (hub targets with hundreds+ of members) are padded only
        # when present: forcing them would cost 8×(level) inert rows
        # per level per superstep — at a 64k-member hub that is ~1M pad
        # emissions, dwarfing the rules' real work — while a big
        # level's presence is next_pow2(hub size), doubly-log stable
        # across similar corpora anyway.  Per-level pad cost is thus
        # bounded by that level's own real emission (quantize at most
        # doubles a present count).
        present = dict(
            zip(*map(lambda a: a.tolist(), np.unique(blens,
                                                     return_counts=True)))
        )
        bc = max(int(blens.max()), 8)
        level = 1
        pad_blens = []
        while level <= bc:
            cnt = present.get(level, 0)
            if cnt or level <= 64:
                pad_blens.extend(
                    [level] * (quantize(max(cnt, 1)) - cnt)
                )
            level *= 2
        pad_blens = np.asarray(pad_blens, np.int64)
        # order0 grows one trailing slot holding the pad-source token;
        # pad segments (count=1, first=that slot) emit it blen times
        order0 = np.append(order0, np.int64(pad_source))
        plan = cls.__new__(cls)
        plan._init_from_segments(
            np.concatenate([seg_targets,
                            np.full(len(pad_blens), pad_target, np.int64)]),
            np.concatenate([counts, np.ones(len(pad_blens), np.int64)]),
            np.concatenate([blens, pad_blens]),
            np.concatenate(
                [first, np.full(len(pad_blens), len(order0) - 1, np.int64)]
            ),
            order0,
        )
        return plan

    def structure(self) -> tuple:
        """The traced-program-relevant shape of this plan — what a
        bucket signature must record so two engines sharing it can share
        one compiled program."""
        return (self.k, self.n_targets, tuple(self._buckets))

    def _init_from_segments(self, seg_targets, counts, blens, first, order0):
        """Build emission order + buckets from per-segment (target, member
        count, padded length, first-member offset into ``order0``).
        Fully vectorized — nf1 alone has ~10^5 segments at 100k-class
        scale, so a per-segment Python loop would dominate engine build."""
        bucket_sort = np.argsort(blens, kind="stable")
        seg_targets = seg_targets[bucket_sort]
        counts = counts[bucket_sort]
        blens = blens[bucket_sort]
        first = first[bucket_sort]
        total = int(blens.sum())
        out_starts = np.r_[0, np.cumsum(blens)[:-1]]
        seg_of = np.repeat(np.arange(len(blens)), blens)
        within = np.arange(total) - out_starts[seg_of]
        # pad each segment with repeats of its own members — OR-idempotent
        order = order0[first[seg_of] + within % counts[seg_of]]
        ubl, ucnt = np.unique(blens, return_counts=True)  # ascending = emission
        self.k = total
        self.order = order
        self.targets = seg_targets
        self._seg_counts = counts
        self._seg_blens = blens
        #: (padded_len, n_segments) per bucket, in emission order
        self._buckets = list(zip(ubl.tolist(), ucnt.tolist()))

    @property
    def n_targets(self) -> int:
        return len(self.targets)

    def reduce(self, rows) -> jnp.ndarray:
        """OR-reduce ``rows`` [k, W] (any integer dtype, already gathered
        through ``order``) within each segment → [n_targets, W]."""
        if not self._buckets:
            return rows[:0]
        outs = []
        pos = 0
        zero = np.zeros((), rows.dtype)
        for blen, nseg in self._buckets:
            chunk = rows[pos : pos + nseg * blen]
            pos += nseg * blen
            if blen == 1:
                outs.append(chunk)
            else:
                chunk = chunk.reshape(nseg, blen, rows.shape[1])
                outs.append(lax.reduce(chunk, zero, lax.bitwise_or, (1,)))
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    def apply(self, state, rows, track=False):
        """OR ``rows`` [k, W] (gathered through ``order``) into ``state``
        [N, W] at this plan's target rows.  ``track=True`` additionally
        returns a scalar "did any bit change"; ``track="rows"`` returns
        the per-target change vector [n_targets] bool instead (the
        frontier signal for chunk gating).  Either way the change is
        computed on the touched rows only, so the caller never needs to
        keep the pre-step state alive for a whole-array comparison
        (which doubles state memory inside the fixed-point loop)."""
        if self.k == 0:
            if track == "rows":
                return state, jnp.zeros(0, bool)
            return (state, jnp.asarray(False)) if track else state
        return self.write(state, self.reduce(rows), track)

    def write(self, state, reduced, track=False, targets=None):
        """The write half of :meth:`apply`: OR already-reduced per-target
        rows ``reduced`` [n_targets, W] into ``state``.  Split out so a
        gated caller can compute ``reduced`` under a ``lax.cond`` (zeros
        when the chunk is clean — OR is the identity on zeros) while the
        row write stays unconditional: only the chunk-bounded rows cross
        the cond boundary, never the multi-GB state (a state-valued cond
        branch forces a full pass-through copy per skipped chunk).
        ``targets``: optional RUNTIME target-row array (shape
        ``[n_targets]``) — bucketed engines pass their argument-carried
        copy so the plan's own ``self.targets`` never becomes a traced
        constant (the compiled program must stay ontology-independent).
        Duplicate targets (a quantized plan's pad segments all aim at
        the one reserved dead row) are safe: every duplicate writes the
        identical ``old | 0`` value."""
        if self.k == 0:
            if track == "rows":
                return state, jnp.zeros(0, bool)
            return (state, jnp.asarray(False)) if track else state
        state = jnp.asarray(state)
        t = jnp.asarray(self.targets) if targets is None else targets
        old = state[t]
        merged = old | reduced
        out = state.at[t].set(merged)
        if track == "rows":
            return out, jnp.any(merged != old, axis=1)
        if track:
            return out, jnp.any(merged != old)
        return out

    def split(self, max_rows: int):
        """Partition into subplans of at most ``max_rows`` (padded) source
        rows each (never splitting a segment, so each target row is
        written by exactly one subplan).  Returns ``[(slice, subplan)]``
        where ``slice`` indexes the caller's ``order``-gathered source
        arrays.  Used to bound per-rule temporaries: a single fused rule
        application materializes O(k·W) gather + reduce buffers, which
        exceeds HBM at ~100k-concept scale."""
        if self.k == 0:
            return []
        max_rows = max(int(max_rows), 1)
        cum = np.cumsum(self._seg_blens)
        pieces = []
        seg_cur = 0
        row_cur = 0
        nseg_total = len(self.targets)
        while seg_cur < nseg_total:
            seg_end = int(np.searchsorted(cum, row_cur + max_rows, "right"))
            seg_end = max(seg_end, seg_cur + 1)  # never an empty piece
            rows = int(cum[seg_end - 1]) - row_cur
            piece = SegmentedRowOr.__new__(SegmentedRowOr)
            blens = self._seg_blens[seg_cur:seg_end]
            first = np.r_[0, np.cumsum(blens)[:-1]]
            # the parent's order-gathered rows arrive already padded, so
            # the piece's members are the identity over its slice
            piece._init_from_segments(
                self.targets[seg_cur:seg_end],
                blens,  # members already padded: count == blen
                blens,
                first,
                np.arange(rows, dtype=np.int64),
            )
            pieces.append((slice(row_cur, row_cur + rows), piece))
            seg_cur = seg_end
            row_cur += rows
        return pieces
