"""Packed AND-OR semiring matmul on the MXU (Pallas TPU kernel).

Computes ``C = (A ⊙ B)`` where ``⊙`` is the boolean AND-OR product and A —
the *state* operand, the saturation engine's R matrix that persists in HBM
across the whole fixed point — is bit-packed 32-to-a-uint32:

    A  [M, KW] uint32 — rows packed along the contraction axis
    B  [K, N]  int8   — per-step operand, rows in *kernel contraction
                        order* (see below)
    C  [M, N]  int8   — 0/1 output

Packing A is the scale lever: the engine's R matrix is read in full by
every step, and packed words move 8x fewer HBM bytes than XLA's
byte-per-bool arrays (32x fewer than bf16).  B and C are axiom-indexed
per-step temporaries, so they stay byte-wide — every in-kernel op on them
is lane-aligned, which keeps the Mosaic program small and fast to compile
(sub-lane uint32 slicing blows up lowering time).

The kernel unpacks each A tile on the VPU (32 shifted copies of the whole
lane-aligned tile, concatenated bit-plane-major) and contracts on the MXU
with f32 accumulation — exact for any count below 2^24 ones.  This is the
hot op of the saturation engine: CR4's ``S[:,b] ∨= R ⊙ W`` and CR6's
chain join (SURVEY.md §7), i.e. the reference's two-sided hash join
(``RolePairHandler.java:421-425``) as one matmul.

Kernel contraction order
------------------------
The concat unpack of an A tile ``[TM, TKW]`` emits bit p of word w at
position ``p*TKW + w``.  B's rows must line up with that order, so a
static permutation (:func:`contraction_bit_order`) maps kernel row
position → logical bit index.  Callers bake the permutation into their
*static* index arrays (fillers, masks) at trace time — nothing is permuted
at runtime.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distel_tpu.ops.bitpack import unpack_words


def _pad_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def contraction_bit_order(n_words: int, tkw: int) -> np.ndarray:
    """Logical bit index for each kernel contraction position.

    ``n_words`` must be a multiple of the tile width ``tkw``.  Position
    ``rho = k*(tkw*32) + p*tkw + w`` (k-th tile, bit-plane p, word w)
    holds logical column ``(k*tkw + w)*32 + p``.
    """
    assert n_words % tkw == 0
    k = np.arange(n_words // tkw)[:, None, None]
    p = np.arange(32)[None, :, None]
    w = np.arange(tkw)[None, None, :]
    return ((k * tkw + w) * 32 + p).reshape(-1)


def _unpack_tile(words: jax.Array, dtype) -> jax.Array:
    """[R, W] uint32 → [R, W*32] dtype; position p*W + w = bit p of word w.
    Mosaic has no uint32→float cast, so bits hop through int32."""
    parts = [
        (
            (words >> jnp.asarray(p, jnp.uint32)) & jnp.asarray(1, jnp.uint32)
        ).astype(jnp.int32)
        for p in range(32)
    ]
    return jnp.concatenate(parts, axis=1).astype(dtype)


def _andor_kernel(a_ref, b_ref, o_ref, acc_ref, *, dtype):
    """Grid (i, j, k), k innermost; acc [TM, TN] f32 persists across k."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    a = _unpack_tile(a_ref[:], dtype)                   # [TM, TKW*32]
    b = b_ref[:].astype(jnp.int32).astype(dtype)        # [TKW*32, TN]
    acc_ref[:] += jnp.dot(a, b, preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        o_ref[:] = (acc_ref[:] > 0).astype(jnp.int32).astype(jnp.int8)


class PackedMatmulPlan:
    """Shape-specialized packed AND-OR matmul.

    ``plan = PackedMatmulPlan(m, kw, n)`` fixes the (padded) operand
    shapes and tiling; ``plan.bit_order`` is the kernel contraction order
    callers use to lay out B's rows; ``plan(a, b)`` runs the kernel.

    ``interpret=True`` runs the Pallas interpreter — the CPU test path.
    ``use_xla=True`` computes the same contract with plain XLA ops
    (unpack → matmul → threshold), used as the reference implementation
    and the fallback on hosts without Mosaic.
    """

    def __init__(
        self,
        m: int,
        kw: int,
        n: int,
        *,
        tm: int = 256,
        tkw: int = 128,
        tn: int = 256,
        dtype=None,
        interpret: bool = False,
        use_xla: Optional[bool] = None,
    ):
        self.m, self.kw, self.n = m, kw, n
        self.tm, self.tkw, self.tn = tm, tkw, tn
        self.m_p = _pad_up(max(m, 1), tm)
        self.kw_p = _pad_up(max(kw, 1), tkw)
        self.n_p = _pad_up(max(n, 1), tn)
        self.k_p = self.kw_p * 32
        if dtype is None:
            dtype = (
                jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
            )
        self.dtype = dtype
        self.interpret = interpret
        if use_xla is None:
            use_xla = jax.default_backend() != "tpu" and not interpret
        self.use_xla = use_xla
        #: kernel row position → logical bit index (length k_p)
        self.bit_order = contraction_bit_order(self.kw_p, tkw)

    # ---------------------------------------------------------------- call

    def _pad(self, a: jax.Array, b: jax.Array):
        a = jnp.pad(
            a, ((0, self.m_p - a.shape[0]), (0, self.kw_p - a.shape[1]))
        )
        b = jnp.pad(
            b, ((0, self.k_p - b.shape[0]), (0, self.n_p - b.shape[1]))
        )
        return a, b

    def __call__(self, a: jax.Array, b: jax.Array) -> jax.Array:
        """a [m, kw] uint32; b [<=k_p, n] int8 rows in ``bit_order``.
        Returns C [m, n] int8 (0/1)."""
        if self.use_xla:
            return self._xla(a, b)
        a, b = self._pad(a, b)
        gm = self.m_p // self.tm
        gn = self.n_p // self.tn
        gk = self.kw_p // self.tkw
        out = pl.pallas_call(
            functools.partial(_andor_kernel, dtype=self.dtype),
            grid=(gm, gn, gk),
            in_specs=[
                pl.BlockSpec(
                    (self.tm, self.tkw),
                    lambda i, j, k: (i, k),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (self.tkw * 32, self.tn),
                    lambda i, j, k: (k, j),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (self.tm, self.tn),
                lambda i, j, k: (i, j),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((self.m_p, self.n_p), jnp.int8),
            scratch_shapes=[pltpu.VMEM((self.tm, self.tn), jnp.float32)],
            interpret=self.interpret,
        )(a, b)
        return out[: self.m, : self.n]

    def _xla(self, a: jax.Array, b: jax.Array) -> jax.Array:
        """Reference/fallback: same contract via unpack → matmul."""
        a, b = self._pad(a, b)
        a_bits = unpack_words(a, self.k_p)          # logical order
        a_kern = a_bits[:, self.bit_order]          # kernel order
        dt = self.dtype
        prod = jnp.matmul(
            a_kern.astype(dt), b.astype(dt),
            preferred_element_type=jnp.float32,
        )
        return (prod > 0).astype(jnp.int8)[: self.m, : self.n]


def _packed_cols_prologue(acc_ref):
    @pl.when(pl.program_id(2) == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)


def _packed_cols_accumulate(a_ref, b_ref, acc_ref, dtype):
    one = jnp.asarray(1, jnp.uint32)
    b = b_ref[:]                                        # [TL, TW] uint32
    bits = jnp.concatenate(
        [
            ((b >> jnp.asarray(p, jnp.uint32)) & one).astype(jnp.int32)
            for p in range(32)
        ],
        axis=1,
    ).astype(dtype)                                     # [TL, 32*TW]
    a = a_ref[:].astype(jnp.int32).astype(dtype)        # [TM, TL]
    acc_ref[:] += jnp.dot(a, bits, preferred_element_type=jnp.float32)


def _packed_cols_epilogue(o_ref, acc_ref, tw: int):
    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _():
        hit = acc_ref[:] > 0                            # [TM, 32*TW]
        word = jnp.zeros(o_ref.shape, jnp.uint32)
        for p in range(32):
            word |= hit[:, p * tw : (p + 1) * tw].astype(jnp.uint32) << p
        o_ref[:] = word


def _packed_cols_kernel(a_ref, b_ref, o_ref, acc_ref, *, dtype, tw: int):
    """Grid (i, j, k), k innermost; acc [TM, 32*TW] f32 persists across k.
    B tiles are packed uint32 words; unpack/repack happen entirely in
    VMEM, bit-plane-major via lane-aligned static slices (no sub-lane
    reshapes, which blow up Mosaic lowering)."""
    _packed_cols_prologue(acc_ref)
    _packed_cols_accumulate(a_ref, b_ref, acc_ref, dtype)
    _packed_cols_epilogue(o_ref, acc_ref, tw)


def _packed_cols_sparse_kernel(
    flags_ref, plk_ref, a_ref, b_ref, o_ref, acc_ref, *, dtype, tw: int
):
    """Tile-skipping variant of :func:`_packed_cols_kernel`.
    ``flags_ref`` (scalar-prefetch, [GM, GK] int32) marks which A tiles
    contain any nonzero: the unpack + MXU dot are skipped for all-zero A
    tiles.  ``plk_ref`` ([GM, GK] int32) holds, per (i, k), the last
    live k' ≤ k: the A/B BlockSpec index maps route dead steps back to
    the block already resident in VMEM, so the pipeline issues **no DMA
    for skipped tiles** — without the redirect a skipped tile still pays
    its HBM→VMEM copy, and at the measured ~93% dead-tile fraction of
    the role-block-diagonal CR6 operand the copies, not the MXU, bound
    the kernel.  The per-step operand A = closure-mask ∧ bit-table is
    ~99.9% element-sparse at saturation scale (emptier in every earlier
    iteration)."""
    _packed_cols_prologue(acc_ref)

    @pl.when(flags_ref[pl.program_id(0), pl.program_id(2)] != 0)
    def _():
        _packed_cols_accumulate(a_ref, b_ref, acc_ref, dtype)

    _packed_cols_epilogue(o_ref, acc_ref, tw)


class PackedColsMatmulPlan:
    """AND-OR semiring matmul with **packed output columns**:
    ``C_packed = pack_x((A ⊙ unpack_x(B_packed)))``

        A         [M, L]  int8/bool — per-step operand (axiom masks)
        B_packed  [L, W]  uint32    — state operand, 32 x-columns/word
        C_packed  [M, W]  uint32

    The complement of :class:`PackedMatmulPlan` (which packs A along the
    *contraction* axis): here the contraction axis L is narrow (the link
    table) and the wide output x-axis stays packed end to end — B is
    unpacked and C repacked per VMEM tile, so the byte-per-bit [L, 32W]
    operand and the 4-byte-per-bit [M, 32W] i32 product that the XLA
    formulation materializes in HBM never exist.  This is CR4/CR6 of the
    row-packed engine (reference: the two-sided join of
    ``RolePairHandler.java:421-425`` / ``base/Type5AxiomProcessorBase.java``).

    ``use_xla=True`` computes the same contract with plain XLA ops — the
    reference implementation and the non-TPU fallback."""

    def __init__(
        self,
        m: int,
        l: int,
        w: int,
        *,
        tm: int = 512,
        tl: int = 256,
        tw: int = 128,
        dtype=None,
        interpret: bool = False,
        use_xla: Optional[bool] = None,
        skip_zero_tiles: Optional[bool] = None,
    ):
        self.m, self.l, self.w = m, l, w
        self.tm, self.tl, self.tw = tm, tl, tw
        self.m_p = _pad_up(max(m, 1), tm)
        self.l_p = _pad_up(max(l, 1), tl)
        self.w_p = _pad_up(max(w, 1), tw)
        if dtype is None:
            dtype = (
                jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
            )
        self.interpret = interpret
        if use_xla is None:
            use_xla = jax.default_backend() != "tpu" and not interpret
        self.use_xla = use_xla
        if skip_zero_tiles is None:
            # the per-tile branch costs pipeline overlap on dense tiles;
            # it pays only once the full contraction is MXU-bound
            # (measured crossover ~1 TFLOP on a v5e)
            skip_zero_tiles = 2 * self.m_p * self.l_p * self.w_p * 32 >= 1e12
        self.skip_zero_tiles = skip_zero_tiles
        if not use_xla and jnp.issubdtype(dtype, jnp.integer):
            # Mosaic's MXU path requires float operands with the f32
            # accumulator; bf16 is exact here (0/1 products, < 2^24 terms)
            dtype = jnp.bfloat16
        self.dtype = dtype

    def __call__(self, a: jax.Array, b_packed: jax.Array) -> jax.Array:
        """a [m, l] int8/bool; b_packed [l, w] uint32 → [m, w] uint32."""
        if self.use_xla:
            return self._xla(a, b_packed)
        a = jnp.pad(
            a.astype(jnp.int8),
            ((0, self.m_p - a.shape[0]), (0, self.l_p - a.shape[1])),
        )
        b = jnp.pad(
            b_packed,
            ((0, self.l_p - b_packed.shape[0]), (0, self.w_p - b_packed.shape[1])),
        )
        gm = self.m_p // self.tm
        gk = self.l_p // self.tl
        grid = (gm, self.w_p // self.tw, gk)
        a_spec = ((self.tm, self.tl), lambda i, j, k: (i, k))
        b_spec = ((self.tl, self.tw), lambda i, j, k: (k, j))
        o_spec = ((self.tm, self.tw), lambda i, j, k: (i, j))
        scratch = [pltpu.VMEM((self.tm, 32 * self.tw), jnp.float32)]
        out_shape = jax.ShapeDtypeStruct((self.m_p, self.w_p), jnp.uint32)
        if not self.skip_zero_tiles:
            out = pl.pallas_call(
                functools.partial(
                    _packed_cols_kernel, dtype=self.dtype, tw=self.tw
                ),
                grid=grid,
                in_specs=[
                    pl.BlockSpec(*a_spec, memory_space=pltpu.VMEM),
                    pl.BlockSpec(*b_spec, memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(*o_spec, memory_space=pltpu.VMEM),
                out_shape=out_shape,
                scratch_shapes=scratch,
                interpret=self.interpret,
            )(a, b)
            return out[: self.m, : self.w]
        # per-A-tile any-nonzero flags, computed by XLA in one cheap pass;
        # index maps gain trailing scalar-prefetch ref arguments
        live = (
            (a != 0)
            .reshape(gm, self.tm, gk, self.tl)
            .any(axis=(1, 3))
        )
        flags = live.astype(jnp.int32)
        # last live k' <= k per row block (leading dead ks clamp to 0):
        # dead grid steps re-"fetch" the block already in VMEM, which
        # the pipeline recognizes as the same index and skips the DMA
        plk = jnp.maximum(
            lax.cummax(
                jnp.where(live, jnp.arange(gk, dtype=jnp.int32)[None, :], -1),
                axis=1,
            ),
            0,
        ).astype(jnp.int32)
        out = pl.pallas_call(
            functools.partial(
                _packed_cols_sparse_kernel, dtype=self.dtype, tw=self.tw
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=grid,
                in_specs=[
                    pl.BlockSpec(
                        a_spec[0],
                        lambda i, j, k, f, p: (i, p[i, k]),
                        memory_space=pltpu.VMEM,
                    ),
                    pl.BlockSpec(
                        b_spec[0],
                        lambda i, j, k, f, p: (p[i, k], j),
                        memory_space=pltpu.VMEM,
                    ),
                ],
                out_specs=pl.BlockSpec(
                    o_spec[0],
                    lambda i, j, k, f, p: (i, j),
                    memory_space=pltpu.VMEM,
                ),
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            interpret=self.interpret,
        )(flags, plk, a, b)
        return out[: self.m, : self.w]

    def _xla(self, a: jax.Array, b_packed: jax.Array) -> jax.Array:
        """Reference/fallback: plane-major unpack → matmul → threshold →
        repack (materializes the wide operands the kernel avoids)."""
        from distel_tpu.ops.bitpack import pack_planes, unpack_words_planes

        bits = unpack_words_planes(b_packed, jnp.int8)
        prod = jnp.matmul(
            a.astype(jnp.int8), bits, preferred_element_type=jnp.int32
        )
        return pack_planes(prod > 0)


def packed_andor_matmul(
    a: jax.Array, b_logical: jax.Array, **plan_kw
) -> jax.Array:
    """One-shot convenience: ``b_logical`` [K, N] int8/bool rows are in
    logical bit order; this permutes them at runtime (a gather) — fine for
    tests/small calls.  Hot paths should build B directly in
    ``plan.bit_order`` instead."""
    plan = PackedMatmulPlan(a.shape[0], a.shape[1], b_logical.shape[1], **plan_kw)
    valid = plan.bit_order < b_logical.shape[0]
    src = np.where(valid, plan.bit_order, 0)
    b = jnp.where(
        jnp.asarray(valid)[:, None],
        b_logical.astype(jnp.int8)[src],
        jnp.asarray(0, jnp.int8),
    )
    return plan(a, b)
