"""TPU ops: bit-packed boolean linear algebra for the saturation engine.

The reference keeps its boolean state as Redis sets/zsets; the dense engine
(``core/engine.py``) keeps it as XLA bool arrays (one byte per bit).  This
package provides the third representation — uint32 bitsets (32 concepts per
word) — plus the Pallas TPU kernels that compute directly on it, which is
what lets the single-chip concept ceiling grow ~8x (SURVEY.md §7 step 6).
"""

from distel_tpu.ops.bitpack import (  # noqa: F401
    gather_bit_columns,
    pack_bool_columns,
    scatter_or_columns,
    unpack_words,
)
from distel_tpu.ops.bitmatmul import (  # noqa: F401
    contraction_bit_order,
    packed_andor_matmul,
)
