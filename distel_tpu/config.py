"""Configuration — the rebuild of ``ShardInfo.properties`` +
``misc/PropertyFileHandler.java``.

The reference's config surface (cluster topology, rule→node weights,
chunk size, work-stealing / instrumentation flags) maps onto the TPU
design like this:

=============================  ==========================================
reference knob                 TPU-native equivalent
=============================  ==========================================
NODES_LIST (:20)               ``mesh_devices`` — #devices on the concept
                               axis of the ``jax.sharding.Mesh``
CR_TYPE* weights (:5-12)       gone: SPMD shards every rule uniformly; a
                               per-rule ``backend`` override survives as
                               the plugin boundary (``rule_backends``)
chunk.size (:27-29)            ``pad_multiple`` — shard granularity of the
                               concept axis
work.stealing.enabled (:31)    gone: static SPMD balance by construction
instrumentation.enabled (:32)  ``instrumentation`` — per-phase timers
NORMALIZE_CACHE node (:24)     ``normalize_cache_path`` — gensym memo file
=============================  ==========================================

``from_properties`` still parses java-style ``key = value`` files so a
reference deployment's config can be carried over.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional


def enable_compile_cache(cache_dir: Optional[str] = None) -> None:
    """Persistent XLA compilation cache (under ``~/.cache/distel_tpu``
    unless the user set JAX_COMPILATION_CACHE_DIR or passed
    ``cache_dir``) — repeat runs skip the 10-100s jit compile of the
    saturation program, and with shape-bucketed programs
    (``ClassifierConfig.shape_buckets``) DIFFERENT ontologies in one
    bucket share the cached entry.  ``DISTEL_CACHE_MIN_COMPILE_S``
    overrides the persistence floor (default 1.0 s; CI and the warmup
    tests set it to 0 so tier-1-sized programs persist too).  Called by
    the jax-using entry points (classify/stream/bench/serve/warmup),
    never on import."""
    if cache_dir is None and os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    try:
        import jax

        cache = cache_dir or os.path.join(
            os.path.expanduser("~"), ".cache", "distel_tpu", "jax-cache"
        )
        jax.config.update("jax_compilation_cache_dir", cache)
        min_s = float(os.environ.get("DISTEL_CACHE_MIN_COMPILE_S", "1.0"))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_s
        )
    except Exception:
        pass  # cache is an optimization, never a requirement


@dataclass
class ClassifierConfig:
    #: number of mesh devices on the concept axis (None = single device)
    mesh_devices: Optional[int] = None
    #: concept-axis padding granularity (MXU tiling + shard divisibility)
    pad_multiple: int = 128
    #: matmul compute dtype for the AND-OR semiring
    #: ("auto"|"int8"|"bfloat16"|"float32") — auto picks int8 for the
    #: rowpacked engine (2x bf16 on the MXU, exact in i32 accumulation),
    #: bf16 on TPU / f32 on CPU for the dense engine
    matmul_dtype: str = "auto"
    max_iterations: int = 10_000
    #: per-phase wall-clock tracing (reference instrumentation.enabled)
    instrumentation: bool = False
    #: persistable gensym cache for incremental re-runs (reference
    #: NORMALIZE_CACHE, ShardInfo.properties:24)
    normalize_cache_path: Optional[str] = None
    #: per-rule backend override, the reference's rule→node plugin boundary:
    #: {"CR1": "tpu", ...}; "cpu" routes that rule through the oracle in
    #: hybrid verification runs
    rule_backends: Dict[str, str] = field(default_factory=dict)
    #: use the C++ load plane (native/distel_loader.cpp) when available —
    #: ~13x faster text→tensors than the Python frontend
    use_native_loader: bool = True
    #: multi-host (DCN) wiring: when set, ELClassifier joins the JAX
    #: multi-controller runtime before building the mesh
    #: (distel_tpu/parallel/mesh.py — the NODES_LIST analog)
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    #: state representation: "rowpacked" (transposed uint32 bitsets,
    #: scatter-free — the flagship: fastest measured and 8x the dense
    #: concept ceiling), "dense" (bool arrays, the simplest reference
    #: path), "packed" (x-major uint32 bitsets + Pallas kernels), or
    #: "auto" (rowpacked)
    engine: str = "auto"
    #: shape-bucketed saturation programs (rowpacked engine only): every
    #: compile-relevant static dimension quantizes onto a geometric
    #: ladder and all ontology content rides in runtime arguments, so
    #: same-bucket ontologies share one compiled program (in-process
    #: registry + persistent cache) — the cold-start compile fix.
    #: Covers the incremental delta fast path too: its B/cross
    #: programs pin the base layout verbatim but bucket their own
    #: table/window structure, so steady-state delta traffic compiles
    #: once per bucket per process (``DISTEL_EXACT_DELTA_PROGRAMS=1``
    #: is the bench A/B hatch back to exact-shape delta programs).
    shape_buckets: bool = True
    #: base-corpus concept count below which an increment takes the
    #: full-rebuild path instead of the delta fast path.  The old
    #: 32_768 floor was justified entirely by the fast path's fixed
    #: compile cost (a 0.3-1 s XLA compile per delta); with bucketed
    #: delta programs that cost is gone in the steady state, and
    #: re-measured on this 2-core CPU host (snomed-shaped corpora,
    #: class-only steady deltas, warm caches) the median walls TIE at
    #: small scale — 344 concepts: fast 10.5 s vs rebuild 10.7 s; 1393
    #: concepts: fast 48.2 s vs rebuild 49.8 s — while the rebuild leg
    #: still pays residual compile churn (3.9 s steady max at 1393:
    #: growing table rungs re-quantize) and an O(corpus) engine
    #: reconstruction per increment that the fast path skips entirely.
    #: 2048 keeps tiny corpora (where construction is trivial and the
    #: single-engine rebuild saturate beats the multi-program
    #: round-robin's overhead) on the rebuild path and everything else
    #: on the compile-free fast path; on TPU hosts (ms steps, s
    #: compiles) the fast path wins from far lower still — tune down
    #: via ``fast.path.min.concepts``.
    fast_path_min_concepts: int = 2_048
    #: geometric ladder step for the corpus-size axes (concept rows,
    #: link rows, rule-table rows) — padding waste per axis is bounded
    #: by (bucket_ratio - 1)
    bucket_ratio: float = 1.25
    #: persistent XLA compile-cache directory override (None = the
    #: enable_compile_cache default under ~/.cache/distel_tpu)
    compile_cache_dir: Optional[str] = None
    #: adaptive sparse-tail execution (rowpacked engine, observed runs,
    #: single-device and mesh — the sparse program builds in the same
    #: shard_map structure as the dense step): when a round's frontier
    #: density drops below
    #: ``sparse_density_threshold``, the controller switches from the
    #: dense step program to a frontier-compacted sparse step that
    #: gathers only the active rule rows/chunks into a small
    #: capacity-quantized workspace — late saturation rounds then cost
    #: what they derive instead of a full corpus sweep
    sparse_tail: bool = True
    #: frontier density (active rule rows / total rule rows) below which
    #: a round is eligible for the sparse tier
    sparse_density_threshold: float = 0.05
    #: number of geometric workspace-capacity rungs the sparse tier may
    #: compile (the roster bound): rung i holds ``floor * 2**i`` rows;
    #: an active set past the largest rung falls back to the dense step
    #: for that round (never drops work)
    sparse_capacity_buckets: int = 8
    #: consecutive below-threshold rounds required before switching to
    #: the sparse tier (switching back to dense is immediate)
    sparse_hysteresis_rounds: int = 2
    #: live-tile CR6 formulation (rowpacked engine, scanned CR6, single
    #: device): the role-chain join contracts role-run row tiles
    #: against densely packed live-link tiles instead of the scanned
    #: role-union windows — byte-identical closure per round, a
    #: fraction of the MAC volume when the live structure is sparse
    #: (``core/cr6_tiles.py``; BENCH_r03 put the window formulation at
    #: 67% of the device step with 93% dead MACs)
    cr6_tiles: bool = True
    #: row-tile height of the live-tile CR6 contraction
    cr6_tiles_tile_m: int = 512
    #: link-tile width (packed live links per contraction tile)
    cr6_tiles_tile_l: int = 256
    #: tiled-vs-window MAC-volume ratio above which the engine keeps
    #: the window formulation (tiles only pay on sparse live structure)
    cr6_tiles_density_threshold: float = 0.5
    #: pipelined observation (rowpacked engine, observed runs): dense
    #: rounds depend only on device-carried state, so up to
    #: ``pipeline_depth`` rounds stay speculatively in flight while the
    #: host retires earlier rounds' changed/bits/frontier folds from a
    #: queue — per-round observability without a blocking host sync per
    #: superstep.  Byte-identical per retired round to the synchronous
    #: loop; the adaptive controller drains the queue before any sparse
    #: tier switch.
    pipeline: bool = True
    #: maximum speculatively in-flight observed rounds (1 = synchronous)
    pipeline_depth: int = 2
    #: device-resident fused rounds (rowpacked engine, observed runs,
    #: single-device and mesh): with ``fused_rounds_k`` > 1 the round
    #: loop itself moves onto the device — one dispatch runs up to K
    #: rounds of the adaptive controller in a ``lax.while_loop`` (the
    #: dense/sparse tier pick, density/hysteresis and convergence
    #: tests all on device) and the host pays its per-round work
    #: (dispatch, frontier fold, ledger/observer callbacks) once per
    #: WINDOW — the reference's per-iteration barrier cost amortized
    #: K×.  Byte-identical per retired round to the per-round
    #: controllers; a round overflowing the window's traced sparse
    #: workspace falls out to the per-round path and never drops work.
    fused_rounds: bool = True
    #: rounds per fused window (K).  1 = the per-round controllers
    #: (the fused program is never built); raise on hosts where the
    #: per-round host round-trip dominates the retire wall.
    fused_rounds_k: int = 1
    #: K-adaptive terminal window: once the derivation tail's geometric
    #: decay predicts fewer remaining rounds than a full window would
    #: speculate, the controller halves K down the power-of-two ladder
    #: (K, K/2, ..., 2) for the next window — the last windows waste
    #: fewer speculative fixed-point rounds.  Retired rounds stay
    #: byte-identical (only window boundaries move); each ladder K is
    #: its own registry program (warmed by precompile/farm-build).
    fused_rounds_adaptive: bool = False
    #: AOT artifact farm (``core/artifacts.py``): directory holding a
    #: ``cli farm-build`` output — serialized executables + shipped
    #: compile-cache entries under a checksummed manifest.  Set, every
    #: entry point installs it over the program registry so covered
    #: programs load with zero trace/compile; unset (None) = compile
    #: as before.
    artifacts_dir: Optional[str] = None
    #: fail startup when ``artifacts_dir`` is set but the manifest is
    #: missing/corrupt or was baked under a different backend/jax
    #: pin/device count (default: warn loudly and fall back to compile)
    artifacts_require: bool = False
    #: serve fleet (``serve/fleet/``): replica processes behind the
    #: router — shared-nothing scale-out of the serve plane (the
    #: reference's NODES_LIST, but processes on one host instead of
    #: Redis nodes)
    fleet_replicas: int = 2
    #: queue-depth divergence (hot − cool) that triggers a live
    #: ontology migration toward the cooler replica
    fleet_depth_divergence: int = 8
    #: router heartbeat period against each replica's /healthz
    fleet_heartbeat_interval_s: float = 1.0
    #: consecutive heartbeat failures before a replica is ejected (and
    #: respawned when a supervisor is attached)
    fleet_eject_failures: int = 3
    #: rebalance sweep period (each sweep migrates at most one ontology)
    fleet_rebalance_interval_s: float = 2.0
    #: observability (``distel_tpu/obs/``): end-to-end request tracing
    #: + the fleet flight recorder.  ``obs_enable=False`` takes every
    #: span fully off-path (no ring writes, no thread-local, no
    #: traceparent parsing) — the flight recorder stays on (it is the
    #: post-incident record; its cost is one dict per control-plane
    #: event)
    obs_enable: bool = True
    #: fraction of root requests that record spans (children inherit
    #: the parent's decision via the traceparent sampled flag)
    obs_sample_rate: float = 1.0
    #: record per-saturation-round span events on traced REBUILD
    #: classifies by running the observed fixed-point loop (byte-
    #: identical per retired round, ~parity wall under the default
    #: pipeline).  Off by default: the observed program is jitted per
    #: engine OUTSIDE the bucket program registry, so it would charge a
    #: fresh XLA compile to every traced load — a warmed bucket's
    #: compile-free load guarantee wins unless the operator opts into
    #: round-level visibility.  (Runs that are already observed —
    #: scale probes, anything through ``saturate_observed`` — emit
    #: round events on traced requests regardless of this knob.)
    obs_trace_rounds: bool = False
    #: run ledger (``distel_tpu/obs/ledger.py``): durable per-round
    #: JSONL telemetry for observed saturations.  On, REBUILD
    #: classifies run the observed fixed-point loop and append one
    #: structured record per superstep round (plus open/close markers)
    #: to a per-process ledger under ``obs_ledger_dir`` — the durable
    #: record SCALE_r05's killed 14h run never had.  Off by default
    #: for the same reason as ``obs_trace_rounds``: the observed
    #: program compiles outside the bucket registry.  (Scale probes
    #: ledger through ``scripts/scale_probe.py --ledger`` regardless
    #: of this knob.)
    obs_ledger: bool = False
    #: directory rebuild ledgers land in (created on demand; one
    #: ``rebuild-<pid>.ledger.jsonl`` per process)
    obs_ledger_dir: str = "runs"
    #: finished-span ring capacity per process (bounded memory — a
    #: resident server traces forever without growing)
    obs_ring_capacity: int = 2048
    #: flight-recorder event ring capacity per process
    obs_flight_capacity: int = 4096
    #: read-optimized query plane (``serve/query/``): on every commit
    #: the registry publishes an immutable versioned snapshot of the
    #: packed closure, and the ``/query/*`` endpoints answer off it —
    #: lock-free, never riding the scheduler lane.  Off: the endpoints
    #: 404 and no per-commit host snapshot is built.
    query_enable: bool = True
    #: decoded-row LRU capacity per snapshot (subsumer/slice reads
    #: decode one wire row lazily; repeat reads of hot classes hit RAM)
    query_row_cache: int = 256
    #: cross-tenant cohort execution (ISSUE 12): the scheduler groups
    #: compatible pending delta requests by bucket signature and the
    #: registry advances the whole cohort under ONE vmapped device
    #: dispatch per vote (states stacked on a leading tenant axis) —
    #: per-tenant results byte-identical to solo execution.  Off: every
    #: delta dispatches inline per tenant (the pre-cohort behavior).
    cohort_enable: bool = True
    #: largest cohort one dispatch advances; cohort programs compile
    #: per power-of-two rung (a cohort of 3 pads to 4), so this also
    #: bounds the cohort-program population
    cohort_max_size: int = 8
    #: bounded formation wait: how long a delta at the head of its lane
    #: holds for same-bucket companions before dispatching anyway (the
    #: classic batching latency/throughput trade — keep it well under a
    #: typical delta's service time)
    cohort_max_wait_ms: float = 25.0
    #: comma-separated cohort sizes ``warm_delta_programs`` AOTs for
    #: the canonical delta rosters ("" = skip cohort warmup): a warmed
    #: replica's FIRST cohort then dispatches compile-free
    cohort_warm_sizes: str = ""
    #: compress registry cold spills (``np.savez_compressed``) — ~8x
    #: smaller on disk for sparse closures (941 MB → low hundreds at
    #: 64k, see ADVICE.md) at the price of zlib wall on the spill;
    #: restore reads both forms transparently
    storage_compress_spills: bool = True
    #: host-RAM warm-tier budget (MiB): hot evictions demote to a
    #: packed host-RAM snapshot first (promotable back in milliseconds,
    #: no frontend replay) and only overflow past this budget spills to
    #: compressed disk.  0 disables the warm tier (evictions go
    #: straight to cold, the pre-tiering behavior).
    storage_warm_budget_mb: float = 0.0
    #: halflife of the per-ontology read/write traffic EWMA that picks
    #: eviction victims and prefetch candidates
    storage_ewma_halflife_s: float = 60.0
    #: period of the background tier promoter (prefetch warm/cold
    #: entries with read traffic back toward hot while budget headroom
    #: exists); 0 disables it
    storage_prefetch_interval_s: float = 5.0

    @classmethod
    def from_properties(cls, path: str) -> "ClassifierConfig":
        """Parse a java-properties-style file (``key = value``, ``#``/``!``
        comments), accepting both our keys and reference spellings."""
        raw: Dict[str, str] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith(("#", "!")):
                    continue
                if "=" in line:
                    k, v = line.split("=", 1)
                    raw[k.strip()] = v.strip()
        cfg = cls()
        if "mesh.devices" in raw:
            cfg.mesh_devices = int(raw["mesh.devices"])
        elif "NODES_LIST" in raw:  # reference spelling: count the nodes
            cfg.mesh_devices = len([n for n in raw["NODES_LIST"].split(",") if n])
        if "pad.multiple" in raw:
            cfg.pad_multiple = int(raw["pad.multiple"])
        elif "chunk.size" in raw:  # nearest reference analog
            cfg.pad_multiple = max(8, min(int(raw["chunk.size"]), 1024))
        if "matmul.dtype" in raw:
            cfg.matmul_dtype = raw["matmul.dtype"]
        if "max.iterations" in raw:
            cfg.max_iterations = int(raw["max.iterations"])
        for key in ("instrumentation.enabled", "instrumentation"):
            if key in raw:
                cfg.instrumentation = raw[key].lower() == "true"
        if "normalize.cache.path" in raw:
            cfg.normalize_cache_path = raw["normalize.cache.path"]
        if "native.loader" in raw:
            cfg.use_native_loader = raw["native.loader"].lower() == "true"
        if "coordinator.address" in raw:
            cfg.coordinator_address = raw["coordinator.address"]
        if "num.processes" in raw:
            cfg.num_processes = int(raw["num.processes"])
        if "process.id" in raw:
            cfg.process_id = int(raw["process.id"])
        if "engine" in raw:
            cfg.engine = raw["engine"]
        if "shape.buckets" in raw:
            cfg.shape_buckets = raw["shape.buckets"].lower() == "true"
        if "bucket.ratio" in raw:
            cfg.bucket_ratio = float(raw["bucket.ratio"])
        if "compile.cache.dir" in raw:
            cfg.compile_cache_dir = raw["compile.cache.dir"]
        if "fast.path.min.concepts" in raw:
            cfg.fast_path_min_concepts = int(
                raw["fast.path.min.concepts"]
            )
        if "sparse_tail.enable" in raw:
            cfg.sparse_tail = raw["sparse_tail.enable"].lower() == "true"
        if "sparse_tail.density_threshold" in raw:
            cfg.sparse_density_threshold = float(
                raw["sparse_tail.density_threshold"]
            )
        if "sparse_tail.capacity_buckets" in raw:
            cfg.sparse_capacity_buckets = int(
                raw["sparse_tail.capacity_buckets"]
            )
        if "sparse_tail.hysteresis_rounds" in raw:
            cfg.sparse_hysteresis_rounds = int(
                raw["sparse_tail.hysteresis_rounds"]
            )
        if "cr6.tiles.enable" in raw:
            cfg.cr6_tiles = raw["cr6.tiles.enable"].lower() == "true"
        if "cr6.tiles.tile_m" in raw:
            cfg.cr6_tiles_tile_m = int(raw["cr6.tiles.tile_m"])
        if "cr6.tiles.tile_l" in raw:
            cfg.cr6_tiles_tile_l = int(raw["cr6.tiles.tile_l"])
        if "cr6.tiles.density_threshold" in raw:
            cfg.cr6_tiles_density_threshold = float(
                raw["cr6.tiles.density_threshold"]
            )
        if "pipeline.enable" in raw:
            cfg.pipeline = raw["pipeline.enable"].lower() == "true"
        if "pipeline.depth" in raw:
            cfg.pipeline_depth = int(raw["pipeline.depth"])
        if "fused.rounds.enable" in raw:
            cfg.fused_rounds = (
                raw["fused.rounds.enable"].lower() == "true"
            )
        if "fused.rounds.k" in raw:
            cfg.fused_rounds_k = int(raw["fused.rounds.k"])
        if "fused.rounds.adaptive" in raw:
            cfg.fused_rounds_adaptive = (
                raw["fused.rounds.adaptive"].lower() == "true"
            )
        if "artifacts.dir" in raw:
            cfg.artifacts_dir = raw["artifacts.dir"]
        if "artifacts.require" in raw:
            cfg.artifacts_require = (
                raw["artifacts.require"].lower() == "true"
            )
        if "fleet.replicas" in raw:
            cfg.fleet_replicas = int(raw["fleet.replicas"])
        if "fleet.depth.divergence" in raw:
            cfg.fleet_depth_divergence = int(raw["fleet.depth.divergence"])
        if "fleet.heartbeat.interval_s" in raw:
            cfg.fleet_heartbeat_interval_s = float(
                raw["fleet.heartbeat.interval_s"]
            )
        if "fleet.eject.failures" in raw:
            cfg.fleet_eject_failures = int(raw["fleet.eject.failures"])
        if "fleet.rebalance.interval_s" in raw:
            cfg.fleet_rebalance_interval_s = float(
                raw["fleet.rebalance.interval_s"]
            )
        if "obs.enable" in raw:
            cfg.obs_enable = raw["obs.enable"].lower() == "true"
        if "obs.sample_rate" in raw:
            cfg.obs_sample_rate = float(raw["obs.sample_rate"])
        if "obs.trace_rounds" in raw:
            cfg.obs_trace_rounds = (
                raw["obs.trace_rounds"].lower() == "true"
            )
        if "obs.ledger.enable" in raw:
            cfg.obs_ledger = raw["obs.ledger.enable"].lower() == "true"
        if "obs.ledger.dir" in raw:
            cfg.obs_ledger_dir = raw["obs.ledger.dir"]
        if "obs.ring.capacity" in raw:
            cfg.obs_ring_capacity = int(raw["obs.ring.capacity"])
        if "obs.flight.capacity" in raw:
            cfg.obs_flight_capacity = int(raw["obs.flight.capacity"])
        if "query.enable" in raw:
            cfg.query_enable = raw["query.enable"].lower() == "true"
        if "query.row.cache" in raw:
            cfg.query_row_cache = int(raw["query.row.cache"])
        if "cohort.enable" in raw:
            cfg.cohort_enable = raw["cohort.enable"].lower() == "true"
        if "cohort.max_size" in raw:
            cfg.cohort_max_size = int(raw["cohort.max_size"])
        if "cohort.max_wait_ms" in raw:
            cfg.cohort_max_wait_ms = float(raw["cohort.max_wait_ms"])
        if "cohort.warm.sizes" in raw:
            cfg.cohort_warm_sizes = raw["cohort.warm.sizes"]
        if "storage.compress.spills" in raw:
            cfg.storage_compress_spills = (
                raw["storage.compress.spills"].lower() == "true"
            )
        if "storage.warm.budget.mb" in raw:
            cfg.storage_warm_budget_mb = float(
                raw["storage.warm.budget.mb"]
            )
        if "storage.ewma.halflife_s" in raw:
            cfg.storage_ewma_halflife_s = float(
                raw["storage.ewma.halflife_s"]
            )
        if "storage.prefetch.interval_s" in raw:
            cfg.storage_prefetch_interval_s = float(
                raw["storage.prefetch.interval_s"]
            )
        for k, v in raw.items():
            if k.startswith("backend."):  # backend.CR1 = tpu
                cfg.rule_backends[k[len("backend."):]] = v
        return cfg

    def cohort_warm_size_list(self) -> list:
        """Parsed ``cohort.warm.sizes`` (empty = no cohort warmup)."""
        return [
            int(s)
            for s in self.cohort_warm_sizes.replace(",", " ").split()
            if s
        ]

    def sparse_tail_config(self) -> Optional[dict]:
        """The rowpacked engine's ``sparse_tail=`` kwarg for this config
        (None = tier disabled)."""
        if not self.sparse_tail:
            return None
        return {
            "enable": True,
            "density_threshold": self.sparse_density_threshold,
            "capacity_buckets": self.sparse_capacity_buckets,
            "hysteresis_rounds": self.sparse_hysteresis_rounds,
        }

    def cr6_tiles_config(self) -> Optional[dict]:
        """The rowpacked engine's ``cr6_tiles=`` kwarg for this config
        (None = window formulation)."""
        if not self.cr6_tiles:
            return None
        return {
            "enable": True,
            "tile_m": self.cr6_tiles_tile_m,
            "tile_l": self.cr6_tiles_tile_l,
            "density_threshold": self.cr6_tiles_density_threshold,
        }

    def pipeline_config(self) -> dict:
        """The rowpacked engine's ``pipeline=`` kwarg for this config:
        the pipelined-observation posture of observed saturation runs
        (``{"enable": False}`` restores the synchronous loop)."""
        return {
            "enable": self.pipeline,
            "depth": self.pipeline_depth,
        }

    def fused_rounds_config(self) -> Optional[dict]:
        """The rowpacked engine's ``fused_rounds=`` kwarg for this
        config (None = per-round controllers; the engine also routes
        per-round when K resolves to 1)."""
        if not self.fused_rounds:
            return None
        return {
            "enable": True,
            "rounds": self.fused_rounds_k,
            "adaptive": self.fused_rounds_adaptive,
        }

    def tracer_kwargs(self) -> dict:
        """The :class:`~distel_tpu.obs.SpanRecorder` construction kwargs
        for this config — the serve/router apps build their recorders
        from it."""
        return {
            "enable": self.obs_enable,
            "sample_rate": self.obs_sample_rate,
            "capacity": self.obs_ring_capacity,
        }

    def matmul_jnp_dtype(self):
        """None means "auto": the engine resolves it against the actual
        backend at construction time."""
        import jax.numpy as jnp

        return {
            "auto": None,
            "int8": jnp.int8,
            "bfloat16": jnp.bfloat16,
            "float32": jnp.float32,
        }[self.matmul_dtype]
