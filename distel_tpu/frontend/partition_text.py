"""Text-level component partitioning of OFN corpora.

``core/components.py`` partitions an already-indexed ontology — the
right tool for mixed corpora, but the GLOBAL index itself is the scale
wall for massively-multiplied corpora: ``role_closure`` and the factored
CR4/CR6 masks are dense in the role count, so a 65k-copy corpus
(~3.3M roles) can never be monolithically indexed, regardless of how
the state is later sharded.  (The reference never hits this because its
Redis hashes are sparse; the dense-role assumption is what buys this
framework its MXU-shaped masks at normal role counts.)

So at weak-scaling size the split happens BEFORE indexing: axiom LINES
of functional-syntax text are union-found over the entity names they
mention (linear in corpus size), components are grouped by a canonical
form that renames entities to first-occurrence ordinals (so the n
renamed copies of ``OntologyMultiplier`` collapse into one group
regardless of their ``__copyK`` suffixes), and ONE representative per
group is parsed/normalized/indexed.  The caller batch-executes each
group with ``core/components.saturate_isomorphic``.

Glue handling mirrors the index-level partitioner: ``owl:Thing`` /
``owl:Nothing`` are not union nodes; a line whose FIRST entity is ⊤/⊥
(a global-conclusion axiom like ⊤ ⊑ B) forces the unpartitioned
fallback.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

#: entity tokens: IRIs in <>, prefixed names (incl. default-prefix
#: ``:A`` — without the optional leading colon, ``:A`` and a bare ``A``
#: would intern as the same union-find node and silently coarsen the
#: partition; advisor r3 item 3), or bare NCNames — anything that is
#: not an OFN keyword/punctuation
_TOKEN = re.compile(r"<[^>]*>|:?[A-Za-z_][\w\-.:#/]*")
#: string literals: their contents must not create interaction-graph
#: edges (a literal that happens to spell an entity name would glue
#: unrelated components).  Canonicalization still renames literal
#: tokens — sound, because closures are equivariant under renaming.
_LITERAL = re.compile(r'"(?:[^"\\]|\\.)*"')
_KEYWORDS = frozenset(
    (
        "SubClassOf", "EquivalentClasses", "DisjointClasses",
        "ObjectIntersectionOf", "ObjectSomeValuesFrom", "ObjectOneOf",
        "ObjectHasValue", "SubObjectPropertyOf", "ObjectPropertyChain",
        "EquivalentObjectProperties", "TransitiveObjectProperty",
        "ReflexiveObjectProperty", "ObjectPropertyDomain",
        "ObjectPropertyRange", "ClassAssertion", "ObjectPropertyAssertion",
        "Ontology", "Prefix", "Declaration", "Class", "ObjectProperty",
        "NamedIndividual", "DataSomeValuesFrom", "DataHasValue",
    )
)
_GLUE = frozenset(("owl:Thing", "owl:Nothing", "<http://www.w3.org/2002/07/owl#Thing>", "<http://www.w3.org/2002/07/owl#Nothing>"))


@dataclass
class TextComponentGroups:
    """``groups[i]`` is (representative_text, member_count); every axiom
    line of the corpus belongs to exactly one member of one group."""

    groups: List[Tuple[str, int]]
    fallback: bool = False  # True => single group holds the whole corpus


#: top-level functors whose lines carry no logical content for the
#: partition: dropped from the interaction graph (Prefix/Declaration
#: lines become a shared preamble instead)
_IGNORABLE = frozenset(
    (
        "Annotation", "AnnotationAssertion", "SubAnnotationPropertyOf",
        "AnnotationPropertyDomain", "AnnotationPropertyRange",
    )
)
_PREAMBLE = ("Prefix(", "Declaration(")
#: logical functors the splitter understands; an unrecognized top-level
#: functor means tokens may not be entities at all — refuse to split
_LOGICAL = frozenset(
    (
        "SubClassOf", "EquivalentClasses", "DisjointClasses",
        "SubObjectPropertyOf", "EquivalentObjectProperties",
        "TransitiveObjectProperty", "ReflexiveObjectProperty",
        "ObjectPropertyDomain", "ObjectPropertyRange", "ClassAssertion",
        "ObjectPropertyAssertion",
    )
)


def _line_entities(line: str) -> List[str]:
    out = []
    for tok in _TOKEN.findall(_LITERAL.sub('""', line)):
        if tok in _KEYWORDS:
            continue
        out.append(tok)
    return out


def partition_ofn_text(text: str) -> TextComponentGroups:
    raw_lines = [
        ln
        for ln in text.splitlines()
        if ln.strip() and not ln.lstrip().startswith(("#", "Ontology(", ")"))
    ]
    preamble: List[str] = []
    lines: List[str] = []
    fallback = False
    for ln in raw_lines:
        s = ln.lstrip()
        if s.startswith(_PREAMBLE):
            preamble.append(ln)
            continue
        functor = s.split("(", 1)[0].strip()
        if functor in _IGNORABLE:
            continue
        if functor not in _LOGICAL:
            fallback = True  # unknown construct: tokens untrustworthy
            break
        lines.append(ln)
    intern: Dict[str, int] = {}
    line_first: List[int] = []
    edges_u: List[int] = []
    edges_v: List[int] = []
    if not fallback:
        for ln in lines:
            ents = _line_entities(ln)
            live = [e for e in ents if e not in _GLUE]
            # global-conclusion hazards (the text-level analog of the
            # index partitioner's ⊤/⊥-LHS refusal): ⊤/⊥ in subject
            # position, or ANYWHERE in an EquivalentClasses (either
            # side of the equivalence becomes an nf1 LHS)
            glue_present = len(live) < len(ents)
            if glue_present and (
                (ents and ents[0] in _GLUE)
                or ln.lstrip().startswith("EquivalentClasses")
            ):
                fallback = True
                break
            if not live:
                fallback = True  # line purely over ⊤/⊥
                break
            ids = []
            for e in live:
                i = intern.setdefault(e, len(intern))
                ids.append(i)
            line_first.append(ids[0])
            for j in ids[1:]:
                edges_u.append(ids[0])
                edges_v.append(j)
    if fallback or not lines:
        return TextComponentGroups(
            groups=[(text, 1)] if raw_lines else [], fallback=True
        )
    pre = "\n".join(preamble)

    n = len(intern)
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components

    adj = coo_matrix(
        (np.ones(len(edges_u), np.int8), (edges_u, edges_v)), shape=(n, n)
    )
    _, labels = connected_components(adj, directed=False)

    comp_lines: Dict[int, List[int]] = {}
    for li, first in enumerate(line_first):
        comp_lines.setdefault(int(labels[first]), []).append(li)

    groups: Dict[bytes, Tuple[str, int]] = {}
    for lab in sorted(comp_lines, key=lambda k: comp_lines[k][0]):
        lis = comp_lines[lab]
        # canonical form: entities renamed to first-occurrence ordinals
        ren: Dict[str, str] = {}

        def sub(m):
            tok = m.group(0)
            if tok in _KEYWORDS or tok in _GLUE:
                return tok
            if tok not in ren:
                ren[tok] = f"e{len(ren)}"
            return ren[tok]

        canon = "\n".join(_TOKEN.sub(sub, lines[li]) for li in lis)
        key = hashlib.sha256(canon.encode()).digest()
        if key in groups:
            rep, cnt = groups[key]
            groups[key] = (rep, cnt + 1)
        else:
            body = "\n".join(lines[li] for li in lis)
            # every representative carries the shared Prefix/Declaration
            # preamble so prefixed names still resolve when parsed alone
            groups[key] = (pre + "\n" + body if pre else body, 1)
    return TextComponentGroups(groups=list(groups.values()))
