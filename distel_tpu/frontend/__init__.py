"""Frontend passes: normalization, profile checking, corpus tools."""
