"""Corpus tools: synthetic generators and ontology transforms.

Equivalents of the reference's corpus tooling:
  * ``synthetic_ontology``   — deterministic EL+ generator (the scale tool
    behind weak-scaling runs; plays the role of the reference's
    ``samples/OntologyMultiplier.java`` synthetic corpora).
  * ``snomed_shaped_ontology`` — deterministic generator with SNOMED CT's
    *role structure*: tens of object properties under a role hierarchy,
    role-group-style conjunctive definitions, transitive partonomy and
    right-identity chains.  The reference's evaluation corpus is SNOMED
    (``ShardInfo.properties:27`` chunk-tuning notes); this generator
    reproduces its axiom-shape mix where the real release cannot ship.
  * ``multiply_ontology``    — n-copy entity renaming and "crossed"
    duplication (reference ``samples/OntologyMultiplier.java:32-88`` and
    :97-…: copy k gets every axiom with entities renamed E→E_k; crossed
    mode additionally mixes copies in conjunctions).
  * ``strip_non_el``         — batch removal of out-of-profile axioms
    (reference ``init/OntologyModifier.java:21-97``).
"""

from __future__ import annotations

import random
from typing import List, Optional

from distel_tpu.owl import syntax as S


def synthetic_ontology(
    n_classes: int = 2000,
    n_anatomy: int = 300,
    n_locations: int = 200,
    n_definitions: int = 100,
    seed: int = 42,
) -> str:
    """Deterministic GALEN/GO-shaped EL+ corpus in functional syntax:
    a binary-tree is-a hierarchy, a transitive partonomy, a located-in
    role with a right-identity chain, domain/range, and conjunctive
    definitions — every completion rule CR1-CR6 gets exercised."""
    rng = random.Random(seed)
    lines: List[str] = [
        "TransitiveObjectProperty(partOf)",
        "SubObjectPropertyOf(ObjectPropertyChain(hasLoc partOf) hasLoc)",
        "SubObjectPropertyOf(hasExactLoc hasLoc)",
        "ObjectPropertyDomain(hasLoc Disease)",
        "ObjectPropertyRange(hasLoc Anatomy)",
    ]
    for i in range(1, n_classes):
        lines.append(f"SubClassOf(C{i} C{i // 2})")
    for i in range(1, n_anatomy):
        lines.append(f"SubClassOf(Anat{i} Anatomy)")
        if i > 1:
            lines.append(
                f"SubClassOf(Anat{i} ObjectSomeValuesFrom(partOf Anat{i // 2}))"
            )
    for _ in range(n_locations):
        c = rng.randrange(n_classes)
        a = rng.randrange(1, n_anatomy)
        role = "hasExactLoc" if rng.random() < 0.3 else "hasLoc"
        lines.append(f"SubClassOf(C{c} ObjectSomeValuesFrom({role} Anat{a}))")
    for i in range(n_definitions):
        c = rng.randrange(n_classes)
        a = rng.randrange(1, n_anatomy)
        lines.append(
            f"EquivalentClasses(Def{i} ObjectIntersectionOf(C{c} "
            f"ObjectSomeValuesFrom(hasLoc Anat{a})))"
        )
    return "\n".join(lines)


def chain_tailed_ontology(
    n_classes: int,
    chain_depth: int,
    *,
    n_anatomy: Optional[int] = None,
    n_locations: Optional[int] = None,
    n_definitions: Optional[int] = None,
    seed: int = 42,
) -> str:
    """:func:`synthetic_ontology` plus a ``SubClassOf`` chain tail
    (``TailChain0 ⊑ … ⊑ TailChain{chain_depth}``, anchored by
    ``Class0 ⊑ TailChain0``) — the adaptive sparse tier's regime:
    late saturation rounds derive exactly one chain hop each, so the
    frontier density collapses while the fixed point keeps running.
    THE shared corpus recipe of the sparse-tail / pipelined / sharded
    A/B probes and their parity tests — one definition so every
    consumer measures the same regime.  Dimension defaults follow the
    GALEN shape (``n//10`` anatomy, ``n//12`` locations, ``n//20``
    definitions)."""
    text = synthetic_ontology(
        n_classes=n_classes,
        n_anatomy=n_anatomy if n_anatomy is not None else n_classes // 10,
        n_locations=(
            n_locations if n_locations is not None else n_classes // 12
        ),
        n_definitions=(
            n_definitions if n_definitions is not None else n_classes // 20
        ),
        seed=seed,
    )
    text += "\n" + "\n".join(
        f"SubClassOf(TailChain{i} TailChain{i + 1})"
        for i in range(chain_depth)
    )
    text += "\nSubClassOf(Class0 TailChain0)"
    return text


def snomed_shaped_ontology(
    n_classes: int = 2000,
    n_roles: int = 60,
    n_defs: int | None = None,
    n_assertions: int | None = None,
    seed: int = 42,
) -> str:
    """Deterministic EL+ corpus with SNOMED CT's role structure.

    Shape (mirroring the SNOMED release this framework targets as its
    north-star corpus, BASELINE.md):

    * five top-level areas (finding, procedure, body, substance,
      organism) of multi-parent is-a DAGs — ~20% of classes get a second
      parent, like SNOMED's DAG;
    * ``n_roles`` attributes in a two-level role hierarchy (SNOMED has
      ~60 active attributes, most under a handful of groupers);
    * a transitive partonomy over body structures plus right-identity
      chains (SNOMED's ``direct-substance o has-ingredient``-style
      axioms);
    * fully-defined concepts as role-group conjunctions: parent ∧
      ∃attr.filler [∧ ∃attr'.filler'] — the dominant SNOMED axiom shape;
    * primitive existential assertions for the rest.

    Unlike :func:`synthetic_ontology` (3 roles), the many-role structure
    makes the CR4/CR6 closure masks block-sparse — the realistic regime
    for the tile-skipping matmul kernel."""
    rng = random.Random(seed)
    n_defs = n_classes // 8 if n_defs is None else n_defs
    n_assertions = n_classes // 4 if n_assertions is None else n_assertions
    areas = ["Find", "Proc", "Body", "Subst", "Org"]
    per_area = max(n_classes // len(areas), 2)
    lines: List[str] = []

    # role hierarchy: grouper roles attrG0.. + leaf roles under them
    n_groupers = max(n_roles // 12, 1)
    for g in range(n_groupers):
        lines.append(f"SubObjectPropertyOf(attrG{g} attrG0)")
    for r in range(n_roles):
        g = rng.randrange(n_groupers)
        lines.append(f"SubObjectPropertyOf(attr{r} attrG{g})")
    lines.append("TransitiveObjectProperty(partOf)")
    lines.append("SubObjectPropertyOf(partOf attrG0)")
    # right-identity chains on a few leaf roles (SNOMED has ~10)
    for r in range(0, min(8, n_roles)):
        lines.append(
            f"SubObjectPropertyOf(ObjectPropertyChain(attr{r} partOf) attr{r})"
        )
    lines.append("ObjectPropertyDomain(attr0 Find)")
    lines.append("ObjectPropertyRange(attr0 Body)")

    # multi-parent is-a DAGs per area
    for area in areas:
        for i in range(1, per_area):
            lines.append(f"SubClassOf({area}{i} {area}{i // 2})")
            if i > 3 and rng.random() < 0.2:
                lines.append(
                    f"SubClassOf({area}{i} {area}{rng.randrange(1, i)})"
                )
    # partonomy over body structures
    for i in range(2, per_area):
        if rng.random() < 0.4:
            lines.append(
                f"SubClassOf(Body{i} ObjectSomeValuesFrom(partOf Body{i // 2}))"
            )

    filler_areas = ["Body", "Subst", "Org"]

    def filler(r: random.Random) -> str:
        return f"{r.choice(filler_areas)}{r.randrange(1, per_area)}"

    # fully-defined concepts: parent ∧ ∃attr.filler [∧ ∃attr'.filler']
    for i in range(n_defs):
        area = rng.choice(["Find", "Proc"])
        parent = f"{area}{rng.randrange(1, per_area)}"
        a1, a2 = rng.randrange(n_roles), rng.randrange(n_roles)
        conj = [
            parent,
            f"ObjectSomeValuesFrom(attr{a1} {filler(rng)})",
        ]
        if rng.random() < 0.5:
            conj.append(f"ObjectSomeValuesFrom(attr{a2} {filler(rng)})")
        lines.append(
            f"EquivalentClasses(SCT{i} ObjectIntersectionOf({' '.join(conj)}))"
        )
    # primitive existential assertions
    for _ in range(n_assertions):
        area = rng.choice(areas)
        c = f"{area}{rng.randrange(1, per_area)}"
        a = rng.randrange(n_roles)
        lines.append(
            f"SubClassOf({c} ObjectSomeValuesFrom(attr{a} {filler(rng)}))"
        )
    return "\n".join(lines)


def _rename_atom(e: S.ClassExpression, k: int) -> S.ClassExpression:
    if isinstance(e, S.Class):
        return S.Class(f"{e.iri}__copy{k}")
    if isinstance(e, S.Individual):
        return S.Individual(f"{e.iri}__copy{k}")
    if isinstance(e, S.ObjectIntersectionOf):
        return S.ObjectIntersectionOf(tuple(_rename_atom(o, k) for o in e.operands))
    if isinstance(e, S.ObjectSomeValuesFrom):
        return S.ObjectSomeValuesFrom(_rename_role(e.role, k), _rename_atom(e.filler, k))
    return e  # ⊤/⊥ shared across copies


def _rename_role(r: S.ObjectProperty, k: int) -> S.ObjectProperty:
    return S.ObjectProperty(f"{r.iri}__copy{k}")


def _rename_axiom(ax: S.Axiom, k: int) -> S.Axiom:
    if isinstance(ax, S.SubClassOf):
        return S.SubClassOf(_rename_atom(ax.sub, k), _rename_atom(ax.sup, k))
    if isinstance(ax, S.EquivalentClasses):
        return S.EquivalentClasses(tuple(_rename_atom(o, k) for o in ax.operands))
    if isinstance(ax, S.DisjointClasses):
        return S.DisjointClasses(tuple(_rename_atom(o, k) for o in ax.operands))
    if isinstance(ax, S.SubObjectPropertyOf):
        return S.SubObjectPropertyOf(
            tuple(_rename_role(r, k) for r in ax.chain), _rename_role(ax.sup, k)
        )
    if isinstance(ax, S.EquivalentObjectProperties):
        return S.EquivalentObjectProperties(
            tuple(_rename_role(r, k) for r in ax.operands)
        )
    if isinstance(ax, S.TransitiveObjectProperty):
        return S.TransitiveObjectProperty(_rename_role(ax.role, k))
    if isinstance(ax, S.ObjectPropertyDomain):
        return S.ObjectPropertyDomain(_rename_role(ax.role, k), _rename_atom(ax.domain, k))
    if isinstance(ax, S.ObjectPropertyRange):
        return S.ObjectPropertyRange(_rename_role(ax.role, k), _rename_atom(ax.range, k))
    if isinstance(ax, S.ClassAssertion):
        return S.ClassAssertion(_rename_atom(ax.cls, k), _rename_atom(ax.individual, k))
    if isinstance(ax, S.ObjectPropertyAssertion):
        return S.ObjectPropertyAssertion(
            _rename_role(ax.role, k),
            _rename_atom(ax.subject, k),
            _rename_atom(ax.object, k),
        )
    return ax


def multiply_ontology(onto: S.Ontology, n_copies: int, crossed: bool = False) -> S.Ontology:
    """Weak-scaling corpus builder: n disjoint renamed copies; ``crossed``
    additionally links copy k to copy k+1 with cross-copy conjunctions
    (the reference's A1⊓B2⊑C1 pattern, ``samples/OntologyMultiplier.java:97-``)."""
    out = S.Ontology(iri=onto.iri + f"-x{n_copies}")
    for k in range(n_copies):
        for ax in onto.axioms:
            out.add(_rename_axiom(ax, k))
    if crossed and n_copies >= 2:
        classes = sorted(onto.classes(), key=lambda c: c.iri)[:50]
        for k in range(n_copies - 1):
            for i in range(0, len(classes) - 1, 2):
                a = _rename_atom(classes[i], k)
                b = _rename_atom(classes[i + 1], k + 1)
                c = _rename_atom(classes[i], k + 1)
                out.add(S.SubClassOf(S.ObjectIntersectionOf((a, b)), c))
    return out


def strip_non_el(onto: S.Ontology) -> S.Ontology:
    """Drop axioms containing out-of-profile constructs (reference
    ``init/OntologyModifier.java:21-97`` / ``test/ELAxiomExtractor.java``)."""
    from distel_tpu.frontend.profile_checker import axiom_in_profile

    out = S.Ontology(iri=onto.iri, prefixes=dict(onto.prefixes))
    for ax in onto.axioms:
        if axiom_in_profile(ax):
            out.add(ax)
    return out
